"""O1 per-op cast lists + runtime patching.

Reference: ``reference:apex/amp/lists/torch_overrides.py`` /
``functional_overrides.py`` / ``tensor_overrides.py`` (the policy tables:
which ops run fp16, which fp32, which promote to the widest input type) and
the registration escape hatches ``register_half_function`` /
``register_float_function`` / ``register_promote_function``
(``reference:apex/amp/amp.py:30-64``), applied by wrapping the listed
callables at ``amp.init`` time (``amp.py:68-177``, ``wrap.py:10-112``).

TPU framing: wholesale-policy casting (:mod:`apex_tpu.amp.policy`) covers
the common case — XLA fuses the casts, and bf16 removes fp16's range traps.
The per-op tables still matter for (a) fp16 workflows that need exp/log/
softmax/norm in fp32, (b) third-party functional code you cannot edit but
can call under :func:`o1_context`, and (c) API parity. The mechanism is the
same as the reference's: the listed functions are wrapped (module attribute
swapped) for the duration of the context, with cast-to-half on the
matmul/conv class, cast-to-fp32 on the numerically-sensitive class, and
widest-input promotion on the mixed-input class. ``disable_casts`` gives
the reference's escape to raw behavior (``reference:apex/amp/handle.py:163-167``).

The default tables translate the reference lists to the JAX namespace:

- FP16 (``torch_overrides.py:7-27``: conv*/BLAS):  ``jnp.matmul``,
  ``jnp.dot``, ``jnp.vdot``, ``jnp.inner``, ``jnp.tensordot``,
  ``jnp.einsum``, ``jax.lax.conv_general_dilated``, ``jax.lax.dot_general``.
- FP32 (``torch_overrides.py:29-59``: transcendental + reductions + norms):
  ``jnp.exp``, ``jnp.expm1``, ``jnp.log``, ``jnp.log10``, ``jnp.log1p``,
  ``jnp.log2``, ``jnp.power``, ``jnp.cosh``, ``jnp.sinh``, ``jnp.sum``,
  ``jnp.prod``, ``jnp.cumsum``, ``jnp.cumprod``, ``jnp.linalg.norm``,
  ``jax.nn.softmax``, ``jax.nn.log_softmax``, ``jax.nn.softplus``,
  ``jax.scipy.special.erf``.
- PROMOTE (``torch_overrides.py:84-116`` CASTS + SEQUENCE_CASTS):
  ``jnp.add``, ``jnp.subtract``, ``jnp.multiply``, ``jnp.true_divide``,
  ``jnp.equal``, ``jnp.concatenate``, ``jnp.stack``.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "register_half_function", "register_float_function",
    "register_promote_function", "o1_context", "disable_casts",
    "casts_are_enabled",
]

_MATH = "half"
_FP32 = "float"
_PROMOTE = "promote"

# (module_object, attr_name) -> category; user registrations extend this
_REGISTRY: List[Tuple[Any, str, str]] = []
_DEFAULTS_BUILT = False
_state = threading.local()


def _cast_enabled() -> bool:
    return getattr(_state, "enabled", True)


def casts_are_enabled() -> bool:
    """False inside :func:`disable_casts`."""
    return _cast_enabled()


def _is_float_array(x: Any) -> bool:
    # array-likes only: Python scalars keep default promotion, matching the
    # reference wrappers which cast tensors and leave scalars alone
    return (hasattr(x, "dtype") and hasattr(x, "shape")
            and jnp.issubdtype(x.dtype, jnp.floating))


def _cast_tree_to(tree: Any, dtype: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


def _widest_float(tree: Any):
    widest = None
    for leaf in jax.tree_util.tree_leaves(tree):
        if _is_float_array(leaf):
            widest = leaf.dtype if widest is None else jnp.promote_types(
                widest, leaf.dtype)
    return widest


def _wrap(fn: Callable, category: str, half_dtype) -> Callable:
    """The cast wrapper (``reference:apex/amp/wrap.py:10-112``): cast float
    array arguments, call, return. Output dtype is whatever the op produces
    from its cast inputs — matching the reference, which casts inputs only."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not _cast_enabled():
            return fn(*args, **kwargs)
        if category == _MATH:
            target = half_dtype
        elif category == _FP32:
            target = jnp.float32
        else:  # promote: widest floating dtype among the inputs
            target = _widest_float((args, kwargs))
        if target is not None:
            args, kwargs = _cast_tree_to((args, kwargs), target)
        return fn(*args, **kwargs)

    wrapped.__amp_wrapped__ = fn
    return wrapped


def register_half_function(module: Any, name: str) -> None:
    """Run ``module.<name>`` in the half dtype under :func:`o1_context`
    (``reference:apex/amp/amp.py:30-39``)."""
    _REGISTRY.append((module, name, _MATH))


def register_float_function(module: Any, name: str) -> None:
    """Run ``module.<name>`` in fp32 under :func:`o1_context`
    (``reference:apex/amp/amp.py:42-50``)."""
    _REGISTRY.append((module, name, _FP32))


def register_promote_function(module: Any, name: str) -> None:
    """Promote mixed inputs of ``module.<name>`` to the widest float dtype
    (``reference:apex/amp/amp.py:53-64``)."""
    _REGISTRY.append((module, name, _PROMOTE))


def _build_default_registry() -> None:
    global _DEFAULTS_BUILT
    if _DEFAULTS_BUILT:
        return
    _DEFAULTS_BUILT = True
    for name in ("matmul", "dot", "vdot", "inner", "tensordot", "einsum"):
        register_half_function(jnp, name)
    register_half_function(jax.lax, "conv_general_dilated")
    register_half_function(jax.lax, "dot_general")
    for name in ("exp", "expm1", "log", "log10", "log1p", "log2", "power",
                 "cosh", "sinh", "sum", "prod", "cumsum", "cumprod"):
        register_float_function(jnp, name)
    register_float_function(jnp.linalg, "norm")
    for name in ("softmax", "log_softmax", "softplus"):
        register_float_function(jax.nn, name)
    register_float_function(jax.scipy.special, "erf")
    for name in ("add", "subtract", "multiply", "true_divide", "equal",
                 "concatenate", "stack"):
        register_promote_function(jnp, name)


@contextlib.contextmanager
def o1_context(half_dtype: Any = jnp.bfloat16):
    """Patch the registered functions with their cast wrappers — the
    functional scope of ``amp.init()``'s namespace patching
    (``reference:apex/amp/amp.py:68-177``). Code called inside the context
    (including code about to be traced by ``jit``) sees the patched ops;
    on exit every attribute is restored.

    Note the tracing caveat: the patching is Python-level, so it applies to
    functions *traced* inside the context. A function jitted (and cached)
    outside keeps its original behavior.
    """
    _build_default_registry()
    originals = []
    try:
        for module, name, category in _REGISTRY:
            fn = getattr(module, name)
            if hasattr(fn, "__amp_wrapped__"):
                continue  # already patched (nested contexts)
            originals.append((module, name, fn))
            setattr(module, name, _wrap(fn, category, jnp.dtype(half_dtype)))
        yield
    finally:
        for module, name, fn in reversed(originals):
            setattr(module, name, fn)


@contextlib.contextmanager
def disable_casts():
    """Temporarily run everything un-cast inside an :func:`o1_context`
    (``reference:apex/amp/handle.py:163-167``)."""
    prev = _cast_enabled()
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev
