"""apex_tpu.amp — mixed-precision policies and loss scaling.

TPU-native re-design of ``reference:apex/amp`` (frontend.py, scaler.py,
_initialize.py, _process_optimizer.py): instead of monkey-patching torch and
optimizers, a :class:`Policy` describes the dtypes, and
:func:`scaled_value_and_grad` threads an on-device loss-scale state through the
train step. See also ``apex_tpu.fp16_utils`` for the legacy-API shims.
"""

from apex_tpu.amp.lists import (  # noqa: F401
    casts_are_enabled, disable_casts, o1_context, register_float_function,
    register_half_function, register_promote_function)
from apex_tpu.amp.policy import (
    O0,
    O1,
    O2,
    O3,
    Policy,
    cast_floating,
    cast_to_compute,
    cast_to_output,
    cast_to_param,
    get_policy,
    with_policy,
)
from apex_tpu.amp.scaler import (
    DynamicLossScale,
    LossScaleState,
    NoOpLossScale,
    StaticLossScale,
    all_finite,
    make_loss_scale,
    scaled_value_and_grad,
    select_tree,
)

__all__ = [
    "Policy", "O0", "O1", "O2", "O3", "get_policy",
    "cast_to_compute", "cast_to_param", "cast_to_output", "cast_floating",
    "with_policy",
    "LossScaleState", "DynamicLossScale", "StaticLossScale", "NoOpLossScale",
    "make_loss_scale", "all_finite", "select_tree", "scaled_value_and_grad",
    "o1_context", "disable_casts", "casts_are_enabled",
    "register_half_function", "register_float_function",
    "register_promote_function",
]
