"""Tensor-list ops — the TPU equivalent of ``amp_C``/``multi_tensor_apply``.

The reference batches elementwise kernels over lists of tensors with a chunked
launcher (``reference:csrc/multi_tensor_apply.cuh:19-133``,
``reference:apex/multi_tensor_apply/multi_tensor_apply.py:3-34``) because eager
CUDA pays per-kernel launch overhead. Under XLA one jitted function over a
pytree compiles to fused loops, so no launcher exists here — we keep the *API*
shape (an op over a list/tree of tensors plus an overflow flag) and let the
compiler do the batching.

The ``noop_flag`` overflow buffer becomes a returned boolean: every op that the
reference guards with the flag returns ``(result, all_finite)`` so callers can
gate updates with :func:`apex_tpu.amp.select_tree` instead of re-reading a
device buffer from the host.
"""

from apex_tpu.multi_tensor_apply.multi_tensor_apply import (  # noqa: F401
    flatten,
    multi_tensor_applier,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    tree_global_norm,
    tree_per_tensor_norms,
    unflatten,
)

__all__ = [
    "flatten",
    "unflatten",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "multi_tensor_applier",
    "tree_global_norm",
    "tree_per_tensor_norms",
]
