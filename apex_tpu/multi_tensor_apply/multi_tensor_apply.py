"""Batched tensor-list math over pytrees (see package docstring)."""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from apex_tpu.amp.scaler import all_finite

__all__ = [
    "flatten", "unflatten", "multi_tensor_scale", "multi_tensor_axpby",
    "multi_tensor_l2norm", "multi_tensor_applier",
    "tree_global_norm", "tree_per_tensor_norms",
]


def flatten(tree: Any) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], Any]]:
    """Pack a pytree into one fp-contiguous 1-D buffer.

    Equivalent of ``apex_C.flatten`` (``reference:csrc/flatten_unflatten.cpp:15-17``)
    used for DDP bucket transport; returns the buffer and the inverse.
    """
    return ravel_pytree(tree)


def unflatten(flat: jnp.ndarray, unravel: Callable[[jnp.ndarray], Any]) -> Any:
    """Inverse of :func:`flatten` (``apex_C.unflatten``)."""
    return unravel(flat)


def _float_leaves(tree: Any) -> List[jnp.ndarray]:
    return [x for x in jax.tree_util.tree_leaves(tree)
            if hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]


def multi_tensor_scale(tree: Any, scale: Any) -> Tuple[Any, jnp.ndarray]:
    """``out = in * scale`` over every float leaf, plus a finite flag.

    Mirrors ``amp_C.multi_tensor_scale`` (``reference:csrc/multi_tensor_scale_kernel.cu:30``),
    which is amp's unscale/copy workhorse (``reference:apex/amp/scaler.py:94-124``).
    The flag is true iff every *output* element is finite.
    """
    scale = jnp.asarray(scale, jnp.float32)

    def _scale(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        return (x.astype(jnp.float32) * scale).astype(x.dtype)

    out = jax.tree_util.tree_map(_scale, tree)
    # observe=None: these are scaled/blended OUTPUT trees, not the
    # amp grad check — recording them as "grads" would corrupt the
    # health watchdog's counts and leaf attribution
    return out, all_finite(out, observe=None)


def multi_tensor_axpby(a: Any, x_tree: Any, b: Any, y_tree: Any,
                       out_dtype: Any = None) -> Tuple[Any, jnp.ndarray]:
    """``out = a*x + b*y`` leafwise with finite flag.

    Mirrors ``amp_C.multi_tensor_axpby`` (``reference:csrc/multi_tensor_axpby_kernel.cu:28``),
    used by ``unscale_with_stashed`` (``reference:apex/amp/scaler.py:152-189``).
    """
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def _axpby(x, y):
        x, y = jnp.asarray(x), jnp.asarray(y)
        out = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return out.astype(out_dtype or x.dtype)

    out = jax.tree_util.tree_map(_axpby, x_tree, y_tree)
    # observe=None: these are scaled/blended OUTPUT trees, not the
    # amp grad check — recording them as "grads" would corrupt the
    # health watchdog's counts and leaf attribution
    return out, all_finite(out, observe=None)


def tree_per_tensor_norms(tree: Any, ord: int = 2) -> Any:
    """Per-leaf L2 (or L-inf with ``ord=0``) norms in fp32, same treedef."""

    def _norm(x):
        x = jnp.asarray(x).astype(jnp.float32)
        if ord == 0:
            return jnp.max(jnp.abs(x))
        return jnp.sqrt(jnp.sum(x * x))

    return jax.tree_util.tree_map(_norm, tree)


def tree_global_norm(tree: Any) -> jnp.ndarray:
    """Global L2 norm across every leaf (fp32 accumulation).

    Mirrors ``amp_C.multi_tensor_l2norm``'s global output
    (``reference:csrc/multi_tensor_l2norm_kernel.cu:29``), which FusedLAMB uses
    for its global grad-norm clip (``reference:apex/optimizers/fused_lamb.py:124-133``).
    """
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = [jnp.sum(jnp.asarray(x).astype(jnp.float32) ** 2) for x in leaves]
    return jnp.sqrt(jnp.stack(sq).sum())


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False):
    """``global_norm`` scalar, or ``(global_norm, per_tensor_norms)`` when
    ``per_tensor=True`` (the reference binding's optional second output)."""
    g = tree_global_norm(tree)
    if per_tensor:
        return g, tree_per_tensor_norms(tree)
    return g


class _MultiTensorApplier:
    """API-compat shim for ``multi_tensor_applier(op, noop_flag, lists, *args)``
    call sites (``reference:apex/multi_tensor_apply/multi_tensor_apply.py:28-34``):
    it calls ``op(*tensor_lists, *args)`` — chunking is XLA's job.

    Note this serves *custom* functional ops whose signature takes one
    positional arg per tensor list. The reference's in-place ``amp_C`` call
    shapes (e.g. ``[grads, out]`` output lists, ``reference:apex/amp/scaler.py:114-124``)
    have no functional equivalent here — use :func:`multi_tensor_scale` /
    :func:`multi_tensor_axpby` / :func:`multi_tensor_l2norm` directly, which
    return their outputs instead of writing into an out-list.
    """

    available = True

    def __call__(self, op, noop_flag_unused, tensor_lists, *args):
        return op(*tensor_lists, *args)


multi_tensor_applier = _MultiTensorApplier()
