"""Native host runtime pieces (C++, ctypes-loaded).

The reference builds its host-side buffer packing as the ``apex_C``
extension (``reference:csrc/flatten_unflatten.cpp``); this package holds
the TPU framework's native host equivalents. The shared object is built
on demand from the checked-in source with the system compiler and cached
next to it; every entry point has a numpy fallback, so the package
degrades gracefully where no toolchain exists (mirroring the reference's
Python-only install story, ``reference:README.md:125-134``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["flatten", "unflatten", "gather_rows", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "flatten.cpp")
_SO = os.path.join(_DIR, "_flatten.so")
_LIB = None
_TRIED = False


def _build() -> Optional[str]:
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-o", _SO, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def _load():
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    path = _SO if (os.path.exists(_SO)
                   and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)) \
        else _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.apex_tpu_flatten.restype = ctypes.c_size_t
        lib.apex_tpu_unflatten.restype = ctypes.c_size_t
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def native_available() -> bool:
    return _load() is not None


def _ptr_array(arrays: Sequence[np.ndarray], writable: bool):
    ptrs = (ctypes.c_void_p * len(arrays))()
    sizes = (ctypes.c_size_t * len(arrays))()
    for i, a in enumerate(arrays):
        if not a.flags["C_CONTIGUOUS"]:
            raise ValueError("arrays must be C-contiguous")
        if writable and not a.flags["WRITEABLE"]:
            raise ValueError("destination arrays must be writable")
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
        sizes[i] = a.nbytes
    return ptrs, sizes


def flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate host arrays into one contiguous uint8 buffer
    (``apex_C.flatten``)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    total = sum(a.nbytes for a in arrays)
    out = np.empty(total, np.uint8)
    lib = _load()
    if lib is None:
        off = 0
        for a in arrays:
            out[off:off + a.nbytes] = a.view(np.uint8).reshape(-1)
            off += a.nbytes
        return out
    ptrs, sizes = _ptr_array(arrays, writable=False)
    lib.apex_tpu_flatten(ptrs, sizes, len(arrays),
                         out.ctypes.data_as(ctypes.c_void_p))
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray]
              ) -> List[np.ndarray]:
    """Split a flat uint8 buffer back into arrays shaped/typed like
    ``like`` (``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat).view(np.uint8).reshape(-1)
    outs = [np.empty(a.shape, a.dtype) for a in like]
    total = sum(o.nbytes for o in outs)
    if flat.nbytes < total:
        raise ValueError(f"flat buffer too small: {flat.nbytes} < {total}")
    lib = _load()
    if lib is None:
        off = 0
        for o in outs:
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + o.nbytes]
            off += o.nbytes
        return outs
    ptrs, sizes = _ptr_array(outs, writable=True)
    lib.apex_tpu_unflatten(flat.ctypes.data_as(ctypes.c_void_p), ptrs,
                           sizes, len(outs))
    return outs


def gather_rows(src: np.ndarray, indices: Sequence[int]) -> np.ndarray:
    """``dst[i] = src[indices[i]]`` over axis 0 — the sampler batch-packing
    hot path (one memcpy per sample)."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(np.asarray(indices, np.int64))
    if idx.ndim != 1:
        raise ValueError("indices must be 1-D")
    if src.ndim < 1:
        raise ValueError("src must have a leading sample axis")
    if idx.size and (idx.min() < 0 or idx.max() >= src.shape[0]):
        raise IndexError("index out of range")
    out = np.empty((idx.size,) + src.shape[1:], src.dtype)
    lib = _load()
    if lib is None:
        np.take(src, idx, axis=0, out=out)
        return out
    row_bytes = src.nbytes // max(src.shape[0], 1)
    lib.apex_tpu_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(row_bytes),
        idx.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(idx.size),
        out.ctypes.data_as(ctypes.c_void_p))
    return out
