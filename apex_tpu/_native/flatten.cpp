// Host-side buffer packing — the apex_C extension's role
// (reference:csrc/flatten_unflatten.cpp:15-18, wrapping
// torch::utils::flatten_dense_tensors).
//
// On TPU the *device-side* flatten is jnp.concatenate inside jit (the
// FlatOptimizer/ZeRO tier); this native module serves the HOST paths the
// reference also used apex_C for: packing many small numpy buffers into one
// contiguous staging buffer (checkpoint assembly, sampler batch packing)
// without Python-loop overhead. Plain C ABI, loaded via ctypes — no
// pybind11 dependency (not available in this image).

#include <cstddef>
#include <cstring>
#include <cstdint>

extern "C" {

// Concatenate n buffers (srcs[i], nbytes[i]) into dst. Returns total bytes.
size_t apex_tpu_flatten(const void **srcs, const size_t *nbytes, size_t n,
                        unsigned char *dst) {
  size_t off = 0;
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(dst + off, srcs[i], nbytes[i]);
    off += nbytes[i];
  }
  return off;
}

// Split src back into n buffers (dsts[i], nbytes[i]). Returns bytes read.
size_t apex_tpu_unflatten(const unsigned char *src, void **dsts,
                          const size_t *nbytes, size_t n) {
  size_t off = 0;
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(dsts[i], src + off, nbytes[i]);
    off += nbytes[i];
  }
  return off;
}

// Gather rows: dst[i, :] = src[indices[i], :] for row_bytes-wide rows —
// the sampler batch-packing hot path (one memcpy per sample instead of a
// Python-level fancy-index + copy).
void apex_tpu_gather_rows(const unsigned char *src, size_t row_bytes,
                          const int64_t *indices, size_t n,
                          unsigned char *dst) {
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(dst + i * row_bytes,
                src + static_cast<size_t>(indices[i]) * row_bytes, row_bytes);
  }
}

}  // extern "C"
