"""Selective activation rematerialization — named policies over tagged
activations.

The reference ships activation checkpointing as an all-or-nothing wrapper
(``reference:apex/transformer/tensor_parallel/memory.py`` +
``random.py:checkpoint`` — the RNG-replaying checkpointed forward), and the
first port mirrored that bluntness: a ``remat: bool`` that wrapped the whole
layer in ``jax.checkpoint`` with the default save-nothing policy, recomputing
every GEMM *and* the flash-attention kernel in the backward. Megatron-style
*selective* recomputation (Korthikanti et al., "Reducing Activation
Recomputation in Large Transformer Models") shows most of the memory win
comes from dropping only the cheap-to-recompute activations (LayerNorms,
gelu, residual adds, reshapes) while keeping GEMM and attention-kernel
outputs resident — recovering the ~30% of backward FLOPs full remat burns.

This module is the single source of truth for that knob:

- :data:`CHECKPOINT_NAMES` — the central registry of
  ``jax.ad_checkpoint.checkpoint_name`` tags the models emit. Every tag
  literal in the package MUST come from this tuple
  (``scripts/check_remat_names.py`` enforces it statically): an orphan tag
  is an activation no policy can address, and a policy naming a tag nobody
  emits silently saves nothing.
- :func:`tag` — the tagging chokepoint (validates against the registry at
  trace time).
- :class:`RematPolicy` — ``none | full | selective | offload`` (plus a
  custom ``names`` save-list), mapping onto ``jax.checkpoint`` with
  ``jax.checkpoint_policies.save_only_these_names`` /
  ``save_and_offload_only_these_names``. ``full`` is *exactly* the old
  ``remat=True`` program (plain ``jax.checkpoint``, no tags — models gate
  their tag calls on :attr:`RematPolicy.uses_names`, so the ``full`` and
  ``none`` jaxprs carry zero ``name`` equations and stay identical to the
  pre-policy programs; asserted in ``tests/test_remat_policy.py``).

Flash attention under ``selective``: saving the kernel's *output* alone
would not keep the kernel out of the recomputed set — its ``custom_vjp``
backward also needs the logsumexp residual, and an unsaved residual forces
the forward kernel to rerun inside the remat region. The kernel therefore
tags both its context output (``flash_ctx``) and its logsumexp
(``flash_lse``) inside the custom_vjp *forward rule* (where residuals are
traced under AD), so ``save_only_these_names`` keeps everything the
backward kernel needs resident and DCE drops the forward kernel from the
recompute entirely (asserted structurally on the jaxpr).

Determinism under recompute: both dropout streams are counter-based — the
in-kernel flash dropout regenerates its keep mask from the packed seed, and
hidden dropout draws from explicit ``jax.random`` keys — so a recomputed
forward reproduces bit-identical masks under every policy (no torch-style
RNG-state save/restore needed; ``tensor_parallel/random.py``'s
``CheckpointFunction`` fork/restore machinery has no analog here).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Callable, Optional, Tuple

import jax
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name

__all__ = ["CHECKPOINT_NAMES", "SELECTIVE_SAVE", "RematPolicy", "tag"]

# The central registry: every checkpoint_name tag the models emit. Keep
# entries as plain string literals — scripts/check_remat_names.py parses
# this tuple from the AST (no jax import) and cross-checks every tag call
# site in the package against it.
CHECKPOINT_NAMES: Tuple[str, ...] = (
    "flash_ctx",       # flash-attention context (kernel output)
    "flash_lse",       # flash-attention logsumexp (custom_vjp residual)
    "qkv_out",         # fused QKV ColumnParallel GEMM output
    "attn_proj_out",   # attention RowParallel projection GEMM output
    "mlp_fc1_out",     # MLP up-projection GEMM output (pre-gelu)
    "mlp_fc2_out",     # MLP down-projection GEMM output
    "ln_out",          # LayerNorm outputs (ln1 / ln2 / final)
)

# Megatron-selective default save-list: GEMM and flash outputs stay
# resident (each costs one GEMM / one kernel launch to recompute); LN
# outputs are dropped (one fused elementwise pass to recompute, the cheap
# trade the selective mode exists for).
SELECTIVE_SAVE: Tuple[str, ...] = (
    "flash_ctx",
    "flash_lse",
    "qkv_out",
    "attn_proj_out",
    "mlp_fc1_out",
    "mlp_fc2_out",
)

_MODES = ("none", "full", "selective", "offload")


def tag(x, name: str):
    """``jax.ad_checkpoint.checkpoint_name`` through the registry: tags
    ``x`` so a name-based :class:`RematPolicy` can save/offload it. A name
    outside :data:`CHECKPOINT_NAMES` raises — an unregistered tag is an
    activation the policies silently miss."""
    if name not in CHECKPOINT_NAMES:
        raise ValueError(
            f"checkpoint name {name!r} is not in remat.CHECKPOINT_NAMES; "
            f"register it there (and in the selective save-list if it "
            f"should stay resident) — orphan tags are unreachable by "
            f"every policy")
    return _checkpoint_name(x, name)


@dataclasses.dataclass(frozen=True)
class RematPolicy:
    """Activation-checkpoint policy for a layer/stage function.

    ``mode``:

    - ``"none"`` — no checkpointing (AD saves every residual);
    - ``"full"`` — plain ``jax.checkpoint`` with the default save-nothing
      policy: the pre-policy ``remat=True`` program, jaxpr-identical;
    - ``"selective"`` — ``save_only_these_names(*save_names)``: registry-
      tagged GEMM/flash outputs stay resident, everything else (LNs, gelu,
      adds) is recomputed;
    - ``"offload"`` — ``save_and_offload_only_these_names``: the same
      tagged set is offloaded to ``offload_dst`` (default pinned host
      memory) during forward and fetched back for backward — HBM cost of
      ``full`` with the recompute cost of ``selective``, paid in
      host-interconnect bandwidth.

    ``names``: custom save/offload list (must be registry members);
    ``None`` selects :data:`SELECTIVE_SAVE`. Only meaningful for the
    name-based modes.
    """

    mode: str = "none"
    names: Optional[Tuple[str, ...]] = None
    offload_src: str = "device"
    offload_dst: str = "pinned_host"

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(
                f"remat mode {self.mode!r}; expected one of {_MODES}")
        if self.names is not None:
            object.__setattr__(self, "names", tuple(self.names))
            if self.mode not in ("selective", "offload"):
                raise ValueError(
                    f"names={self.names!r} is only meaningful for "
                    f"selective/offload policies, not mode={self.mode!r}")
            unknown = [n for n in self.names if n not in CHECKPOINT_NAMES]
            if unknown:
                raise ValueError(
                    f"unregistered checkpoint names {unknown}; the "
                    f"registry is remat.CHECKPOINT_NAMES={CHECKPOINT_NAMES}")

    # -- derived ----------------------------------------------------------
    @property
    def uses_names(self) -> bool:
        """Whether this policy consumes ``checkpoint_name`` tags — models
        gate their tag emission on this so ``none``/``full`` programs stay
        byte-identical to the pre-policy ones."""
        return self.mode in ("selective", "offload")

    @property
    def save_names(self) -> Tuple[str, ...]:
        return self.names if self.names is not None else SELECTIVE_SAVE

    # -- application ------------------------------------------------------
    def wrap(self, fn: Callable) -> Callable:
        """The ``jax.checkpoint`` wrapper this policy denotes (identity
        for ``none``)."""
        if self.mode == "none":
            return fn
        if self.mode == "full":
            # exactly the legacy remat=True spelling — no policy kwarg, so
            # the traced program cannot drift from the pre-policy one
            return jax.checkpoint(fn)
        if self.mode == "selective":
            policy = jax.checkpoint_policies.save_only_these_names(
                *self.save_names)
        else:  # offload
            policy = jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=list(self.save_names),
                offload_src=self.offload_src,
                offload_dst=self.offload_dst)
        return jax.checkpoint(fn, policy=policy)

    # -- construction -----------------------------------------------------
    @classmethod
    def resolve(cls, value: Any = None, legacy_bool: Optional[bool] = None,
                owner: str = "config") -> "RematPolicy":
        """Normalize every accepted spelling to a policy object.

        ``value``: ``None`` | mode string | bool | :class:`RematPolicy`.
        ``legacy_bool``: the deprecated ``remat: bool`` config field,
        consulted only when ``value`` is None — ``True`` maps to ``full``
        with a :class:`DeprecationWarning` (the config round-trip keeps
        working; new code should set ``remat_policy``). A bool passed as
        ``value`` (the pipeline schedules' ``remat`` flag) maps silently —
        that flag predates the policies and stays a supported API.
        """
        if isinstance(value, cls):
            return value
        if value is None:
            if legacy_bool:
                warnings.warn(
                    f"{owner}.remat=True (bool) is deprecated; use "
                    f"remat_policy='full' (or 'selective'/'offload' for "
                    f"the cheaper name-based policies)",
                    DeprecationWarning, stacklevel=3)
                return cls(mode="full")
            return cls(mode="none")
        if isinstance(value, bool):
            return cls(mode="full" if value else "none")
        if isinstance(value, str):
            return cls(mode=value)
        raise TypeError(
            f"cannot resolve a remat policy from {value!r} "
            f"(expected None, bool, mode string, or RematPolicy)")
