"""RNN-T (transducer) joint and loss.

Reference: ``reference:apex/contrib/csrc/transducer/transducer_joint_kernel.cu``
(f ⊕ g broadcast-add with optional fused ReLU + dropout, :979 LoC) and
``transducer_loss_kernel.cu`` (alpha/beta forward-backward recursion + fused
log-softmax backward, :767 LoC), host semantics pinned by
``reference:apex/contrib/test/transducer/transducer_ref.py``.

TPU redesign:

- **Joint**: the broadcast add + ReLU (+ dropout) is one fused XLA
  elementwise program — the CUDA kernel's whole purpose (avoiding 3 HBM
  round trips) is an XLA fusion built-in. The reference's ``pack_output``
  variant exists to skip padded (t, u) cells in HBM; under XLA's static
  shapes the padded layout IS the native form, so packing is intentionally
  not reproduced — mask the loss instead (``loss_mask`` helper).
- **Loss**: the alpha/beta dynamic program runs as a ``lax.scan`` over time
  with each row's in-row dependency solved by ``lax.associative_scan`` in
  the log semiring — the recurrence ``row[u] = LSE(base[u], row[u-1] +
  step[u])`` is a first-order linear recurrence whose transforms compose
  associatively, so the U dimension parallelizes onto the VPU instead of
  running 1-by-1 like the CUDA kernel's per-thread loop. Variable lengths
  are handled by masking *transitions* (-inf) and injecting the terminal
  blank emission ``(f_len-1, y_len)`` as a boundary reward, so one static
  (T, U+1) grid serves the whole batch.
- **Backward** is the analytic alpha+beta gradient of the reference
  (``transducer_ref.py:47-66``) fused with the log-softmax backward, as a
  ``custom_vjp`` — O(B·T·U) memory, no AD through the scans.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["transducer_joint", "transducer_loss", "TransducerJoint",
           "TransducerLoss"]

_NEG = -1e30


def transducer_joint(f: jnp.ndarray, g: jnp.ndarray,
                     f_len: Optional[jnp.ndarray] = None,
                     g_len: Optional[jnp.ndarray] = None,
                     relu: bool = False, dropout_rate: float = 0.0,
                     dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """``h[b,t,u,:] = f[b,t,:] + g[b,u,:]`` with optional fused ReLU and
    dropout (``transducer_joint_kernel.cu``; module `TransducerJoint`).

    ``f``: (B, T, H) encoder; ``g``: (B, U, H) predictor. Returns
    (B, T, U, H). Padded cells (t >= f_len or u >= g_len) are zeroed so
    downstream reductions need no NaN guards (the kernel writes zeros there
    for the same reason)."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
    if f_len is not None:
        t_ok = jnp.arange(h.shape[1])[None, :, None, None] < \
            f_len[:, None, None, None]
        h = jnp.where(t_ok, h, 0.0)
    if g_len is not None:
        u_ok = jnp.arange(h.shape[2])[None, None, :, None] < \
            g_len[:, None, None, None]
        h = jnp.where(u_ok, h, 0.0)
    return h


def _lse(a, b):
    return jnp.logaddexp(a, b)


def _row_scan(base: jnp.ndarray, step: jnp.ndarray,
              reverse: bool = False) -> jnp.ndarray:
    """Solve ``row[u] = LSE(base[u], row[u +/- 1] + step[u])`` over the last
    axis with an associative scan in the log semiring. ``step[u]`` is the
    cost of entering cell ``u`` from its in-row predecessor."""
    def combine(a, b):
        (ca, da), (cb, db) = a, b
        return _lse(cb, ca + db), da + db

    if reverse:
        base = jnp.flip(base, -1)
        step = jnp.flip(step, -1)
    c, _ = jax.lax.associative_scan(combine, (base, step), axis=-1)
    return jnp.flip(c, -1) if reverse else c


def _prep(x_log, label, f_len, y_len, blank_idx):
    """Masked transition log-probs on the full (T, U+1) grid.

    Returns ``(blank_m, lab_m, term)``: blank transitions valid for
    ``t <= f_len-2``; label transitions valid for ``t <= f_len-1`` and
    ``u <= y_len-1``; ``term`` holds the terminal blank emission at
    ``(f_len-1, y_len)`` and -inf elsewhere."""
    B, T, U1, V = x_log.shape
    x_blank = x_log[..., blank_idx]                     # (B, T, U1)
    lab = jnp.take_along_axis(
        x_log[:, :, :U1 - 1, :],
        label[:, None, :, None].astype(jnp.int32), axis=-1)[..., 0]
    lab = jnp.pad(lab, ((0, 0), (0, 0), (0, 1)), constant_values=_NEG)

    t_idx = jnp.arange(T)[None, :, None]
    u_idx = jnp.arange(U1)[None, None, :]
    fl = f_len[:, None, None]
    yl = y_len[:, None, None]

    blank_m = jnp.where(t_idx <= fl - 2, x_blank, _NEG)
    lab_m = jnp.where((t_idx <= fl - 1) & (u_idx <= yl - 1), lab, _NEG)
    term = jnp.where((t_idx == fl - 1) & (u_idx == yl), x_blank, _NEG)
    return blank_m, lab_m, term


def _forward_alpha(blank_m, lab_m):
    """alpha[t,u] = LSE(alpha[t-1,u] + blank_m[t-1,u],
                        alpha[t,u-1] + lab_m[t,u-1]); alpha[0,0] = 0."""
    B, T, U1 = blank_m.shape
    first_base = jnp.full((B, U1), _NEG).at[:, 0].set(0.0)
    # entering column u from u-1 costs lab_m[t, u-1]
    step = jnp.pad(lab_m[:, :, :-1], ((0, 0), (0, 0), (1, 0)),
                   constant_values=_NEG)

    def row(prev_row, xs):
        blank_prev, step_t = xs           # (B,U1) each
        base = prev_row + blank_prev
        new = _row_scan(base, step_t)
        return new, new

    row0 = _row_scan(first_base, step[:, 0])
    _, rest = jax.lax.scan(
        row, row0,
        (jnp.swapaxes(blank_m[:, :-1], 0, 1), jnp.swapaxes(step[:, 1:], 0, 1)))
    return jnp.concatenate([row0[:, None], jnp.swapaxes(rest, 0, 1)], axis=1)


def _backward_beta(blank_m, lab_m, term):
    """beta[t,u] = LSE(term[t,u], beta[t+1,u] + blank_m[t,u],
                       beta[t,u+1] + lab_m[t,u])."""
    B, T, U1 = blank_m.shape
    # entering column u from u+1 (reverse scan) costs lab_m[t, u] — no
    # shift, unlike the forward direction
    def row(next_row, xs):
        blank_t, lab_t, term_t = xs
        base = _lse(term_t, next_row + blank_t)
        new = _row_scan(base, lab_t, reverse=True)
        return new, new

    last_base = term[:, T - 1]
    rowT = _row_scan(last_base, lab_m[:, T - 1], reverse=True)
    _, rest = jax.lax.scan(
        row, rowT,
        (jnp.swapaxes(blank_m[:, :-1], 0, 1),
         jnp.swapaxes(lab_m[:, :-1], 0, 1),
         jnp.swapaxes(term[:, :-1], 0, 1)),
        reverse=True)
    return jnp.concatenate([jnp.swapaxes(rest, 0, 1), rowT[:, None]], axis=1)


def _alpha_beta(x, label, f_len, y_len, blank_idx):
    x_log = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    blank_m, lab_m, term = _prep(x_log, label, f_len, y_len, blank_idx)
    alpha = _forward_alpha(blank_m, lab_m)
    beta = _backward_beta(blank_m, lab_m, term)
    return x_log, alpha, beta


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def transducer_loss(x: jnp.ndarray, label: jnp.ndarray, f_len: jnp.ndarray,
                    y_len: jnp.ndarray, blank_idx: int = 0) -> jnp.ndarray:
    """Per-sequence RNN-T negative log-likelihood, shape (B,).

    ``x``: (B, T, U+1, V) joint logits (NOT log-softmaxed — the log-softmax
    is fused, ``TransducerLoss(fuse_softmax_backward=True)``); ``label``:
    (B, U) int targets; ``f_len``/``y_len``: per-sequence valid lengths.
    """
    _, _, beta = _alpha_beta(x, label, f_len, y_len, blank_idx)
    return -beta[:, 0, 0].astype(x.dtype)


def _loss_fwd(x, label, f_len, y_len, blank_idx):
    x_log, alpha, beta = _alpha_beta(x, label, f_len, y_len, blank_idx)
    return -beta[:, 0, 0].astype(x.dtype), (x_log, alpha, beta, label,
                                            f_len, y_len)


def _loss_bwd(blank_idx, res, loss_grad):
    """Analytic gradient (``transducer_ref.py:47-66``) fused with the
    log-softmax backward (``fuse_softmax_backward``)."""
    x_log, alpha, beta, label, f_len, y_len = res
    B, T, U1, V = x_log.shape
    ll = beta[:, 0, 0]
    # d(-log p)/dx_log common factor; loss_grad folds in the upstream grad
    common = alpha - ll[:, None, None]                      # (B, T, U1)

    t_idx = jnp.arange(T)[None, :, None]
    u_idx = jnp.arange(U1)[None, None, :]
    fl = f_len[:, None, None]
    yl = y_len[:, None, None]

    x_blank = x_log[..., blank_idx]
    lab = jnp.take_along_axis(
        x_log[:, :, :U1 - 1, :],
        label[:, None, :, None].astype(jnp.int32), axis=-1)[..., 0]

    # label transitions: valid t < f_len, u < y_len
    beta_next_u = jnp.pad(beta[:, :, 1:], ((0, 0), (0, 0), (0, 1)),
                          constant_values=_NEG)
    g_lab = -jnp.exp(common[:, :, :U1 - 1] + beta_next_u[:, :, :U1 - 1]
                     + lab)
    g_lab = jnp.where((t_idx <= fl - 1)[:, :, :U1 - 1]
                      & (u_idx[:, :, :U1 - 1] <= yl - 1), g_lab, 0.0)

    # blank transitions: t <= f_len-2, any u <= y_len; plus terminal cell
    beta_next_t = jnp.pad(beta[:, 1:], ((0, 0), (0, 1), (0, 0)),
                          constant_values=_NEG)
    g_blank = -jnp.exp(common + beta_next_t + x_blank)
    g_blank = jnp.where((t_idx <= fl - 2) & (u_idx <= yl), g_blank, 0.0)
    g_term = -jnp.exp(common + x_blank)
    g_term = jnp.where((t_idx == fl - 1) & (u_idx == yl), g_term, 0.0)
    g_blank = g_blank + g_term

    # scatter into the vocab axis
    grad_xlog = jnp.zeros_like(x_log)
    grad_xlog = grad_xlog.at[..., blank_idx].add(g_blank)
    lab_scatter = jnp.zeros_like(x_log[:, :, :U1 - 1, :]).at[
        jnp.arange(B)[:, None, None],
        jnp.arange(T)[None, :, None],
        jnp.arange(U1 - 1)[None, None, :],
        label[:, None, :].astype(jnp.int32)].add(g_lab)
    grad_xlog = grad_xlog.at[:, :, :U1 - 1, :].add(lab_scatter)

    grad_xlog = grad_xlog * loss_grad[:, None, None, None].astype(
        grad_xlog.dtype)
    # log-softmax backward: dx = g - softmax(x) * sum_v g
    gsum = jnp.sum(grad_xlog, axis=-1, keepdims=True)
    dx = (grad_xlog - jnp.exp(x_log) * gsum).astype(jnp.result_type(x_log))
    return (dx, None, None, None)


transducer_loss.defvjp(_loss_fwd, _loss_bwd)


class TransducerJoint:
    """Module-shaped wrapper (``reference:apex/contrib/transducer/
    transducer.py:5-66``); ``pack_output`` is intentionally unsupported
    (see module docstring)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "pack_output=True is a GPU memory-layout optimization; on "
                "TPU keep the padded layout and mask the loss")
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, dropout_rng=None):
        rate = self.dropout_prob if self.dropout else 0.0
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_rate=rate, dropout_rng=dropout_rng)


class TransducerLoss:
    """Module-shaped wrapper (``transducer.py:68-125``); the fused
    log-softmax backward is always on (the unfused variant exists in the
    reference only as a fallback)."""

    def __init__(self, packed_input: bool = False):
        if packed_input:
            raise NotImplementedError(
                "packed_input=True is a GPU memory-layout optimization; "
                "feed the padded (B, T, U+1, V) joint output")

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
