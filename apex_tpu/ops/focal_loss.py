"""Fused sigmoid focal loss (detection).

Reference: ``reference:apex/contrib/focal_loss/focal_loss.py`` over
``reference:apex/contrib/csrc/focal_loss/focal_loss_cuda_kernel.cu:30-110``.
Target encoding per anchor: ``-2`` = ignore (zero loss/grad), ``-1`` = all
classes are negatives, ``y >= 0`` = class ``y`` positive, rest negative.
Element math (kernel :74-101): with ``sigma = sigmoid(logit)`` and
``softplus(-x) = log(1+exp(-x))`` —

  negative: ``(1-alpha) * sigma**gamma     * (nn*x + softplus(-x))``
  positive: ``alpha     * (1-sigma)**gamma * (pn*x + softplus(-x))``

where without smoothing ``nn=1, pn=0`` (i.e. ``-log(1-sigma)`` and
``-log(sigma)``), and label smoothing ``s`` sets ``nn=1-s/K``, ``pn=s-s/K``.
The sum is normalized by ``num_positives_sum``; classes at index
``>= num_real_classes`` (padding for vectorization) are skipped. All math is
fp32; AD provides the backward (the reference caches ``partial_grad`` only to
avoid re-reading logits — XLA rematerializes the same expression for free).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "FocalLoss"]


def focal_loss(cls_output: jnp.ndarray, cls_targets: jnp.ndarray,
               num_positives_sum: jnp.ndarray, num_real_classes: int,
               alpha: float, gamma: float,
               label_smoothing: float = 0.0) -> jnp.ndarray:
    """Scalar total loss. ``cls_output``: ``(..., K)`` logits;
    ``cls_targets``: ``(...,)`` int labels in {-2, -1, 0..K-1}."""
    x = cls_output.astype(jnp.float32)
    k = x.shape[-1]
    y = cls_targets[..., None]

    if label_smoothing > 0.0:
        s = label_smoothing
        nn, np_ = 1.0 - s / k, s / k
        pn, pp = s - s / k, 1.0 - s + s / k
    else:
        nn, np_, pn, pp = 1.0, 0.0, 0.0, 1.0
    del np_, pp  # forward only needs nn/pn; off_b terms belong to the grad

    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    is_pos = (y >= 0) & (col == y)
    valid = (y != -2) & (col < num_real_classes)

    sigma = jax.nn.sigmoid(x)
    off_a = jax.nn.softplus(-x)
    loss_neg = (1.0 - alpha) * jnp.power(sigma, gamma) * (nn * x + off_a)
    loss_pos = alpha * jnp.power(1.0 - sigma, gamma) * (pn * x + off_a)
    elem = jnp.where(is_pos, loss_pos, loss_neg)
    elem = jnp.where(valid, elem, 0.0)
    return jnp.sum(elem) / num_positives_sum.astype(jnp.float32).reshape(())


class FocalLoss:
    """Autograd-Function-style alias for ported call sites."""

    @staticmethod
    def apply(cls_output, cls_targets_at_level, num_positives_sum,
              num_real_classes, alpha, gamma, label_smoothing=0.0):
        return focal_loss(cls_output, cls_targets_at_level, num_positives_sum,
                          num_real_classes, alpha, gamma, label_smoothing)
