"""Inverted dropout (the torch convention the reference models use).

Reference call sites: hidden/embedding dropout in
``reference:apex/transformer/testing/standalone_gpt.py`` (bias_dropout_add,
embedding dropout) and the fused attention-probability dropout in
``reference:apex/contrib/csrc/multihead_attn/dropout.cuh:272`` — the latter
lives inside :func:`apex_tpu.ops.flash_attention.flash_attention`
(``dropout_rate``/``dropout_seed``), not here.

Scaling at train time (``x/(1-rate)``), identity at eval, matching
``torch.nn.functional.dropout``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["dropout"]


def dropout(x: jnp.ndarray, rate: float, key: Optional[jax.Array],
            deterministic: bool = False) -> jnp.ndarray:
    """Inverted dropout; no-op when ``rate == 0``, ``deterministic``, or
    ``key is None`` (eval mode)."""
    if rate == 0.0 or deterministic or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, jnp.shape(x))
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
