"""Fused MLP / dense layers.

Reference: ``reference:apex/mlp/mlp.py:8-79`` (whole-MLP fused fwd/bwd over
``csrc/mlp_cuda.cu`` cuBLAS GEMMs + fused bias/activation kernels) and
``reference:apex/fused_dense/fused_dense.py:53-86`` (cuBLASLt epilogue GEMMs:
linear+bias, linear+bias+GELU+linear+bias).

On TPU every GEMM+bias+activation chain is one XLA fusion feeding the MXU —
the hand-fused kernels' entire purpose is already met by the compiler, so
these are thin functional modules that (a) keep the reference API surface,
(b) pin ``preferred_element_type=float32`` so bf16 inputs accumulate in fp32
on the MXU like the CUDA kernels accumulate in fp32, and (c) initialize
exactly like the reference (uniform ±1/sqrt(fan_in), ``mlp.py:41-46``).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["MLP", "FusedDense", "FusedDenseGeluDense", "mlp_forward",
           "fused_dense", "fused_dense_gelu_dense"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def _dense(x, w, b):
    # w stored (out, in) like torch; MXU matmul with fp32 accumulation
    y = jax.lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y


def mlp_forward(params: Sequence[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
                x: jnp.ndarray, activation: str = "relu") -> jnp.ndarray:
    """Chain of (weight, bias) pairs with ``activation`` between layers and
    after the last layer — matching ``MlpFunction``'s behavior of applying
    the activation to every layer output (``reference:csrc/mlp_cuda.cu:437-659``)."""
    act = _ACTIVATIONS[activation]
    y = x
    for w, b in params:
        y = act(_dense(y, w, b)).astype(x.dtype)
    return y


class MLP:
    """``apex.mlp.MLP(mlp_sizes, bias=True, relu=True, activation='relu')``
    (``reference:apex/mlp/mlp.py:26-79``)."""

    def __init__(self, mlp_sizes: Sequence[int], bias: bool = True,
                 activation: str = "relu", param_dtype=jnp.float32):
        if len(mlp_sizes) < 2:
            raise ValueError("mlp_sizes must have at least 2 entries")
        if activation not in _ACTIVATIONS:
            raise ValueError(f"activation must be one of {list(_ACTIVATIONS)}")
        self.mlp_sizes = tuple(int(s) for s in mlp_sizes)
        self.bias = bias
        self.activation = activation
        self.param_dtype = param_dtype

    def init(self, key: jax.Array) -> list:
        """Uniform ±1/sqrt(fan_in) for weights and biases
        (``reference:apex/mlp/mlp.py:41-46`` reset_parameters)."""
        params = []
        for i in range(len(self.mlp_sizes) - 1):
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            key, wk, bk = jax.random.split(key, 3)
            bound = 1.0 / math.sqrt(fan_in)
            w = jax.random.uniform(wk, (fan_out, fan_in), self.param_dtype,
                                   -bound, bound)
            b = (jax.random.uniform(bk, (fan_out,), self.param_dtype,
                                    -bound, bound) if self.bias else None)
            params.append((w, b))
        return params

    def __call__(self, params, x):
        return mlp_forward(params, x, self.activation)


def fused_dense(x, weight, bias):
    """``fused_dense_cuda.linear_bias_forward`` — GEMM + bias epilogue."""
    return _dense(x, weight, bias).astype(x.dtype)


def fused_dense_gelu_dense(x, w1, b1, w2, b2):
    """``fused_dense_cuda.linear_gelu_linear_forward``: GEMM+bias+GELU+GEMM+bias
    in one fusion (tanh GELU, matching cuBLASLt's CUBLASLT_EPILOGUE_GELU)."""
    h = jax.nn.gelu(_dense(x, w1, b1), approximate=True)
    return _dense(h.astype(x.dtype), w2, b2).astype(x.dtype)


class FusedDense:
    """``reference:apex/fused_dense/fused_dense.py:53-67``."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 param_dtype=jnp.float32):
        self.in_features, self.out_features = in_features, out_features
        self.bias = bias
        self.param_dtype = param_dtype

    def init(self, key: jax.Array) -> dict:
        bound = 1.0 / math.sqrt(self.in_features)
        key, wk, bk = jax.random.split(key, 3)
        p = {"weight": jax.random.uniform(
            wk, (self.out_features, self.in_features), self.param_dtype,
            -bound, bound)}
        if self.bias:
            p["bias"] = jax.random.uniform(bk, (self.out_features,),
                                           self.param_dtype, -bound, bound)
        return p

    def __call__(self, params, x):
        return fused_dense(x, params["weight"], params.get("bias"))


class FusedDenseGeluDense:
    """``reference:apex/fused_dense/fused_dense.py:71-86``."""

    def __init__(self, in_features: int, intermediate_features: int,
                 out_features: int, bias: bool = True, param_dtype=jnp.float32):
        if not bias:
            raise ValueError("FusedDenseGeluDense requires bias=True "
                             "(as in the reference)")
        self.d1 = FusedDense(in_features, intermediate_features, True, param_dtype)
        self.d2 = FusedDense(intermediate_features, out_features, True, param_dtype)

    def init(self, key: jax.Array) -> dict:
        k1, k2 = jax.random.split(key)
        return {"dense1": self.d1.init(k1), "dense2": self.d2.init(k2)}

    def __call__(self, params, x):
        return fused_dense_gelu_dense(
            x, params["dense1"]["weight"], params["dense1"]["bias"],
            params["dense2"]["weight"], params["dense2"]["bias"])
