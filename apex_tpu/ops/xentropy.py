"""Fused softmax cross-entropy with label smoothing.

Reference: ``reference:apex/contrib/xentropy/softmax_xentropy.py:4-28`` over
``reference:apex/contrib/csrc/xentropy/xentropy_kernel.cu`` — the fusion's
point is *memory*: forward saves only ``max_log_sum_exp`` per row instead of
the full softmax, and backward recomputes probabilities from logits + that
scalar. Loss math (kernel :424-429): with smoothing ``s``,
``loss = logsumexp - (1-s)*logit[target] - s*mean(logits)``; backward
(:441-473): ``grad = softmax - ((1-s)*onehot + s/classes)``, zeroed where
``label == padding_idx``.

The TPU version keeps the same save-one-scalar structure via ``custom_vjp``
(XLA would otherwise stash the softmax for backward), so activation memory is
O(rows) not O(rows*classes) — same win as the CUDA kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy_loss", "SoftmaxCrossEntropyLoss"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent(logits, labels, smoothing, padding_idx):
    losses, _ = _xent_fwd_math(logits, labels, smoothing, padding_idx)
    return losses


def _xent_fwd_math(logits, labels, smoothing, padding_idx):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1, keepdims=True)
    sumexp = jnp.sum(jnp.exp(lf - m), axis=-1)
    mlse = m[..., 0] + jnp.log(sumexp)  # max_log_sum_exp, the saved scalar
    picked = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    if smoothing == 0.0:
        losses = mlse - picked
    else:
        mean_logits = jnp.mean(lf, axis=-1)
        losses = mlse - (1.0 - smoothing) * picked - smoothing * mean_logits
    if padding_idx is not None:
        losses = jnp.where(labels == padding_idx, 0.0, losses)
    return losses, mlse


def _xent_vjp_fwd(logits, labels, smoothing, padding_idx):
    losses, mlse = _xent_fwd_math(logits, labels, smoothing, padding_idx)
    return losses, (logits, labels, mlse)


def _xent_vjp_bwd(smoothing, padding_idx, res, g):
    logits, labels, mlse = res
    lf = logits.astype(jnp.float32)
    probs = jnp.exp(lf - mlse[..., None])  # recomputed, not saved
    n_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)
    target = (1.0 - smoothing) * onehot + smoothing / n_classes
    gg = g
    if padding_idx is not None:
        gg = jnp.where(labels == padding_idx, 0.0, g)
    grad = (probs - target) * gg[..., None]
    return grad.astype(logits.dtype), None


_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


def softmax_cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                               smoothing: float = 0.0,
                               padding_idx: Optional[int] = 0,
                               half_to_float: bool = False) -> jnp.ndarray:
    """Per-row losses, shape ``labels.shape``. ``half_to_float`` returns fp32
    losses from half logits (they are fp32 internally either way), matching
    the reference flag."""
    losses = _xent(logits, labels, float(smoothing), padding_idx)
    return losses if half_to_float else losses.astype(logits.dtype)


# Class-style alias matching `SoftmaxCrossEntropyLoss.apply(...)` call sites.
class SoftmaxCrossEntropyLoss:
    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)
