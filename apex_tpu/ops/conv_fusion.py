"""Fused conv + bias (+ ReLU / mask / frozen scale-bias) ops.

Reference: ``reference:apex/contrib/conv_bias_relu/`` over the
cudnn-frontend graph extension (``apex/contrib/csrc/conv_bias_relu/``,
1,639 LoC): ``ConvBiasReLU``, ``ConvBias``, ``ConvBiasMaskReLU``,
``ConvFrozenScaleBiasReLU``.

On TPU these are *definitionally* fused — XLA folds bias/scale/ReLU/mask
elementwise epilogues into the convolution's output fusion — so each
function below is the semantic spec (NHWC, torch-compatible padding/stride)
and the fusion is the compiler's. They exist as named entry points for API
parity and so the parity tests pin the numerics against torch.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["conv_bias", "conv_bias_relu", "conv_bias_mask_relu",
           "conv_frozen_scale_bias_relu"]


def _conv2d_nhwc(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride),
        [(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv_bias(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
              stride: int = 1, padding: int = 0) -> jnp.ndarray:
    """``ConvBias``: NHWC conv + per-channel bias."""
    return _conv2d_nhwc(x, weight, stride, padding) + bias.astype(x.dtype)


def conv_bias_relu(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
                   stride: int = 1, padding: int = 0) -> jnp.ndarray:
    """``ConvBiasReLU``: conv + bias + ReLU in one fusion."""
    return jax.nn.relu(conv_bias(x, weight, bias, stride, padding))


def conv_bias_mask_relu(x: jnp.ndarray, weight: jnp.ndarray,
                        bias: jnp.ndarray, mask: jnp.ndarray,
                        stride: int = 1, padding: int = 0) -> jnp.ndarray:
    """``ConvBiasMaskReLU``: conv + bias, elementwise mask, then ReLU."""
    return jax.nn.relu(conv_bias(x, weight, bias, stride, padding)
                       * mask.astype(x.dtype))


def conv_frozen_scale_bias_relu(x: jnp.ndarray, weight: jnp.ndarray,
                                scale: jnp.ndarray, bias: jnp.ndarray,
                                stride: int = 1, padding: int = 0
                                ) -> jnp.ndarray:
    """``ConvFrozenScaleBiasReLU``: conv, then frozen-BN affine (per-channel
    scale + bias), then ReLU — inference-mode folded batchnorm."""
    out = _conv2d_nhwc(x, weight, stride, padding)
    return jax.nn.relu(out * scale.astype(x.dtype) + bias.astype(x.dtype))
