"""Flash attention — Pallas TPU kernel family.

TPU replacement for the reference's two fused-attention stacks:
``reference:apex/contrib/csrc/fmha/`` (FlashAttention-style fixed-seqlen
kernels, fp16, seqlen<=512) and
``reference:apex/contrib/csrc/multihead_attn/`` (fused QKV/softmax/AV with
mask + optional residual+LN epilogues, seqlen<=2048 via the Megatron softmax).
One blockwise-online-softmax kernel subsumes both with no seqlen cap: scores
never materialize in HBM, so memory is O(sq·d) instead of O(sq·sk).

Forward: grid ``(b*h, sq/block_q, sk/block_k)`` with the kv dimension
innermost; running ``(m, l, acc)`` live in VMEM scratch across kv steps
(TPU grid execution is sequential per core, the canonical Pallas flash
pattern). Backward recomputes probabilities from the saved per-row logsumexp
(same recompute-not-store trade as the CUDA dgrad kernels) in two kernels:
one gridded over q blocks (dq), one over kv blocks (dk, dv).

``bias`` is an additive score bias (the general form of the reference's
padding masks — additive -10000 fills, ``scaled_masked_softmax.h``) and is
non-differentiable, as in the reference. Dropout inside the kernel (the
``philox.cuh`` path of fast_multihead_attn) is not implemented yet; apply
dropout to the output, or pass pre-masked bias for deterministic ablation.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "mha_reference", "supports_flash"]

NEG_INF = -1e30


def supports_flash(sq: int, sk: int, d: int, block_q: int, block_k: int) -> bool:
    """Eligibility for the Pallas path (cf. the reference's per-kernel seqlen
    gates, ``fused_softmax.py:159-179`` / ``setup.py:544-560`` — here the gate
    is only tile alignment, not a seqlen cap)."""
    return (sq % block_q == 0 and sk % block_k == 0 and d % 8 == 0
            and block_q % 8 == 0 and block_k % 128 == 0)


def mha_reference(q, k, v, bias=None, causal=False,
                  softmax_scale: Optional[float] = None):
    """Plain-XLA attention; the parity reference for the kernel (the role of
    the Python attention in ``reference:apex/contrib/test/fmha/test_fmha.py``)."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * softmax_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row + (sk - sq), NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                n_kv, offset):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip blocks entirely above the diagonal (with the sk-sq
    # offset so cross-shaped causal matches mha_reference)
    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row + offset, NEG_INF, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(j == n_kv - 1)
    def _():
        l = l_ref[:]
        # fully-masked rows (l==0) produce 0 output, not NaN
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(safe_l)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k, n_kv, offset):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row + offset, NEG_INF, s)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == n_kv - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, n_q, offset):
    j, i = pl.program_id(1), pl.program_id(2)  # kv outer, q inner

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row + offset, NEG_INF, s)
        p = jnp.exp(s - lse_ref[0])
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _interp() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_pallas(q3, k3, v3, bias3, *, scale, causal, block_q, block_k):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    n_q, n_kv = sq // block_q, sk // block_k
    has_bias = bias3 is not None

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, block_q, block_k),
                                     lambda b, i, j: (b, i, j),
                                     memory_space=pltpu.VMEM))
        args.append(bias3)

    def kernel(*refs):
        if has_bias:
            q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, acc, m, l = refs
        else:
            q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l = refs
            bias_ref = None
        _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, acc, m, l,
                    scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, n_kv=n_kv, offset=sk - sq)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_kv),
        in_specs=in_specs,
        out_specs=(q_spec,
                   pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
                   jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=_interp(),
    )(*args)
    return out, lse


def _bwd_pallas(q3, k3, v3, bias3, do3, lse, delta, *, scale, causal,
                block_q, block_k):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    n_q, n_kv = sq // block_q, sk // block_k
    has_bias = bias3 is not None

    # --- dq: grid (bh, n_q, n_kv), kv innermost ---
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, block_q, block_k),
                                     lambda b, i, j: (b, i, j),
                                     memory_space=pltpu.VMEM))
        args.append(bias3)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do3, lse, delta]

    def dq_kernel(*refs):
        if has_bias:
            (q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
             dq_ref, dq_acc) = refs
        else:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
             dq_ref, dq_acc) = refs
            bias_ref = None
        _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                       delta_ref, dq_ref, dq_acc, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k, n_kv=n_kv, offset=sk - sq)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, n_q, n_kv),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interp(),
    )(*args)

    # --- dk/dv: grid (bh, n_kv, n_q), q innermost ---
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                            memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    in_specs2 = [q_spec2, kv_spec2, kv_spec2]
    args2 = [q3, k3, v3]
    if has_bias:
        in_specs2.append(pl.BlockSpec((1, block_q, block_k),
                                      lambda b, j, i: (b, i, j),
                                      memory_space=pltpu.VMEM))
        args2.append(bias3)
    in_specs2 += [q_spec2, row_spec2, row_spec2]
    args2 += [do3, lse, delta]

    def dkv_kernel(*refs):
        if has_bias:
            (q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
        else:
            (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
             dk_ref, dv_ref, dk_acc, dv_acc) = refs
            bias_ref = None
        _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                        delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                        scale=scale, causal=causal, block_q=block_q,
                        block_k=block_k, n_q=n_q, offset=sk - sq)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, n_kv, n_q),
        in_specs=in_specs2,
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(jax.ShapeDtypeStruct((bh, sk, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v3.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interp(),
    )(*args2)
    return dq, dk, dv


@functools.lru_cache(maxsize=None)
def _make_flash(scale: float, causal: bool, block_q: int, block_k: int,
                has_bias: bool):
    @jax.custom_vjp
    def flash(q3, k3, v3, bias3):
        out, _ = _fwd_pallas(q3, k3, v3, bias3 if has_bias else None,
                             scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k)
        return out

    def fwd(q3, k3, v3, bias3):
        out, lse = _fwd_pallas(q3, k3, v3, bias3 if has_bias else None,
                               scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
        return out, (q3, k3, v3, bias3, out, lse)

    def bwd(res, do3):
        q3, k3, v3, bias3, out, lse = res
        delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq, dk, dv = _bwd_pallas(q3, k3, v3, bias3 if has_bias else None,
                                 do3, lse, delta, scale=scale, causal=causal,
                                 block_q=block_q, block_k=block_k)
        dbias = jnp.zeros_like(bias3) if has_bias else None
        return dq, dk, dv, dbias

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    softmax_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    use_pallas: Optional[bool] = None):
    """Fused attention over ``(b, h, s, d)`` tensors.

    ``bias``: additive fp32 score bias broadcastable to ``(b, h, sq, sk)``
    (use ``-10000``-filled masks for padding, as the reference softmax does).
    Falls back to the XLA reference when shapes aren't tile-aligned.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(d)
    if use_pallas is None:
        use_pallas = supports_flash(sq, sk, d, block_q, block_k)
    if not use_pallas:
        return mha_reference(q, k, v, bias, causal, softmax_scale)

    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    has_bias = bias is not None
    if has_bias:
        bias3 = jnp.broadcast_to(bias.astype(jnp.float32),
                                 (b, h, sq, sk)).reshape(b * h, sq, sk)
    else:
        bias3 = jnp.zeros((), jnp.float32)  # placeholder pytree leaf
    fn = _make_flash(float(softmax_scale), bool(causal), block_q, block_k,
                     has_bias)
    out = fn(q3, k3, v3, bias3)
    return out.reshape(b, h, sq, d)
