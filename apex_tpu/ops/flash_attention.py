"""Flash attention — Pallas TPU kernel family.

TPU replacement for the reference's two fused-attention stacks:
``reference:apex/contrib/csrc/fmha/`` (FlashAttention-style fixed-seqlen
kernels, fp16, seqlen<=512) and
``reference:apex/contrib/csrc/multihead_attn/`` (fused QKV/softmax/AV with
mask + optional residual+LN epilogues, seqlen<=2048 via the Megatron softmax).
One blockwise-online-softmax kernel subsumes both with no seqlen cap: scores
never materialize in HBM, so memory is O(sq·d) instead of O(sq·sk).

Forward: grid ``(b*h, sq/block_q, sk/block_k)`` with the kv dimension
innermost; running ``(m, l, acc)`` live in VMEM scratch across kv steps
(TPU grid execution is sequential per core, the canonical Pallas flash
pattern). Backward recomputes probabilities from the saved per-row logsumexp
(same recompute-not-store trade as the CUDA dgrad kernels) in two kernels:
one gridded over q blocks (dq), one over kv blocks (dk, dv). Rows that are
fully masked out save ``lse = +inf`` so the backward's
``p = exp(s - lse)`` underflows to exactly zero instead of producing
``exp(-inf - -inf) = 1`` garbage (ADVICE r1).

``bias`` is an additive score bias (the general form of the reference's
padding masks — additive -10000 fills, ``scaled_masked_softmax.h``). It is
kept in its broadcastable shape end to end: broadcast dims map to block
index 0 in the BlockSpec and broadcasting happens in VMEM, so a padding
mask ``(b, 1, 1, sk)`` costs O(b·sk) HBM, not O(b·h·sq·sk).

``bias`` gradients: **zero by default** — differentiating through ``bias``
without passing ``bias_requires_grad=True`` silently yields zeros (the
padding-mask case, where a gradient is meaningless). For *learned* biases
(ALiBi slopes, relative-position tables) pass ``bias_requires_grad=True``:
a dedicated kernel recomputes the score cotangent ds blockwise and
accumulates its sum over the broadcast dims directly into a bias-shaped
output — dbias costs O(|bias|) HBM, never the full score matrix.

Dropout runs *inside* the kernel (the ``philox.cuh`` path of
fast_multihead_attn / ``dropout.cuh:272``): a counter-based hash RNG keyed
on ``(seed, batch·head, global row, global col)`` generates the keep mask
blockwise, so the backward regenerates the identical mask from the same
counters with no mask storage — the Philox design, in backend-portable
uint32 ops (``pltpu.prng_*`` has no CPU interpret path). Masks are applied
to the normalized probabilities (scaled 1/(1-rate)); the softmax normalizer
uses the undropped probabilities, matching the reference kernels.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "mha_reference", "supports_flash",
           "dropout_keep_mask", "decode_attention", "supports_paged",
           "paged_decode_attention"]

NEG_INF = -1e30

# murmur3 finalizer constants — numpy scalars embed as immediates in the
# kernel jaxpr (jnp scalars would be captured consts, which Pallas rejects)
_MIX1 = np.uint32(0x85EBCA6B)
_MIX2 = np.uint32(0xC2B2AE35)
_GOLD = np.uint32(0x9E3779B1)


def _mix32(x):
    x = x ^ (x >> 16)
    x = x * _MIX1
    x = x ^ (x >> 13)
    x = x * _MIX2
    return x ^ (x >> 16)


def _pack_seed(dropout_seed) -> jnp.ndarray:
    """Full 32-bit seed as two fp32-exact 16-bit halves ``[hi, lo]`` —
    fp32 is the SMEM/custom_vjp-friendly carrier but only represents ints to
    2**24, so the seed rides split (each half < 2**16 is exact)."""
    s = jnp.asarray(dropout_seed).astype(jnp.int32)
    hi = jax.lax.shift_right_logical(s, 16) & 0xFFFF
    lo = s & 0xFFFF
    return jnp.stack([hi, lo]).astype(jnp.float32).reshape(2)


def _unpack_seed(hi_f, lo_f):
    # f32 -> i32 -> u32: Mosaic has no direct float->unsigned cast
    hi = hi_f.astype(jnp.int32)
    lo = lo_f.astype(jnp.int32)
    return (jax.lax.shift_left(hi, 16) | lo).astype(jnp.uint32)


def _keep_mask(seed2, bh, i, j, block_q, block_k, rate):
    """Counter-based dropout keep mask for score block (i, j) of batch-head
    ``bh`` — the ``philox.cuh`` analog. ``seed2`` is the ``(hi, lo)`` fp32
    pair from :func:`_pack_seed`. Depends only on the *global*
    (seed, bh, row, col) coordinates, so every kernel (fwd, dq, dkv, dbias)
    and the host-side test reference regenerate the identical mask."""
    seed = _unpack_seed(seed2[0], seed2[1])
    row = (i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)).astype(jnp.uint32)
    col = (j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)).astype(jnp.uint32)
    h = _mix32(seed ^ _mix32(jnp.asarray(bh).astype(jnp.uint32)))
    # two finalizer rounds over the combined counter (single-round murmur
    # finalizers show detectable structure; a second round is cheap)
    x = _mix32(_mix32(h ^ _mix32(row * _GOLD + col)) + _GOLD)
    # compare in the integer domain (Mosaic has no unsigned->float cast):
    # keep iff the top-24-bit draw >= rate * 2^24
    thresh = np.int32(int(rate * (1 << 24)))
    return (x >> np.uint32(8)).astype(jnp.int32) >= thresh


def dropout_keep_mask(seed, b, h, sq, sk, rate):
    """Host/XLA version of the in-kernel dropout mask (for parity tests and
    the non-Pallas fallback): (b, h, sq, sk) boolean keep mask identical to
    what the kernels generate for ``seed``."""
    seed2 = _pack_seed(seed)
    bh_ids = jnp.arange(b * h, dtype=jnp.int32)
    masks = jax.vmap(
        lambda bh: _keep_mask((seed2[0], seed2[1]), bh, 0, 0, sq, sk, rate))(
            bh_ids)
    return masks.reshape(b, h, sq, sk)


def supports_flash(sq: int, sk: int, d: int, block_q: int, block_k: int) -> bool:
    """Eligibility for the Pallas path (cf. the reference's per-kernel seqlen
    gates, ``fused_softmax.py:159-179`` / ``setup.py:544-560`` — here the gate
    is only tile alignment, not a seqlen cap).

    Decode shapes (``sq == 1`` against a cached ``sk``) are eligible too:
    a single query row rides one padded sublane tile (``block_q == 1``), so
    only the key-side tiling gates. Callers historically assumed
    ``sq == sk`` — the KV-cached decode path is the second caller family.
    """
    if sq == 1:
        return (sk % block_k == 0 and d % 8 == 0 and block_k % 128 == 0
                and block_q == 1)
    return (sq % block_q == 0 and sk % block_k == 0 and d % 8 == 0
            and block_q % 8 == 0 and block_k % 128 == 0)


def _norm_segment_ids(segment_ids, sq, sk):
    """Accept ``ids (b, s)`` (self-attention) or ``(q_ids, kv_ids)``."""
    if isinstance(segment_ids, (tuple, list)):
        q_ids, kv_ids = segment_ids
    else:
        if sq != sk:
            raise ValueError(
                "cross-attention needs segment_ids=(q_ids, kv_ids)")
        q_ids = kv_ids = segment_ids
    q_ids = jnp.asarray(q_ids)
    kv_ids = jnp.asarray(kv_ids)
    if q_ids.shape[-1] != sq or kv_ids.shape[-1] != sk:
        raise ValueError(
            f"segment id lengths {q_ids.shape[-1]}/{kv_ids.shape[-1]} do "
            f"not match sequence lengths {sq}/{sk}")
    return q_ids, kv_ids


def mha_reference(q, k, v, bias=None, causal=False,
                  softmax_scale: Optional[float] = None,
                  dropout_rate: float = 0.0, dropout_seed=None,
                  segment_ids=None, kv_length=None):
    """Plain-XLA attention; the parity reference for the kernel (the role of
    the Python attention in ``reference:apex/contrib/test/fmha/test_fmha.py``).
    With ``dropout_rate > 0`` it applies the *same* counter-based mask as the
    Pallas kernels, so fallback and kernel paths agree bitwise in expectation
    and exactly for a given seed.

    ``kv_length``: the KV-cache oracle path — an int array ``(b,)`` giving
    the number of VALID cache entries per batch row; key positions at or
    beyond it are masked out (the ground truth for
    :func:`decode_attention`, whose ``k``/``v`` are preallocated
    ``max_len`` caches carrying garbage past the write cursor). Rows with
    length 0 produce an exactly-zero output, matching the kernel."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * softmax_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if kv_length is not None:
        lengths = jnp.asarray(kv_length).astype(jnp.int32)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, k.shape[2]), 3)
        s = jnp.where(col < lengths[:, None, None, None], s, NEG_INF)
    if segment_ids is not None:
        q_ids, kv_ids = _norm_segment_ids(segment_ids, q.shape[2], k.shape[2])
        s = jnp.where((q_ids[:, None, :, None] == kv_ids[:, None, None, :]),
                      s, NEG_INF)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col > row + (sk - sq), NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.max(s, axis=-1, keepdims=True) <= NEG_INF, 0.0, p)
    if dropout_rate > 0.0:
        b, h, sq, sk = p.shape
        keep = dropout_keep_mask(dropout_seed, b, h, sq, sk, dropout_rate)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _seg_mask(q_seg_ref, kv_seg_ref):
    """(block_q, block_k) keep-mask from packed-sequence segment ids — the
    TPU-native form of the reference's varlen ``cu_seqlens`` packing
    (``reference:apex/contrib/csrc/fmha/fmha_api.cpp:420``): tokens attend
    only within their own segment."""
    q_seg = q_seg_ref[0, 0]        # (block_q,)
    kv_seg = kv_seg_ref[0, 0]      # (block_k,)
    return q_seg[:, None] == kv_seg[None, :]


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, q_seg_ref,
                kv_seg_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                n_kv, offset, dropout_rate):
    bh, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip blocks entirely above the diagonal (with the sk-sq
    # offset so cross-shaped causal matches mha_reference)
    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0]  # (1|bq, bk) broadcasts over the block
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row + offset, NEG_INF, s)
        if q_seg_ref is not None:
            smask = _seg_mask(q_seg_ref, kv_seg_ref)
            s = jnp.where(smask, s, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # rows fully masked within a running block have m_new == NEG_INF,
            # so exp(s - m_new) == 1 on masked entries — zero them explicitly
            p = jnp.where(col > row + offset, 0.0, p)
        if q_seg_ref is not None:
            p = jnp.where(smask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        # softmax normalizer uses the UNdropped probabilities
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        if dropout_rate > 0.0:
            keep = _keep_mask((seed_ref[0], seed_ref[1]), bh, i, j,
                              block_q, block_k,
                              dropout_rate)
            p = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(j == n_kv - 1)
    def _():
        l = l_ref[:]
        # fully-masked rows (l==0): 0 output, and lse=+inf so the backward's
        # exp(s - lse) underflows to 0 for every entry of the row
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l == 0.0, jnp.inf,
                               m_ref[:] + jnp.log(safe_l))


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _recompute_p_ds(q_ref, k_ref, v_ref, bias_ref, seed_ref, q_seg_ref,
                    kv_seg_ref, do_ref, lse_ref,
                    delta_ref, bh, i, j, *, scale, causal, block_q, block_k,
                    offset, dropout_rate):
    """Shared backward recompute: p = exp(s - lse) with causal masking
    (including the explicit p-zeroing of masked entries — masked rows of a
    running block have lse = +inf so exp underflows, and causally-masked
    entries are zeroed directly), plus ds = p * (dp_eff - delta).

    With dropout the identical keep mask is regenerated from the counters:
    ``p_eff`` (for dv) is the dropped-and-rescaled probability, and
    ``dp_eff = keep ⊙ dp/(1-rate)`` feeds ds — the exact transpose of the
    forward's dropout-after-normalizer placement.
    """
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if bias_ref is not None:
        s = s + bias_ref[0, 0]  # (1|bq, bk) broadcasts over the block
    if causal:
        row = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(col > row + offset, NEG_INF, s)
    if q_seg_ref is not None:
        # masked s = -1e30 underflows through exp(s - lse) whether lse is
        # finite (row has valid keys) or +inf (fully masked row)
        s = jnp.where(_seg_mask(q_seg_ref, kv_seg_ref), s, NEG_INF)
    p = jnp.exp(s - lse_ref[0])
    if causal:
        p = jnp.where(col > row + offset, 0.0, p)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if dropout_rate > 0.0:
        keep = _keep_mask((seed_ref[0], seed_ref[1]), bh, i, j,
                          block_q, block_k,
                          dropout_rate)
        inv = 1.0 / (1.0 - dropout_rate)
        p_eff = jnp.where(keep, p, 0.0) * inv
        dp_eff = jnp.where(keep, dp, 0.0) * inv
    else:
        p_eff, dp_eff = p, dp
    ds = p * (dp_eff - delta_ref[0])
    return p_eff, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, q_seg_ref,
                   kv_seg_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale, causal, block_q,
                   block_k, n_kv, offset, dropout_rate):
    bh, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, bias_ref, seed_ref,
                                q_seg_ref, kv_seg_ref,
                                do_ref, lse_ref, delta_ref, bh, i, j,
                                scale=scale, causal=causal, block_q=block_q,
                                block_k=block_k, offset=offset,
                                dropout_rate=dropout_rate)
        k = k_ref[0]
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == n_kv - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dbias_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, q_seg_ref,
                  kv_seg_ref, do_ref, lse_ref,
                  delta_ref, db_ref, *, scale, causal, block_q, block_k,
                  swap, offset, dropout_rate, bh_fn):
    """Accumulate dbias = ds summed over the bias's broadcast dims.

    Grid is ``(kept_bh, a, b, r)`` with the reduced bh slices ``r``
    innermost (and, when the bias broadcasts over sq, the q-blocks too via
    ``swap``), so the output tile is revisited on consecutive steps and the
    reduction accumulates in VMEM — dbias costs O(|bias|) HBM, never the
    full (b·h, sq, sk) score matrix."""
    g, a, b_, r = (pl.program_id(n) for n in range(4))
    bh = bh_fn(g, r)  # program_id must be read at kernel top level, not
    # inside a pl.when branch (interpret mode cannot substitute it there)
    if swap:       # bias broadcast over sq: reduce over q-blocks as well
        j, i = a, b_
        first = jnp.logical_and(i == 0, r == 0)
    else:
        i, j = a, b_
        first = r == 0

    @pl.when(first)
    def _():
        db_ref[...] = jnp.zeros_like(db_ref)

    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, bias_ref, seed_ref,
                                q_seg_ref, kv_seg_ref,
                                do_ref, lse_ref, delta_ref, bh,
                                i, j, scale=scale, causal=causal,
                                block_q=block_q, block_k=block_k,
                                offset=offset, dropout_rate=dropout_rate)
        if swap:
            db_ref[0, 0] += jnp.sum(ds, axis=0, keepdims=True)
        else:
            db_ref[0, 0] += ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, q_seg_ref,
                    kv_seg_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    causal, block_q, block_k, n_q, offset, dropout_rate):
    bh = pl.program_id(0)
    j, i = pl.program_id(1), pl.program_id(2)  # kv outer, q inner

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (j * block_k <= i * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _():
        p, ds = _recompute_p_ds(q_ref, k_ref, v_ref, bias_ref, seed_ref,
                                q_seg_ref, kv_seg_ref,
                                do_ref, lse_ref, delta_ref, bh, i, j,
                                scale=scale, causal=causal, block_q=block_q,
                                block_k=block_k, offset=offset,
                                dropout_rate=dropout_rate)
        q, do = q_ref[0], do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _interp() -> bool:
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of ``like`` — a
    pallas_call inside ``shard_map`` (check_vma) must declare how its
    outputs vary; they vary exactly like the q/k/v operands."""
    from apex_tpu.utils.vma import leaf_vma
    vma = leaf_vma(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _bias_spec(bias4, h, block_q, block_k, *, swapped):
    """BlockSpec for the 4D broadcastable bias ``(bb, hb, sqb, sk)`` where
    ``bb``/``hb``/``sqb`` are each 1 or full: broadcast dims map to block 0
    and the kernel broadcasts in VMEM (ADVICE r1: never materialize the
    full (b·h, sq, sk) bias in HBM)."""
    bb, hb, sqb, _ = bias4.shape
    bq = block_q if sqb > 1 else 1

    def imap_fwd(b, i, j):
        return (b // h if bb > 1 else 0, b % h if hb > 1 else 0,
                i if sqb > 1 else 0, j)

    def imap_swapped(b, j, i):
        return (b // h if bb > 1 else 0, b % h if hb > 1 else 0,
                i if sqb > 1 else 0, j)

    return pl.BlockSpec((1, 1, bq, block_k),
                        imap_swapped if swapped else imap_fwd,
                        memory_space=pltpu.VMEM)


def _seed_spec():
    """Dropout seed: a (2,) fp32 ``(hi, lo)`` pair in SMEM (see
    ``_pack_seed``), shared by every block."""
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _seg_specs(h, block_q, block_k, *, swapped):
    """Specs for packed-segment id arrays ``(b, 1, sq)`` / ``(b, 1, sk)``:
    one id row per *batch* (shared across heads), blocked along the
    sequence."""
    def q_map(b, a, c):
        i = c if swapped else a
        return (b // h, 0, i)

    def kv_map(b, a, c):
        j = a if swapped else c
        return (b // h, 0, j)

    return (pl.BlockSpec((1, 1, block_q), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), kv_map, memory_space=pltpu.VMEM))


def _fwd_pallas(q3, k3, v3, bias4, seed, segs, h, *, scale, causal, block_q,
                block_k, dropout_rate):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    n_q, n_kv = sq // block_q, sk // block_k
    has_bias = bias4 is not None
    has_drop = dropout_rate > 0.0
    has_seg = segs is not None

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(_bias_spec(bias4, h, block_q, block_k, swapped=False))
        args.append(bias4)
    if has_drop:
        in_specs.append(_seed_spec())
        args.append(seed)
    if has_seg:
        sq_spec, sk_spec = _seg_specs(h, block_q, block_k, swapped=False)
        in_specs += [sq_spec, sk_spec]
        args += list(segs)

    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref = refs[:3]
        nxt = 3
        bias_ref = refs[nxt] if has_bias else None
        nxt += has_bias
        seed_ref = refs[nxt] if has_drop else None
        nxt += has_drop
        qs_ref = refs[nxt] if has_seg else None
        ks_ref = refs[nxt + 1] if has_seg else None
        nxt += 2 * has_seg
        o_ref, lse_ref, acc, m, l = refs[nxt:]
        _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, qs_ref, ks_ref,
                    o_ref, lse_ref,
                    acc, m, l, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, n_kv=n_kv, offset=sk - sq,
                    dropout_rate=dropout_rate)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_kv),
        in_specs=in_specs,
        out_specs=(q_spec,
                   pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(_sds((bh, sq, d), q3.dtype, q3),
                   _sds((bh, sq, 1), jnp.float32, q3)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=_interp(),
    )(*args)
    return out, lse


def _bwd_pallas(q3, k3, v3, bias4, seed, segs, h, do3, lse, delta, *, scale,
                causal, block_q, block_k, dropout_rate):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    n_q, n_kv = sq // block_q, sk // block_k
    has_bias = bias4 is not None
    has_drop = dropout_rate > 0.0
    has_seg = segs is not None

    # --- dq: grid (bh, n_q, n_kv), kv innermost ---
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(_bias_spec(bias4, h, block_q, block_k, swapped=False))
        args.append(bias4)
    if has_drop:
        in_specs.append(_seed_spec())
        args.append(seed)
    if has_seg:
        sq_spec, sk_spec = _seg_specs(h, block_q, block_k, swapped=False)
        in_specs += [sq_spec, sk_spec]
        args += list(segs)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do3, lse, delta]

    def dq_kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref = refs[:3]
        nxt = 3
        bias_ref = refs[nxt] if has_bias else None
        nxt += has_bias
        seed_ref = refs[nxt] if has_drop else None
        nxt += has_drop
        qs_ref = refs[nxt] if has_seg else None
        ks_ref = refs[nxt + 1] if has_seg else None
        nxt += 2 * has_seg
        do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs[nxt:]
        _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, qs_ref,
                       ks_ref, do_ref,
                       lse_ref, delta_ref, dq_ref, dq_acc, scale=scale,
                       causal=causal, block_q=block_q, block_k=block_k,
                       n_kv=n_kv, offset=sk - sq, dropout_rate=dropout_rate)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, n_q, n_kv),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=_sds((bh, sq, d), q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interp(),
    )(*args)

    # --- dk/dv: grid (bh, n_kv, n_q), q innermost ---
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                            memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    in_specs2 = [q_spec2, kv_spec2, kv_spec2]
    args2 = [q3, k3, v3]
    if has_bias:
        in_specs2.append(_bias_spec(bias4, h, block_q, block_k, swapped=True))
        args2.append(bias4)
    if has_drop:
        in_specs2.append(_seed_spec())
        args2.append(seed)
    if has_seg:
        sq_spec2, sk_spec2 = _seg_specs(h, block_q, block_k, swapped=True)
        in_specs2 += [sq_spec2, sk_spec2]
        args2 += list(segs)
    in_specs2 += [q_spec2, row_spec2, row_spec2]
    args2 += [do3, lse, delta]

    def dkv_kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref = refs[:3]
        nxt = 3
        bias_ref = refs[nxt] if has_bias else None
        nxt += has_bias
        seed_ref = refs[nxt] if has_drop else None
        nxt += has_drop
        qs_ref = refs[nxt] if has_seg else None
        ks_ref = refs[nxt + 1] if has_seg else None
        nxt += 2 * has_seg
        (do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc,
         dv_acc) = refs[nxt:]
        _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, qs_ref,
                        ks_ref, do_ref,
                        lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                        scale=scale, causal=causal, block_q=block_q,
                        block_k=block_k, n_q=n_q, offset=sk - sq,
                        dropout_rate=dropout_rate)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, n_kv, n_q),
        in_specs=in_specs2,
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(_sds((bh, sk, d), k3.dtype, k3),
                   _sds((bh, sk, d), v3.dtype, v3)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interp(),
    )(*args2)
    return dq, dk, dv


def _dbias_pallas(q3, k3, v3, bias4, seed, segs, h, do3, lse, delta, *,
                  scale, causal, block_q, block_k, dropout_rate):
    """dbias via the accumulating kernel; HBM cost is O(|bias|)."""
    has_drop = dropout_rate > 0.0
    has_seg = segs is not None
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    n_q, n_kv = sq // block_q, sk // block_k
    bb, hb, sqb, _ = bias4.shape
    HB = bb * hb          # kept bh slices (one dbias tile-plane each)
    R = bh // HB          # bh slices reduced into each kept slice
    swap = sqb == 1       # bias broadcast over sq: reduce q-blocks too
    bq = block_q if not swap else 1

    def bh_of(g, r):
        if bb > 1 and hb > 1:
            return g
        if hb > 1:          # broadcast over batch: r enumerates b
            return r * hb + g
        if bb > 1:          # broadcast over heads: r enumerates h
            return g * h + r
        return r            # broadcast over both

    def kept(g):
        if bb > 1 and hb > 1:
            return (g // hb, g % hb)
        if hb > 1:
            return (0, g)
        if bb > 1:
            return (g, 0)
        return (0, 0)

    def ij(a, b_):
        return (b_, a) if swap else (a, b_)

    def q_map(g, a, b_, r):
        return (bh_of(g, r), ij(a, b_)[0], 0)

    def kv_map(g, a, b_, r):
        return (bh_of(g, r), ij(a, b_)[1], 0)

    def row_map(g, a, b_, r):
        return (bh_of(g, r), ij(a, b_)[0], 0)

    def bias_map(g, a, b_, r):
        bhv = bh_of(g, r)
        i, j = ij(a, b_)
        return (bhv // h if bb > 1 else 0, bhv % h if hb > 1 else 0,
                i if sqb > 1 else 0, j)

    def db_map(g, a, b_, r):
        i, j = ij(a, b_)
        return (*kept(g), i if sqb > 1 else 0, j)

    grid = (HB, n_kv, n_q, R) if swap else (HB, n_q, n_kv, R)
    q_spec = pl.BlockSpec((1, block_q, d), q_map, memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), kv_map, memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block_q, 1), row_map, memory_space=pltpu.VMEM)
    bias_spec = pl.BlockSpec((1, 1, bq, block_k), bias_map,
                             memory_space=pltpu.VMEM)
    db_spec = pl.BlockSpec((1, 1, bq, block_k), db_map,
                           memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_spec, kv_spec, bias_spec]
    args = [q3, k3, v3, bias4]
    if has_drop:
        in_specs.append(_seed_spec())
        args.append(seed)
    if has_seg:
        def qseg_map(g, a, b_, r):
            return (bh_of(g, r) // h, 0, ij(a, b_)[0])

        def kseg_map(g, a, b_, r):
            return (bh_of(g, r) // h, 0, ij(a, b_)[1])

        in_specs += [pl.BlockSpec((1, 1, block_q), qseg_map,
                                  memory_space=pltpu.VMEM),
                     pl.BlockSpec((1, 1, block_k), kseg_map,
                                  memory_space=pltpu.VMEM)]
        args += list(segs)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do3, lse, delta]

    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, bias_ref = refs[:4]
        nxt = 4
        seed_ref = refs[nxt] if has_drop else None
        nxt += has_drop
        qs_ref = refs[nxt] if has_seg else None
        ks_ref = refs[nxt + 1] if has_seg else None
        nxt += 2 * has_seg
        do_ref, lse_ref, delta_ref, db_ref = refs[nxt:]
        _dbias_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, qs_ref,
                      ks_ref, do_ref,
                      lse_ref, delta_ref, db_ref, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, swap=swap,
                      offset=sk - sq, dropout_rate=dropout_rate,
                      bh_fn=bh_of)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=db_spec,
        out_shape=_sds(bias4.shape, jnp.float32, q3),
        interpret=_interp(),
    )(*args)


@functools.lru_cache(maxsize=None)
def _make_flash(scale: float, causal: bool, block_q: int, block_k: int,
                has_bias: bool, need_dbias: bool, h: int,
                dropout_rate: float, has_seg: bool,
                checkpoint_names: bool = False):
    def _segs(qs, ks):
        return (qs, ks) if has_seg else None

    @jax.custom_vjp
    def flash(q3, k3, v3, bias4, seed, qseg, kseg):
        out, _ = _fwd_pallas(q3, k3, v3, bias4 if has_bias else None, seed,
                             _segs(qseg, kseg),
                             h, scale=scale, causal=causal, block_q=block_q,
                             block_k=block_k, dropout_rate=dropout_rate)
        return out

    def fwd(q3, k3, v3, bias4, seed, qseg, kseg):
        out, lse = _fwd_pallas(q3, k3, v3, bias4 if has_bias else None, seed,
                               _segs(qseg, kseg),
                               h, scale=scale, causal=causal, block_q=block_q,
                               block_k=block_k, dropout_rate=dropout_rate)
        if checkpoint_names:
            # Tag the kernel residuals INSIDE the fwd rule (the trace a
            # name-based jax.checkpoint policy sees under AD). Saving the
            # context alone would not keep the forward kernel out of the
            # recompute — the backward kernels also consume the logsumexp,
            # and an unsaved residual forces the fwd kernel to rerun in
            # the remat region. With both tagged, DCE drops the fwd kernel
            # from the recomputed set entirely (see apex_tpu/remat.py).
            from apex_tpu.remat import tag as _remat_tag
            out = _remat_tag(out, "flash_ctx")
            lse = _remat_tag(lse, "flash_lse")
        return out, (q3, k3, v3, bias4, seed, qseg, kseg, out, lse)

    def bwd(res, do3):
        q3, k3, v3, bias4, seed, qseg, kseg, out, lse = res
        delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq, dk, dv = _bwd_pallas(
            q3, k3, v3, bias4 if has_bias else None, seed,
            _segs(qseg, kseg), h, do3, lse,
            delta, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, dropout_rate=dropout_rate)
        if has_bias and need_dbias:
            dbias = _dbias_pallas(q3, k3, v3, bias4, seed,
                                  _segs(qseg, kseg), h, do3, lse,
                                  delta, scale=scale, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  dropout_rate=dropout_rate)
        else:
            # documented: zero unless opted in (scalar placeholder when
            # there is no bias at all)
            dbias = jnp.zeros_like(bias4)
        return (dq, dk, dv, dbias, jnp.zeros_like(seed),
                jnp.zeros_like(qseg), jnp.zeros_like(kseg))

    flash.defvjp(fwd, bwd)
    return flash


def _auto_block(seq: int, choices=(512, 256, 128)) -> int:
    """Largest tile from ``choices`` dividing ``seq`` (0 if none divide —
    the caller then falls back to XLA). 512x512 blocks measured ~4x faster
    than 128x128 on v5e (fewer grid steps, better MXU occupancy; bench
    seq=4096: 26.5ms vs 123ms fwd+bwd, XLA 86.5ms)."""
    for c in choices:
        if seq % c == 0:
            return c
    return 0


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    softmax_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    use_pallas: Optional[bool] = None,
                    bias_requires_grad: bool = False,
                    dropout_rate: float = 0.0,
                    dropout_seed=None,
                    segment_ids=None,
                    checkpoint_names: bool = False):
    """Fused attention over ``(b, h, s, d)`` tensors.

    ``segment_ids``: packed-sequence (varlen) attention — the TPU-native
    form of the reference's ``cu_seqlens`` packing
    (``reference:apex/contrib/csrc/fmha/fmha_api.cpp:420``). Pass an int
    array ``(b, s)`` (self-attention) or a ``(q_ids, kv_ids)`` pair; tokens
    attend only within their own segment, masked blockwise in VMEM (O(b·s)
    HBM, never O(s²)). Compose with ``causal`` for packed causal LM batches.

    ``bias``: additive fp32 score bias broadcastable to ``(b, h, sq, sk)``
    (use ``-10000``-filled masks for padding, as the reference softmax does).
    Broadcast dims stay broadcast — a padding mask costs O(b·sk) memory.

    ``bias_requires_grad``: the Pallas path returns **zero** gradient for
    ``bias`` unless this is True (see module docstring). Set it when the
    bias is a learned parameter (ALiBi/relative-position); leave False for
    padding masks to keep the backward O(s·d)-memory.

    ``dropout_rate``/``dropout_seed``: in-kernel attention-probability
    dropout (``philox.cuh`` analog; see module docstring). ``dropout_seed``
    is an int scalar (vary it per step/layer, e.g. from
    :func:`~apex_tpu.transformer.tensor_parallel.random.get_rng_tracker`);
    required when ``dropout_rate > 0``.

    ``checkpoint_names``: emit the ``flash_ctx``/``flash_lse``
    ``jax.ad_checkpoint.checkpoint_name`` tags (registry:
    ``apex_tpu/remat.py``) so a name-based remat policy can keep the
    kernel's residuals resident and the forward kernel out of the
    recomputed set. Off by default so untagged programs stay
    jaxpr-identical to the pre-policy ones.

    Falls back to the XLA reference when shapes aren't tile-aligned (same
    dropout mask and same zero-bias-grad semantics on both paths).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(d)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if block_q is None:
        # decode shape: a lone query row rides one padded sublane tile
        block_q = (1 if sq == 1 else
                   _auto_block(sq, (512, 256, 128, 64, 32, 16, 8)) or 128)
    if block_k is None:
        block_k = _auto_block(sk) or 128
    if use_pallas is None:
        use_pallas = supports_flash(sq, sk, d, block_q, block_k)
    if not use_pallas:
        # honor bias_requires_grad here too so gradient semantics do not
        # silently flip with tile alignment
        if bias is not None and not bias_requires_grad:
            bias = jax.lax.stop_gradient(bias)
        out = mha_reference(q, k, v, bias, causal, softmax_scale,
                            dropout_rate=dropout_rate,
                            dropout_seed=dropout_seed,
                            segment_ids=segment_ids)
        if checkpoint_names:
            # no custom_vjp on the XLA path — tagging the context still
            # lets name policies keep it resident (the plain-op attention
            # body is recomputed, which is exactly XLA ops, no kernel)
            from apex_tpu.remat import tag as _remat_tag
            out = _remat_tag(out, "flash_ctx")
        return out

    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    has_bias = bias is not None
    if has_bias:
        bias4 = jnp.asarray(bias, jnp.float32)
        if bias4.ndim > 4:
            raise ValueError(f"bias rank {bias4.ndim} > 4")
        while bias4.ndim < 4:
            bias4 = bias4[None]
        for ax, (dim, full) in enumerate(zip(bias4.shape, (b, h, sq, sk))):
            if dim not in (1, full):
                raise ValueError(
                    f"bias dim {ax} is {dim}; must be 1 or {full}")
        if bias4.shape[3] == 1 and sk > 1:
            # keys dim must be materialized for the (…, block_k) tiles
            bias4 = jnp.broadcast_to(bias4, (*bias4.shape[:3], sk))
    else:
        bias4 = jnp.zeros((), jnp.float32)  # placeholder pytree leaf
    if dropout_rate > 0.0:
        # (hi, lo) fp32 pair (SMEM-friendly and a differentiable
        # placeholder for custom_vjp); full 32-bit seed space composed with
        # per-element counters (ADVICE r2: was 24-bit)
        seed = _pack_seed(dropout_seed)
    else:
        seed = jnp.zeros((2,), jnp.float32)
    has_seg = segment_ids is not None
    if has_seg:
        q_ids, kv_ids = _norm_segment_ids(segment_ids, sq, sk)
        # fp32 carrier: exact for id counts < 2**24, and custom_vjp wants
        # float cotangents for every primal
        qseg = q_ids.astype(jnp.float32).reshape(b, 1, sq)
        kseg = kv_ids.astype(jnp.float32).reshape(b, 1, sk)
    else:
        qseg = kseg = jnp.zeros((), jnp.float32)  # placeholder leaf
    fn = _make_flash(float(softmax_scale), bool(causal), block_q, block_k,
                     has_bias, bool(bias_requires_grad), h,
                     float(dropout_rate), has_seg,
                     bool(checkpoint_names))
    with jax.named_scope("flash_attention"):
        out = fn(q3, k3, v3, bias4, seed, qseg, kseg)
    return out.reshape(b, h, sq, d)


# ---------------------------------------------------------------------------
# decode kernel — single-query attention over a preallocated KV cache
# ---------------------------------------------------------------------------
#
# The serving fast path (docs/SERVING.md). The training kernels above are
# built for sq == sk score tiles; autoregressive decode is the opposite
# regime — ONE query row per sequence against a long cached key stripe, a
# memory-bound streaming reduction with no backward pass (the reference
# ships a separate inference attention family, fmhalib /
# fast_multihead_attn, for exactly this reason). This kernel:
#
# - grids ``(b*h, max_len/block_k)`` with the cache blocks innermost and
#   streams the flash-LSE running ``(m, l, acc)`` in VMEM scratch across
#   them (the same online-softmax recurrence as ``_fwd_kernel``, one query
#   row wide — the row rides a padded sublane tile);
# - masks by a per-sequence integer write cursor (``lengths``) held in
#   SMEM, and SKIPS the compute of cache blocks entirely past the cursor
#   (a sequence at position t prices O(t) MXU work). NOTE the grid — and
#   therefore the pipelined HBM->VMEM block fetches — is still shaped by
#   max_len HERE: this dense-cache kernel streams the full stripe and
#   skips only the math, so its memory-bound cost is O(max_len) per slot
#   per step. The paged kernel below (``paged_decode_attention``) bounds
#   the fetches too — scalar-prefetched block tables whose index map
#   clamps past the cursor, so Pallas elides the repeat DMAs and HBM
#   traffic is O(actual context); dense engines keep this kernel, paged
#   engines (docs/SERVING.md "Paged serving") take the bounded grid;
# - optionally dequantizes an int8 cache blockwise in VMEM against
#   per-(position, head) fp32 scales — the cache stays int8 in HBM, which
#   is where a decode step's bytes actually go;
# - returns the per-row logsumexp so the caller can fold in the CURRENT
#   token's k/v with one exact two-way LSE merge (``_merge_current``) —
#   the cache is read before the new token is appended, so the kernel
#   never needs a variable-position write. Empty rows (length 0) return
#   lse = -inf, the correct identity for that merge (the training
#   kernel's +inf convention exists only for its backward).

def _decode_kernel(len_ref, q_ref, k_ref, v_ref, ksc_ref, vsc_ref, o_ref,
                   lse_ref, acc_ref, m_ref, l_ref, *, scale, block_k, n_kv):
    bh, j = pl.program_id(0), pl.program_id(1)
    length = len_ref[bh]

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip the COMPUTE of cache blocks past the write cursor (the
    # pipeline still fetches them — see the section comment)
    @pl.when(j * block_k < length)
    def _():
        q = q_ref[0].astype(jnp.float32)          # (q_len, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        if ksc_ref is not None:
            # int8 cache: dequantize blockwise in VMEM against the
            # per-(position, head) scales — HBM only ever holds int8
            k = k.astype(jnp.float32) * ksc_ref[0][:, None]
            v = v.astype(jnp.float32) * vsc_ref[0][:, None]
        s = jax.lax.dot_general(q, k.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(col < length, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(p, v.astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(j == n_kv - 1)
    def _():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # -inf (NOT the training kernels' +inf): the empty row must be
        # the identity of the two-way merge with the current token
        lse_ref[0] = jnp.where(l == 0.0, -jnp.inf,
                               m_ref[:] + jnp.log(safe_l))


def _decode_pallas(q3, k3, v3, lengths_bh, ksc, vsc, *, scale, block_k):
    bh, T, d = k3.shape
    # q3 is (bh, q_len, d): q_len == 1 is the classic decode step; the
    # speculative verify path rides q_len == k drafts + 1 bonus row
    # through the SAME kernel body (every reduction in it is already
    # per-row) — only the block/scratch shapes widen. All q rows share
    # one prefix mask (the drafts are NOT in the cache; causality among
    # them is the caller's exact merge, _merge_drafts).
    q_len = q3.shape[1]
    n_kv = T // block_k
    has_scale = ksc is not None

    q_spec = pl.BlockSpec((1, q_len, d), lambda b, j: (b, 0, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    sc_spec = pl.BlockSpec((1, block_k), lambda b, j: (b, j),
                           memory_space=pltpu.VMEM)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), q_spec, kv_spec,
                kv_spec]
    args = [lengths_bh, q3, k3, v3]
    if has_scale:
        in_specs += [sc_spec, sc_spec]
        args += [ksc, vsc]

    def kernel(*refs):
        refs = list(refs)
        len_ref, q_ref, k_ref, v_ref = refs[:4]
        nxt = 4
        ksc_ref = refs[nxt] if has_scale else None
        vsc_ref = refs[nxt + 1] if has_scale else None
        nxt += 2 * has_scale
        o_ref, lse_ref, acc, m, l = refs[nxt:]
        _decode_kernel(len_ref, q_ref, k_ref, v_ref, ksc_ref, vsc_ref,
                       o_ref, lse_ref, acc, m, l, scale=scale,
                       block_k=block_k, n_kv=n_kv)

    out_dtype = q3.dtype if q3.dtype != jnp.int8 else jnp.float32
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_kv),
        in_specs=in_specs,
        out_specs=(q_spec,
                   pl.BlockSpec((1, q_len, 1), lambda b, j: (b, 0, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((bh, q_len, d), out_dtype),
                   jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((q_len, d), jnp.float32),
                        pltpu.VMEM((q_len, 1), jnp.float32),
                        pltpu.VMEM((q_len, 1), jnp.float32)],
        interpret=_interp(),
    )(*args)
    return out, lse


def _dequant(x, scale):
    """int8 cache block -> fp32 against per-(position, head) scales
    ``(b, h, T)``."""
    return x.astype(jnp.float32) * scale[..., None]


def _merge_current(out, lse, q, k_new, v_new, scale, out_dtype):
    """Exact two-way logsumexp merge of the cached-prefix attention
    ``(out, lse)`` with the CURRENT token's ``(k_new, v_new)`` — the new
    token always attends to itself, and merging here (instead of writing
    it into the cache first) keeps the kernel free of variable-position
    writes. All fp32; an empty prefix (lse == -inf) reduces to exactly
    ``v_new``."""
    q32 = q.astype(jnp.float32)
    s_new = jnp.sum(q32 * k_new.astype(jnp.float32), axis=-1) * scale  # (b,h)
    m = jnp.maximum(lse, s_new)
    a_old = jnp.exp(lse - m)           # 0 when the prefix is empty
    a_new = jnp.exp(s_new - m)
    merged = (a_old[..., None] * out.astype(jnp.float32)
              + a_new[..., None] * v_new.astype(jnp.float32))
    return (merged / (a_old + a_new)[..., None]).astype(out_dtype)


def _merge_drafts(out, lse, q, k_new, v_new, k_cast, v_cast, scale,
                  out_dtype):
    """Exact (q_len+1)-way logsumexp merge for the speculative verify
    path: fold the cached-prefix attention ``(out, lse)`` — per draft
    row — with the q_len IN-FLIGHT tokens' keys/values, causally masked
    so row i attends rows 0..i (itself plus the earlier drafts). None of
    the in-flight tokens are in the cache yet; a sequential decode would
    have round-tripped rows j < i through the cache's storage dtype
    before row i read them, so the caller passes ``k_cast``/``v_cast``
    (the store+load images of ``k_new``/``v_new``) and the merge uses
    those OFF-diagonal while the diagonal (self-attention) stays fresh —
    exactly the numerics of k single-token steps. Reduces to
    ``_merge_current`` at q_len == 1.

    Shapes: out/q/k_new/v_new/k_cast/v_cast ``(b, h, q_len, d)``, lse
    ``(b, h, q_len)``."""
    q32 = q.astype(jnp.float32)
    qlen = q.shape[2]
    # off-diagonal scores against the cache-dtype images; diagonal fresh
    s_cast = jnp.einsum("bhid,bhjd->bhij", q32,
                        k_cast.astype(jnp.float32)) * scale
    s_self = jnp.sum(q32 * k_new.astype(jnp.float32), axis=-1) * scale
    row = jax.lax.broadcasted_iota(jnp.int32, (qlen, qlen), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (qlen, qlen), 1)
    below = col < row                              # strictly-earlier drafts
    s_off = jnp.where(below, s_cast, -jnp.inf)
    m = jnp.maximum(lse, jnp.maximum(s_self, jnp.max(s_off, axis=-1)))
    a_old = jnp.exp(lse - m)                       # 0 when prefix empty
    p_self = jnp.exp(s_self - m)
    p_off = jnp.where(below, jnp.exp(s_cast - m[..., None]), 0.0)
    denom = a_old + p_self + jnp.sum(p_off, axis=-1)
    merged = (a_old[..., None] * out.astype(jnp.float32)
              + p_self[..., None] * v_new.astype(jnp.float32)
              + jnp.einsum("bhij,bhjd->bhid", p_off,
                           v_cast.astype(jnp.float32)))
    return (merged / denom[..., None]).astype(out_dtype)


def decode_attention(q, k, v, lengths, k_new=None, v_new=None,
                     k_scale=None, v_scale=None,
                     softmax_scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     use_pallas: Optional[bool] = None,
                     k_cast=None, v_cast=None):
    """Single-query attention over a preallocated KV cache — the serving
    decode kernel (see the section comment above).

    Speculative verify: pass ``q`` as ``(b, h, q_len, d)`` (with matching
    rank-4 ``k_new``/``v_new``) to score q_len in-flight tokens per slot
    in ONE cache pass — the kernel prices the cached prefix once for all
    rows, and causality among the in-flight tokens is an exact LSE merge
    (``_merge_drafts``). ``k_cast``/``v_cast`` optionally carry the
    cache-dtype store+load images of ``k_new``/``v_new`` so cross-draft
    attention reproduces sequential decode's numerics bit-for-bit
    (default: the fresh values). The return is ``(b, h, q_len, d)``.

    Args:
      q: ``(b, h, d)`` — one query row per sequence slot — or
        ``(b, h, q_len, d)`` for the verify path.
      k, v: ``(b, h, max_len, d)`` preallocated caches (bf16/fp32, or int8
        with ``k_scale``/``v_scale``). Entries at or past ``lengths`` are
        never read.
      lengths: ``(b,)`` int — the per-slot write cursor: number of valid
        cache positions (the already-written PREFIX; the current token is
        NOT in the cache — pass it as ``k_new``/``v_new``).
      k_new, v_new: optional ``(b, h, d)`` — the current token's key/value,
        folded in by an exact two-way LSE merge. With an empty prefix the
        result is exactly ``v_new`` (softmax over one position).
      k_scale, v_scale: ``(b, h, max_len)`` fp32 per-(position, head)
        dequantization scales, required iff the cache dtype is int8.
      block_k: cache streaming block (default: largest of 512/256/128
        dividing ``max_len``).

    Returns ``(b, h, d)`` in ``q.dtype``. Rows whose prefix is empty AND
    have no ``k_new`` are exactly zero.

    Falls back to the XLA reference (:func:`mha_reference` with its
    ``kv_length`` oracle path) when the cache isn't tile-aligned.
    """
    multi = q.ndim == 4
    if multi:
        b, h, q_len, d = q.shape
    else:
        b, h, d = q.shape
        q_len = 1
    T = k.shape[2]
    if k.shape != (b, h, T, d) or v.shape != (b, h, T, d):
        raise ValueError(f"cache shapes {k.shape}/{v.shape} do not match "
                         f"q {q.shape} with max_len {T}")
    quantized = k.dtype == jnp.int8
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("int8 caches need k_scale/v_scale")
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(d)
    if block_k is None:
        block_k = _auto_block(T) or 128
    if use_pallas is None:
        use_pallas = supports_flash(1, T, d, 1, block_k)
    elif use_pallas and not supports_flash(1, T, d, 1, block_k):
        # a forced kernel on a misaligned cache would silently drop the
        # T % block_k tail (or never write the output at T < block_k) —
        # refuse instead of decoding garbage
        raise ValueError(
            f"use_pallas=True but cache max_len {T} / head_dim {d} are "
            f"not tile-aligned for block_k={block_k}; pass a dividing "
            "block_k or let use_pallas auto-select the XLA fallback")
    lengths = jnp.asarray(lengths).astype(jnp.int32)

    with jax.named_scope("decode_attention"):
        if multi:
            # verify path: q_len rows per slot, ONE pass over the cached
            # prefix (the mask is the same for every row — none of the
            # in-flight tokens are in the cache), then the causal merge
            if use_pallas:
                q3 = q.reshape(b * h, q_len, d)
                k3 = k.reshape(b * h, T, d)
                v3 = v.reshape(b * h, T, d)
                lengths_bh = jnp.repeat(lengths, h)
                ksc = k_scale.reshape(b * h, T) if quantized else None
                vsc = v_scale.reshape(b * h, T) if quantized else None
                out3, lse3 = _decode_pallas(q3, k3, v3, lengths_bh, ksc,
                                            vsc,
                                            scale=float(softmax_scale),
                                            block_k=block_k)
                out = out3.reshape(b, h, q_len, d)
                lse = lse3.reshape(b, h, q_len)
            else:
                kd = _dequant(k, k_scale) if quantized else k
                vd = _dequant(v, v_scale) if quantized else v
                s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                               kd.astype(jnp.float32)) * softmax_scale
                col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, T), 3)
                valid = col < lengths[:, None, None, None]
                s = jnp.where(valid, s, NEG_INF)
                m = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.where(valid, jnp.exp(s - m), 0.0)
                l = jnp.sum(p, axis=-1, keepdims=True)
                safe_l = jnp.where(l == 0.0, 1.0, l)
                out = jnp.einsum("bhqk,bhkd->bhqd", p / safe_l,
                                 vd.astype(jnp.float32))
                lse = jnp.where(lengths[:, None, None] == 0, -jnp.inf,
                                (m + jnp.log(safe_l))[..., 0])
            if k_new is not None:
                out = _merge_drafts(
                    out, lse, q, k_new, v_new,
                    k_new if k_cast is None else k_cast,
                    v_new if v_cast is None else v_cast,
                    float(softmax_scale), q.dtype)
            return out.astype(q.dtype)
        if use_pallas:
            q3 = q.reshape(b * h, 1, d)
            k3 = k.reshape(b * h, T, d)
            v3 = v.reshape(b * h, T, d)
            # per-slot cursor fanned out per head for the SMEM lookup
            lengths_bh = jnp.repeat(lengths, h)
            ksc = k_scale.reshape(b * h, T) if quantized else None
            vsc = v_scale.reshape(b * h, T) if quantized else None
            out3, lse3 = _decode_pallas(q3, k3, v3, lengths_bh, ksc, vsc,
                                        scale=float(softmax_scale),
                                        block_k=block_k)
            out = out3.reshape(b, h, d)
            lse = lse3.reshape(b, h)
        else:
            # XLA fallback, same math as the kernel (and as
            # mha_reference's kv_length oracle — the parity tests pin all
            # three together): ONE masked score pass feeds both the
            # output and the lse the merge needs
            kd = _dequant(k, k_scale) if quantized else k
            vd = _dequant(v, v_scale) if quantized else v
            s = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                           kd.astype(jnp.float32)) * softmax_scale
            col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, T), 2)
            valid = col < lengths[:, None, None]
            s = jnp.where(valid, s, NEG_INF)
            m = jnp.max(s, axis=-1, keepdims=True)
            # fully-masked rows have m == NEG_INF and exp(s - m) == 1 on
            # every entry — zero them explicitly (the kernels' rule)
            p = jnp.where(valid, jnp.exp(s - m), 0.0)
            l = jnp.sum(p, axis=-1, keepdims=True)
            safe_l = jnp.where(l == 0.0, 1.0, l)
            out = jnp.einsum("bhk,bhkd->bhd", p / safe_l,
                             vd.astype(jnp.float32))
            lse = jnp.where(lengths[:, None] == 0, -jnp.inf,
                            (m + jnp.log(safe_l))[..., 0])
        if k_new is not None:
            out = _merge_current(out, lse, q, k_new, v_new,
                                 float(softmax_scale), q.dtype)
        return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# paged decode kernel — bounded-grid attention over a block-pool KV cache
# ---------------------------------------------------------------------------
#
# The v2 serving kernel (docs/SERVING.md "Paged serving"): vLLM-style
# PagedAttention (Kwon et al.) brought to Pallas. The dense kernel above
# streams a per-slot ``(max_len, d)`` stripe and only SKIPS the compute
# past the cursor — its pipelined HBM fetches stay O(max_len). Here the
# cache is a global block pool ``(num_blocks, h, block_size, d)`` and each
# slot owns an int32 row of pool indices (its block table), so:
#
# - the per-slot block table and cursor ride as SCALAR-PREFETCH arguments
#   (``pltpu.PrefetchScalarGridSpec``): they are resident before the grid
#   starts, and the K/V BlockSpec index maps read them to aim each fetch
#   at ``table[slot, j]`` — the pool block holding that slot's j-th
#   logical block;
# - the fetch sequence is bounded by the cursor: past the slot's last
#   valid block the index map CLAMPS to that block, so consecutive grid
#   steps resolve to the SAME pool block and the Pallas pipeline elides
#   the re-fetch (equal block index => no new DMA) — HBM traffic per slot
#   per step is O(actual_context), not O(max_len). Compute past the
#   cursor is skipped with the same ``@pl.when`` the dense kernel uses;
# - the online-softmax recurrence, the int8 blockwise dequant (scales are
#   pooled alongside the blocks), the -inf empty-row convention and the
#   exact two-way ``_merge_current`` with the current token are the dense
#   kernel's, unchanged — the parity tests pin all of them to
#   ``mha_reference(kv_length=)``;
# - ``mean_context`` (an expected-occupancy hint, tokens) sizes the
#   ``pl.CostEstimate`` attached to the kernel so the pyprof roofline
#   prices the fetch-elided traffic instead of the worst-case table span
#   (``pyprof/model.py`` reads it off the ``pallas_call`` eqn). It never
#   changes the math — only the modeled bytes.

def supports_paged(block_size: int, d: int) -> bool:
    """Pallas eligibility for the paged decode kernel: lane-aligned
    blocks on real TPUs; anything goes under interpret mode (the CPU
    CI path — alignment is a hardware tiling constraint, not a
    correctness one)."""
    if _interp():
        return block_size >= 1 and d >= 1
    return block_size % 128 == 0 and d % 8 == 0


def _paged_decode_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, ksc_ref,
                         vsc_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                         scale, block_size, n_blocks):
    s, j = pl.program_id(0), pl.program_id(2)
    length = len_ref[s]

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip the COMPUTE past the cursor; the FETCH is already bounded by
    # the clamped index map (see the section comment)
    @pl.when(j * block_size < length)
    def _():
        # classic decode rides a rank-3 (1, 1, d) q block — the exact
        # pre-speculation program, kept byte-identical so non-spec
        # engines never recompile or shift numerics; the verify path
        # widens to a rank-4 (1, 1, q_len, d) block
        q = (q_ref[0] if q_ref.ndim == 3
             else q_ref[0, 0]).astype(jnp.float32)  # (q_len, d)
        k = k_ref[0, 0]                           # (block_size, d)
        v = v_ref[0, 0]
        if ksc_ref is not None:
            # int8 pool: dequantize blockwise in VMEM against the pooled
            # per-(position, head) scales — HBM only ever holds int8
            k = k.astype(jnp.float32) * ksc_ref[0, 0][:, None]
            v = v.astype(jnp.float32) * vsc_ref[0, 0][:, None]
        s_ = jax.lax.dot_general(q, k.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        col = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s_ = jnp.where(col < length, s_, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=1, keepdims=True))
        p = jnp.exp(s_ - m_new)
        p = jnp.where(col < length, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(p, v.astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(j == n_blocks - 1)
    def _():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        # -inf on empty rows: the identity of the _merge_current fold
        if o_ref.ndim == 3:
            o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
            lse_ref[0] = jnp.where(l == 0.0, -jnp.inf,
                                   m_ref[:] + jnp.log(safe_l))
        else:
            o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
            lse_ref[0, 0] = jnp.where(l == 0.0, -jnp.inf,
                                      m_ref[:] + jnp.log(safe_l))


def _paged_cost(s, h, d, kv_dtype, quantized, n_blocks_slot, block_size,
                mean_context, q_len=1):
    """``pl.CostEstimate`` for one paged decode call: the fetch-elided
    HBM bytes at ``mean_context`` tokens of ACTUAL context per slot (the
    index-map clamp makes repeated blocks free), so the pyprof roofline
    prices what the kernel moves, not the worst-case table span.

    ``q_len > 1`` is the speculative verify call: the MXU work and the
    q/out traffic scale by q_len, but the dominant KV stream does NOT —
    the cached stripe is fetched once for all q_len rows, which is
    exactly why the roofline shows the per-token HBM cost dropping ~k×
    at acceptance."""
    cap = n_blocks_slot * block_size
    ctx = cap if mean_context is None else mean_context
    ctx = float(min(max(ctx, 1), cap))
    # fetched context rounds up to whole blocks per slot
    ctx = math.ceil(ctx / block_size) * block_size
    itemsize = jnp.dtype(kv_dtype).itemsize
    kv_bytes = 2.0 * s * h * ctx * d * itemsize
    if quantized:
        kv_bytes += 2.0 * s * h * ctx * 4
    io_bytes = (kv_bytes + 2.0 * s * h * q_len * d * 4
                + s * (n_blocks_slot + 1) * 4)
    flops = 4.0 * s * h * ctx * d * q_len  # qk^T + pv, 2 MACs each
    return pl.CostEstimate(flops=int(flops), bytes_accessed=int(io_bytes),
                           transcendentals=int(s * h * ctx * q_len))


def _paged_decode_pallas(q, kp, vp, tables, lengths, ksc, vsc, *, scale,
                         mean_context):
    # q rank-3 (S, h, d) is the classic decode step — its program is
    # kept BYTE-identical to the pre-speculation kernel (same block
    # ranks, same index maps) so non-spec engines are untouched; rank-4
    # (S, h, q_len, d) is the verify path, which only widens the
    # q/out/scratch shapes — the kernel body is per-row throughout and
    # the KV fetch sequence (and its clamp) is q_len-independent.
    multi = q.ndim == 4
    if multi:
        S, h, q_len, d = q.shape
    else:
        S, h, d = q.shape
        q_len = 1
    _nb_pool, _, block_size, _ = kp.shape
    n_blocks = tables.shape[1]
    has_scale = ksc is not None

    if multi:
        def q_map(s, hh, j, tabs, lens):
            return (s, hh, 0, 0)
        q_block, lse_block = (1, 1, q_len, d), (1, 1, q_len, 1)
        out_shapes = ((S, h, q_len, d), (S, h, q_len, 1))
    else:
        def q_map(s, hh, j, tabs, lens):
            return (s, hh, 0)
        q_block, lse_block = (1, 1, d), (1, 1, 1)
        out_shapes = ((S, h, d), (S, h, 1))

    def kv_map(s, hh, j, tabs, lens):
        # clamp past-the-cursor steps to the slot's LAST valid block:
        # equal consecutive indices elide the fetch, which is what
        # bounds HBM traffic to the actual context. An empty slot
        # (length 0) clamps to table entry 0 — the allocator's null
        # block — and its compute is fully masked.
        nb_valid = jnp.maximum(
            (lens[s] + block_size - 1) // block_size, 1)
        jj = jnp.minimum(j, nb_valid - 1)
        return (tabs[s, jj], hh, 0, 0)

    def sc_map(s, hh, j, tabs, lens):
        nb_valid = jnp.maximum(
            (lens[s] + block_size - 1) // block_size, 1)
        jj = jnp.minimum(j, nb_valid - 1)
        return (tabs[s, jj], hh, 0)

    in_specs = [pl.BlockSpec(q_block, q_map),
                pl.BlockSpec((1, 1, block_size, d), kv_map),
                pl.BlockSpec((1, 1, block_size, d), kv_map)]
    args = [q, kp, vp]
    if has_scale:
        in_specs += [pl.BlockSpec((1, 1, block_size), sc_map),
                     pl.BlockSpec((1, 1, block_size), sc_map)]
        args += [ksc, vsc]

    def kernel(*refs):
        refs = list(refs)
        tab_ref, len_ref, q_ref, k_ref, v_ref = refs[:5]
        nxt = 5
        ksc_ref = refs[nxt] if has_scale else None
        vsc_ref = refs[nxt + 1] if has_scale else None
        nxt += 2 * has_scale
        o_ref, lse_ref, acc, m, l = refs[nxt:]
        _paged_decode_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref,
                             ksc_ref, vsc_ref, o_ref, lse_ref, acc, m, l,
                             scale=scale, block_size=block_size,
                             n_blocks=n_blocks)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, h, n_blocks),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec(q_block, q_map),
                   pl.BlockSpec(lse_block, q_map)),
        scratch_shapes=[pltpu.VMEM((q_len, d), jnp.float32),
                        pltpu.VMEM((q_len, 1), jnp.float32),
                        pltpu.VMEM((q_len, 1), jnp.float32)])
    out_dtype = q.dtype if q.dtype != jnp.int8 else jnp.float32
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(out_shapes[0], out_dtype),
                   jax.ShapeDtypeStruct(out_shapes[1], jnp.float32)),
        cost_estimate=_paged_cost(S, h, d, kp.dtype, has_scale, n_blocks,
                                  block_size, mean_context, q_len=q_len),
        interpret=_interp(),
        name="paged_decode_attention",
    )(tables, lengths, *args)
    return out, lse[..., 0]


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths,
                           k_new=None, v_new=None, k_scale=None,
                           v_scale=None,
                           softmax_scale: Optional[float] = None,
                           mean_context: Optional[float] = None,
                           use_pallas: Optional[bool] = None,
                           k_cast=None, v_cast=None):
    """Single-query attention over a PAGED KV cache (see the section
    comment above) — the v2 serving decode kernel.

    Speculative verify: pass ``q`` as ``(b, h, q_len, d)`` (with rank-4
    ``k_new``/``v_new`` and optional ``k_cast``/``v_cast`` store+load
    images) to score q_len in-flight tokens per slot against ONE bounded
    fetch of the cached blocks — the block-table walk and its clamp are
    q_len-independent, so the per-token HBM cost drops ~q_len× at full
    acceptance. Returns ``(b, h, q_len, d)``.

    Args:
      q: ``(b, h, d)`` — one query row per sequence slot — or
        ``(b, h, q_len, d)`` for the verify path.
      k_pool, v_pool: ``(num_blocks, h, block_size, d)`` global block
        pools (bf16/fp32, or int8 with pooled scales). Only the blocks a
        slot's table names are ever read for it.
      block_tables: ``(b, n_blocks_per_slot)`` int32 — pool indices of
        each slot's logical blocks, in order. Entries past
        ``ceil(length/block_size)`` are never read (the index map clamps
        before them); unmapped entries should name the allocator's null
        block (0).
      lengths: ``(b,)`` int32 per-slot cursor — valid cache positions
        (the current token is NOT in the cache; pass it via ``k_new``).
      k_new, v_new: optional ``(b, h, d)`` current token, folded in with
        the exact two-way LSE merge (empty prefix reduces to ``v_new``).
      k_scale, v_scale: ``(num_blocks, h, block_size)`` fp32 pooled
        dequantization scales, required iff the pool dtype is int8.
      mean_context: expected ACTUAL context per slot (tokens), used only
        to size the kernel's ``CostEstimate`` for the pyprof roofline —
        never changes the math. Default: the worst-case table span.

    Returns ``(b, h, d)`` in ``q.dtype``.

    Falls back to a gather-then-reference XLA path (same math, priced
    O(table span)) when the pool isn't tile-aligned for Pallas.
    """
    multi = q.ndim == 4
    if multi:
        b, h, q_len, d = q.shape
    else:
        b, h, d = q.shape
        q_len = 1
    nb_pool, hp, block_size, dp = k_pool.shape
    if v_pool.shape != k_pool.shape or hp != h or dp != d:
        raise ValueError(f"pool shapes {k_pool.shape}/{v_pool.shape} do "
                         f"not match q {q.shape}")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(f"block_tables must be (b, n_blocks_per_slot), "
                         f"got {block_tables.shape}")
    quantized = k_pool.dtype == jnp.int8
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("int8 pools need k_scale/v_scale")
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(d)
    if use_pallas is None:
        use_pallas = supports_paged(block_size, d)
    elif use_pallas and not supports_paged(block_size, d):
        raise ValueError(
            f"use_pallas=True but block_size {block_size} / head_dim {d} "
            "are not tile-aligned for the paged kernel; resize the pool "
            "or let use_pallas auto-select the XLA fallback")
    block_tables = jnp.asarray(block_tables).astype(jnp.int32)
    lengths = jnp.asarray(lengths).astype(jnp.int32)

    with jax.named_scope("decode_attention"):
        if use_pallas:
            # rank-3 q emits the classic (byte-identical) decode
            # program; rank-4 q emits the widened verify program
            out, lse = _paged_decode_pallas(
                q, k_pool, v_pool, block_tables, lengths,
                k_scale if quantized else None,
                v_scale if quantized else None,
                scale=float(softmax_scale), mean_context=mean_context)
            if multi:
                if k_new is not None:
                    out = _merge_drafts(
                        out, lse, q, k_new, v_new,
                        k_new if k_cast is None else k_cast,
                        v_new if v_cast is None else v_cast,
                        float(softmax_scale), q.dtype)
                return out.astype(q.dtype)
            if k_new is not None:
                out = _merge_current(out, lse, q, k_new, v_new,
                                     float(softmax_scale), q.dtype)
            return out.astype(q.dtype)
        # XLA fallback: gather the table-mapped blocks into the dense
        # layout and run the dense fallback (one masked score pass +
        # the same merge) — identical math, O(table span) traffic
        T = block_tables.shape[1] * block_size
        def gather(pool):
            g = pool[block_tables]              # (b, nbs, h, bs, d)
            return g.transpose(0, 2, 1, 3, 4).reshape(b, h, T, d)
        kd = gather(k_pool)
        vd = gather(v_pool)
        ksc = vsc = None
        if quantized:
            def gather_sc(sc):
                g = sc[block_tables]            # (b, nbs, h, bs)
                return g.transpose(0, 2, 1, 3).reshape(b, h, T)
            ksc = gather_sc(k_scale)
            vsc = gather_sc(v_scale)
        return decode_attention(q, kd, vd, lengths, k_new=k_new,
                                v_new=v_new, k_scale=ksc, v_scale=vsc,
                                softmax_scale=softmax_scale,
                                use_pallas=False, k_cast=k_cast,
                                v_cast=v_cast)
