"""Fused scale + mask + softmax.

Reference: ``reference:apex/transformer/functional/fused_softmax.py`` —
``ScaledUpperTriangMaskedSoftmax`` (:21-50, causal, 3D ``(b*np, sq, sk)``),
``ScaledMaskedSoftmax`` (:71-92, arbitrary bool mask, 4D ``(b, np, sq, sk)``),
and the ``FusedScaleMaskSoftmax`` dispatcher (:101-207) with its kernel
eligibility rules (:159-179) and torch fallback (:185-201).

On TPU the scale+mask+softmax chain is a single XLA fusion already (one VMEM
pass), so there is no separate Pallas kernel here — the *fused attention*
kernel (:mod:`apex_tpu.ops.flash_attention`) is where softmax fusion buys
memory traffic, subsuming the reference's seqlen<=2048 limit. The dispatcher
keeps the reference's eligibility/fallback split so callers can port
unchanged; both paths compute identical values.

Mask convention matches Megatron: ``mask == True`` marks positions to *drop*,
filled with -10000.0 before the softmax (the reference kernels use the same
additive fill, ``reference:csrc/megatron/scaled_masked_softmax.h``).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "AttnMaskType", "scaled_upper_triang_masked_softmax",
    "scaled_masked_softmax", "FusedScaleMaskSoftmax",
]

_MASK_FILL = -10000.0


class AttnMaskType(enum.Enum):
    """``reference:apex/transformer/enums.py`` (padding/causal)."""
    padding = 1
    causal = 2


def scaled_upper_triang_masked_softmax(x: jnp.ndarray,
                                       scale: float = 1.0) -> jnp.ndarray:
    """Causal softmax over ``(..., sq, sk)`` — the
    ``scaled_upper_triang_masked_softmax_cuda`` op. Computed in fp32, returned
    in the input dtype."""
    sq, sk = x.shape[-2], x.shape[-1]
    xf = x.astype(jnp.float32) * scale
    row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    causal = col > row + (sk - sq)
    xf = jnp.where(causal, _MASK_FILL, xf)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)


def scaled_masked_softmax(x: jnp.ndarray, mask: Optional[jnp.ndarray],
                          scale: float = 1.0) -> jnp.ndarray:
    """Arbitrary-bool-mask softmax (``scaled_masked_softmax_cuda``); ``mask``
    broadcasts over ``(b, np, sq, sk)`` and True means masked."""
    xf = x.astype(jnp.float32) * scale
    if mask is not None:
        xf = jnp.where(mask, _MASK_FILL, xf)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)


class FusedScaleMaskSoftmax:
    """Dispatcher mirroring ``FusedScaleMaskSoftmax`` (:101-207).

    The eligibility predicate is kept for API parity and introspection
    (tests assert on it), though on TPU both branches lower to the same fused
    XLA computation — ``is_kernel_available`` answers "would the reference
    have used its CUDA kernel here".
    """

    def __init__(self, input_in_fp16: bool = False, input_in_bf16: bool = False,
                 attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func: Optional[Callable] = None,
                 softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError(
                "both fp16 and bf16 flags cannot be active at the same time.")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if not (scale is None or softmax_in_fp32):
            raise RuntimeError("softmax should be in fp32 when scaled")

    def __call__(self, x: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        assert x.ndim == 4, "input must be (b, np, sq, sk)"
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = x.shape
            assert sq == sk, "causal mask is only for self attention"
            out = scaled_upper_triang_masked_softmax(
                x.reshape(-1, sq, sk), scale)
            return out.reshape(b, np_, sq, sk)
        if self.mask_func is not None and not self.scaled_masked_softmax_fusion:
            # torch-fallback parity path (:185-201): user mask_func + softmax
            xf = x.astype(jnp.float32) if (self.input_in_float16 and
                                           self.softmax_in_fp32) else x
            xf = xf * scale
            xf = self.mask_func(xf, mask) if mask is not None else xf
            probs = jax.nn.softmax(xf, axis=-1)
            return probs.astype(x.dtype)
        return scaled_masked_softmax(x, mask, scale)

    def is_kernel_available(self, mask, b: int, np_: int, sq: int, sk: int) -> bool:
        """Reference eligibility (:159-179); informational on TPU."""
        attn_batches = b * np_
        if not (self.scaled_masked_softmax_fusion and self.input_in_float16
                and mask is not None and 16 < sk <= 2048
                and sq % 4 == 0 and attn_batches % 4 == 0):
            return False
        batch_per_block = self.get_batch_per_block(sq, sk, b, np_)
        if self.attn_mask_type == AttnMaskType.causal:
            return attn_batches % batch_per_block == 0
        return sq % batch_per_block == 0

    @staticmethod
    def get_batch_per_block(sq: int, sk: int, b: int, np_: int) -> int:
        # CUDA heuristic (scaled_masked_softmax.h): 128-thread blocks over
        # next-pow2(sk) columns; kept so eligibility matches the reference.
        pow2 = 1 << max(sk - 1, 1).bit_length()
        warp_size = min(32, pow2)
        batches_per_warp = 2 if pow2 <= 128 else 1
        warps_per_block = 128 // warp_size
        return warps_per_block * batches_per_warp
