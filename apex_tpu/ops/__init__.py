"""Fused ops: softmax, attention, losses, dense blocks.

TPU equivalents of the reference's kernel-backed op layer
(``reference:apex/transformer/functional/``, ``apex/contrib/xentropy``,
``apex/contrib/focal_loss``, ``apex/contrib/fmha``,
``apex/contrib/multihead_attn``, ``apex/mlp``, ``apex/fused_dense``).
"""

from apex_tpu.ops.dropout import dropout  # noqa: F401
from apex_tpu.ops.flash_attention import (  # noqa: F401
    dropout_keep_mask, flash_attention, mha_reference, supports_flash)
from apex_tpu.ops.focal_loss import FocalLoss, focal_loss  # noqa: F401
from apex_tpu.ops.fused_softmax import (  # noqa: F401
    AttnMaskType, FusedScaleMaskSoftmax, scaled_masked_softmax,
    scaled_upper_triang_masked_softmax)
from apex_tpu.ops.conv_fusion import (  # noqa: F401
    conv_bias, conv_bias_mask_relu, conv_bias_relu,
    conv_frozen_scale_bias_relu)
from apex_tpu.ops.multihead_attn import (  # noqa: F401
    EncdecMultiheadAttn, SelfMultiheadAttn)
from apex_tpu.ops.transducer import (  # noqa: F401
    TransducerJoint, TransducerLoss, transducer_joint, transducer_loss)
from apex_tpu.ops.mlp import (  # noqa: F401
    MLP, FusedDense, FusedDenseGeluDense, fused_dense,
    fused_dense_gelu_dense, mlp_forward)
from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss, softmax_cross_entropy_loss)

__all__ = [
    "flash_attention", "mha_reference", "supports_flash",
    "FocalLoss", "focal_loss",
    "AttnMaskType", "FusedScaleMaskSoftmax", "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "MLP", "FusedDense", "FusedDenseGeluDense", "fused_dense",
    "fused_dense_gelu_dense", "mlp_forward",
    "SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss",
    "TransducerJoint", "TransducerLoss", "transducer_joint",
    "transducer_loss",
    "SelfMultiheadAttn", "EncdecMultiheadAttn",
    "conv_bias", "conv_bias_relu", "conv_bias_mask_relu",
    "conv_frozen_scale_bias_relu",
]
