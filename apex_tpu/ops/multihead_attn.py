"""Fused multi-head attention modules — self & encoder-decoder, with the
optional fused pre-LayerNorm + residual ("norm-add") variant.

Reference: ``reference:apex/contrib/multihead_attn/`` (1,842 LoC Python over
the 8,020-LoC ``fast_multihead_attn`` CUDA extension) — ``SelfMultiheadAttn``,
``EncdecMultiheadAttn``, each with ``include_norm_add`` fusing the pre-LN
and residual add around the attention core
(``self_multihead_attn_norm_add_cuda.cu``).

TPU redesign: the CUDA extension exists to fuse QKV GEMM + masked softmax +
dropout + AV GEMM (+ LN/residual); here the attention core is the Pallas
flash kernel (:mod:`apex_tpu.ops.flash_attention` — softmax/mask/dropout
fused in-kernel, no seqlen cap) and the LN/projection epilogues are XLA
fusions. The module surface keeps the reference semantics: seq-first
``(T, B, H)`` tensors (torch ``MultiheadAttention`` layout, which the
parity tests compare against), combined or separate in-projections, and
the norm-add wiring ``x + attn(LN(x))``.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.ops.flash_attention import flash_attention

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _xavier(key, shape):
    fan_out, fan_in = shape[0], shape[1]
    bound = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def _heads(x, heads):
    # (T, B, H) -> (B, heads, T, dh)
    t, b, h = x.shape
    return jnp.transpose(x.reshape(t, b, heads, h // heads), (1, 2, 0, 3))


def _unheads(x):
    # (B, heads, T, dh) -> (T, B, H)
    b, nh, t, dh = x.shape
    return jnp.transpose(x, (2, 0, 1, 3)).reshape(t, b, nh * dh)


def _mask_bias(key_padding_mask):
    """(B, T) True=pad -> additive (B, 1, 1, T) bias (the reference's
    -10000 padding-mask convention)."""
    if key_padding_mask is None:
        return None
    return jnp.where(key_padding_mask[:, None, None, :], -10000.0,
                     0.0).astype(jnp.float32)


def _dropout_seed(dropout_rng):
    if dropout_rng is None:
        return None
    return jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1)


class _MultiheadBase:
    def __init__(self, embed_dim: int, num_heads: int, dropout: float,
                 bias: bool, include_norm_add: bool):
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim "
                f"({embed_dim})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.use_bias = bias
        self.include_norm_add = include_norm_add

    def _maybe_norm(self, params, x):
        if not self.include_norm_add:
            return x
        return fused_layer_norm_affine(
            x, params["lyr_nrm"]["weight"].astype(x.dtype),
            params["lyr_nrm"]["bias"].astype(x.dtype), self.embed_dim)

    def _norm_params(self):
        if not self.include_norm_add:
            return {}
        return {"lyr_nrm": {"weight": jnp.ones(self.embed_dim),
                            "bias": jnp.zeros(self.embed_dim)}}

    def _out_proj(self, params, ctx, residual):
        out = _unheads(ctx) @ params["out"]["weight"].astype(
            ctx.dtype).T
        if self.use_bias:
            out = out + params["out"]["bias"].astype(out.dtype)
        return residual + out if self.include_norm_add else out


class SelfMultiheadAttn(_MultiheadBase):
    """``reference:apex/contrib/multihead_attn/self_multihead_attn.py``.

    ``__call__(params, x, ...)`` with ``x`` (T, B, H); returns (T, B, H).
    ``include_norm_add`` returns ``x + attn(LN(x))`` (the norm-add fused
    variant). ``key_padding_mask``: (B, T) True = pad.
    """

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False):
        super().__init__(embed_dim, num_heads, dropout, bias,
                         include_norm_add)

    def init(self, key: jax.Array) -> dict:
        k1, k2 = jax.random.split(key)
        p = {"qkv": {"weight": _xavier(k1, (3 * self.embed_dim,
                                            self.embed_dim))},
             "out": {"weight": _xavier(k2, (self.embed_dim,
                                            self.embed_dim))},
             **self._norm_params()}
        if self.use_bias:
            p["qkv"]["bias"] = jnp.zeros(3 * self.embed_dim)
            p["out"]["bias"] = jnp.zeros(self.embed_dim)
        return p

    def __call__(self, params: dict, x: jnp.ndarray,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 attn_mask_causal: bool = False,
                 dropout_rng=None) -> jnp.ndarray:
        residual = x
        x = self._maybe_norm(params, x)
        qkv = x @ params["qkv"]["weight"].astype(x.dtype).T
        if self.use_bias:
            qkv = qkv + params["qkv"]["bias"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        rate = self.dropout if dropout_rng is not None else 0.0
        ctx = flash_attention(
            _heads(q, self.num_heads), _heads(k, self.num_heads),
            _heads(v, self.num_heads), bias=_mask_bias(key_padding_mask),
            causal=attn_mask_causal, dropout_rate=rate,
            dropout_seed=_dropout_seed(dropout_rng))
        return self._out_proj(params, ctx, residual)


class EncdecMultiheadAttn(_MultiheadBase):
    """``reference:apex/contrib/multihead_attn/encdec_multihead_attn.py``:
    queries from the decoder stream, keys/values from the encoder output
    (separate q and kv in-projections)."""

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False):
        super().__init__(embed_dim, num_heads, dropout, bias,
                         include_norm_add)

    def init(self, key: jax.Array) -> dict:
        k1, k2, k3 = jax.random.split(key, 3)
        p = {"q": {"weight": _xavier(k1, (self.embed_dim, self.embed_dim))},
             "kv": {"weight": _xavier(k2, (2 * self.embed_dim,
                                           self.embed_dim))},
             "out": {"weight": _xavier(k3, (self.embed_dim,
                                            self.embed_dim))},
             **self._norm_params()}
        if self.use_bias:
            p["q"]["bias"] = jnp.zeros(self.embed_dim)
            p["kv"]["bias"] = jnp.zeros(2 * self.embed_dim)
            p["out"]["bias"] = jnp.zeros(self.embed_dim)
        return p

    def __call__(self, params: dict, query: jnp.ndarray,
                 key_value: jnp.ndarray,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 dropout_rng=None) -> jnp.ndarray:
        residual = query
        query = self._maybe_norm(params, query)
        q = query @ params["q"]["weight"].astype(query.dtype).T
        kv = key_value @ params["kv"]["weight"].astype(key_value.dtype).T
        if self.use_bias:
            q = q + params["q"]["bias"].astype(q.dtype)
            kv = kv + params["kv"]["bias"].astype(kv.dtype)
        k, v = jnp.split(kv, 2, axis=-1)
        rate = self.dropout if dropout_rng is not None else 0.0
        ctx = flash_attention(
            _heads(q, self.num_heads), _heads(k, self.num_heads),
            _heads(v, self.num_heads), bias=_mask_bias(key_padding_mask),
            dropout_rate=rate, dropout_seed=_dropout_seed(dropout_rng))
        return self._out_proj(params, ctx, residual)
