"""Continuous slot batching: admit into freed slots, retire mid-flight.

The classic serving loop has a batch barrier — requests grouped into a
batch enter together and the batch ends when its LAST member finishes,
so every short sequence idles its slot while the longest one drags on.
This scheduler has none: the decode program always steps all
``max_seqs`` slots (fixed shape, zero recompiles), and between steps the
host admits queued requests into whatever slots just freed and retires
whatever finished — a sequence occupies hardware for exactly its own
lifetime. Occupancy under load approaches 100% of slots instead of the
~50% a barrier averages on mixed-length traffic.

Host-side state is deliberately tiny (per-slot last token, temperature,
budget counters); everything sequence-shaped lives in the device cache
behind its write cursor. The loop emits the ``serve/*`` host-registry
metric family (docs/OBSERVABILITY.md) each step.

**Request lifecycle.** Every request carries a
:class:`~apex_tpu.observability.reqtrace.RequestRecord`: ``submit``
stamps the enqueue time, admission/prefill/decode/retire each stamp one
``time.perf_counter()`` per transition (the WHOLE hot-loop tracing
overhead — the device programs are untouched), so completions report
measured ``queue_wait_ms``/``ttft_ms``/``tpot_ms``/``e2e_ms`` and the
registry grows the matching ``serve/*`` latency histograms. Attaching a
:class:`~apex_tpu.observability.reqtrace.RequestTrace` (``trace=``)
additionally keeps retired records in its ring buffer (with per-tick
timestamps) for the Chrome-trace export; an
:class:`~apex_tpu.observability.slo.SLOTracker` (``slo=``) ingests each
retirement for goodput/burn-rate. Both default off and neither adds
device work (asserted in ``tests/test_reqtrace.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.observability import get_registry
from apex_tpu.observability.reqtrace import (LATENCY_BUCKETS_MS,
                                             RequestRecord)

__all__ = ["Request", "Completion", "SlotScheduler"]


@dataclasses.dataclass
class Request:
    """One generation request. ``temperature`` <= 0 is greedy;
    ``eos_token`` (optional) stops generation early; ``max_new_tokens``
    always bounds it."""
    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    eos_token: Optional[int] = None
    request_id: Optional[int] = None


@dataclasses.dataclass
class Completion:
    """A finished request: the generated tokens (prompt excluded), why
    generation stopped (``"eos"`` | ``"length"`` | ``"capacity"``), and
    the measured per-request latencies — ``queue_wait_ms`` (submit →
    slot), ``ttft_ms`` (submit → first token, queue wait included),
    ``tpot_ms`` (mean per-token after the first; None for single-token
    requests), ``e2e_ms`` (submit → retire)."""
    request_id: int
    tokens: List[int]
    finish_reason: str
    queue_wait_ms: Optional[float] = None
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    e2e_ms: Optional[float] = None


@dataclasses.dataclass
class _Active:
    request: Request
    generated: List[int]
    position: int            # prompt_len + len(generated), vs cache capacity
    record: RequestRecord


class SlotScheduler:
    """See module docstring. Drive it with :meth:`submit` + :meth:`step`
    (one decode step per call), or :meth:`run` for a closed batch.

    ``trace`` (optional :class:`RequestTrace`) keeps retired request
    records in a bounded ring for Chrome-trace export / flight-recorder
    dumps; ``slo`` (optional :class:`SLOTracker`) ingests each
    retirement. With both None the only lifecycle cost left is one
    timestamp per transition — the latency fields on completions and the
    ``serve/*_ms`` histograms are always real measurements."""

    def __init__(self, engine, registry=None, trace=None, slo=None):
        self.engine = engine
        self._reg = registry if registry is not None else get_registry()
        self.trace = trace
        self.slo = slo
        self.queue: collections.deque = collections.deque()
        self.free: List[int] = list(range(engine.max_seqs))[::-1]
        self.active: Dict[int, _Active] = {}
        self.completed: List[Completion] = []
        self._tokens = np.zeros(engine.max_seqs, np.int32)
        self._temps = np.zeros(engine.max_seqs, np.float32)
        self._next_id = 0
        self._tok_count = 0
        self._tok_t0: Optional[float] = None

    # -- submission ---------------------------------------------------------

    def submit(self, request: Request) -> int:
        # validate HERE, not at admission: a bad request must bounce off
        # the caller, never kill the serving loop mid-step (by then it
        # has already been popped from the queue and other admissions
        # are half-done)
        if len(request.prompt) == 0:
            raise ValueError("empty prompt")
        if len(request.prompt) > self.engine.prefill_len:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds the "
                f"engine's prefill window {self.engine.prefill_len}")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{request.max_new_tokens} (the prefill always samples "
                "one token)")
        if request.request_id is None:
            request.request_id = self._next_id
        self._next_id = max(self._next_id, request.request_id) + 1
        # the enqueue stamp: queue wait is measured from here, not
        # inferred from admission order
        record = RequestRecord(request_id=request.request_id,
                               prompt_len=len(request.prompt),
                               submit_t=time.perf_counter())
        self.queue.append((request, record))
        return request.request_id

    @property
    def pending(self) -> int:
        return len(self.queue) + len(self.active)

    # -- the loop -----------------------------------------------------------

    def _retire(self, slot: int, reason: str, now: float) -> None:
        st = self.active.pop(slot)
        # zero the cursor: an idle slot left deep in the cache would keep
        # paying full-prefix attention on every later decode step
        self.engine.release_slot(slot)
        self.free.append(slot)
        rec = st.record
        rec.retire_t = now
        rec.finish_reason = reason
        rec.generated = len(st.generated)
        self.completed.append(Completion(
            st.request.request_id, st.generated, reason,
            queue_wait_ms=rec.queue_wait_ms, ttft_ms=rec.ttft_ms,
            tpot_ms=rec.tpot_ms, e2e_ms=rec.e2e_ms))
        self._reg.counter("serve/retired").inc()
        if rec.queue_wait_ms is not None:
            self._reg.histogram("serve/queue_wait_ms",
                                LATENCY_BUCKETS_MS).observe(
                                    rec.queue_wait_ms)
        if rec.ttft_ms is not None:
            self._reg.histogram("serve/ttft_ms",
                                LATENCY_BUCKETS_MS).observe(rec.ttft_ms)
        if rec.tpot_ms is not None:
            self._reg.histogram("serve/tpot_ms",
                                LATENCY_BUCKETS_MS).observe(rec.tpot_ms)
        if rec.e2e_ms is not None:
            self._reg.histogram("serve/e2e_ms",
                                LATENCY_BUCKETS_MS).observe(rec.e2e_ms)
        if self.trace is not None:
            self.trace.append(rec)
        if self.slo is not None:
            self.slo.observe(rec)

    def _finish_reason(self, st: _Active, tok: int) -> Optional[str]:
        req = st.request
        if req.eos_token is not None and tok == req.eos_token:
            return "eos"
        if len(st.generated) >= req.max_new_tokens:
            return "length"
        if st.position >= self.engine.max_len:
            return "capacity"
        return None

    def _record(self, tok: int, st: _Active, slot: int, now: float,
                is_tick: bool) -> None:
        st.generated.append(tok)
        st.position += 1
        self._tokens[slot] = tok
        self._tok_count += 1
        st.record.last_token_t = now
        if is_tick and self.trace is not None:
            st.record.decode_ts.append(now)
        reason = self._finish_reason(st, tok)
        if reason is not None:
            self._retire(slot, reason, now)

    def _admit(self) -> int:
        admitted = 0
        while self.queue and self.free:
            req, rec = self.queue.popleft()
            slot = self.free.pop()
            rec.admit_t = time.perf_counter()
            rec.slot = slot
            first = self.engine.prefill(req.prompt, slot, req.temperature)
            # prefill() syncs on the sampled token, so this stamp is the
            # honest first-token time (prefill-done == first-token: the
            # admission program samples it)
            rec.prefill_done_t = rec.first_token_t = time.perf_counter()
            st = _Active(req, [], len(req.prompt), rec)
            self.active[slot] = st
            self._temps[slot] = req.temperature
            self._reg.counter("serve/admitted").inc()
            self._reg.counter("serve/prefill_tokens").inc(len(req.prompt))
            admitted += 1
            # the prefill already sampled this request's first token —
            # it may even complete here (max_new_tokens == 1)
            self._record(first, st, slot, rec.first_token_t,
                         is_tick=False)
        return admitted

    def step(self) -> int:
        """Admit whatever fits, then run ONE decode step for the whole
        slot grid (skipped when nothing is active). Returns the number of
        tokens generated (prefill first-tokens included)."""
        if self._tok_t0 is None:
            self._tok_t0 = time.perf_counter()
        before = self._tok_count
        self._admit()
        if self.active:
            mask = np.zeros(self.engine.max_seqs, np.bool_)
            mask[list(self.active)] = True
            nxt = self.engine.decode(self._tokens, self._temps, mask)
            self._reg.counter("serve/decode_steps").inc()
            # ONE stamp for the whole grid's tick (decode() synced on
            # the fetched tokens) — the per-transition overhead contract
            now = time.perf_counter()
            # snapshot: _record may retire and free slots mid-harvest
            for slot in list(self.active):
                self._record(int(nxt[slot]), self.active[slot], slot, now,
                             is_tick=True)
        generated = self._tok_count - before
        self._reg.counter("serve/generated_tokens").inc(generated)
        self._reg.gauge("serve/queue_depth").set(len(self.queue))
        self._reg.gauge("serve/active_slots").set(len(self.active))
        elapsed = time.perf_counter() - self._tok_t0
        if elapsed > 0:
            self._reg.gauge("serve/tokens_per_sec").set(
                self._tok_count / elapsed)
        return generated

    def drain_completed(self) -> List[Completion]:
        """Pop and return the completion buffer. A long-lived server
        driving :meth:`step` must drain this — completions (with their
        full token lists) accumulate until collected."""
        out, self.completed = self.completed, []
        return out

    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None,
            no_recompile: bool = False) -> Dict[int, Completion]:
        """Submit ``requests``, loop :meth:`step` until all complete (or
        ``max_steps``), and return ``{request_id: Completion}`` for the
        completions of THIS run (requests finishing during it —
        including ones submitted before the call); earlier runs' results
        stay in :attr:`completed` until drained.

        ``no_recompile=True`` wraps the loop in the analysis engine's
        :class:`~apex_tpu.analysis.program.recompile_guard`: after the
        first (warmup) iteration, any movement of the compile-storm
        counters raises ``AnalysisError`` — the serving loop's
        zero-recompile contract as a live assertion instead of a test-
        only one (the three programs are AOT-compiled at engine
        construction, so steady-state steps must never trace)."""
        from contextlib import nullcontext

        if no_recompile:
            from apex_tpu.analysis.program import recompile_guard
            guard = recompile_guard("SlotScheduler.run")
        else:
            guard = nullcontext()
        n0 = len(self.completed)
        for r in requests:
            self.submit(r)
        steps = 0
        with guard:
            while self.pending:
                self.step()
                steps += 1
                if no_recompile and steps == 1:
                    guard.rebase()  # first-dispatch host paths warmed
                if max_steps is not None and steps >= max_steps:
                    break
        return {c.request_id: c for c in self.completed[n0:]}
