"""Continuous slot batching: admit into freed slots, retire mid-flight.

The classic serving loop has a batch barrier — requests grouped into a
batch enter together and the batch ends when its LAST member finishes,
so every short sequence idles its slot while the longest one drags on.
This scheduler has none: the decode program always steps all
``max_seqs`` slots (fixed shape, zero recompiles), and between steps the
host admits queued requests into whatever slots just freed and retires
whatever finished — a sequence occupies hardware for exactly its own
lifetime. Occupancy under load approaches 100% of slots instead of the
~50% a barrier averages on mixed-length traffic.

Host-side state is deliberately tiny (per-slot last token, temperature,
budget counters); everything sequence-shaped lives in the device cache
behind its write cursor. The loop emits the ``serve/*`` host-registry
metric family (docs/OBSERVABILITY.md) each step.

**Request lifecycle.** Every request carries a
:class:`~apex_tpu.observability.reqtrace.RequestRecord`: ``submit``
stamps the enqueue time, admission/prefill/decode/retire each stamp one
``time.perf_counter()`` per transition (the WHOLE hot-loop tracing
overhead — the device programs are untouched), so completions report
measured ``queue_wait_ms``/``ttft_ms``/``tpot_ms``/``e2e_ms`` and the
registry grows the matching ``serve/*`` latency histograms. Attaching a
:class:`~apex_tpu.observability.reqtrace.RequestTrace` (``trace=``)
additionally keeps retired records in its ring buffer (with per-tick
timestamps) for the Chrome-trace export; an
:class:`~apex_tpu.observability.slo.SLOTracker` (``slo=``) ingests each
retirement for goodput/burn-rate. Both default off and neither adds
device work (asserted in ``tests/test_reqtrace.py``).

**Resilience** (docs/SERVING.md "Resilience"; the policy objects live in
:mod:`apex_tpu.serving.resilience`): ``max_queue=`` bounds admission —
an over-limit ``submit`` returns a typed
:class:`~apex_tpu.serving.resilience.Rejection` instead of growing the
queue without bound; ``default_deadline_ms=`` / per-request
``deadline_ms`` expire requests while queued and mid-flight
(``finish_reason="expired"``) and :meth:`~SlotScheduler.cancel` removes
one by id; a quarantine engine retires a NaN-poisoned slot alone
(``finish_reason="poisoned"``, CrashDump flight record); ``brownout=``
sheds or caps admissions at SLO burn rate > 1; :meth:`~SlotScheduler
.drain` + :meth:`~SlotScheduler.swap_params` roll weights with zero
recompiles; ``fault_plan=`` scripts deterministic serving chaos
(:class:`~apex_tpu.elastic.faults.FaultPlan` ``poison_logits`` /
``slow_decode_s``). All host-side: every feature off leaves the three
AOT programs byte-identical (``tests/test_resilience.py``).

**Speculative decoding** (docs/SERVING.md "Speculative decoding"):
``speculate_k=k`` drives the engine's AOT ``verify`` program instead of
``decode`` — a host-side :class:`DraftSource` (default
:class:`NGramDraftSource`, prompt-lookup self-drafting, zero compiles)
proposes ``k`` tokens per active slot, one program dispatch scores the
whole window against the cached prefix, and each slot emits its
accepted prefix plus one correction/bonus token — 1 to ``k + 1`` tokens
per step. Greedy slots emit streams bitwise-identical to non-speculative
greedy; the ``serve/spec_*`` metric family tracks the acceptance rate
that decides whether ``k`` pays.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from apex_tpu.observability import get_registry
from apex_tpu.observability.reqtrace import (LATENCY_BUCKETS_MS,
                                             RequestRecord)
from apex_tpu.serving.cache import PoolExhausted
from apex_tpu.serving.resilience import Rejection

__all__ = ["Request", "Completion", "SlotScheduler", "DraftSource",
           "NGramDraftSource"]


class DraftSource:
    """Interface a speculative draft proposer implements: given a slot's
    full token context (prompt + everything generated so far), propose
    the next ``k`` tokens. Runs on the HOST between steps — a draft
    source never touches the compiled programs, so swapping sources (or
    later, backing one with a small draft model) is free of recompiles.
    Drafts are a pure throughput hint: a wrong draft costs its slot the
    rejected rows' compute, never correctness (the verify step's
    acceptance rule guarantees the output distribution)."""

    def draft(self, context: Sequence[int], k: int) -> List[int]:
        """Return exactly ``k`` proposed tokens to follow ``context``
        (``context`` is never empty — the prompt admitted)."""
        raise NotImplementedError


class NGramDraftSource(DraftSource):
    """Prompt-lookup / n-gram self-drafting (the zero-model draft
    source): find the longest suffix of the context — up to
    ``max_ngram`` tokens — that also occurred EARLIER in the context,
    and propose the ``k`` tokens that followed its most recent earlier
    occurrence (padded by repeating the last proposal when the match
    sits near the end). No match falls back to repeating the last
    context token. Repetitive text (code, templated prose, retrieval
    contexts) accepts most of these drafts; adversarially random text
    accepts few — the ``serve/spec_accept_rate`` gauge is the knob
    watcher."""

    def __init__(self, max_ngram: int = 3):
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.max_ngram = int(max_ngram)

    def draft(self, context: Sequence[int], k: int) -> List[int]:
        ctx = [int(t) for t in context]
        n = len(ctx)
        for m in range(min(self.max_ngram, n - 1), 0, -1):
            suffix = ctx[n - m:]
            for start in range(n - m - 1, -1, -1):
                if ctx[start:start + m] == suffix:
                    out = ctx[start + m:start + m + k]
                    while len(out) < k:
                        out.append(out[-1])
                    return out
        return [ctx[-1]] * k


@dataclasses.dataclass
class Request:
    """One generation request. ``temperature`` <= 0 is greedy;
    ``eos_token`` (optional) stops generation early; ``max_new_tokens``
    always bounds it. ``deadline_ms`` (optional, > 0, measured from
    submission) expires the request both while queued and mid-flight —
    the scheduler's ``default_deadline_ms`` applies when None."""
    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    eos_token: Optional[int] = None
    request_id: Optional[int] = None
    deadline_ms: Optional[float] = None


@dataclasses.dataclass
class Completion:
    """A finished request: the generated tokens (prompt excluded), why
    generation stopped (``"eos"`` | ``"length"`` | ``"capacity"`` |
    ``"expired"`` | ``"cancelled"`` | ``"poisoned"`` | ``"error"``), and
    the measured per-request latencies — ``queue_wait_ms`` (submit →
    slot), ``ttft_ms`` (submit → first token, queue wait included),
    ``tpot_ms`` (mean per-token after the first; None for single-token
    requests), ``e2e_ms`` (submit → retire). A request retired before
    admission (queued expiry/cancel) has no slot-side latencies and an
    empty token list."""
    request_id: int
    tokens: List[int]
    finish_reason: str
    queue_wait_ms: Optional[float] = None
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    e2e_ms: Optional[float] = None


@dataclasses.dataclass
class _Active:
    request: Request
    generated: List[int]
    position: int            # prompt_len + len(generated), vs cache capacity
    record: RequestRecord
    deadline_t: Optional[float] = None  # perf_counter seconds, absolute


# retirement reasons with their own dedicated counter next to the
# aggregate serve/retired (docs/OBSERVABILITY.md)
_REASON_COUNTERS = {"expired": "serve/expired",
                    "cancelled": "serve/cancelled",
                    "poisoned": "serve/poisoned",
                    "error": "serve/errors"}


class SlotScheduler:
    """See module docstring. Drive it with :meth:`submit` + :meth:`step`
    (one decode step per call), or :meth:`run` for a closed batch.

    ``trace`` (optional :class:`RequestTrace`) keeps retired request
    records in a bounded ring for Chrome-trace export / flight-recorder
    dumps; ``slo`` (optional :class:`SLOTracker`) ingests each
    retirement. With both None the only lifecycle cost left is one
    timestamp per transition — the latency fields on completions and the
    ``serve/*_ms`` histograms are always real measurements.

    Resilience knobs (all optional; see the module docstring and
    docs/SERVING.md "Resilience"): ``max_queue`` (admission bound),
    ``default_deadline_ms`` (deadline for requests that set none),
    ``brownout`` (a :class:`~apex_tpu.serving.resilience
    .BrownoutPolicy`), ``fault_plan`` (a :class:`~apex_tpu.elastic
    .faults.FaultPlan` with serving faults — a poison plan requires a
    quarantine engine and is refused here otherwise), ``dump_dir``
    (where poison-quarantine CrashDumps land).

    ``speculate_k=k`` (with an engine built ``speculate_k=k`` — the
    static window must agree) switches the loop onto the engine's AOT
    ``verify`` program: ``draft_source`` (default
    :class:`NGramDraftSource`) proposes ``k`` tokens per slot on the
    host, one dispatch verifies them all, and slots emit 1 to ``k + 1``
    tokens per step. Every other knob composes unchanged — deadlines and
    quarantine can retire a slot mid-harvest (the cursor only ever
    advanced by the accepted count, so nothing needs rolling back) and
    paged pool exhaustion retires the starved slot loudly."""

    def __init__(self, engine, registry=None, trace=None, slo=None, *,
                 max_queue: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 brownout=None, fault_plan=None, dump_dir: str = ".",
                 speculate_k: int = 0,
                 draft_source: Optional[DraftSource] = None):
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if speculate_k:
            if getattr(engine, "speculate_k", 0) != speculate_k:
                raise ValueError(
                    f"speculate_k={speculate_k} but the engine compiled "
                    f"speculate_k={getattr(engine, 'speculate_k', 0)} — "
                    "the verify program's window is static, so the "
                    "scheduler and engine must agree at construction")
        elif draft_source is not None:
            raise ValueError(
                "draft_source without speculate_k — pass speculate_k=k "
                "(matching the engine's) to enable speculative decoding")
        if default_deadline_ms is not None and default_deadline_ms <= 0:
            raise ValueError("default_deadline_ms must be positive, "
                             f"got {default_deadline_ms}")
        if (fault_plan is not None
                and getattr(fault_plan, "poison_logits", None)
                and not engine.quarantine):
            raise ValueError(
                "fault_plan schedules poison_logits but the engine has "
                "no quarantine check compiled in — the fault would be "
                "silently dropped; build the engine with quarantine=True")
        self.engine = engine
        self._reg = registry if registry is not None else get_registry()
        self.trace = trace
        self.slo = slo
        self.max_queue = max_queue
        self.default_deadline_ms = default_deadline_ms
        self.brownout = brownout
        self.fault_plan = fault_plan
        self.dump_dir = dump_dir
        self.speculate_k = int(speculate_k)
        self.draft_source = draft_source if draft_source is not None \
            else (NGramDraftSource() if speculate_k else None)
        self._spec_drafted = 0
        self._spec_accepted = 0
        self.queue: collections.deque = collections.deque()
        self.free: List[int] = list(range(engine.max_seqs))[::-1]
        self.active: Dict[int, _Active] = {}
        self.completed: List[Completion] = []
        self.steps = 0              # decode steps executed (fault keying)
        self.poison_dumps: List[str] = []
        self._tokens = np.zeros(engine.max_seqs, np.int32)
        self._temps = np.zeros(engine.max_seqs, np.float32)
        self._next_id = 0
        self._in_flight_ids = set()
        self._draining = False
        # deadline-free schedulers skip the per-step queue walk entirely
        self._any_deadlines = default_deadline_ms is not None
        self._tok_count = 0
        self._tok_t0: Optional[float] = None
        # paged engines only: the allocator's monotonic COW counter at
        # the last step, so serve/blocks_cow_copied emits deltas
        self._cow_seen = 0

    # -- submission ---------------------------------------------------------

    def submit(self, request: Request) -> Union[int, Rejection]:
        """Enqueue ``request`` and return its id — or a falsy typed
        :class:`Rejection` under backpressure (``queue_full`` at the
        ``max_queue`` bound, ``shed`` from the brownout policy,
        ``draining`` during :meth:`drain`). Malformed input still
        RAISES: a load condition is the server's problem, a bad request
        is the caller's."""
        # validate HERE, not at admission: a bad request must bounce off
        # the caller, never kill the serving loop mid-step (by then it
        # has already been popped from the queue and other admissions
        # are half-done)
        if len(request.prompt) == 0:
            raise ValueError("empty prompt")
        if len(request.prompt) > self.engine.prefill_len:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds the "
                f"engine's prefill window {self.engine.prefill_len}")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{request.max_new_tokens} (the prefill always samples "
                "one token)")
        if request.deadline_ms is not None and request.deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be positive, got "
                f"{request.deadline_ms} (None means no deadline)")
        if (request.request_id is not None
                and request.request_id in self._in_flight_ids):
            raise ValueError(
                f"request_id {request.request_id} is already in flight "
                "(queued or active) — completions are keyed by id, so a "
                "duplicate would make one of them unaccountable")
        # backpressure: typed rejections, never unbounded growth
        if self._draining:
            self._reg.counter("serve/rejected").inc()
            return Rejection("draining", request.request_id,
                             "scheduler is draining in-flight requests")
        if (self.max_queue is not None
                and len(self.queue) >= self.max_queue):
            self._reg.counter("serve/rejected").inc()
            return Rejection("queue_full", request.request_id,
                             f"queue at max_queue={self.max_queue}")
        alloc = getattr(self.engine, "allocator", None)
        if alloc is not None:
            # paged admission control: a prompt that could never fit
            # the WHOLE pool is refused up front (queueing it would
            # deadlock the queue head forever); transient pressure —
            # blocks held by in-flight sequences — queues instead and
            # _admit waits for retirements to free blocks
            need = alloc.blocks_for(len(request.prompt))
            if need > alloc.num_blocks - 1:
                self._reg.counter("serve/rejected").inc()
                return Rejection(
                    "pool_exhausted", request.request_id,
                    f"prompt needs {need} blocks but the pool only has "
                    f"{alloc.num_blocks - 1} allocatable")
        if self.brownout is not None:
            engaged = self.brownout.engaged()
            self._reg.gauge("serve/brownout").set(1.0 if engaged else 0.0)
            if engaged:
                if self.brownout.shed:
                    self._reg.counter("serve/shed").inc()
                    return Rejection(
                        "shed", request.request_id,
                        "SLO burn rate over the brownout threshold")
                capped = self.brownout.cap(request.max_new_tokens)
                if capped != request.max_new_tokens:
                    # cap a COPY: the caller's Request must not carry a
                    # transient brownout's truncation to its retries or
                    # to another replica
                    request = dataclasses.replace(
                        request, max_new_tokens=capped)
        if request.request_id is None:
            request.request_id = self._next_id
        self._next_id = max(self._next_id, request.request_id) + 1
        self._in_flight_ids.add(request.request_id)
        if request.deadline_ms is not None:
            self._any_deadlines = True
        # the enqueue stamp: queue wait is measured from here, not
        # inferred from admission order
        record = RequestRecord(request_id=request.request_id,
                               prompt_len=len(request.prompt),
                               submit_t=time.perf_counter())
        self.queue.append((request, record))
        return request.request_id

    @property
    def pending(self) -> int:
        return len(self.queue) + len(self.active)

    @property
    def draining(self) -> bool:
        return self._draining

    def _deadline_t(self, request: Request,
                    record: RequestRecord) -> Optional[float]:
        ms = request.deadline_ms if request.deadline_ms is not None \
            else self.default_deadline_ms
        return None if ms is None else record.submit_t + ms / 1e3

    # -- the loop -----------------------------------------------------------

    def _retire(self, slot: int, reason: str, now: float) -> None:
        st = self.active.pop(slot)
        # zero the cursor: an idle slot left deep in the cache would keep
        # paying full-prefix attention on every later decode step
        release_exc = None
        try:
            self.engine.release_slot(slot)
        except Exception as exc:
            # the HOST bookkeeping below (record retired, slot freed,
            # completion visible, id released) must complete regardless
            # — popping from active and then raising would strand the
            # slot and the request forever. On the "error" path the
            # engine is already known-broken (the failed dispatch may
            # have consumed the donated cache) and the original fault
            # is what propagates; on every other path the release
            # failure itself re-raises AFTER the books are straight.
            if reason != "error":
                release_exc = exc
        self.free.append(slot)
        self._in_flight_ids.discard(st.request.request_id)
        rec = st.record
        rec.retire_t = now
        rec.finish_reason = reason
        rec.generated = len(st.generated)
        self.completed.append(Completion(
            st.request.request_id, st.generated, reason,
            queue_wait_ms=rec.queue_wait_ms, ttft_ms=rec.ttft_ms,
            tpot_ms=rec.tpot_ms, e2e_ms=rec.e2e_ms))
        self._reg.counter("serve/retired").inc()
        if reason in _REASON_COUNTERS:
            self._reg.counter(_REASON_COUNTERS[reason]).inc()
        if rec.queue_wait_ms is not None:
            self._reg.histogram("serve/queue_wait_ms",
                                LATENCY_BUCKETS_MS).observe(
                                    rec.queue_wait_ms)
        if rec.ttft_ms is not None:
            self._reg.histogram("serve/ttft_ms",
                                LATENCY_BUCKETS_MS).observe(rec.ttft_ms)
        if rec.tpot_ms is not None:
            self._reg.histogram("serve/tpot_ms",
                                LATENCY_BUCKETS_MS).observe(rec.tpot_ms)
        if rec.e2e_ms is not None:
            self._reg.histogram("serve/e2e_ms",
                                LATENCY_BUCKETS_MS).observe(rec.e2e_ms)
        if self.trace is not None:
            self.trace.append(rec)
        if self.slo is not None:
            self.slo.observe(rec)
        if release_exc is not None:
            raise release_exc

    def _retire_queued(self, request: Request, record: RequestRecord,
                       reason: str, now: float) -> None:
        """Retire a request that never reached a slot (queued expiry or
        cancel): no slot-side latencies, empty token list, NOT counted
        as ``serve/retired`` (that counter means "slot freed") but under
        the reason's own counter; still observed by the trace ring and
        the SLO tracker (an expired request is a served-badly request —
        it must hurt goodput, not vanish from it)."""
        record.retire_t = now
        record.finish_reason = reason
        self._in_flight_ids.discard(request.request_id)
        self.completed.append(Completion(
            request.request_id, [], reason, e2e_ms=record.e2e_ms))
        if reason in _REASON_COUNTERS:
            self._reg.counter(_REASON_COUNTERS[reason]).inc()
        if self.trace is not None:
            self.trace.append(record)
        if self.slo is not None:
            self.slo.observe(record)

    def _expire_queued(self, now: float) -> None:
        if not self._any_deadlines:
            return  # nothing queued can ever expire: skip the walk
        kept: collections.deque = collections.deque()
        while self.queue:
            req, rec = self.queue.popleft()
            deadline = self._deadline_t(req, rec)
            if deadline is not None and now >= deadline:
                self._retire_queued(req, rec, "expired", now)
            else:
                kept.append((req, rec))
        self.queue = kept

    def _quarantine(self, slot: int, now: float) -> None:
        """Retire ONLY the poisoned slot (``finish_reason="poisoned"``,
        cursor zeroed through the same AOT release program as any
        retirement) and write a CrashDump-style flight record — the
        serving twin of the health monitor's non-finite dump. Every
        other slot keeps decoding untouched (the isolation contract:
        their greedy streams are identical to a fault-free run)."""
        from apex_tpu.observability.health import CrashDump

        st = self.active[slot]
        rec = st.record
        self._retire(slot, "poisoned", now)
        records = ([r.to_dict() for r in self.trace.last(16)]
                   if self.trace is not None else [rec.to_dict()])
        dump = CrashDump.from_payload(self.steps, dict(self._reg.snapshot()),
                                      requests=records)
        dump.config = {"slot": int(slot),
                       "request_id": int(st.request.request_id),
                       "prompt_len": int(rec.prompt_len),
                       "generated": int(rec.generated),
                       "finish_reason": "poisoned"}
        self.poison_dumps.append(dump.write(self.dump_dir,
                                            prefix="poison_dump"))

    def _abort_in_flight(self) -> None:
        """Exception-safety cleanup: a decode/prefill dispatch raised,
        so every in-flight request is retired ``finish_reason="error"``
        (records stamped, slots released where the engine still can,
        completions visible) before the error propagates — nothing is
        stranded in ``active`` holding a slot forever."""
        now = time.perf_counter()
        for slot in list(self.active):
            self._retire(slot, "error", now)

    def _finish_reason(self, st: _Active, tok: int) -> Optional[str]:
        req = st.request
        if req.eos_token is not None and tok == req.eos_token:
            return "eos"
        if len(st.generated) >= req.max_new_tokens:
            return "length"
        if st.position >= self.engine.max_len:
            return "capacity"
        return None

    def _record(self, tok: int, st: _Active, slot: int, now: float,
                is_tick: bool) -> None:
        st.generated.append(tok)
        st.position += 1
        self._tokens[slot] = tok
        self._tok_count += 1
        st.record.last_token_t = now
        if is_tick and self.trace is not None:
            st.record.decode_ts.append(now)
        reason = self._finish_reason(st, tok)
        if reason is not None:
            self._retire(slot, reason, now)

    def _build_drafts(self) -> np.ndarray:
        """The host drafting pass: one :meth:`DraftSource.draft` call
        per active slot over its full context (prompt + generated).
        Free slots draft zeros — their verify rows are masked inactive
        and their counts come back 0."""
        drafts = np.zeros((self.engine.max_seqs, self.speculate_k),
                          np.int32)
        for slot, st in self.active.items():
            ctx = list(st.request.prompt) + st.generated
            drafts[slot] = self.draft_source.draft(ctx, self.speculate_k)
        return drafts

    def _admit(self) -> int:
        admitted = 0
        while self.queue and self.free:
            req, rec = self.queue.popleft()
            now = time.perf_counter()
            deadline = self._deadline_t(req, rec)
            if deadline is not None and now >= deadline:
                # expired while waiting: never spend a prefill on it
                self._retire_queued(req, rec, "expired", now)
                continue
            if (hasattr(self.engine, "can_admit")
                    and not self.engine.can_admit(req.prompt)):
                # paged block-pool pressure: the blocks exist (submit
                # bounds the prompt to the pool) but in-flight
                # sequences hold them — requeue at the head and wait
                # for retirements to free blocks
                self.queue.appendleft((req, rec))
                break
            slot = self.free.pop()
            rec.admit_t = now
            rec.slot = slot
            try:
                first = self.engine.prefill(req.prompt, slot,
                                            req.temperature)
            except PoolExhausted:
                # can_admit is conservative but the shared-path COW
                # headroom can still miss by a block under extreme
                # pressure: requeue, never error-retire (host rolled
                # the partial allocation back)
                self.free.append(slot)
                self.queue.appendleft((req, rec))
                break
            except Exception:
                # the popped request must not vanish: retire it as an
                # error (host bookkeeping only — the slot never held a
                # cursor) and surface the engine fault to the caller
                self.free.append(slot)
                self._retire_queued(req, rec, "error", now)
                raise
            # prefill() syncs on the sampled token, so this stamp is the
            # honest first-token time (prefill-done == first-token: the
            # admission program samples it)
            rec.prefill_done_t = rec.first_token_t = time.perf_counter()
            st = _Active(req, [], len(req.prompt), rec,
                         deadline_t=deadline)
            self.active[slot] = st
            self._temps[slot] = req.temperature
            self._reg.counter("serve/admitted").inc()
            self._reg.counter("serve/prefill_tokens").inc(len(req.prompt))
            plan = getattr(self.engine, "last_admit", None)
            if plan is not None and not plan.prefill:
                # a prefix-shared admission: the shared span skipped
                # prefill entirely — serve/ttft_prefix_ms is the TTFT
                # histogram the acceptance bar compares against the
                # cold serve/ttft_ms population
                self._reg.counter("serve/prefix_hits").inc()
                self._reg.counter("serve/prefix_hit_tokens").inc(
                    plan.shared_tokens)
                self._reg.histogram("serve/ttft_prefix_ms",
                                    LATENCY_BUCKETS_MS).observe(
                    (rec.first_token_t - rec.admit_t) * 1e3)
            admitted += 1
            # the prefill already sampled this request's first token —
            # it may even complete here (max_new_tokens == 1)
            self._record(first, st, slot, rec.first_token_t,
                         is_tick=False)
        return admitted

    def step(self) -> int:
        """Expire what's overdue, admit whatever fits (skipped while
        draining), then run ONE decode step for the whole slot grid
        (skipped when nothing is active). Returns the number of tokens
        generated (prefill first-tokens included).

        Exception safety: a raised engine fault retires every in-flight
        request ``finish_reason="error"`` (slots released, records
        stamped, completions visible) before re-raising — a dead decode
        never strands ``active`` state."""
        if self._tok_t0 is None:
            self._tok_t0 = time.perf_counter()
        before = self._tok_count
        self._expire_queued(time.perf_counter())
        try:
            if not self._draining:
                self._admit()
            if self.active:
                # satellite of the paged PR: a slot AT capacity must
                # retire loudly BEFORE the decode dispatch — its append
                # would be dropped (KVCache.append writes nothing at
                # max_len; the paged pool has no block to give), so one
                # more step would sample a token whose KV never landed
                now = time.perf_counter()
                for slot in list(self.active):
                    if self.active[slot].position >= self.engine.max_len:
                        self._retire(slot, "capacity", now)
            if self.active:
                step_idx = self.steps + 1  # this decode step, 1-based
                poison = None
                if self.fault_plan is not None:
                    self.fault_plan.before_decode(step_idx)
                    pslot = self.fault_plan.poison_slot(step_idx)
                    if pslot is not None:
                        poison = np.zeros(self.engine.max_seqs,
                                          np.float32)
                        poison[pslot] = np.nan
                mask = np.zeros(self.engine.max_seqs, np.bool_)
                mask[list(self.active)] = True
                counts = None
                if self.speculate_k:
                    nxt, counts = self.engine.verify(
                        self._tokens, self._build_drafts(), self._temps,
                        mask, poison=poison)
                else:
                    nxt = self.engine.decode(self._tokens, self._temps,
                                             mask, poison=poison)
                self.steps = step_idx
                self._reg.counter("serve/decode_steps").inc()
                finite = (self.engine.last_finite
                          if self.engine.quarantine else None)
                # ONE stamp for the whole grid's tick (decode() synced on
                # the fetched tokens) — the per-transition overhead
                # contract
                now = time.perf_counter()
                if counts is not None:
                    self._reg.counter("serve/spec_steps").inc()
                    drafted = int(mask.sum()) * self.speculate_k
                    self._spec_drafted += drafted
                    self._reg.counter("serve/spec_drafted").inc(drafted)
                # snapshot: _record may retire and free slots mid-harvest
                accepted = 0
                for slot in list(self.active):
                    if finite is not None and not finite[slot]:
                        # the poison-slot quarantine: retire ONLY this
                        # slot; its sampled token is garbage-from-NaN and
                        # is discarded, every neighbor harvests normally
                        self._quarantine(slot, now)
                        continue
                    if counts is None:
                        self._record(int(nxt[slot]), self.active[slot],
                                     slot, now, is_tick=True)
                        continue
                    # speculative harvest: the accepted prefix plus one
                    # correction/bonus token. The engine already advanced
                    # this slot's cursor by EXACTLY counts[slot], so a
                    # retirement mid-harvest (eos / length / capacity)
                    # abandons only tokens whose KV sits above the
                    # cursor — a re-admitted slot can never read a
                    # drafted-but-rejected entry
                    accepted += max(0, int(counts[slot]) - 1)
                    st = self.active[slot]
                    for j in range(int(counts[slot])):
                        self._record(int(nxt[slot, j]), st, slot, now,
                                     is_tick=True)
                        if slot not in self.active:
                            break
                if counts is not None:
                    self._spec_accepted += accepted
                    if accepted:
                        self._reg.counter("serve/spec_accepted").inc(
                            accepted)
                    if self._spec_drafted:
                        self._reg.gauge("serve/spec_accept_rate").set(
                            self._spec_accepted / self._spec_drafted)
                # paged engines: slots the exhausted pool could not
                # give a write block retire loudly as "capacity" — this
                # step's sampled token is valid (the kernel merges the
                # current token in-flight) but its KV was dropped, so
                # one more step would decode against a hole. On the
                # speculative path a failed slot's window aimed at the
                # null block and its count came back 0, so it emitted
                # nothing this step before retiring
                for slot in getattr(self.engine, "last_failed", ()):
                    if slot in self.active:
                        self._retire(slot, "capacity", now)
                # mid-flight deadline enforcement: overdue survivors of
                # the harvest retire now, slot released for the next
                # admission
                for slot in list(self.active):
                    st = self.active[slot]
                    if st.deadline_t is not None and now >= st.deadline_t:
                        self._retire(slot, "expired", now)
        except Exception:
            self._abort_in_flight()
            raise
        generated = self._tok_count - before
        self._reg.counter("serve/generated_tokens").inc(generated)
        self._reg.gauge("serve/queue_depth").set(len(self.queue))
        self._reg.gauge("serve/active_slots").set(len(self.active))
        alloc = getattr(self.engine, "allocator", None)
        if alloc is not None:
            self._reg.gauge("serve/pool_blocks_free").set(
                alloc.free_blocks)
            # used + utilization next to free: free blocks alone cannot
            # separate fragmentation from load (block 0 is the reserved
            # null block, so allocatable capacity is num_blocks - 1)
            capacity = alloc.num_blocks - 1
            used = capacity - alloc.free_blocks
            self._reg.gauge("serve/pool_blocks_used").set(used)
            self._reg.gauge("serve/pool_utilization").set(
                used / capacity if capacity else 0.0)
            if alloc.cow_copies > self._cow_seen:
                self._reg.counter("serve/blocks_cow_copied").inc(
                    alloc.cow_copies - self._cow_seen)
                self._cow_seen = alloc.cow_copies
        elapsed = time.perf_counter() - self._tok_t0
        if elapsed > 0:
            self._reg.gauge("serve/tokens_per_sec").set(
                self._tok_count / elapsed)
        return generated

    # -- resilience surface -------------------------------------------------

    def cancel(self, request_id: int) -> bool:
        """Cancel one request by id, wherever it is: still queued (it
        just never admits) or mid-flight (retired now,
        ``finish_reason="cancelled"``, slot released). Returns False for
        an unknown/already-finished id — cancelling twice is a no-op,
        not an error (the client's disconnect usually races the
        completion)."""
        now = time.perf_counter()
        for i, (req, rec) in enumerate(self.queue):
            if req.request_id == request_id:
                del self.queue[i]
                self._retire_queued(req, rec, "cancelled", now)
                return True
        for slot, st in list(self.active.items()):
            if st.request.request_id == request_id:
                self._retire(slot, "cancelled", now)
                return True
        return False

    def drain(self, deadline_s: Optional[float] = None
              ) -> Dict[int, Completion]:
        """Graceful drain: stop admitting (concurrent :meth:`submit`
        calls get ``Rejection(reason="draining")``), keep stepping until
        every IN-FLIGHT request finishes, and return this drain's
        completions. Queued requests stay queued — after a weight swap
        they are served by the new weights, which is the rollover point
        of draining at all. ``deadline_s`` bounds the wait: leftovers
        retire ``finish_reason="expired"`` when it runs out — the drain
        budget is a deadline the SERVER imposed, so these are
        server-side failures that count against goodput
        (:data:`~apex_tpu.observability.slo.FAILED_REASONS`), unlike a
        user's :meth:`cancel`. Admission resumes when the method
        returns (``serve/drains`` counts calls)."""
        self._draining = True
        t0 = time.perf_counter()
        n0 = len(self.completed)
        try:
            while self.active:
                if (deadline_s is not None
                        and time.perf_counter() - t0 >= deadline_s):
                    now = time.perf_counter()
                    for slot in list(self.active):
                        self._retire(slot, "expired", now)
                    break
                self.step()
        finally:
            self._draining = False
        self._reg.counter("serve/drains").inc()
        return {c.request_id: c for c in self.completed[n0:]}

    def swap_params(self, new_params) -> None:
        """Hot weight swap through :meth:`ServingEngine.swap_params`
        (zero recompiles, structure/shape/dtype-checked, donation
        re-linted), counted as ``serve/swaps``. Safe mid-:meth:`run`:
        in-flight requests keep their old-weight KV prefix and finish
        under the new weights; call :meth:`drain` first for a clean
        generation boundary."""
        self.engine.swap_params(new_params)
        self._reg.counter("serve/swaps").inc()

    def drain_completed(self) -> List[Completion]:
        """Pop and return the completion buffer. A long-lived server
        driving :meth:`step` must drain this — completions (with their
        full token lists) accumulate until collected."""
        out, self.completed = self.completed, []
        return out

    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None,
            no_recompile: bool = False) -> Dict[int, Completion]:
        """Submit ``requests``, loop :meth:`step` until all complete (or
        ``max_steps``), and return ``{request_id: Completion}`` for the
        completions of THIS run (requests finishing during it —
        including ones submitted before the call); earlier runs' results
        stay in :attr:`completed` until drained.

        Backpressure: a closed batch knows the rest of its work, so a
        ``queue_full`` rejection PACES the run — the request waits
        host-side and resubmits as the queue drains (the queue bound
        still holds throughout; silently dropping work a later step
        could serve would be a shedding decision the caller never
        made). ``shed``/``draining`` rejections are final and the
        request is dropped (counted on ``serve/shed``/``serve/
        rejected``), exactly as for a live ``submit`` caller.

        ``no_recompile=True`` wraps the loop in the analysis engine's
        :class:`~apex_tpu.analysis.program.recompile_guard`: after the
        first (warmup) iteration, any movement of the compile-storm
        counters raises ``AnalysisError`` — the serving loop's
        zero-recompile contract as a live assertion instead of a test-
        only one (the three programs are AOT-compiled at engine
        construction, so steady-state steps must never trace)."""
        from contextlib import nullcontext

        if no_recompile:
            from apex_tpu.analysis.program import recompile_guard
            guard = recompile_guard("SlotScheduler.run")
        else:
            guard = nullcontext()
        n0 = len(self.completed)
        waiting = collections.deque(requests)

        def feed():
            while waiting:
                if (self.max_queue is not None
                        and len(self.queue) >= self.max_queue):
                    # wait for the next step to drain the queue WITHOUT
                    # probing submit(): a paced retry is not a refused
                    # submission, so it must not tick serve/rejected
                    return
                res = self.submit(waiting[0])
                if isinstance(res, Rejection) \
                        and res.reason == "queue_full":
                    return  # raced the bound: resubmit after a step
                waiting.popleft()  # admitted, or finally rejected

        feed()
        steps = 0
        with guard:
            while self.pending or waiting:
                self.step()
                feed()
                steps += 1
                if no_recompile and steps == 1:
                    guard.rebase()  # first-dispatch host paths warmed
                if max_steps is not None and steps >= max_steps:
                    break
        return {c.request_id: c for c in self.completed[n0:]}
