"""AOT-compiled prefill/decode steps with a donated KV cache.

The engine owns the cache and the two compiled programs a serving
process runs forever:

- **prefill**: one request's padded prompt ``(1, prefill_len)`` through
  the ordinary causal forward (the training flash path), K/V written
  into one cache slot, the first output token sampled from the logits at
  the prompt's true last position;
- **decode**: ONE token for EVERY slot ``(max_seqs, 1)`` through the
  decode attention kernel, K/V appended at each slot's cursor, next
  tokens sampled.

Both are ``jax.jit(..., donate_argnums=<cache>)`` and compiled ONCE at
construction (``.trace().lower().compile()`` — the bench/test AOT
convention), which buys the two serving-latency properties the tests
pin down:

- **zero allocation**: the cache buffers are donated and every write is
  a fixed-position dynamic_update_slice, so XLA aliases them in place
  (``input_output_alias`` asserted over every cache leaf in
  ``tests/test_serving.py``) — a decode step never copies the cache;
- **zero recompilation**: every per-request quantity is an array
  argument (tokens, temperatures, cursors-in-cache) and every
  shape-changing knob is fixed at construction (``max_seqs``,
  ``prefill_len``, ``top_k``), so admission/retirement never retraces —
  the compile-storm counters (PR 1) are asserted flat across steps.

Capacity: :meth:`ServingEngine.suggest_max_seqs` turns the compiled
decode step's static memory plan (``observability/costs.memory_budget``)
into "how many concurrent sequences fit this chip's HBM" — the
ROADMAP's cache-capacity accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.observability.costs import memory_budget
from apex_tpu.serving.cache import (KVCache, PagedKVCache, BlockAllocator,
                                    AdmitPlan, PoolExhausted,
                                    cache_bytes_per_slot, paged_block_bytes)
from apex_tpu.serving.sampling import sample_tokens, verify_tokens

__all__ = ["ServingEngine", "PagedServingEngine"]


class ServingEngine:
    """See module docstring.

    Args:
      model: a :class:`~apex_tpu.models.gpt.GPTModel` (tp=1, no SP).
      params: its :meth:`init` pytree.
      max_seqs: concurrent sequence slots (the decode batch width).
      max_len: per-slot cache capacity in tokens (<= the model's
        ``max_position_embeddings``).
      prefill_len: the fixed prompt window; prompts are right-padded to
        it (longer prompts are rejected — one bucket keeps this PR's
        program count at two).
      cache_dtype: ``jnp.bfloat16`` (default) or ``jnp.int8`` (quantized
        cache with per-(position, head) scales).
      top_k: static top-k sampling cutoff (0 = full vocab).
      quarantine: compile the poison-slot quarantine check into the
        decode program — one per-slot ``isfinite`` reduction over the
        sampling-path logits (fused into the head matmul's consumers,
        no extra memory pass) plus a ``(max_seqs,)`` poison-injection
        array argument (NaN for a slot poisons its logits — the
        deterministic :class:`~apex_tpu.elastic.faults.FaultPlan`
        injection path, zero extra compiles). After each
        :meth:`decode`, :attr:`last_finite` carries the per-slot flags
        the scheduler's quarantine reads. Default off — the decode
        program is byte-identical to a quarantine-free engine's (the
        PR 3 zero-cost idiom, asserted in ``tests/test_resilience.py``).
      speculate_k: when > 0, compile a FOURTH AOT program — ``verify``
        — that scores each slot's last accepted token plus ``k``
        drafted tokens in ONE pass over the cached prefix
        (:meth:`~apex_tpu.models.gpt.GPTModel.verify_forward`), runs
        the acceptance rule
        (:func:`~apex_tpu.serving.sampling.verify_tokens`) and appends
        the whole window with a k-token cache write
        (:meth:`~apex_tpu.serving.cache.KVCache.append_k`). ``k`` is
        the only static knob; draft tokens, temperatures and the
        active mask are array arguments, so speculative serving keeps
        the zero-recompile contract. Default 0 — the engine is
        byte-identical to a pre-speculation one.
    """

    def __init__(self, model, params, *, max_seqs: int, max_len: int,
                 prefill_len: int, cache_dtype=jnp.bfloat16,
                 top_k: int = 0, rng_seed: int = 0,
                 quarantine: bool = False, speculate_k: int = 0):
        model._require_cacheable()
        cfg = model.cfg
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        if prefill_len > max_len:
            raise ValueError(f"prefill_len {prefill_len} exceeds max_len "
                             f"{max_len}")
        self.model = model
        self.params = params
        self.max_seqs = int(max_seqs)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.top_k = int(top_k)
        self.quarantine = bool(quarantine)
        self.speculate_k = int(speculate_k)
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, got {speculate_k}")
        if self.speculate_k + 1 > max_len:
            raise ValueError(
                f"speculate_k {speculate_k} needs a {speculate_k + 1}-token "
                f"verify window, which exceeds max_len {max_len}")
        self.last_finite: Optional[np.ndarray] = None
        self.swaps = 0
        self.cache = KVCache.create(
            cfg.num_layers, max_seqs, cfg.num_attention_heads, max_len,
            cfg.head_dim, dtype=cache_dtype)

        def prefill_step(params, cache, tokens, slot, true_len,
                         temperature, rng):
            with jax.named_scope("serve_prefill"):
                # last_logit_only: the admission samples exactly one row
                # of the head, so only that row is projected
                logits, cache = model.forward(params, tokens,
                                              kv_cache=cache, slot=slot,
                                              prompt_len=true_len,
                                              last_logit_only=True)
                tok = sample_tokens(logits[0], rng, temperature[None],
                                    self.top_k)[0]
            return cache, tok

        if self.quarantine:
            # the quarantine variant: one extra (S,) array argument
            # (``poison``, normally zeros — adding NaN to a slot's row is
            # the deterministic fault-injection path) and one extra
            # per-slot output (``finite``). Both ride the SAME compiled
            # program forever — injecting or clearing poison never
            # retraces. The finite reduction runs on the post-injection
            # sampling-path logits, so a NaN from ANY upstream source
            # (poisoned cache, bad weights, the injection arg) flags the
            # slot the very step it first reaches sampling.
            def decode_step(params, cache, tokens, temperature, active,
                            rng, poison):
                with jax.named_scope("serve_decode"):
                    logits, cache = model.forward(params, tokens[:, None],
                                                  kv_cache=cache,
                                                  active=active)
                    logits = logits + poison[:, None]
                    finite = jnp.all(jnp.isfinite(logits), axis=-1)
                    toks = sample_tokens(logits, rng, temperature,
                                         self.top_k)
                return cache, toks, finite
        else:
            def decode_step(params, cache, tokens, temperature, active,
                            rng):
                with jax.named_scope("serve_decode"):
                    logits, cache = model.forward(params, tokens[:, None],
                                                  kv_cache=cache,
                                                  active=active)
                    toks = sample_tokens(logits, rng, temperature,
                                         self.top_k)
                return cache, toks

        key = jax.random.PRNGKey(rng_seed)
        self._key, _ = jax.random.split(key)  # also warms split's compile
        S = self.max_seqs
        ex_tokens = jnp.zeros((1, self.prefill_len), jnp.int32)
        ex_scalar = jnp.zeros((), jnp.int32)
        ex_temp = jnp.zeros((), jnp.float32)
        self.prefill_traced = jax.jit(
            prefill_step, donate_argnums=(1,)).trace(
                params, self.cache, ex_tokens, ex_scalar, ex_scalar,
                ex_temp, self._key)
        self.prefill_compiled = self.prefill_traced.lower().compile()
        self._zero_poison = jnp.zeros((S,), jnp.float32)
        decode_args = (params, self.cache, jnp.zeros((S,), jnp.int32),
                       jnp.zeros((S,), jnp.float32),
                       jnp.ones((S,), jnp.bool_), self._key)
        if self.quarantine:
            decode_args += (self._zero_poison,)
        self.decode_traced = jax.jit(
            decode_step, donate_argnums=(1,)).trace(*decode_args)
        self.decode_compiled = self.decode_traced.lower().compile()

        self.verify_traced = None
        self.verify_compiled = None
        if self.speculate_k > 0:
            K = self.speculate_k

            def _verify_core(params, cache, tokens, drafts, temperature,
                             active, rng, poison=None):
                # score the whole window BEFORE appending: the accepted
                # count decides the cursor advance, and append_k writes
                # every row that fits — rejected rows land above the
                # cursor, masked from every read (the rollback story)
                logits, (k_new, v_new), cache = model.verify_forward(
                    params, tokens, cache)
                finite = None
                if poison is not None:
                    logits = logits + poison[:, None, None]
                    finite = jnp.all(jnp.isfinite(logits), axis=(-2, -1))
                toks, accepted = verify_tokens(logits, drafts, rng,
                                               temperature, self.top_k)
                counts = jnp.where(active, accepted + 1, 0)
                cache = cache.append_k(k_new, v_new, counts)
                if finite is not None:
                    return cache, toks, counts, finite
                return cache, toks, counts

            if self.quarantine:
                def verify_step(params, cache, tokens, drafts,
                                temperature, active, rng, poison):
                    with jax.named_scope("serve_verify"):
                        return _verify_core(params, cache, tokens, drafts,
                                            temperature, active, rng,
                                            poison)
            else:
                def verify_step(params, cache, tokens, drafts,
                                temperature, active, rng):
                    with jax.named_scope("serve_verify"):
                        return _verify_core(params, cache, tokens, drafts,
                                            temperature, active, rng)

            verify_args = (params, self.cache,
                           jnp.zeros((S, K + 1), jnp.int32),
                           jnp.zeros((S, K), jnp.int32),
                           jnp.zeros((S,), jnp.float32),
                           jnp.ones((S,), jnp.bool_), self._key)
            if self.quarantine:
                verify_args += (self._zero_poison,)
            self.verify_traced = jax.jit(
                verify_step, donate_argnums=(1,)).trace(*verify_args)
            self.verify_compiled = self.verify_traced.lower().compile()

        def release_step(cache, slot):
            # zero one slot's cursor so a freed slot stops paying
            # attention over its dead prefix on every later decode step
            lengths = jax.lax.dynamic_update_slice(
                cache.lengths, jnp.zeros((1,), jnp.int32), (slot,))
            return dataclasses.replace(cache, lengths=lengths)

        self.release_compiled = jax.jit(
            release_step, donate_argnums=(0,)).trace(
                self.cache, ex_scalar).lower().compile()

        # construction-time donation self-check (analysis rule
        # jaxpr-donation, docs/ANALYSIS.md): every cache leaf must be
        # input/output-aliased in all three compiled programs, and no
        # two cache leaves may share one buffer — a KVCache built with a
        # shared scale plane would donate the SAME buffer twice, the
        # exact class PR 9's review caught by hand
        from apex_tpu.analysis.program import (lint_serving_engine,
                                               verify_findings)
        verify_findings(lint_serving_engine(self),
                        "ServingEngine construction")

    # -- stepping -----------------------------------------------------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def pad_prompt(self, prompt: Sequence[int]) -> jnp.ndarray:
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.prefill_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the prefill window "
                f"{self.prefill_len} (pick a larger prefill_len at "
                "engine construction)")
        padded = np.zeros((1, self.prefill_len), np.int32)
        padded[0, : len(prompt)] = np.asarray(prompt, np.int32)
        return jnp.asarray(padded)

    def prefill(self, prompt: Sequence[int], slot: int,
                temperature: float = 0.0) -> int:
        """Admit ``prompt`` into ``slot`` and return the first sampled
        token (a host int). Consumes and replaces the donated cache."""
        if not 0 <= int(slot) < self.max_seqs:
            # an out-of-range slot would CLAMP inside the compiled
            # dynamic_update_slice and silently clobber the last valid
            # slot's in-flight sequence
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        self.cache, tok = self.prefill_compiled(
            self.params, self.cache, self.pad_prompt(prompt),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(len(prompt), jnp.int32),
            jnp.asarray(temperature, jnp.float32), self._next_key())
        return int(tok)

    def decode(self, tokens: np.ndarray, temperatures: np.ndarray,
               active: Optional[np.ndarray] = None,
               poison: Optional[np.ndarray] = None) -> np.ndarray:
        """One decode step for every slot: ``tokens (max_seqs,)`` are the
        last emitted token per slot (anything for free slots), returns
        the next token per slot. ``active`` (``(max_seqs,)`` bool,
        default all): slots outside it keep a frozen cursor — free slots
        never grow an attention prefix. Consumes and replaces the
        donated cache.

        ``poison`` (quarantine engines only, ``(max_seqs,)`` f32,
        default zeros) is added to each slot's sampling-path logits —
        the deterministic fault-injection argument. On a quarantine
        engine :attr:`last_finite` holds this step's per-slot finite
        flags afterwards; on a plain engine it stays None (and a poison
        array is refused — the fault would be silently dropped)."""
        if active is None:
            active = np.ones(self.max_seqs, np.bool_)
        args = (self.params, self.cache,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(temperatures, jnp.float32),
                jnp.asarray(active, jnp.bool_), self._next_key())
        if self.quarantine:
            pvec = self._zero_poison if poison is None else \
                jnp.asarray(poison, jnp.float32)
            self.cache, toks, finite = self.decode_compiled(*args, pvec)
            self.last_finite = np.asarray(finite)
        else:
            if poison is not None:
                raise ValueError(
                    "poison injection requires a quarantine engine "
                    "(ServingEngine(..., quarantine=True)) — on a plain "
                    "engine the fault would be silently dropped")
            self.cache, toks = self.decode_compiled(*args)
        return np.asarray(toks)

    def verify(self, tokens: np.ndarray, drafts: np.ndarray,
               temperatures: np.ndarray,
               active: Optional[np.ndarray] = None,
               poison: Optional[np.ndarray] = None):
        """One speculative verify step for every slot: ``tokens
        (max_seqs,)`` are each slot's last emitted token, ``drafts
        (max_seqs, speculate_k)`` the draft-source proposals after it.
        Returns ``(out_tokens (max_seqs, speculate_k + 1), counts
        (max_seqs,))`` — slot ``s`` emits ``out_tokens[s, :counts[s]]``
        this step (``counts`` is 0 for inactive slots, otherwise
        ``accepted_drafts + 1``), and its cursor has already advanced by
        exactly ``counts[s]``: rejected rows sit above the cursor where
        no read masks them in, so retiring the slot at ANY point leaves
        no drafted-but-rejected KV visible. Consumes and replaces the
        donated cache; requires ``speculate_k > 0`` at construction.

        ``poison`` follows the :meth:`decode` quarantine contract — on a
        quarantine engine :attr:`last_finite` carries the per-slot
        finite flags of the VERIFY logits afterwards."""
        if self.verify_compiled is None:
            raise ValueError(
                "verify requires a speculative engine "
                f"({type(self).__name__}(..., speculate_k=k) with k > 0)")
        if active is None:
            active = np.ones(self.max_seqs, np.bool_)
        drafts = np.asarray(drafts, np.int32).reshape(
            self.max_seqs, self.speculate_k)
        tok_mat = np.concatenate(
            [np.asarray(tokens, np.int32).reshape(self.max_seqs, 1),
             drafts], axis=1)
        args = (self.params, self.cache, jnp.asarray(tok_mat),
                jnp.asarray(drafts),
                jnp.asarray(temperatures, jnp.float32),
                jnp.asarray(active, jnp.bool_), self._next_key())
        if self.quarantine:
            pvec = self._zero_poison if poison is None else \
                jnp.asarray(poison, jnp.float32)
            self.cache, toks, counts, finite = self.verify_compiled(
                *args, pvec)
            self.last_finite = np.asarray(finite)
        else:
            if poison is not None:
                raise ValueError(
                    "poison injection requires a quarantine engine "
                    "(ServingEngine(..., quarantine=True)) — on a plain "
                    "engine the fault would be silently dropped")
            self.cache, toks, counts = self.verify_compiled(*args)
        return np.asarray(toks), np.asarray(counts)

    def release_slot(self, slot: int) -> None:
        """Zero ``slot``'s write cursor (AOT-compiled, donated like the
        steps). Call when a sequence retires: the decode kernel skips
        the compute of blocks past the cursor (and the XLA fallback
        skips nothing but masks), so an idle slot left at a deep cursor
        would keep paying prefix attention math on every step until
        reused — and the cursor is also the capacity/accounting truth
        the next admission relies on."""
        if not 0 <= int(slot) < self.max_seqs:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        self.cache = self.release_compiled(self.cache,
                                           jnp.asarray(slot, jnp.int32))

    # -- hot weight swap ----------------------------------------------------

    def swap_params(self, new_params, *, relint: bool = True) -> None:
        """Swap the serving weights in place with ZERO recompiles.

        The params are a plain (non-donated) array argument of all three
        AOT programs, so replacing the pytree retargets every subsequent
        prefill/decode/release dispatch at the new weights — no retrace,
        no recompile, no cache reallocation (the compile-storm counters
        stay flat; asserted under ``recompile_guard`` in
        ``tests/test_resilience.py``). In-flight sequences keep their
        OLD-weight KV prefix and extend it under the new weights — the
        standard serve-while-train rollover semantics; drain first
        (:meth:`~apex_tpu.serving.scheduler.SlotScheduler.drain`) for a
        clean generation boundary.

        ``new_params`` must match the compiled programs' structure
        exactly (same treedef, same leaf shapes/dtypes) — anything else
        would retrace on next dispatch, which is exactly the compile
        storm this method exists to avoid, so it is refused here at the
        host boundary. ``relint=True`` re-runs the analysis engine's
        donation/aliasing lint over the three compiled programs after
        the swap (rule ``jaxpr-donation`` — the construction-time
        self-check repeated at every rollover).
        """
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_params: new params tree structure differs from "
                "the compiled programs' — a swap must never retrace "
                f"(old {old_def}, new {new_def})")
        converted = []
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            # one device_put per leaf: validate on the converted array
            # and keep it, rather than transferring the model twice
            n = jnp.asarray(n)
            if o.shape != n.shape or o.dtype != n.dtype:
                raise ValueError(
                    f"swap_params: leaf {i} is {n.shape}/{n.dtype}, "
                    f"compiled for {o.shape}/{o.dtype} — a swap must "
                    "never retrace")
            converted.append(n)
        self.params = jax.tree_util.tree_unflatten(new_def, converted)
        self.swaps += 1
        if relint:
            from apex_tpu.analysis.program import (lint_serving_engine,
                                                   verify_findings)
            verify_findings(lint_serving_engine(self),
                            "ServingEngine.swap_params")

    # -- capacity -----------------------------------------------------------

    def bytes_per_slot(self) -> int:
        cfg = self.model.cfg
        return cache_bytes_per_slot(cfg.num_layers,
                                    cfg.num_attention_heads, self.max_len,
                                    cfg.head_dim, self.cache.k.dtype)

    def overhead_bytes(self) -> Optional[int]:
        """Non-cache HBM the compiled decode step pins (params, logits,
        temporaries), from the executable's static memory plan — None
        when the backend reports no analysis."""
        budget = memory_budget(self.decode_compiled)
        if budget is None:
            return None
        return max(0, int(budget["peak_hbm_bytes"]) - self.cache.nbytes())

    def suggest_max_seqs(self, hbm_bytes: int,
                         reserve_fraction: float = 0.1) -> int:
        """Max concurrent sequence slots that fit ``hbm_bytes``: the
        compiled step's non-cache footprint (measured, not guessed) is
        subtracted, a ``reserve_fraction`` safety margin held back, and
        the rest divided by the per-slot cache bytes. Falls back to the
        raw params size as the overhead estimate when the backend
        exposes no memory analysis."""
        overhead = self.overhead_bytes()
        if overhead is None:
            overhead = sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(self.params))
        avail = int(hbm_bytes * (1.0 - reserve_fraction)) - overhead
        return max(0, avail // self.bytes_per_slot())


class PagedServingEngine(ServingEngine):
    """The v2 paged engine: same three-AOT-program contract as
    :class:`ServingEngine` (compiled once at construction, cache
    donated, ``lint_serving_engine`` self-check, zero recompiles across
    admit/COW/retire), but the cache is a global
    :class:`~apex_tpu.serving.cache.PagedKVCache` block pool — a slot
    reserves ``ceil(context/block_size)`` blocks instead of ``max_len``
    positions, the decode step's HBM traffic is O(actual context)
    (``paged_decode_attention``), and admissions whose prompt prefix is
    already pooled SHARE those blocks and skip prefill for the shared
    span (copy-on-write; the TTFT win ``serve/ttft_prefix_ms`` tracks).

    Host state (block tables, cursors, refcounts, the prefix-hash
    index) lives in :attr:`allocator` — a
    :class:`~apex_tpu.serving.cache.BlockAllocator` — and rides into
    the fixed-shape programs as plain array arguments, so per-request
    bookkeeping never retraces anything.

    Extra construction knobs vs the dense engine:

    Args:
      num_blocks: global pool size in blocks (block 0 is the reserved
        null block — allocatable capacity is ``num_blocks - 1``). Size
        with :meth:`suggest_pool_blocks`.
      block_size: tokens per block. On TPU the paged Pallas kernel
        wants ``block_size % 128 == 0``; any size works via the XLA
        fallback (and under interpret mode on CPU).
      prefix_suffix_cap: longest un-shared prompt TAIL (tokens) worth
        serving through per-token decode steps on a prefix hit; a hit
        whose tail is longer falls back to the cold full prefill
        (sequential decode would beat one batched prefill only near
        full coverage). Default: ``block_size``.
    """

    def __init__(self, model, params, *, max_seqs: int, max_len: int,
                 prefill_len: int, num_blocks: int, block_size: int,
                 cache_dtype=jnp.bfloat16, top_k: int = 0,
                 rng_seed: int = 0, quarantine: bool = False,
                 prefix_suffix_cap: Optional[int] = None,
                 mean_context: Optional[float] = None,
                 speculate_k: int = 0):
        model._require_cacheable()
        cfg = model.cfg
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        if prefill_len > max_len:
            raise ValueError(f"prefill_len {prefill_len} exceeds max_len "
                             f"{max_len}")
        if prefill_len % block_size != 0:
            raise ValueError(
                f"prefill_len {prefill_len} must be a multiple of "
                f"block_size {block_size} (the prefill program writes "
                "whole pool blocks)")
        self.model = model
        self.params = params
        self.max_seqs = int(max_seqs)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.top_k = int(top_k)
        self.quarantine = bool(quarantine)
        self.speculate_k = int(speculate_k)
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, got {speculate_k}")
        if self.speculate_k + 1 > max_len:
            raise ValueError(
                f"speculate_k {speculate_k} needs a {speculate_k + 1}-token "
                f"verify window, which exceeds max_len {max_len}")
        self.prefix_suffix_cap = int(block_size if prefix_suffix_cap
                                     is None else prefix_suffix_cap)
        self.mean_context = mean_context
        self.last_finite: Optional[np.ndarray] = None
        self.last_admit: Optional[AdmitPlan] = None
        self.last_failed: list = []
        self.swaps = 0
        self.prefill_blocks = self.prefill_len // self.block_size
        blocks_per_slot = -(-self.max_len // self.block_size)
        self.cache = PagedKVCache.create(
            cfg.num_layers, num_blocks, cfg.num_attention_heads,
            block_size, cfg.head_dim, dtype=cache_dtype)
        self.allocator = BlockAllocator(num_blocks, block_size,
                                        blocks_per_slot, max_seqs)

        def prefill_step(params, cache, tokens, block_row, true_len,
                         temperature, rng):
            with jax.named_scope("serve_prefill"):
                logits, cache = model.forward(params, tokens,
                                              kv_cache=cache,
                                              block_row=block_row,
                                              prompt_len=true_len,
                                              last_logit_only=True)
                tok = sample_tokens(logits[0], rng, temperature[None],
                                    self.top_k)[0]
            return cache, tok

        mc = self.mean_context

        def _decode_core(params, cache, tables, lengths, tokens,
                         temperature, block_ids, offsets, cow_src,
                         cow_dst, rng, poison=None):
            logits, cache = model.forward(
                params, tokens[:, None], kv_cache=cache,
                block_tables=tables, lengths=lengths,
                append_block_ids=block_ids, append_offsets=offsets,
                cow_src=cow_src, cow_dst=cow_dst, mean_context=mc)
            if poison is not None:
                logits = logits + poison[:, None]
                finite = jnp.all(jnp.isfinite(logits), axis=-1)
                toks = sample_tokens(logits, rng, temperature,
                                     self.top_k)
                return cache, toks, finite
            toks = sample_tokens(logits, rng, temperature, self.top_k)
            return cache, toks

        if self.quarantine:
            def decode_step(params, cache, tables, lengths, tokens,
                            temperature, block_ids, offsets, cow_src,
                            cow_dst, rng, poison):
                with jax.named_scope("serve_decode"):
                    return _decode_core(params, cache, tables, lengths,
                                        tokens, temperature, block_ids,
                                        offsets, cow_src, cow_dst, rng,
                                        poison)
        else:
            def decode_step(params, cache, tables, lengths, tokens,
                            temperature, block_ids, offsets, cow_src,
                            cow_dst, rng):
                with jax.named_scope("serve_decode"):
                    return _decode_core(params, cache, tables, lengths,
                                        tokens, temperature, block_ids,
                                        offsets, cow_src, cow_dst, rng)

        key = jax.random.PRNGKey(rng_seed)
        self._key, _ = jax.random.split(key)
        S = self.max_seqs
        ex_tokens = jnp.zeros((1, self.prefill_len), jnp.int32)
        ex_row = jnp.zeros((self.prefill_blocks,), jnp.int32)
        ex_scalar = jnp.zeros((), jnp.int32)
        ex_temp = jnp.zeros((), jnp.float32)
        self.prefill_traced = jax.jit(
            prefill_step, donate_argnums=(1,)).trace(
                params, self.cache, ex_tokens, ex_row, ex_scalar,
                ex_temp, self._key)
        self.prefill_compiled = self.prefill_traced.lower().compile()
        self._zero_poison = jnp.zeros((S,), jnp.float32)
        zs = jnp.zeros((S,), jnp.int32)
        decode_args = (params, self.cache,
                       jnp.zeros((S, blocks_per_slot), jnp.int32), zs,
                       zs, jnp.zeros((S,), jnp.float32), zs, zs, zs, zs,
                       self._key)
        if self.quarantine:
            decode_args += (self._zero_poison,)
        self.decode_traced = jax.jit(
            decode_step, donate_argnums=(1,)).trace(*decode_args)
        self.decode_compiled = self.decode_traced.lower().compile()

        self.verify_traced = None
        self.verify_compiled = None
        if self.speculate_k > 0:
            K = self.speculate_k

            def _verify_core(params, cache, tables, lengths, tokens,
                             drafts, temperature, active, block_ids,
                             offsets, cow_src, cow_dst, rng, poison=None):
                # COW resolution happens inside verify_forward (before
                # any read), exactly like the decode leg; the append
                # targets every row of the window — rejected rows land
                # in blocks above the host cursor mirror, which only
                # ever advances by the accepted count
                logits, (k_new, v_new), cache = model.verify_forward(
                    params, tokens, cache, block_tables=tables,
                    lengths=lengths, cow_src=cow_src, cow_dst=cow_dst,
                    mean_context=mc)
                finite = None
                if poison is not None:
                    logits = logits + poison[:, None, None]
                    finite = jnp.all(jnp.isfinite(logits), axis=(-2, -1))
                toks, accepted = verify_tokens(logits, drafts, rng,
                                               temperature, self.top_k)
                counts = jnp.where(active, accepted + 1, 0)
                cache = cache.append_k(k_new, v_new, block_ids, offsets)
                if finite is not None:
                    return cache, toks, counts, finite
                return cache, toks, counts

            if self.quarantine:
                def verify_step(params, cache, tables, lengths, tokens,
                                drafts, temperature, active, block_ids,
                                offsets, cow_src, cow_dst, rng, poison):
                    with jax.named_scope("serve_verify"):
                        return _verify_core(params, cache, tables,
                                            lengths, tokens, drafts,
                                            temperature, active,
                                            block_ids, offsets, cow_src,
                                            cow_dst, rng, poison)
            else:
                def verify_step(params, cache, tables, lengths, tokens,
                                drafts, temperature, active, block_ids,
                                offsets, cow_src, cow_dst, rng):
                    with jax.named_scope("serve_verify"):
                        return _verify_core(params, cache, tables,
                                            lengths, tokens, drafts,
                                            temperature, active,
                                            block_ids, offsets, cow_src,
                                            cow_dst, rng)

            zq = jnp.zeros((S, K + 1), jnp.int32)
            verify_args = (params, self.cache,
                           jnp.zeros((S, blocks_per_slot), jnp.int32),
                           zs, zq, jnp.zeros((S, K), jnp.int32),
                           jnp.zeros((S,), jnp.float32),
                           jnp.ones((S,), jnp.bool_), zq, zq, zs, zs,
                           self._key)
            if self.quarantine:
                verify_args += (self._zero_poison,)
            self.verify_traced = jax.jit(
                verify_step, donate_argnums=(1,)).trace(*verify_args)
            self.verify_compiled = self.verify_traced.lower().compile()

        def release_step(cache):
            # re-zero the reserved null block: every masked write
            # (inactive slot, saturated slot, prompt padding) lands in
            # it, so a retire is the natural point to scrub the garbage
            # back to the "reads as zeros" invariant. Real in-place
            # writes on every donated leaf — the donation lint holds.
            from apex_tpu.serving.cache import NULL_BLOCK, _MIN_SCALE
            new = {"k": cache.k.at[:, NULL_BLOCK].set(0),
                   "v": cache.v.at[:, NULL_BLOCK].set(0)}
            if cache.quantized:
                new["k_scale"] = cache.k_scale.at[:, NULL_BLOCK].set(
                    jnp.float32(_MIN_SCALE))
                new["v_scale"] = cache.v_scale.at[:, NULL_BLOCK].set(
                    jnp.float32(_MIN_SCALE))
            return dataclasses.replace(cache, **new)

        self.release_compiled = jax.jit(
            release_step, donate_argnums=(0,)).trace(
                self.cache).lower().compile()

        from apex_tpu.analysis.program import (lint_serving_engine,
                                               verify_findings)
        verify_findings(lint_serving_engine(self),
                        "PagedServingEngine construction")

    # -- admission ----------------------------------------------------------

    def can_admit(self, prompt: Sequence[int]) -> bool:
        """Whether the pool can take ``prompt`` right now (conservative:
        assumes a cold admission; a prefix hit needs fewer blocks)."""
        return (self.allocator.free_blocks
                >= self.allocator.blocks_for(len(prompt)))

    def prefill(self, prompt: Sequence[int], slot: int,
                temperature: float = 0.0) -> int:
        """Admit ``prompt`` into ``slot`` and return the first sampled
        token. Two paths, chosen by the allocator's prefix index:

        - **cold**: allocate blocks, run the batched prefill program.
        - **prefix hit** (tail within ``prefix_suffix_cap``): map the
          shared blocks (refcount++), skip prefill for the shared span,
          and feed ONLY the un-shared tail through the decode program
          one token at a time (``active`` = this slot alone — other
          slots' cursors and blocks are untouched). The final step's
          sample is the first token.

        Raises :class:`~apex_tpu.serving.cache.PoolExhausted` when the
        blocks aren't there — the scheduler queues on that (typed
        :class:`~apex_tpu.serving.resilience.Rejection` at submit).
        Sets :attr:`last_admit` to the chosen
        :class:`~apex_tpu.serving.cache.AdmitPlan` for the scheduler's
        prefix metrics."""
        if not 0 <= int(slot) < self.max_seqs:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        prompt = [int(t) for t in prompt]
        shared = self.allocator.lookup(prompt)
        covered = min(len(shared) * self.block_size, len(prompt) - 1)
        if shared and len(prompt) - covered > self.prefix_suffix_cap:
            shared = []        # tail too long: cold prefill wins
        if not shared:
            plan = self.allocator.admit(slot, prompt,
                                        self.prefill_blocks,
                                        share=False)
        else:
            plan = self.allocator.admit(slot, prompt,
                                        self.prefill_blocks)
        self.last_admit = plan
        if plan.prefill:
            self.cache, tok = self.prefill_compiled(
                self.params, self.cache, self.pad_prompt(prompt),
                jnp.asarray(np.asarray(plan.block_row, np.int32)),
                jnp.asarray(len(prompt), jnp.int32),
                jnp.asarray(temperature, jnp.float32), self._next_key())
            # index the freshly written full blocks so LATER admissions
            # can share them
            self.allocator.register_prefix(slot, prompt)
            return int(tok)
        # prefix hit: decode the un-shared tail token by token through
        # the ordinary decode program (same compiled program — zero
        # recompiles), other slots frozen
        active = np.zeros(self.max_seqs, np.bool_)
        active[slot] = True
        tokens = np.zeros(self.max_seqs, np.int32)
        temps = np.zeros(self.max_seqs, np.float32)
        temps[slot] = temperature
        tok = 0
        for t in plan.suffix:
            tokens[slot] = t
            toks = self.decode(tokens, temps, active=active)
            tok = int(toks[slot])
        return tok

    # -- stepping -----------------------------------------------------------

    def decode(self, tokens: np.ndarray, temperatures: np.ndarray,
               active: Optional[np.ndarray] = None,
               poison: Optional[np.ndarray] = None) -> np.ndarray:
        """One decode step for every slot (same call contract as
        :meth:`ServingEngine.decode`). Per-step block bookkeeping
        happens HERE: pending copy-on-writes are resolved (the device
        copies the block before writing it), cursors that crossed a
        block boundary get a fresh block, and slots the exhausted pool
        could not serve land in :attr:`last_failed` — their append is
        dropped (null block) and the scheduler retires them loudly."""
        if active is None:
            active = np.ones(self.max_seqs, np.bool_)
        active = np.asarray(active, bool)
        step = self.allocator.prepare_step(list(np.flatnonzero(active)))
        self.last_failed = list(step.failed)
        ok = active.copy()
        ok[step.failed] = False
        block_ids, offsets = self.allocator.append_targets(ok)
        args = (self.params, self.cache,
                jnp.asarray(self.allocator.tables),
                jnp.asarray(self.allocator.lengths),
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(temperatures, jnp.float32),
                jnp.asarray(block_ids), jnp.asarray(offsets),
                jnp.asarray(step.cow_src), jnp.asarray(step.cow_dst),
                self._next_key())
        if self.quarantine:
            pvec = self._zero_poison if poison is None else \
                jnp.asarray(poison, jnp.float32)
            self.cache, toks, finite = self.decode_compiled(*args, pvec)
            self.last_finite = np.asarray(finite)
        else:
            if poison is not None:
                raise ValueError(
                    "poison injection requires a quarantine engine "
                    "(PagedServingEngine(..., quarantine=True)) — on a "
                    "plain engine the fault would be silently dropped")
            self.cache, toks = self.decode_compiled(*args)
        self.allocator.advance(list(np.flatnonzero(ok)))
        return np.asarray(toks)

    def verify(self, tokens: np.ndarray, drafts: np.ndarray,
               temperatures: np.ndarray,
               active: Optional[np.ndarray] = None,
               poison: Optional[np.ndarray] = None):
        """Paged speculative verify (same call contract as
        :meth:`ServingEngine.verify`). Per-window block bookkeeping
        happens HERE: :meth:`~apex_tpu.serving.cache.BlockAllocator.
        prepare_verify` makes every block the ``speculate_k + 1``-token
        window touches slot-private and writable (COW resolved, fresh
        blocks mapped, atomic per slot), slots the exhausted pool could
        not fully serve land in :attr:`last_failed` (their window aims
        at the null block and their count comes back 0 — the scheduler
        retires them loudly), and the cursor mirror advances by each
        surviving slot's ACCEPTED count only."""
        if self.verify_compiled is None:
            raise ValueError(
                "verify requires a speculative engine "
                f"({type(self).__name__}(..., speculate_k=k) with k > 0)")
        Q = self.speculate_k + 1
        if active is None:
            active = np.ones(self.max_seqs, np.bool_)
        active = np.asarray(active, bool)
        step = self.allocator.prepare_verify(
            list(np.flatnonzero(active)), Q)
        self.last_failed = list(step.failed)
        ok = active.copy()
        ok[step.failed] = False
        block_ids, offsets = self.allocator.verify_targets(ok, Q)
        drafts = np.asarray(drafts, np.int32).reshape(
            self.max_seqs, self.speculate_k)
        tok_mat = np.concatenate(
            [np.asarray(tokens, np.int32).reshape(self.max_seqs, 1),
             drafts], axis=1)
        args = (self.params, self.cache,
                jnp.asarray(self.allocator.tables),
                jnp.asarray(self.allocator.lengths),
                jnp.asarray(tok_mat), jnp.asarray(drafts),
                jnp.asarray(temperatures, jnp.float32),
                jnp.asarray(ok), jnp.asarray(block_ids),
                jnp.asarray(offsets), jnp.asarray(step.cow_src),
                jnp.asarray(step.cow_dst), self._next_key())
        if self.quarantine:
            pvec = self._zero_poison if poison is None else \
                jnp.asarray(poison, jnp.float32)
            self.cache, toks, counts, finite = self.verify_compiled(
                *args, pvec)
            self.last_finite = np.asarray(finite)
        else:
            if poison is not None:
                raise ValueError(
                    "poison injection requires a quarantine engine "
                    "(PagedServingEngine(..., quarantine=True)) — on a "
                    "plain engine the fault would be silently dropped")
            self.cache, toks, counts = self.verify_compiled(*args)
        counts = np.asarray(counts)
        okidx = np.flatnonzero(ok)
        self.allocator.advance_counts(
            list(okidx), [int(counts[s]) for s in okidx])
        return np.asarray(toks), counts

    def release_slot(self, slot: int) -> None:
        """Retire ``slot``: drop its block references on the host
        (shared blocks survive for their other readers — and for the
        prefix cache) and scrub the null block on device."""
        if not 0 <= int(slot) < self.max_seqs:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        self.allocator.release(slot)
        self.cache = self.release_compiled(self.cache)

    # -- capacity -----------------------------------------------------------

    def block_bytes(self) -> int:
        cfg = self.model.cfg
        return paged_block_bytes(cfg.num_layers, cfg.num_attention_heads,
                                 self.block_size, cfg.head_dim,
                                 self.cache.k.dtype)

    def suggest_pool_blocks(self, hbm_bytes: int, mean_len: float,
                            reserve_fraction: float = 0.1) -> int:
        """Pool blocks that fit ``hbm_bytes`` — the paged successor of
        :meth:`ServingEngine.suggest_max_seqs`. The compiled step's
        non-cache footprint is measured and subtracted (params, logits,
        temporaries), a ``reserve_fraction`` margin held back, and the
        rest divided by the per-block bytes. The mean-length capacity
        math reads off it: a pool of ``B`` blocks sustains about
        ``B * block_size / mean_len`` concurrent sequences — versus the
        dense engine's hard ``HBM / (max_len bytes-per-slot)`` ceiling,
        a ``max_len / mean_len`` capacity win at the same HBM."""
        if mean_len <= 0:
            raise ValueError(f"mean_len must be positive, got {mean_len}")
        overhead = self.overhead_bytes()
        if overhead is None:
            overhead = sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(self.params))
        avail = int(hbm_bytes * (1.0 - reserve_fraction)) - overhead
        return max(0, avail // self.block_bytes())

    def suggest_max_seqs_for_pool(self, num_blocks: int,
                                  mean_len: float) -> int:
        """Concurrent sequences a ``num_blocks`` pool sustains at the
        observed ``mean_len`` (the second half of the capacity math)."""
        per_seq = max(1, -(-int(mean_len) // self.block_size))
        return max(0, (num_blocks - 1) // per_seq)
