"""AOT-compiled prefill/decode steps with a donated KV cache.

The engine owns the cache and the two compiled programs a serving
process runs forever:

- **prefill**: one request's padded prompt ``(1, prefill_len)`` through
  the ordinary causal forward (the training flash path), K/V written
  into one cache slot, the first output token sampled from the logits at
  the prompt's true last position;
- **decode**: ONE token for EVERY slot ``(max_seqs, 1)`` through the
  decode attention kernel, K/V appended at each slot's cursor, next
  tokens sampled.

Both are ``jax.jit(..., donate_argnums=<cache>)`` and compiled ONCE at
construction (``.trace().lower().compile()`` — the bench/test AOT
convention), which buys the two serving-latency properties the tests
pin down:

- **zero allocation**: the cache buffers are donated and every write is
  a fixed-position dynamic_update_slice, so XLA aliases them in place
  (``input_output_alias`` asserted over every cache leaf in
  ``tests/test_serving.py``) — a decode step never copies the cache;
- **zero recompilation**: every per-request quantity is an array
  argument (tokens, temperatures, cursors-in-cache) and every
  shape-changing knob is fixed at construction (``max_seqs``,
  ``prefill_len``, ``top_k``), so admission/retirement never retraces —
  the compile-storm counters (PR 1) are asserted flat across steps.

Capacity: :meth:`ServingEngine.suggest_max_seqs` turns the compiled
decode step's static memory plan (``observability/costs.memory_budget``)
into "how many concurrent sequences fit this chip's HBM" — the
ROADMAP's cache-capacity accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.observability.costs import memory_budget
from apex_tpu.serving.cache import KVCache, cache_bytes_per_slot
from apex_tpu.serving.sampling import sample_tokens

__all__ = ["ServingEngine"]


class ServingEngine:
    """See module docstring.

    Args:
      model: a :class:`~apex_tpu.models.gpt.GPTModel` (tp=1, no SP).
      params: its :meth:`init` pytree.
      max_seqs: concurrent sequence slots (the decode batch width).
      max_len: per-slot cache capacity in tokens (<= the model's
        ``max_position_embeddings``).
      prefill_len: the fixed prompt window; prompts are right-padded to
        it (longer prompts are rejected — one bucket keeps this PR's
        program count at two).
      cache_dtype: ``jnp.bfloat16`` (default) or ``jnp.int8`` (quantized
        cache with per-(position, head) scales).
      top_k: static top-k sampling cutoff (0 = full vocab).
      quarantine: compile the poison-slot quarantine check into the
        decode program — one per-slot ``isfinite`` reduction over the
        sampling-path logits (fused into the head matmul's consumers,
        no extra memory pass) plus a ``(max_seqs,)`` poison-injection
        array argument (NaN for a slot poisons its logits — the
        deterministic :class:`~apex_tpu.elastic.faults.FaultPlan`
        injection path, zero extra compiles). After each
        :meth:`decode`, :attr:`last_finite` carries the per-slot flags
        the scheduler's quarantine reads. Default off — the decode
        program is byte-identical to a quarantine-free engine's (the
        PR 3 zero-cost idiom, asserted in ``tests/test_resilience.py``).
    """

    def __init__(self, model, params, *, max_seqs: int, max_len: int,
                 prefill_len: int, cache_dtype=jnp.bfloat16,
                 top_k: int = 0, rng_seed: int = 0,
                 quarantine: bool = False):
        model._require_cacheable()
        cfg = model.cfg
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_len {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        if prefill_len > max_len:
            raise ValueError(f"prefill_len {prefill_len} exceeds max_len "
                             f"{max_len}")
        self.model = model
        self.params = params
        self.max_seqs = int(max_seqs)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.top_k = int(top_k)
        self.quarantine = bool(quarantine)
        self.last_finite: Optional[np.ndarray] = None
        self.swaps = 0
        self.cache = KVCache.create(
            cfg.num_layers, max_seqs, cfg.num_attention_heads, max_len,
            cfg.head_dim, dtype=cache_dtype)

        def prefill_step(params, cache, tokens, slot, true_len,
                         temperature, rng):
            with jax.named_scope("serve_prefill"):
                # last_logit_only: the admission samples exactly one row
                # of the head, so only that row is projected
                logits, cache = model.forward(params, tokens,
                                              kv_cache=cache, slot=slot,
                                              prompt_len=true_len,
                                              last_logit_only=True)
                tok = sample_tokens(logits[0], rng, temperature[None],
                                    self.top_k)[0]
            return cache, tok

        if self.quarantine:
            # the quarantine variant: one extra (S,) array argument
            # (``poison``, normally zeros — adding NaN to a slot's row is
            # the deterministic fault-injection path) and one extra
            # per-slot output (``finite``). Both ride the SAME compiled
            # program forever — injecting or clearing poison never
            # retraces. The finite reduction runs on the post-injection
            # sampling-path logits, so a NaN from ANY upstream source
            # (poisoned cache, bad weights, the injection arg) flags the
            # slot the very step it first reaches sampling.
            def decode_step(params, cache, tokens, temperature, active,
                            rng, poison):
                with jax.named_scope("serve_decode"):
                    logits, cache = model.forward(params, tokens[:, None],
                                                  kv_cache=cache,
                                                  active=active)
                    logits = logits + poison[:, None]
                    finite = jnp.all(jnp.isfinite(logits), axis=-1)
                    toks = sample_tokens(logits, rng, temperature,
                                         self.top_k)
                return cache, toks, finite
        else:
            def decode_step(params, cache, tokens, temperature, active,
                            rng):
                with jax.named_scope("serve_decode"):
                    logits, cache = model.forward(params, tokens[:, None],
                                                  kv_cache=cache,
                                                  active=active)
                    toks = sample_tokens(logits, rng, temperature,
                                         self.top_k)
                return cache, toks

        key = jax.random.PRNGKey(rng_seed)
        self._key, _ = jax.random.split(key)  # also warms split's compile
        S = self.max_seqs
        ex_tokens = jnp.zeros((1, self.prefill_len), jnp.int32)
        ex_scalar = jnp.zeros((), jnp.int32)
        ex_temp = jnp.zeros((), jnp.float32)
        self.prefill_traced = jax.jit(
            prefill_step, donate_argnums=(1,)).trace(
                params, self.cache, ex_tokens, ex_scalar, ex_scalar,
                ex_temp, self._key)
        self.prefill_compiled = self.prefill_traced.lower().compile()
        self._zero_poison = jnp.zeros((S,), jnp.float32)
        decode_args = (params, self.cache, jnp.zeros((S,), jnp.int32),
                       jnp.zeros((S,), jnp.float32),
                       jnp.ones((S,), jnp.bool_), self._key)
        if self.quarantine:
            decode_args += (self._zero_poison,)
        self.decode_traced = jax.jit(
            decode_step, donate_argnums=(1,)).trace(*decode_args)
        self.decode_compiled = self.decode_traced.lower().compile()

        def release_step(cache, slot):
            # zero one slot's cursor so a freed slot stops paying
            # attention over its dead prefix on every later decode step
            lengths = jax.lax.dynamic_update_slice(
                cache.lengths, jnp.zeros((1,), jnp.int32), (slot,))
            return dataclasses.replace(cache, lengths=lengths)

        self.release_compiled = jax.jit(
            release_step, donate_argnums=(0,)).trace(
                self.cache, ex_scalar).lower().compile()

        # construction-time donation self-check (analysis rule
        # jaxpr-donation, docs/ANALYSIS.md): every cache leaf must be
        # input/output-aliased in all three compiled programs, and no
        # two cache leaves may share one buffer — a KVCache built with a
        # shared scale plane would donate the SAME buffer twice, the
        # exact class PR 9's review caught by hand
        from apex_tpu.analysis.program import (lint_serving_engine,
                                               verify_findings)
        verify_findings(lint_serving_engine(self),
                        "ServingEngine construction")

    # -- stepping -----------------------------------------------------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def pad_prompt(self, prompt: Sequence[int]) -> jnp.ndarray:
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.prefill_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the prefill window "
                f"{self.prefill_len} (pick a larger prefill_len at "
                "engine construction)")
        padded = np.zeros((1, self.prefill_len), np.int32)
        padded[0, : len(prompt)] = np.asarray(prompt, np.int32)
        return jnp.asarray(padded)

    def prefill(self, prompt: Sequence[int], slot: int,
                temperature: float = 0.0) -> int:
        """Admit ``prompt`` into ``slot`` and return the first sampled
        token (a host int). Consumes and replaces the donated cache."""
        if not 0 <= int(slot) < self.max_seqs:
            # an out-of-range slot would CLAMP inside the compiled
            # dynamic_update_slice and silently clobber the last valid
            # slot's in-flight sequence
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        self.cache, tok = self.prefill_compiled(
            self.params, self.cache, self.pad_prompt(prompt),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(len(prompt), jnp.int32),
            jnp.asarray(temperature, jnp.float32), self._next_key())
        return int(tok)

    def decode(self, tokens: np.ndarray, temperatures: np.ndarray,
               active: Optional[np.ndarray] = None,
               poison: Optional[np.ndarray] = None) -> np.ndarray:
        """One decode step for every slot: ``tokens (max_seqs,)`` are the
        last emitted token per slot (anything for free slots), returns
        the next token per slot. ``active`` (``(max_seqs,)`` bool,
        default all): slots outside it keep a frozen cursor — free slots
        never grow an attention prefix. Consumes and replaces the
        donated cache.

        ``poison`` (quarantine engines only, ``(max_seqs,)`` f32,
        default zeros) is added to each slot's sampling-path logits —
        the deterministic fault-injection argument. On a quarantine
        engine :attr:`last_finite` holds this step's per-slot finite
        flags afterwards; on a plain engine it stays None (and a poison
        array is refused — the fault would be silently dropped)."""
        if active is None:
            active = np.ones(self.max_seqs, np.bool_)
        args = (self.params, self.cache,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(temperatures, jnp.float32),
                jnp.asarray(active, jnp.bool_), self._next_key())
        if self.quarantine:
            pvec = self._zero_poison if poison is None else \
                jnp.asarray(poison, jnp.float32)
            self.cache, toks, finite = self.decode_compiled(*args, pvec)
            self.last_finite = np.asarray(finite)
        else:
            if poison is not None:
                raise ValueError(
                    "poison injection requires a quarantine engine "
                    "(ServingEngine(..., quarantine=True)) — on a plain "
                    "engine the fault would be silently dropped")
            self.cache, toks = self.decode_compiled(*args)
        return np.asarray(toks)

    def release_slot(self, slot: int) -> None:
        """Zero ``slot``'s write cursor (AOT-compiled, donated like the
        steps). Call when a sequence retires: the decode kernel skips
        the compute of blocks past the cursor (and the XLA fallback
        skips nothing but masks), so an idle slot left at a deep cursor
        would keep paying prefix attention math on every step until
        reused — and the cursor is also the capacity/accounting truth
        the next admission relies on."""
        if not 0 <= int(slot) < self.max_seqs:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        self.cache = self.release_compiled(self.cache,
                                           jnp.asarray(slot, jnp.int32))

    # -- hot weight swap ----------------------------------------------------

    def swap_params(self, new_params, *, relint: bool = True) -> None:
        """Swap the serving weights in place with ZERO recompiles.

        The params are a plain (non-donated) array argument of all three
        AOT programs, so replacing the pytree retargets every subsequent
        prefill/decode/release dispatch at the new weights — no retrace,
        no recompile, no cache reallocation (the compile-storm counters
        stay flat; asserted under ``recompile_guard`` in
        ``tests/test_resilience.py``). In-flight sequences keep their
        OLD-weight KV prefix and extend it under the new weights — the
        standard serve-while-train rollover semantics; drain first
        (:meth:`~apex_tpu.serving.scheduler.SlotScheduler.drain`) for a
        clean generation boundary.

        ``new_params`` must match the compiled programs' structure
        exactly (same treedef, same leaf shapes/dtypes) — anything else
        would retrace on next dispatch, which is exactly the compile
        storm this method exists to avoid, so it is refused here at the
        host boundary. ``relint=True`` re-runs the analysis engine's
        donation/aliasing lint over the three compiled programs after
        the swap (rule ``jaxpr-donation`` — the construction-time
        self-check repeated at every rollover).
        """
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_params: new params tree structure differs from "
                "the compiled programs' — a swap must never retrace "
                f"(old {old_def}, new {new_def})")
        converted = []
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            # one device_put per leaf: validate on the converted array
            # and keep it, rather than transferring the model twice
            n = jnp.asarray(n)
            if o.shape != n.shape or o.dtype != n.dtype:
                raise ValueError(
                    f"swap_params: leaf {i} is {n.shape}/{n.dtype}, "
                    f"compiled for {o.shape}/{o.dtype} — a swap must "
                    "never retrace")
            converted.append(n)
        self.params = jax.tree_util.tree_unflatten(new_def, converted)
        self.swaps += 1
        if relint:
            from apex_tpu.analysis.program import (lint_serving_engine,
                                                   verify_findings)
            verify_findings(lint_serving_engine(self),
                            "ServingEngine.swap_params")

    # -- capacity -----------------------------------------------------------

    def bytes_per_slot(self) -> int:
        cfg = self.model.cfg
        return cache_bytes_per_slot(cfg.num_layers,
                                    cfg.num_attention_heads, self.max_len,
                                    cfg.head_dim, self.cache.k.dtype)

    def overhead_bytes(self) -> Optional[int]:
        """Non-cache HBM the compiled decode step pins (params, logits,
        temporaries), from the executable's static memory plan — None
        when the backend reports no analysis."""
        budget = memory_budget(self.decode_compiled)
        if budget is None:
            return None
        return max(0, int(budget["peak_hbm_bytes"]) - self.cache.nbytes())

    def suggest_max_seqs(self, hbm_bytes: int,
                         reserve_fraction: float = 0.1) -> int:
        """Max concurrent sequence slots that fit ``hbm_bytes``: the
        compiled step's non-cache footprint (measured, not guessed) is
        subtracted, a ``reserve_fraction`` safety margin held back, and
        the rest divided by the per-slot cache bytes. Falls back to the
        raw params size as the overhead estimate when the backend
        exposes no memory analysis."""
        overhead = self.overhead_bytes()
        if overhead is None:
            overhead = sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(self.params))
        avail = int(hbm_bytes * (1.0 - reserve_fraction)) - overhead
        return max(0, avail // self.bytes_per_slot())
