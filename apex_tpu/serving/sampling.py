"""Fixed-shape token sampling for the AOT decode step.

Everything here traces into the compiled decode program, so every knob
that can vary per request rides as an ARRAY argument (per-slot
temperature), and every knob that changes the program shape is a static
compile-time constant (``top_k``). Greedy decoding is temperature 0 —
selected per slot with a ``where``, not a branch — so one compiled
program serves any mix of greedy and stochastic requests in the same
batch, and admitting a request never recompiles.

:func:`verify_tokens` is the speculative-decoding acceptance rule, traced
into the AOT ``verify`` program: greedy slots accept a draft iff it IS
the argmax (exact prefix match — the spec stream is bitwise the non-spec
stream), stochastic slots run standard rejection sampling against the
deterministic draft proposal with the corrected residual distribution,
which makes the output distribution EXACTLY the model's (docs/SERVING.md
"Speculative decoding" carries the two-line proof).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_tokens", "verify_tokens"]

# temperatures at or below this sample greedily (exact argmax, not a
# division by epsilon — the where keeps logits/0 out of the graph)
_GREEDY_EPS = 1e-6


def _mask_top_k(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return logits


def sample_tokens(logits: jnp.ndarray, rng: jax.Array,
                  temperature: jnp.ndarray, top_k: int = 0) -> jnp.ndarray:
    """One next-token per row of ``logits (S, vocab)``.

    ``temperature (S,)``: <= 0 means greedy for that slot; otherwise the
    logits are temperature-scaled and sampled categorically.
    ``top_k`` (static): when > 0, mask everything below the k-th logit
    before sampling (``top_k=1`` is exactly greedy). Returns ``(S,)``
    int32.
    """
    logits = _mask_top_k(logits.astype(jnp.float32), top_k)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.maximum(temperature, _GREEDY_EPS)[:, None]
    sampled = jax.random.categorical(rng, logits / safe_t,
                                     axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= _GREEDY_EPS, greedy, sampled)


def verify_tokens(logits: jnp.ndarray, drafts: jnp.ndarray, rng: jax.Array,
                  temperature: jnp.ndarray, top_k: int = 0):
    """Speculative verification over ``logits (S, Q, vocab)`` — row i is
    the model's next-token distribution AFTER in-flight token i (the
    last accepted token at i == 0, then the ``Q - 1`` drafts) — against
    ``drafts (S, Q-1)`` int32 from the (deterministic) draft source.

    Per slot, position i < Q-1 proposes ``drafts[:, i]``:

    - greedy (``temperature <= 0``): accept iff the draft IS the argmax;
      the emitted token is the argmax either way, so the stream is
      bitwise-identical to non-speculative greedy;
    - stochastic: accept with probability ``P_i(draft)`` (rejection
      sampling against a point-mass proposal); on rejection emit a
      sample of the corrected residual — ``P_i`` with the draft's mass
      zeroed and renormalized — which makes the marginal of the emitted
      token exactly ``P_i``. Temperature and ``top_k`` shape ``P_i``
      exactly as :func:`sample_tokens` does.

    Row Q-1 has no draft to check: it is the bonus token, a plain
    :func:`sample_tokens` draw from the last verified position.

    Returns ``(tokens (S, Q) int32, accepted (S,) int32)``: slot ``s``
    emits ``tokens[s, :accepted[s] + 1]`` this step — the accepted
    drafts, then the first correction (or the bonus). Callers gate
    inactive slots themselves.
    """
    S, Q, _ = logits.shape
    logits = _mask_top_k(logits.astype(jnp.float32), top_k)
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy_slot = (temperature <= _GREEDY_EPS)[:, None]          # (S, 1)
    safe_t = jnp.maximum(temperature, _GREEDY_EPS)[:, None, None]
    scaled = logits / safe_t

    argmax = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # (S, Q)
    r_acc, r_res, r_bonus = jax.random.split(rng, 3)

    head = scaled[:, :-1]                                        # (S, Q-1, V)
    # P_i(draft): softmax mass of the proposed token under the model
    p_draft = jnp.take_along_axis(
        jax.nn.softmax(head, axis=-1), drafts[..., None],
        axis=-1)[..., 0]                                         # (S, Q-1)
    u = jax.random.uniform(r_acc, drafts.shape)
    accept_stoch = u < p_draft
    accept_greedy = argmax[:, :-1] == drafts
    accept = jnp.where(greedy_slot, accept_greedy, accept_stoch)

    # corrected residual: the model distribution with the rejected
    # draft's mass removed — emitted only on rejection, so the marginal
    # stays exactly the model's
    vocab_iota = jax.lax.broadcasted_iota(jnp.int32, head.shape, 2)
    residual = jnp.where(vocab_iota == drafts[..., None], -jnp.inf, head)
    res_tok = jax.random.categorical(r_res, residual,
                                     axis=-1).astype(jnp.int32)
    head_tok = jnp.where(greedy_slot, argmax[:, :-1],
                         jnp.where(accept, drafts, res_tok))

    bonus = sample_tokens(logits[:, -1], r_bonus, temperature, top_k=0)
    tokens = jnp.concatenate([head_tok, bonus[:, None]], axis=1)
    accepted = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                       axis=1)
    return tokens, accepted
