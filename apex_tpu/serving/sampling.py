"""Fixed-shape token sampling for the AOT decode step.

Everything here traces into the compiled decode program, so every knob
that can vary per request rides as an ARRAY argument (per-slot
temperature), and every knob that changes the program shape is a static
compile-time constant (``top_k``). Greedy decoding is temperature 0 —
selected per slot with a ``where``, not a branch — so one compiled
program serves any mix of greedy and stochastic requests in the same
batch, and admitting a request never recompiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_tokens"]

# temperatures at or below this sample greedily (exact argmax, not a
# division by epsilon — the where keeps logits/0 out of the graph)
_GREEDY_EPS = 1e-6


def sample_tokens(logits: jnp.ndarray, rng: jax.Array,
                  temperature: jnp.ndarray, top_k: int = 0) -> jnp.ndarray:
    """One next-token per row of ``logits (S, vocab)``.

    ``temperature (S,)``: <= 0 means greedy for that slot; otherwise the
    logits are temperature-scaled and sampled categorically.
    ``top_k`` (static): when > 0, mask everything below the k-th logit
    before sampling (``top_k=1`` is exactly greedy). Returns ``(S,)``
    int32.
    """
    logits = logits.astype(jnp.float32)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.maximum(temperature, _GREEDY_EPS)[:, None]
    sampled = jax.random.categorical(rng, logits / safe_t,
                                     axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= _GREEDY_EPS, greedy, sampled)
