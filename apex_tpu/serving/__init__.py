"""Serving fast path: KV-cached decode for the GPT model.

The inference half of the library (docs/SERVING.md): a fixed-layout
:class:`~apex_tpu.serving.cache.KVCache`, AOT-compiled prefill/decode
steps with donated cache buffers
(:class:`~apex_tpu.serving.engine.ServingEngine`), fixed-shape sampling
(:mod:`~apex_tpu.serving.sampling`), and a continuous slot batcher
(:class:`~apex_tpu.serving.scheduler.SlotScheduler`) emitting the
``serve/*`` metric family. The request-lifecycle observability layer
(per-request TTFT/TPOT/queue-wait tracing, the Chrome swimlane export,
and SLO goodput tracking) lives in
:mod:`apex_tpu.observability.reqtrace` /
:mod:`~apex_tpu.observability.slo` and is re-exported here for
wiring convenience (``SlotScheduler(engine, trace=..., slo=...)``).
The resilience layer (typed admission rejections, deadlines,
poison-slot quarantine, graceful drain + zero-recompile hot weight
swap, SLO brownout — docs/SERVING.md "Resilience") lives in
:mod:`~apex_tpu.serving.resilience` plus scheduler/engine wiring.

The paged layer (v2, docs/SERVING.md "Paged serving"): a global
:class:`~apex_tpu.serving.cache.PagedKVCache` block pool with a
host-side :class:`~apex_tpu.serving.cache.BlockAllocator` (refcounts,
prefix-hash sharing, copy-on-write) driven by
:class:`~apex_tpu.serving.engine.PagedServingEngine` — decode HBM
traffic O(actual context) instead of O(max_len), admission reserves
blocks instead of whole ``max_len`` slots, and shared prompt prefixes
skip their prefill.

Speculative decoding (docs/SERVING.md "Speculative decoding"): both
engines compile a fourth AOT ``verify`` program at
``speculate_k=k`` that scores a slot's last token plus ``k`` host-drafted
tokens (:class:`~apex_tpu.serving.scheduler.NGramDraftSource`, a
:class:`~apex_tpu.serving.scheduler.DraftSource`) in one pass and
appends the window with a k-token cache write — 1 to ``k + 1`` tokens
per step at one step's HBM cost, greedy streams bitwise-identical to
non-speculative greedy.
"""

from apex_tpu.observability.reqtrace import (RequestRecord, RequestTrace,
                                             chrome_request_trace)
from apex_tpu.observability.slo import (SLOTarget, SLOTracker,
                                        SLOViolationError)
from apex_tpu.serving.cache import (AdmitPlan, BlockAllocator, KVCache,
                                    PagedKVCache, PoolExhausted, StepPlan,
                                    cache_bytes_per_slot,
                                    paged_block_bytes)
from apex_tpu.serving.engine import PagedServingEngine, ServingEngine
from apex_tpu.serving.resilience import (REJECTION_REASONS,
                                         BrownoutPolicy,
                                         CheckpointWatcher, Rejection,
                                         watch_checkpoints)
from apex_tpu.serving.sampling import sample_tokens, verify_tokens
from apex_tpu.serving.scheduler import (Completion, DraftSource,
                                        NGramDraftSource, Request,
                                        SlotScheduler)

__all__ = ["KVCache", "cache_bytes_per_slot", "ServingEngine",
           "PagedKVCache", "BlockAllocator", "AdmitPlan", "StepPlan",
           "PoolExhausted", "paged_block_bytes", "PagedServingEngine",
           "sample_tokens", "verify_tokens", "Completion", "Request",
           "SlotScheduler", "DraftSource", "NGramDraftSource",
           "RequestRecord", "RequestTrace", "chrome_request_trace",
           "SLOTarget", "SLOTracker", "SLOViolationError",
           "Rejection", "REJECTION_REASONS", "BrownoutPolicy",
           "CheckpointWatcher", "watch_checkpoints"]
