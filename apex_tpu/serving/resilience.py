"""Serving resilience: typed admission rejections, SLO-driven brownout,
and the serve-while-train checkpoint watcher.

PR 12 made the serving stack *measurable* (request tracing, latency
percentiles, SLO burn rate); this module plus the scheduler/engine
wiring makes it *survivable* — the PAPER's composable-wrapper philosophy
applied to the serving loop the way ``amp``/health hardened the training
loop. Four failure classes, each with a contract test:

- **overload** — ``SlotScheduler(max_queue=...)`` bounds the queue;
  :meth:`~apex_tpu.serving.scheduler.SlotScheduler.submit` returns a
  typed :class:`Rejection` (``reason="queue_full"``) instead of growing
  without bound, and the in-SLO goodput of ADMITTED requests stays
  comparable to an unloaded run (the load-shedding contract);
- **deadlines** — per-:class:`~apex_tpu.serving.scheduler.Request`
  ``deadline_ms`` (or the scheduler's ``default_deadline_ms``) expires
  requests while queued AND mid-flight (``finish_reason="expired"``,
  slot released through the AOT release program), plus
  ``cancel(request_id)``;
- **poison slots** — a quarantine engine
  (``ServingEngine(quarantine=True)``) checks the sampling-path logits
  per slot per decode step; a non-finite slot is retired alone
  (``finish_reason="poisoned"``) with a
  :class:`~apex_tpu.observability.health.CrashDump` flight record,
  instead of burning capacity on NaN context forever;
- **rollover** — ``SlotScheduler.drain(deadline_s=...)`` +
  ``ServingEngine.swap_params`` +
  :class:`CheckpointWatcher`: pick up the latest COMMITTED checkpoint
  from a live training run with zero recompiles (serve-while-train).

:class:`BrownoutPolicy` is the graceful-degradation hook between the
SLO tracker and admission: at burn rate > 1 (on track to violate), shed
new admissions and/or cap ``max_new_tokens`` — degrade, don't collapse.

Everything here is host-side; with every feature off the three AOT
serving programs are byte-identical to a pre-resilience engine's (the
established zero-cost idiom, asserted in ``tests/test_resilience.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

__all__ = ["Rejection", "REJECTION_REASONS", "BrownoutPolicy",
           "CheckpointWatcher", "watch_checkpoints"]

# the closed vocabulary of submit()-time rejections: queue_full (the
# max_queue bound), shed (BrownoutPolicy), draining (a drain() in
# progress), pool_exhausted (a paged engine whose KV block pool could
# never hold the prompt — transient pressure queues instead). Bad INPUT
# (empty/oversized prompt, non-positive deadline, duplicate in-flight
# id) still raises ValueError at the caller — a malformed request is a
# caller bug, not a load condition.
REJECTION_REASONS = ("queue_full", "shed", "draining", "pool_exhausted")


@dataclasses.dataclass(frozen=True)
class Rejection:
    """A typed admission refusal: WHY the request was not enqueued.

    Returned by :meth:`SlotScheduler.submit` (instead of the request id)
    so callers can branch on backpressure — retry with jitter on
    ``queue_full``, fail fast to the user on ``shed``, reroute to
    another replica on ``draining`` — without parsing exception text.
    Check with ``isinstance(r, Rejection)`` — NOT truthiness: request
    id 0 is a valid admission and ints make ``0`` falsy too, so ``if
    not sched.submit(req)`` would misread the first auto-id request as
    rejected. (A Rejection is still falsy, as a belt-and-suspenders for
    admitted-or-None flows, but isinstance is the contract.)"""

    reason: str
    request_id: Optional[int] = None
    detail: str = ""

    def __post_init__(self):
        if self.reason not in REJECTION_REASONS:
            raise ValueError(f"reason must be one of {REJECTION_REASONS}, "
                             f"got {self.reason!r}")

    def __bool__(self) -> bool:
        return False


class BrownoutPolicy:
    """SLO-driven graceful degradation: when the attached
    :class:`~apex_tpu.observability.slo.SLOTracker`'s burn rate crosses
    ``burn_threshold`` (1.0 = on track to violate the SLO), the
    scheduler's admission path consults this policy and either sheds the
    new request (``shed=True`` → :class:`Rejection(reason="shed")`,
    counted as ``serve/shed``) or caps its ``max_new_tokens`` at
    ``cap_max_new_tokens`` — shorter answers for everyone beats no
    answers for some. Both knobs may be combined; shedding wins.

    The engaged/disengaged state is re-evaluated per submission from the
    tracker's rolling window (O(targets) — the incremental counters the
    tracker already maintains) and exported as the 0/1 ``serve/brownout``
    gauge by the scheduler. No device work anywhere.
    """

    def __init__(self, tracker, *, burn_threshold: float = 1.0,
                 shed: bool = True,
                 cap_max_new_tokens: Optional[int] = None):
        if burn_threshold <= 0.0:
            raise ValueError("burn_threshold must be positive, "
                             f"got {burn_threshold!r}")
        if cap_max_new_tokens is not None and cap_max_new_tokens < 1:
            raise ValueError("cap_max_new_tokens must be >= 1, "
                             f"got {cap_max_new_tokens!r}")
        if not shed and cap_max_new_tokens is None:
            raise ValueError("a BrownoutPolicy with shed=False and no "
                             "cap_max_new_tokens would do nothing")
        self.tracker = tracker
        self.burn_threshold = float(burn_threshold)
        self.shed = bool(shed)
        self.cap_max_new_tokens = cap_max_new_tokens

    def engaged(self) -> bool:
        """True when the tracker's worst burn rate exceeds the
        threshold (NaN — an empty window — never engages: a cold server
        must admit; NaN > x is False)."""
        return self.tracker.max_burn_rate() > self.burn_threshold

    def cap(self, max_new_tokens: int) -> int:
        if self.cap_max_new_tokens is None:
            return max_new_tokens
        return min(max_new_tokens, self.cap_max_new_tokens)


class CheckpointWatcher:
    """Serve-while-train: roll the engine's weights onto the latest
    COMMITTED checkpoint step under ``run_dir`` (the
    :func:`~apex_tpu.checkpoint.save_checkpoint` layout a live
    :class:`~apex_tpu.elastic.runner.ElasticRunner` keeps appending to).

    :meth:`poll` is cheap when nothing changed (one ``latest_step``
    directory listing); when a NEW committed step appears it restores
    onto ``target`` (default: arrays shaped like the engine's params —
    the params-only checkpoint a serving deployment publishes), applies
    ``extract`` (for checkpoints whose state pytree nests the model
    params inside larger trainer state — pass the full-state ``target``
    and ``extract=lambda state: state[...]``), and calls
    ``engine.swap_params`` — zero recompiles, donation re-linted. Torn
    dirs (a writer died mid-save) are invisible by construction:
    ``latest_step`` only ever names COMMITTED steps, so the watcher can
    never roll onto a half-written checkpoint.

    Drive it from the serving loop's idle moments (e.g. between
    :meth:`~apex_tpu.serving.scheduler.SlotScheduler.step` calls, or
    after a ``drain()`` for a clean generation boundary). Each rollover
    ticks ``serve/swaps`` on ``registry`` (the process default when
    None — the same fallback the scheduler uses, so the documented
    counter moves without explicit wiring).
    """

    def __init__(self, engine, run_dir: str, *, target: Any = None,
                 extract: Optional[Callable[[Any], Any]] = None,
                 registry=None):
        from apex_tpu.observability import get_registry

        self.engine = engine
        self.run_dir = run_dir
        self.target = target
        self.extract = extract
        self.registry = registry if registry is not None \
            else get_registry()
        self.step: Optional[int] = None  # last step swapped in

    def poll(self) -> Optional[int]:
        """Swap in the newest COMMITTED step if it is newer than the
        last one swapped; returns that step, or None when nothing
        changed (including: no checkpoint exists yet — a serving process
        may outrun its trainer's first save)."""
        from apex_tpu.checkpoint import latest_step, restore_checkpoint
        import jax

        step = latest_step(self.run_dir)
        if step is None or (self.step is not None and step <= self.step):
            return None
        target = self.target
        if target is None:
            target = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                self.engine.params)
        state, _ = restore_checkpoint(self.run_dir, target, step=step)
        params = self.extract(state) if self.extract is not None else state
        self.engine.swap_params(params)
        self.step = step
        self.registry.counter("serve/swaps").inc()
        return step


def watch_checkpoints(engine, run_dir: str, **kw) -> CheckpointWatcher:
    """Convenience spelling: ``watch_checkpoints(engine, run_dir)``
    builds the :class:`CheckpointWatcher` and performs one immediate
    :meth:`~CheckpointWatcher.poll` (rolling onto the latest COMMITTED
    step if one already exists)."""
    watcher = CheckpointWatcher(engine, run_dir, **kw)
    watcher.poll()
    return watcher
