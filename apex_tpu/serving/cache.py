"""KV cache: the fixed-layout pytree the serving fast path decodes from.

One preallocated buffer pair per layer stack — ``k``/``v`` shaped
``(num_layers, max_seqs, num_heads, max_len, head_dim)`` — plus a per-slot
integer write cursor ``lengths``. The layout is chosen so that

- the layer dim scans (``lax.scan`` over the GPT stack feeds each layer
  its ``(S, H, T, D)`` slice, exactly like the stacked params);
- each ``(slot, head)``'s positions are contiguous along ``T`` — the
  stripe the decode kernel streams blockwise
  (:func:`apex_tpu.ops.flash_attention.decode_attention`);
- every program over it is FIXED SHAPE: admission, retirement and
  variable sequence lengths are all expressed through the cursor, never
  through array shapes, so the AOT-compiled decode step never recompiles.

Writes are in-place-friendly by construction: :meth:`KVCache.append` is
one batched ``dynamic_update_slice`` (a scatter over slots) appending one
token to every slot at its own cursor, and :meth:`KVCache.write_prompt`
is a single slot-indexed ``dynamic_update_slice`` — both alias their
donated operands under ``jit`` (asserted in ``tests/test_serving.py``),
so a decode step allocates nothing.

``dtype=jnp.int8`` stores the cache quantized with per-(position, head)
fp32 scales (symmetric absmax over the head dim, quantized at write
time — every token is quantized against its own range, so there is no
prefill-vs-decode calibration order to get wrong). HBM cost per token
drops 2x vs bf16 at ~6% scale overhead; the decode kernel dequantizes
blockwise in VMEM.

**Paged layout (v2, docs/SERVING.md "Paged serving")**: the dense
``(L, S, H, max_len, D)`` reservation pins max_len HBM per slot for its
whole lifetime. :class:`PagedKVCache` replaces it with a global
``(L, num_blocks, H, block_size, D)`` block POOL; which pool blocks a
slot owns is host-side state in :class:`BlockAllocator` (per-slot int32
block tables + cursors, refcounts, a chained prefix-hash index for
copy-on-write prompt sharing). The device pytree holds ONLY the pool
(+ scales) — tables and cursors ride as plain array arguments of the
AOT serving programs, so admission, retirement, block growth, prefix
sharing and COW are all zero-recompile by construction. Block index 0
is the allocator's reserved NULL block: unmapped table entries and
masked writes land there, keeping every device program total.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCache", "cache_bytes_per_slot", "PagedKVCache",
           "BlockAllocator", "AdmitPlan", "StepPlan", "PoolExhausted",
           "paged_block_bytes", "store_roundtrip"]

# floor for the absmax quantization scale: keeps an all-zero row (e.g. a
# never-written slot) from producing 0/0 at dequantization
_MIN_SCALE = 1e-8


def _quantize(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the trailing (head) dim: ``(..., D)`` ->
    ``(int8 (..., D), fp32 scale (...))``."""
    scale = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0,
        _MIN_SCALE)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def store_roundtrip(x: jnp.ndarray, cache_dtype,
                    quantized: bool) -> jnp.ndarray:
    """The store+load image of ``x``: exactly what a later step would
    read back after this cache appended ``x`` (dtype cast, or int8
    quantize + fp32 dequantize). The speculative verify path feeds this
    to the attention merge for cross-draft keys/values, so one k-token
    verify step reproduces the numerics of k single-token steps — the
    greedy bitwise-stream contract rides on it."""
    if quantized:
        q, scale = _quantize(x)
        return q.astype(jnp.float32) * scale[..., None]
    return x.astype(cache_dtype)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class KVCache:
    """See module docstring. Leaves: ``k``, ``v``, ``lengths`` (+
    ``k_scale``/``v_scale`` when quantized)."""

    k: jnp.ndarray                       # (L, S, H, T, D)
    v: jnp.ndarray                       # (L, S, H, T, D)
    lengths: jnp.ndarray                 # (S,) int32 write cursor
    k_scale: Optional[jnp.ndarray] = None  # (L, S, H, T) fp32 iff int8
    v_scale: Optional[jnp.ndarray] = None

    # -- pytree protocol ----------------------------------------------------

    def tree_flatten(self):
        if self.quantized:
            return ((self.k, self.v, self.lengths, self.k_scale,
                     self.v_scale), True)
        return ((self.k, self.v, self.lengths), False)

    @classmethod
    def tree_unflatten(cls, quantized, leaves):
        if quantized:
            return cls(*leaves)
        k, v, lengths = leaves
        return cls(k, v, lengths)

    # -- shape/bookkeeping --------------------------------------------------

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def max_seqs(self) -> int:
        return self.k.shape[1]

    @property
    def num_heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    def nbytes(self) -> int:
        """Total cache bytes (the number capacity planning divides)."""
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in self.tree_flatten()[0])

    # -- construction -------------------------------------------------------

    @classmethod
    def create(cls, num_layers: int, max_seqs: int, num_heads: int,
               max_len: int, head_dim: int,
               dtype=jnp.bfloat16) -> "KVCache":
        """Zero-filled cache. ``dtype=jnp.int8`` enables the quantized
        layout (scales allocated alongside)."""
        shape = (num_layers, max_seqs, num_heads, max_len, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        lengths = jnp.zeros((max_seqs,), jnp.int32)
        if jnp.dtype(dtype) == jnp.int8:
            # two DISTINCT buffers: a shared array would be donated twice
            # by the AOT steps (XLA rejects duplicate donation)
            return cls(k, v, lengths,
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32),
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32))
        return cls(k, v, lengths)

    # -- writes -------------------------------------------------------------

    def _store(self, x: jnp.ndarray):
        """(value-to-store, scale-or-None) in the cache dtype."""
        if self.quantized:
            return _quantize(x)
        return x.astype(self.k.dtype), None

    def append(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
               active: Optional[jnp.ndarray] = None) -> "KVCache":
        """Append one token to EVERY slot at its own cursor:
        ``k_new``/``v_new`` are ``(L, S, H, D)``. Only slots where
        ``active`` (``(S,)`` bool, default all) advance their cursor —
        an idle slot writes its garbage at a FROZEN cursor (overwritten
        by the next prefill) instead of creeping one position per step,
        which would otherwise grow every free slot's attention prefix
        without bound. Slots already at ``max_len`` write NOTHING and
        stay saturated: silently overwriting the last position (the v1
        behavior) corrupted the newest KV entry of any sequence the
        scheduler failed to retire in time — saturation is now loud at
        the scheduler (retire-capacity before the step) and harmless
        here (regression-tested in ``tests/test_serving.py``). One
        batched dynamic_update_slice per array — in-place on donated
        buffers."""
        pos = jnp.minimum(self.lengths, self.max_len - 1)
        # saturated slots must NOT overwrite position max_len-1: write
        # back the value already there (a no-op update keeps the one
        # batched in-place DUS shape the donation contract relies on)
        writable = self.lengths < self.max_len
        L, H, D = self.num_layers, self.num_heads, self.head_dim

        def upd(cache_s, new_s, p, w):
            # per-slot: (L, H, T, D) <- (L, H, 1, D) at position p
            old = jax.lax.dynamic_slice(cache_s, (0, 0, p, 0),
                                        (L, H, 1, D))
            return jax.lax.dynamic_update_slice(
                cache_s, jnp.where(w, new_s[:, :, None, :], old),
                (0, 0, p, 0))

        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        k = jax.vmap(upd, in_axes=(1, 1, 0, 0), out_axes=1)(
            self.k, kq, pos, writable)
        v = jax.vmap(upd, in_axes=(1, 1, 0, 0), out_axes=1)(
            self.v, vq, pos, writable)
        advanced = jnp.minimum(self.lengths + 1, self.max_len)
        if active is not None:
            advanced = jnp.where(jnp.asarray(active, jnp.bool_),
                                 advanced, self.lengths)
        new = {"k": k, "v": v, "lengths": advanced}
        if self.quantized:
            def upd_sc(sc_s, new_s, p, w):
                # per-slot: (L, H, T) <- (L, H, 1) at position p
                old = jax.lax.dynamic_slice(sc_s, (0, 0, p), (L, H, 1))
                return jax.lax.dynamic_update_slice(
                    sc_s, jnp.where(w, new_s[:, :, None], old),
                    (0, 0, p))

            new["k_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0, 0),
                                      out_axes=1)(self.k_scale, ks, pos,
                                                  writable)
            new["v_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0, 0),
                                      out_axes=1)(self.v_scale, vs, pos,
                                                  writable)
        return dataclasses.replace(self, **new)

    def append_k(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                 counts: jnp.ndarray) -> "KVCache":
        """Speculative verify append: write a WINDOW of up to ``K``
        tokens per slot at its cursor in one batched DUS per array —
        ``k_new``/``v_new`` are ``(L, S, H, K, D)`` (row i belongs at
        position ``cursor + i``) and ``counts`` ``(S,)`` int32 is each
        slot's cursor advance (accepted drafts + 1; 0 for
        inactive/failed slots). Every row that FITS below ``max_len`` is
        written — rows past the accepted count hold drafted-but-rejected
        KV, which lands ABOVE the advanced cursor where no read ever
        masks it in and the next step's window overwrites it. That is
        the whole mid-verify rollback story: the cursor only ever moves
        by the accepted count, so retiring a slot at ANY point (deadline,
        poison) can never strand rejected entries below it (negative
        test in ``tests/test_speculative.py``). Near saturation the
        window clamps: rows that would land at or past ``max_len`` are
        dropped and positions below the cursor are written back
        unchanged; a slot AT ``max_len`` writes nothing."""
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        T = self.max_len
        K = k_new.shape[3]
        if K > T:
            raise ValueError(f"verify window {K} exceeds max_len {T}")
        start = jnp.minimum(self.lengths, T - K)
        # >0 only near saturation: the window slid back so it fits, and
        # row r of the new KV sits at window offset r + shift
        shift = self.lengths - start
        w = jnp.arange(K)

        def upd(cache_s, new_s, st, sh):
            # per-slot: (L, H, T, D) window <- (L, H, K, D) at st
            old = jax.lax.dynamic_slice(cache_s, (0, 0, st, 0),
                                        (L, H, K, D))
            r = w - sh
            rows = jnp.take(new_s, jnp.clip(r, 0, K - 1), axis=2)
            vals = jnp.where((r >= 0)[None, None, :, None], rows, old)
            return jax.lax.dynamic_update_slice(cache_s, vals,
                                                (0, 0, st, 0))

        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        k = jax.vmap(upd, in_axes=(1, 1, 0, 0), out_axes=1)(
            self.k, kq, start, shift)
        v = jax.vmap(upd, in_axes=(1, 1, 0, 0), out_axes=1)(
            self.v, vq, start, shift)
        advanced = jnp.minimum(
            self.lengths + jnp.asarray(counts, jnp.int32), T)
        new = {"k": k, "v": v, "lengths": advanced}
        if self.quantized:
            def upd_sc(sc_s, new_s, st, sh):
                old = jax.lax.dynamic_slice(sc_s, (0, 0, st), (L, H, K))
                r = w - sh
                rows = jnp.take(new_s, jnp.clip(r, 0, K - 1), axis=2)
                vals = jnp.where((r >= 0)[None, None, :], rows, old)
                return jax.lax.dynamic_update_slice(sc_s, vals,
                                                    (0, 0, st))

            new["k_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0, 0),
                                      out_axes=1)(self.k_scale, ks,
                                                  start, shift)
            new["v_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0, 0),
                                      out_axes=1)(self.v_scale, vs,
                                                  start, shift)
        return dataclasses.replace(self, **new)

    def write_prompt(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                     slot, true_len) -> "KVCache":
        """Prefill write: ``k_new``/``v_new`` are ``(L, H, P, D)`` for ONE
        slot; positions ``[0, P)`` are overwritten and the slot's cursor
        is set to ``true_len`` (<= P — right-padded prompts write their
        padding too, but the cursor masks it from every future read and
        the next appends overwrite it)."""
        slot = jnp.asarray(slot, jnp.int32)
        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        k = jax.lax.dynamic_update_slice(
            self.k, kq[:, None], (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            self.v, vq[:, None], (0, slot, 0, 0, 0))
        lengths = jax.lax.dynamic_update_slice(
            self.lengths, jnp.asarray(true_len, jnp.int32)[None], (slot,))
        new = {"k": k, "v": v, "lengths": lengths}
        if self.quantized:
            new["k_scale"] = jax.lax.dynamic_update_slice(
                self.k_scale, ks[:, None], (0, slot, 0, 0))
            new["v_scale"] = jax.lax.dynamic_update_slice(
                self.v_scale, vs[:, None], (0, slot, 0, 0))
        return dataclasses.replace(self, **new)


def cache_bytes_per_slot(num_layers: int, num_heads: int, max_len: int,
                         head_dim: int, dtype=jnp.bfloat16) -> int:
    """HBM bytes one sequence slot pins for its whole lifetime — the unit
    of the capacity math in :func:`apex_tpu.serving.engine.suggest_max_seqs`
    (k + v, plus the fp32 scales when int8)."""
    per_pos = 2 * num_layers * num_heads * head_dim * jnp.dtype(dtype).itemsize
    if jnp.dtype(dtype) == jnp.int8:
        per_pos += 2 * num_layers * num_heads * 4
    return per_pos * max_len


def paged_block_bytes(num_layers: int, num_heads: int, block_size: int,
                      head_dim: int, dtype=jnp.bfloat16) -> int:
    """HBM bytes of ONE pool block (k + v across all layers, plus the
    fp32 scales when int8) — the unit of the paged capacity math in
    :meth:`apex_tpu.serving.engine.PagedServingEngine.suggest_pool_blocks`."""
    return cache_bytes_per_slot(num_layers, num_heads, block_size,
                                head_dim, dtype)


# ---------------------------------------------------------------------------
# paged layout: the device-side block pool
# ---------------------------------------------------------------------------

# the reserved null/garbage block: table entry 0 means "unmapped", and
# every masked device write (inactive slot, saturated slot, prompt
# padding past the last real block) is redirected at it — device
# programs stay total and fixed-shape, the allocator simply never hands
# block 0 out
NULL_BLOCK = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVCache:
    """The paged serving cache: a global block pool (see the module
    docstring). Leaves: ``k``, ``v`` (+ ``k_scale``/``v_scale`` when
    quantized) — per-slot block tables and cursors are HOST state
    (:class:`BlockAllocator`) threaded into the AOT programs as plain
    array arguments, never pytree leaves, so they are neither donated
    nor shape-bearing."""

    k: jnp.ndarray                       # (L, NB, H, block_size, D)
    v: jnp.ndarray                       # (L, NB, H, block_size, D)
    k_scale: Optional[jnp.ndarray] = None  # (L, NB, H, block_size) fp32
    v_scale: Optional[jnp.ndarray] = None

    # -- pytree protocol ----------------------------------------------------

    def tree_flatten(self):
        if self.quantized:
            return ((self.k, self.v, self.k_scale, self.v_scale), True)
        return ((self.k, self.v), False)

    @classmethod
    def tree_unflatten(cls, quantized, leaves):
        if quantized:
            return cls(*leaves)
        k, v = leaves
        return cls(k, v)

    # -- shape/bookkeeping --------------------------------------------------

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def num_heads(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    def nbytes(self) -> int:
        """Total pool bytes (the number the paged capacity math sizes)."""
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in self.tree_flatten()[0])

    # -- construction -------------------------------------------------------

    @classmethod
    def create(cls, num_layers: int, num_blocks: int, num_heads: int,
               block_size: int, head_dim: int,
               dtype=jnp.bfloat16) -> "PagedKVCache":
        """Zero-filled pool. ``num_blocks`` INCLUDES the reserved null
        block 0, so the allocatable capacity is ``num_blocks - 1``
        blocks. ``dtype=jnp.int8`` enables the quantized layout."""
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        shape = (num_layers, num_blocks, num_heads, block_size, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        if jnp.dtype(dtype) == jnp.int8:
            # two DISTINCT scale buffers — see KVCache.create
            return cls(k, v,
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32),
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32))
        return cls(k, v)

    # -- writes (device-side, inside the AOT programs) ----------------------

    def _store(self, x: jnp.ndarray):
        if self.quantized:
            return _quantize(x)
        return x.astype(self.k.dtype), None

    def append(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
               block_ids: jnp.ndarray,
               offsets: jnp.ndarray) -> "PagedKVCache":
        """Append one token per slot: ``k_new``/``v_new`` are
        ``(L, S, H, D)``, ``block_ids``/``offsets`` ``(S,)`` int32 name
        the pool block and in-block position each slot writes (the HOST
        computes them from its cursor mirror; masked slots point at the
        null block). One batched scatter per array — in-place on donated
        buffers (asserted by the engine's donation lint)."""
        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        # two advanced indices split by slices -> update dims lead: (S, L, H, D)
        k = self.k.at[:, block_ids, :, offsets, :].set(
            jnp.transpose(kq, (1, 0, 2, 3)), mode="drop")
        v = self.v.at[:, block_ids, :, offsets, :].set(
            jnp.transpose(vq, (1, 0, 2, 3)), mode="drop")
        new = {"k": k, "v": v}
        if self.quantized:
            new["k_scale"] = self.k_scale.at[:, block_ids, :, offsets].set(
                jnp.transpose(ks, (1, 0, 2)), mode="drop")
            new["v_scale"] = self.v_scale.at[:, block_ids, :, offsets].set(
                jnp.transpose(vs, (1, 0, 2)), mode="drop")
        return dataclasses.replace(self, **new)

    def append_k(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                 block_ids: jnp.ndarray,
                 offsets: jnp.ndarray) -> "PagedKVCache":
        """Speculative verify append: up to ``K`` tokens per slot —
        ``k_new``/``v_new`` are ``(L, S, H, K, D)`` and
        ``block_ids``/``offsets`` ``(S, K)`` int32 name each token's
        pool block and in-block position (HOST-computed by
        :meth:`BlockAllocator.verify_targets`; the window may CROSS a
        block boundary, which is why the ids are per-token, not
        per-slot). Masked tokens — inactive slots, rows past capacity —
        aim at the null block. One batched scatter per array, in-place
        on donated buffers; the cursor mirror advances host-side by the
        ACCEPTED count only (:meth:`BlockAllocator.advance_counts`), so
        rejected rows land in slot-private blocks above the cursor."""
        S, K = block_ids.shape
        bid = block_ids.reshape(S * K)
        off = offsets.reshape(S * K)
        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)

        def scatter(pool, x):
            # (L, S, H, K, D) -> (S*K, L, H, D): the two advanced
            # indices are split by a slice, so update dims lead
            upd = jnp.transpose(x, (1, 3, 0, 2, 4)).reshape(
                S * K, x.shape[0], x.shape[2], x.shape[4])
            return pool.at[:, bid, :, off, :].set(upd, mode="drop")

        new = {"k": scatter(self.k, kq), "v": scatter(self.v, vq)}
        if self.quantized:
            def scatter_sc(pool, sc):
                upd = jnp.transpose(sc, (1, 3, 0, 2)).reshape(
                    S * K, sc.shape[0], sc.shape[2])
                return pool.at[:, bid, :, off].set(upd, mode="drop")
            new["k_scale"] = scatter_sc(self.k_scale, ks)
            new["v_scale"] = scatter_sc(self.v_scale, vs)
        return dataclasses.replace(self, **new)

    def write_prompt_blocks(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                            block_row: jnp.ndarray) -> "PagedKVCache":
        """Prefill write: ``k_new``/``v_new`` are ``(L, H, P, D)`` for
        ONE slot with ``P`` a multiple of ``block_size``; ``block_row``
        ``(P // block_size,)`` int32 names the destination pool block of
        each prompt chunk (null entries absorb the padding past the last
        real block). Positions past the true prompt length hold padding
        garbage — the cursor masks them from every read."""
        L, H, P, D = k_new.shape
        bs = self.block_size
        npb = P // bs
        if npb * bs != P:
            raise ValueError(f"prompt window {P} must be a multiple of "
                             f"block_size {bs}")

        def scatter(pool, x):
            # (L, H, P, D) -> (L, NPB, H, bs, D): one advanced index at
            # axis 1 keeps its position, so the update leads with L
            blocks = x.reshape(L, H, npb, bs, D).transpose(0, 2, 1, 3, 4)
            return pool.at[:, block_row].set(blocks, mode="drop")

        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        new = {"k": scatter(self.k, kq), "v": scatter(self.v, vq)}
        if self.quantized:
            def scatter_sc(pool, sc):
                blocks = sc.reshape(L, H, npb, bs).transpose(0, 2, 1, 3)
                return pool.at[:, block_row].set(blocks, mode="drop")
            new["k_scale"] = scatter_sc(self.k_scale, ks)
            new["v_scale"] = scatter_sc(self.v_scale, vs)
        return dataclasses.replace(self, **new)

    def cow_copy(self, src: jnp.ndarray, dst: jnp.ndarray) -> "PagedKVCache":
        """Copy-on-write resolution: pool block ``dst[s] <- src[s]`` per
        slot, BEFORE this step's reads and append (the caller sequences
        it first). The null no-op is ``src == dst == 0`` — block 0 onto
        itself — so a step with no pending COW runs the identical
        program (zero-recompile across admit/COW/retire)."""
        def copy(pool):
            return pool.at[:, dst].set(pool[:, src], mode="drop")
        new = {"k": copy(self.k), "v": copy(self.v)}
        if self.quantized:
            new["k_scale"] = copy(self.k_scale)
            new["v_scale"] = copy(self.v_scale)
        return dataclasses.replace(self, **new)


# ---------------------------------------------------------------------------
# host-side block allocator: refcounts, prefix hashing, copy-on-write
# ---------------------------------------------------------------------------


class PoolExhausted(RuntimeError):
    """No allocatable pool block (free list empty, nothing evictable)."""


@dataclasses.dataclass
class AdmitPlan:
    """What :meth:`BlockAllocator.admit` decided for one admission.

    ``shared_tokens > 0`` means a prefix hit: the first
    ``shared_tokens`` positions are already in mapped (refcounted)
    shared blocks and the engine must run ONLY ``suffix`` through the
    decode program — the TTFT win. ``prefill=True`` is the cold path:
    run the full prefill program into ``block_row``."""

    slot: int
    prompt_len: int
    prefill: bool
    block_row: List[int]        # prefill destinations (cold path only)
    shared_tokens: int = 0
    suffix: Tuple[int, ...] = ()
    cow_pending: bool = False   # the last shared block awaits COW


@dataclasses.dataclass
class StepPlan:
    """Per-decode-step device arguments from
    :meth:`BlockAllocator.prepare_step`: the COW copy pairs (null
    no-ops when nothing is pending) and the slots that could NOT be
    given a block to write (pool exhausted) — the scheduler retires
    those loudly instead of letting a write silently drop."""

    cow_src: np.ndarray         # (S,) int32
    cow_dst: np.ndarray         # (S,) int32
    failed: List[int]


class BlockAllocator:
    """Host-side bookkeeping for a :class:`PagedKVCache` (see the module
    docstring): the free list, per-block refcounts, per-slot block
    tables + cursors (the mirrors threaded into the AOT programs), the
    chained prefix-hash index, and lazily-resolved copy-on-write.

    Prefix sharing: a COLD admission registers each FULL prompt block
    under a chained hash (block i's key digests block i-1's key plus
    the chunk's tokens, so a hit at depth i certifies the whole prefix).
    A later admission walks the chain; hits map the shared blocks into
    its table (refcount++) and skip prefill for the shared span. Hash
    collisions cannot serve wrong KV: every index entry stores its
    exact token chunk and a mismatch falls back to the cold path
    (tested in ``tests/test_paged.py``). Retired blocks whose content
    is still registered park in an LRU "cached" pool (refcount 0, not
    yet freed) so a follow-up admission with the same prefix still
    hits; allocation pressure evicts them oldest-first.

    Copy-on-write: when a hit covers the WHOLE prompt, the admission
    maps the final shared block but must write its own KV into it (the
    last prompt position belongs to this request's divergence point) —
    the block is marked COW-pending and the next
    :meth:`prepare_step` that sees the slot's cursor inside it
    allocates a private copy target; the device copies before it
    writes. Writes into fully-shared spans never happen (appends past
    the shared span land in freshly-owned blocks), so this lazy single
    pending block is the complete COW story."""

    def __init__(self, num_blocks: int, block_size: int,
                 blocks_per_slot: int, max_seqs: int):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.blocks_per_slot = int(blocks_per_slot)
        self.max_seqs = int(max_seqs)
        # LIFO free list; block 0 is the reserved null block
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self.refcount = np.zeros(num_blocks, np.int32)
        self.refcount[NULL_BLOCK] = 1           # pinned forever
        self.tables = np.zeros((max_seqs, blocks_per_slot), np.int32)
        self.lengths = np.zeros(max_seqs, np.int32)
        # prefix index: chain digest -> (block, parent digest, chunk)
        self._index: Dict[bytes, Tuple[int, Optional[bytes],
                                       Tuple[int, ...]]] = {}
        self._block_key: Dict[int, bytes] = {}
        # refcount-0 blocks still registered: evictable LRU
        self._cached: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._cow_pending: Dict[int, int] = {}   # slot -> table index
        # monotonic host counters the scheduler snapshots into serve/*
        self.cow_copies = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0

    # -- capacity -----------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        """Immediately allocatable blocks (free + evictable cached)."""
        return len(self._free) + len(self._cached)

    @property
    def capacity_tokens(self) -> int:
        """Per-slot token capacity (the table width in tokens)."""
        return self.blocks_per_slot * self.block_size

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    # -- low-level block lifecycle ------------------------------------------

    def _evict_one(self) -> int:
        block, _ = self._cached.popitem(last=False)   # oldest first
        self._unregister(block)
        return block

    def _take_block(self) -> int:
        if self._free:
            return self._free.pop()
        if self._cached:
            return self._evict_one()
        raise PoolExhausted(
            f"block pool exhausted: {self.num_blocks - 1} allocatable "
            "blocks all referenced")

    def _unregister(self, block: int) -> None:
        key = self._block_key.pop(block, None)
        if key is not None and self._index.get(key, (None,))[0] == block:
            del self._index[key]

    def _release_block(self, block: int) -> None:
        if block == NULL_BLOCK:
            return
        self.refcount[block] -= 1
        if self.refcount[block] > 0:
            return
        if block in self._block_key:
            # content still registered: park it for prefix reuse
            self._cached[block] = None
        else:
            self._free.append(block)

    def _revive(self, block: int) -> None:
        """refcount 0 -> 1 on a cached (registered, unowned) block."""
        if self.refcount[block] == 0:
            self._cached.pop(block, None)
        self.refcount[block] += 1

    # -- prefix hashing ------------------------------------------------------

    @staticmethod
    def _digest(parent: Optional[bytes],
                chunk: Sequence[int]) -> bytes:
        h = hashlib.sha256(parent or b"")
        h.update(np.asarray(chunk, np.int64).tobytes())
        return h.digest()

    def _chain(self, prompt: Sequence[int]):
        """(digest, chunk) per FULL block of ``prompt``, chained."""
        bs = self.block_size
        out = []
        parent: Optional[bytes] = None
        for i in range(len(prompt) // bs):
            chunk = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
            digest = self._digest(parent, chunk)
            out.append((digest, chunk))
            parent = digest
        return out

    def lookup(self, prompt: Sequence[int]) -> List[int]:
        """Longest verified chain of live shared blocks covering
        ``prompt``'s full-block prefix. Verification compares the STORED
        token chunk, so a digest collision reads as a miss (falls back
        to full prefill — never serves wrong KV)."""
        blocks: List[int] = []
        for digest, chunk in self._chain(prompt):
            entry = self._index.get(digest)
            if entry is None or entry[2] != chunk:
                break
            blocks.append(entry[0])
        return blocks

    # -- admission / registration / release ---------------------------------

    def admit(self, slot: int, prompt: Sequence[int],
              prefill_blocks: int, share: bool = True) -> AdmitPlan:
        """Map ``slot``'s table for ``prompt`` and return the plan.

        ``prefill_blocks`` is the engine's static prompt window in
        blocks — the cold path allocates only ``ceil(P/block_size)``
        real blocks and pads the row with nulls. ``share=False`` forces
        the cold path even on a prefix hit (the engine's
        ``prefix_suffix_cap`` policy). Raises :class:`PoolExhausted`
        when the blocks aren't there (admission control queues on
        that); every partial allocation is rolled back first."""
        P = len(prompt)
        if not 0 <= slot < self.max_seqs:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.max_seqs})")
        if P > self.capacity_tokens:
            raise ValueError(f"prompt length {P} exceeds the per-slot "
                             f"capacity {self.capacity_tokens}")
        if np.any(self.tables[slot] != NULL_BLOCK) or self.lengths[slot]:
            raise ValueError(f"slot {slot} still holds blocks — release "
                             "it before re-admitting")
        shared = self.lookup(prompt) if share else []
        if shared:
            n_shared = len(shared)
            covers_all = n_shared * self.block_size >= P
            # the LAST prompt position is this request's divergence
            # point: it must be decoded (it samples the first token)
            # and its KV written — never shared
            shared_tokens = (P - 1 if covers_all
                             else n_shared * self.block_size)
            for b in shared:
                self._revive(b)
            self.tables[slot, :n_shared] = shared
            self.lengths[slot] = shared_tokens
            if covers_all:
                # the write at P-1 lands INSIDE the final shared block:
                # copy-on-write, resolved lazily at the next step
                self._cow_pending[slot] = n_shared - 1
            self.prefix_hits += 1
            self.prefix_hit_tokens += int(shared_tokens)
            return AdmitPlan(slot, P, prefill=False, block_row=[],
                             shared_tokens=int(shared_tokens),
                             suffix=tuple(int(t)
                                          for t in prompt[shared_tokens:]),
                             cow_pending=covers_all)
        # cold path: real blocks for the prompt, nulls for the padding
        n_real = self.blocks_for(P)
        row: List[int] = []
        try:
            for _ in range(n_real):
                row.append(self._take_block())
        except PoolExhausted:
            for b in row:
                self._free.append(b)
            raise
        for b in row:
            self.refcount[b] = 1
        self.tables[slot, :n_real] = row
        self.lengths[slot] = P
        return AdmitPlan(slot, P, prefill=True,
                         block_row=row + [NULL_BLOCK] *
                         (prefill_blocks - n_real))

    def register_prefix(self, slot: int, prompt: Sequence[int]) -> None:
        """After a COLD prefill lands: index ``slot``'s full prompt
        blocks under their chain digests so later admissions can share
        them. Existing registrations win (their block is already
        shared-ready); a block never re-registers under a second key."""
        for i, (digest, chunk) in enumerate(self._chain(prompt)):
            block = int(self.tables[slot, i])
            if block == NULL_BLOCK or block in self._block_key:
                continue
            if digest in self._index:
                continue
            self._index[digest] = (block, None, chunk)
            self._block_key[block] = digest

    def release(self, slot: int) -> None:
        """Retire ``slot``: every mapped block drops a reference
        (registered blocks park in the prefix cache at refcount 0,
        unregistered ones free immediately); table and cursor zero."""
        for b in self.tables[slot]:
            self._release_block(int(b))
        self.tables[slot] = NULL_BLOCK
        self.lengths[slot] = 0
        self._cow_pending.pop(slot, None)

    # -- per-step device arguments ------------------------------------------

    def append_targets(self, active: np.ndarray):
        """``(block_ids, offsets)`` ``(S,)`` int32 for this step's
        append: each ACTIVE slot writes at its cursor; inactive or
        saturated slots aim at the null block."""
        cur = self.lengths
        bidx = np.minimum(cur // self.block_size,
                          self.blocks_per_slot - 1)
        bid = self.tables[np.arange(self.max_seqs), bidx].copy()
        ok = np.asarray(active, bool) & (cur < self.capacity_tokens)
        bid[~ok] = NULL_BLOCK
        return bid.astype(np.int32), (cur % self.block_size).astype(
            np.int32)

    def verify_targets(self, active: np.ndarray, k: int):
        """``(block_ids, offsets)`` ``(S, k)`` int32 for a k-token
        verify append: ACTIVE slot ``s`` writes token ``i`` at cursor
        position ``cursor + i`` — a window that may cross a block
        boundary, so each token names its own (block, offset) pair.
        Inactive slots and positions past capacity aim at the null
        block. :meth:`prepare_verify` must have mapped the touched
        blocks first."""
        cur = self.lengths[:, None].astype(np.int64)
        pos = cur + np.arange(k)[None, :]                       # (S, k)
        bidx = np.minimum(pos // self.block_size,
                          self.blocks_per_slot - 1)
        bid = np.take_along_axis(self.tables, bidx.astype(np.intp),
                                 axis=1).copy()
        ok = np.asarray(active, bool)[:, None] & \
            (pos < self.capacity_tokens)
        bid[~ok] = NULL_BLOCK
        return bid.astype(np.int32), (pos % self.block_size).astype(
            np.int32)

    def prepare_step(self, active_slots: Sequence[int]) -> StepPlan:
        """Make every active slot writable for ONE append: resolve any
        COW whose block the cursor is about to enter (allocate the
        private copy, swap the table entry, emit the device copy pair)
        and allocate a fresh block where the cursor crossed into an
        unmapped table entry. Slots the pool cannot serve land in
        ``failed`` — the scheduler retires them loudly."""
        return self.prepare_verify(active_slots, 1)

    def prepare_verify(self, active_slots: Sequence[int],
                       k: int) -> StepPlan:
        """:meth:`prepare_step` generalized to a k-token verify window:
        every block the window ``[cursor, cursor + k)`` touches — up to
        ``ceil(k/block_size) + 1`` table entries — is made slot-private
        and writable BEFORE the step: the cursor block's pending COW is
        resolved (rejected drafts must never scribble a shared block)
        and unmapped entries get fresh blocks. Allocation is atomic per
        slot: a slot the pool cannot fully serve rolls its partial
        grab back and lands in ``failed``. Blocks mapped for rows the
        verify then REJECTS stay mapped — they sit above the advanced
        cursor and the next window reuses them; release() frees them
        with the rest of the row."""
        cow_src = np.zeros(self.max_seqs, np.int32)
        cow_dst = np.zeros(self.max_seqs, np.int32)
        failed: List[int] = []
        for slot in active_slots:
            cur = int(self.lengths[slot])
            if cur >= self.capacity_tokens:
                failed.append(slot)
                continue
            first = cur // self.block_size
            last = min((cur + k - 1) // self.block_size,
                       self.blocks_per_slot - 1)
            pend = self._cow_pending.get(slot)
            if pend is not None and pend == first:
                old = int(self.tables[slot, first])
                try:
                    new = self._take_block()
                except PoolExhausted:
                    failed.append(slot)
                    continue
                self.refcount[new] = 1
                self.tables[slot, first] = new
                cow_src[slot] = old
                cow_dst[slot] = new
                # the device copies old -> new THIS step before any
                # write; dropping the reference now is safe because the
                # content survives in the still-live readers' mapping
                self._release_block(old)
                del self._cow_pending[slot]
                self.cow_copies += 1
            taken: List[int] = []
            short = False
            for bidx in range(first, last + 1):
                if self.tables[slot, bidx] != NULL_BLOCK:
                    continue
                try:
                    new = self._take_block()
                except PoolExhausted:
                    short = True
                    break
                self.refcount[new] = 1
                self.tables[slot, bidx] = new
                taken.append(bidx)
            if short:
                # atomic per slot: hand the partial grab back so a
                # sibling slot (or the next step) can use it
                for bidx in taken:
                    b = int(self.tables[slot, bidx])
                    self.tables[slot, bidx] = NULL_BLOCK
                    self._release_block(b)
                failed.append(slot)
        return StepPlan(cow_src, cow_dst, failed)

    def advance(self, slots: Sequence[int]) -> None:
        """Cursor mirror +1 for the slots whose append just landed."""
        for slot in slots:
            self.lengths[slot] = min(int(self.lengths[slot]) + 1,
                                     self.capacity_tokens)

    def advance_counts(self, slots: Sequence[int],
                       counts: Sequence[int]) -> None:
        """Cursor mirror advance by each slot's ACCEPTED verify count —
        the rejected tail of the window stays above the cursor, invisible
        to every read."""
        for slot, n in zip(slots, counts):
            self.lengths[slot] = min(int(self.lengths[slot]) + int(n),
                                     self.capacity_tokens)
