"""KV cache: the fixed-layout pytree the serving fast path decodes from.

One preallocated buffer pair per layer stack — ``k``/``v`` shaped
``(num_layers, max_seqs, num_heads, max_len, head_dim)`` — plus a per-slot
integer write cursor ``lengths``. The layout is chosen so that

- the layer dim scans (``lax.scan`` over the GPT stack feeds each layer
  its ``(S, H, T, D)`` slice, exactly like the stacked params);
- each ``(slot, head)``'s positions are contiguous along ``T`` — the
  stripe the decode kernel streams blockwise
  (:func:`apex_tpu.ops.flash_attention.decode_attention`);
- every program over it is FIXED SHAPE: admission, retirement and
  variable sequence lengths are all expressed through the cursor, never
  through array shapes, so the AOT-compiled decode step never recompiles.

Writes are in-place-friendly by construction: :meth:`KVCache.append` is
one batched ``dynamic_update_slice`` (a scatter over slots) appending one
token to every slot at its own cursor, and :meth:`KVCache.write_prompt`
is a single slot-indexed ``dynamic_update_slice`` — both alias their
donated operands under ``jit`` (asserted in ``tests/test_serving.py``),
so a decode step allocates nothing.

``dtype=jnp.int8`` stores the cache quantized with per-(position, head)
fp32 scales (symmetric absmax over the head dim, quantized at write
time — every token is quantized against its own range, so there is no
prefill-vs-decode calibration order to get wrong). HBM cost per token
drops 2x vs bf16 at ~6% scale overhead; the decode kernel dequantizes
blockwise in VMEM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["KVCache", "cache_bytes_per_slot"]

# floor for the absmax quantization scale: keeps an all-zero row (e.g. a
# never-written slot) from producing 0/0 at dequantization
_MIN_SCALE = 1e-8


def _quantize(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the trailing (head) dim: ``(..., D)`` ->
    ``(int8 (..., D), fp32 scale (...))``."""
    scale = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0,
        _MIN_SCALE)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class KVCache:
    """See module docstring. Leaves: ``k``, ``v``, ``lengths`` (+
    ``k_scale``/``v_scale`` when quantized)."""

    k: jnp.ndarray                       # (L, S, H, T, D)
    v: jnp.ndarray                       # (L, S, H, T, D)
    lengths: jnp.ndarray                 # (S,) int32 write cursor
    k_scale: Optional[jnp.ndarray] = None  # (L, S, H, T) fp32 iff int8
    v_scale: Optional[jnp.ndarray] = None

    # -- pytree protocol ----------------------------------------------------

    def tree_flatten(self):
        if self.quantized:
            return ((self.k, self.v, self.lengths, self.k_scale,
                     self.v_scale), True)
        return ((self.k, self.v, self.lengths), False)

    @classmethod
    def tree_unflatten(cls, quantized, leaves):
        if quantized:
            return cls(*leaves)
        k, v, lengths = leaves
        return cls(k, v, lengths)

    # -- shape/bookkeeping --------------------------------------------------

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def max_seqs(self) -> int:
        return self.k.shape[1]

    @property
    def num_heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    def nbytes(self) -> int:
        """Total cache bytes (the number capacity planning divides)."""
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in self.tree_flatten()[0])

    # -- construction -------------------------------------------------------

    @classmethod
    def create(cls, num_layers: int, max_seqs: int, num_heads: int,
               max_len: int, head_dim: int,
               dtype=jnp.bfloat16) -> "KVCache":
        """Zero-filled cache. ``dtype=jnp.int8`` enables the quantized
        layout (scales allocated alongside)."""
        shape = (num_layers, max_seqs, num_heads, max_len, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        lengths = jnp.zeros((max_seqs,), jnp.int32)
        if jnp.dtype(dtype) == jnp.int8:
            # two DISTINCT buffers: a shared array would be donated twice
            # by the AOT steps (XLA rejects duplicate donation)
            return cls(k, v, lengths,
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32),
                       jnp.full(shape[:-1], _MIN_SCALE, jnp.float32))
        return cls(k, v, lengths)

    # -- writes -------------------------------------------------------------

    def _store(self, x: jnp.ndarray):
        """(value-to-store, scale-or-None) in the cache dtype."""
        if self.quantized:
            return _quantize(x)
        return x.astype(self.k.dtype), None

    def append(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
               active: Optional[jnp.ndarray] = None) -> "KVCache":
        """Append one token to EVERY slot at its own cursor:
        ``k_new``/``v_new`` are ``(L, S, H, D)``. Only slots where
        ``active`` (``(S,)`` bool, default all) advance their cursor —
        an idle slot writes its garbage at a FROZEN cursor (overwritten
        by the next prefill) instead of creeping one position per step,
        which would otherwise grow every free slot's attention prefix
        without bound. Slots already at ``max_len`` overwrite their last
        position and stay saturated (the scheduler retires a sequence
        before that matters). One batched dynamic_update_slice per
        array — in-place on donated buffers."""
        pos = jnp.minimum(self.lengths, self.max_len - 1)

        def upd(cache_s, new_s, p):
            # per-slot: (L, H, T, D) <- (L, H, 1, D) at position p
            return jax.lax.dynamic_update_slice(
                cache_s, new_s[:, :, None, :], (0, 0, p, 0))

        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        k = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(self.k, kq, pos)
        v = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(self.v, vq, pos)
        advanced = jnp.minimum(self.lengths + 1, self.max_len)
        if active is not None:
            advanced = jnp.where(jnp.asarray(active, jnp.bool_),
                                 advanced, self.lengths)
        new = {"k": k, "v": v, "lengths": advanced}
        if self.quantized:
            def upd_sc(sc_s, new_s, p):
                # per-slot: (L, H, T) <- (L, H, 1) at position p
                return jax.lax.dynamic_update_slice(
                    sc_s, new_s[:, :, None], (0, 0, p))

            new["k_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0),
                                      out_axes=1)(self.k_scale, ks, pos)
            new["v_scale"] = jax.vmap(upd_sc, in_axes=(1, 1, 0),
                                      out_axes=1)(self.v_scale, vs, pos)
        return dataclasses.replace(self, **new)

    def write_prompt(self, k_new: jnp.ndarray, v_new: jnp.ndarray,
                     slot, true_len) -> "KVCache":
        """Prefill write: ``k_new``/``v_new`` are ``(L, H, P, D)`` for ONE
        slot; positions ``[0, P)`` are overwritten and the slot's cursor
        is set to ``true_len`` (<= P — right-padded prompts write their
        padding too, but the cursor masks it from every future read and
        the next appends overwrite it)."""
        slot = jnp.asarray(slot, jnp.int32)
        kq, ks = self._store(k_new)
        vq, vs = self._store(v_new)
        k = jax.lax.dynamic_update_slice(
            self.k, kq[:, None], (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            self.v, vq[:, None], (0, slot, 0, 0, 0))
        lengths = jax.lax.dynamic_update_slice(
            self.lengths, jnp.asarray(true_len, jnp.int32)[None], (slot,))
        new = {"k": k, "v": v, "lengths": lengths}
        if self.quantized:
            new["k_scale"] = jax.lax.dynamic_update_slice(
                self.k_scale, ks[:, None], (0, slot, 0, 0))
            new["v_scale"] = jax.lax.dynamic_update_slice(
                self.v_scale, vs[:, None], (0, slot, 0, 0))
        return dataclasses.replace(self, **new)


def cache_bytes_per_slot(num_layers: int, num_heads: int, max_len: int,
                         head_dim: int, dtype=jnp.bfloat16) -> int:
    """HBM bytes one sequence slot pins for its whole lifetime — the unit
    of the capacity math in :func:`apex_tpu.serving.engine.suggest_max_seqs`
    (k + v, plus the fp32 scales when int8)."""
    per_pos = 2 * num_layers * num_heads * head_dim * jnp.dtype(dtype).itemsize
    if jnp.dtype(dtype) == jnp.int8:
        per_pos += 2 * num_layers * num_heads * 4
    return per_pos * max_len
