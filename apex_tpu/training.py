"""High-level train-step builders.

The reference leaves loop assembly to users (NeMo/Megatron-style trainers);
here the one genuinely intricate assembly — the hybrid TP x PP x DP GPT
step with pipelined embedding + tied head — is packaged once and shared by
``examples/gpt_pretrain.py`` and the driver dryrun (``__graft_entry__``),
so the spec plumbing lives in exactly one place.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.config import TrainConfig
from apex_tpu.optimizers import AdamState
from apex_tpu.transformer.amp import GradScaler
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving)
from apex_tpu.utils.vma import cast_to_vma

__all__ = ["GPTHybridTrainer"]


class GPTHybridTrainer:
    """Everything needed to train the flagship GPT over a
    ``tp x pp x dp`` mesh from one :class:`~apex_tpu.config.TrainConfig`:

        trainer = GPTHybridTrainer(cfg, mesh)
        state = trainer.init_state(jax.random.PRNGKey(0))
        step = jax.jit(trainer.train_step)
        loss, *state = step(*state, tokens, targets)

    ``tokens``/``targets``: ``(M, dp*mb, seq)`` int arrays (sharded over
    ``data`` on axis 1). The step runs the pipelined schedule with the
    vocab-parallel embedding on stage 0 and the tied head + loss on the
    last stage, DP grad averaging, MP-synced dynamic loss scaling, and the
    config's optimizer over (stage, shared) params.
    """

    def __init__(self, cfg: TrainConfig, mesh, init_scale: float = 2.0 ** 8):
        self.cfg = cfg
        self.mesh = mesh
        self.pp = cfg.parallel.pipeline_model_parallel_size
        self.model = cfg.build_model()
        self.opt = cfg.build_optimizer()
        self.scaler = GradScaler(init_scale=init_scale)
        _, self.split_params = self.model.stage_fn(self.pp)

    # -- state ------------------------------------------------------------
    def init_state(self, key: jax.Array) -> Tuple[Any, Any, Any, Any]:
        params = self.model.init(key)
        stage_stack = self.split_params(params)
        shared = {"embedding": params["embedding"],
                  "final_ln": params["final_ln"]}
        opt_state = self.opt.init((stage_stack, shared))
        return stage_stack, shared, opt_state, self.scaler.init()

    # -- shardings --------------------------------------------------------
    @staticmethod
    def stage_specs(stage_stack) -> Any:
        # per-layer TP stacks carry (pp, per, tp, ...); ln leaves don't
        return jax.tree_util.tree_map(
            lambda p: P("pipe", None, "tensor") if p.ndim >= 4
            else P("pipe"), stage_stack)

    shared_specs = {
        "embedding": {"word": {"weight": P("tensor")}, "position": P()},
        "final_ln": {"weight": P(), "bias": P()},
    }

    def state_specs(self, stage_stack):
        specs_p = (self.stage_specs(stage_stack), self.shared_specs)
        return (specs_p[0], specs_p[1],
                AdamState(step=P(), exp_avg=specs_p, exp_avg_sq=specs_p),
                P())

    # -- the step ---------------------------------------------------------
    def train_step(self, stage_stack, shared, opt_state, ls, tokens,
                   targets):
        model, opt, scaler, pp = self.model, self.opt, self.scaler, self.pp

        def inner(stage_stack, shared, opt_state, ls, tokens, targets):
            # rebuild the pipeline closures over THIS dp-rank's targets
            stage, embed_fn, head_fn, _, _ = model.pipeline_fns(pp, targets)
            # DDP pattern: params enter the differentiated region
            # data-VARYING so AD yields per-replica grads, averaged
            # explicitly below (pmean = the reference DDP allreduce)
            vary = lambda t: jax.tree_util.tree_map(
                lambda x: cast_to_vma(x, frozenset({"data"})), t)
            my_stage = vary(jax.tree_util.tree_map(
                lambda p: p[0], stage_stack))
            loss, (sg, shg) = \
                forward_backward_pipelining_without_interleaving(
                    stage, tokens, my_stage, loss_fn=head_fn,
                    shared_params=vary(shared), embed_fn=embed_fn,
                    grad_scale=ls.loss_scale)
            grads = (jax.tree_util.tree_map(lambda g: g[None], sg), shg)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            finite = scaler.all_finite_synced(grads)
            new_ls = scaler.update(ls, finite)
            new_p, new_s = opt.step(grads, opt_state,
                                    (stage_stack, shared),
                                    grads_finite=finite)
            return (jax.lax.pmean(loss, "data"), new_p[0], new_p[1],
                    new_s, new_ls)

        sspec = self.stage_specs(stage_stack)
        _, shspec, ospec, lspec = self.state_specs(stage_stack)
        return shard_map(
            inner, mesh=self.mesh,
            in_specs=(sspec, shspec, ospec, lspec,
                      P(None, "data"), P(None, "data")),
            out_specs=(P(), sspec, shspec, ospec, lspec))(
                stage_stack, shared, opt_state, ls, tokens, targets)
