"""High-level train-step builders.

The reference leaves loop assembly to users (NeMo/Megatron-style trainers);
here the one genuinely intricate assembly — the hybrid TP x PP x DP GPT
step with pipelined embedding + tied head — is packaged once and shared by
``examples/gpt_pretrain.py`` and the driver dryrun (``__graft_entry__``),
so the spec plumbing lives in exactly one place.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.config import TrainConfig
from apex_tpu.observability import health as _health
from apex_tpu.observability import ingraph
from apex_tpu.optimizers import AdamState
from apex_tpu.optimizers.distributed_fused import (_DistributedFusedBase,
                                                   ZeroAdamState)
from apex_tpu.parallel.distributed import allreduce_grads
from apex_tpu.transformer.amp import GradScaler
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving)
from apex_tpu.utils.compat import (HAS_VMA, shard_map_unchecked,
                                   axis_size as _compat_axis_size)
from apex_tpu.utils.vma import cast_to_vma, scan_stable_vma

__all__ = ["GPTHybridTrainer", "accumulate_gradients",
           "resolve_bucket_bytes"]


def resolve_bucket_bytes(cfg: TrainConfig, model, mesh) -> int:
    """Resolve ``ddp_bucket_bytes="auto"`` for one trainer: price the
    model's per-microbatch fwd+bwd with the pyprof roofline and hand
    :func:`apex_tpu.pyprof.tune_bucket_bytes` the resulting hide window
    (smallest bucket whose RS+AG wire time is fully hideable — see
    pyprof/tune.py for the decision rule).

    Pricing convention: a single-chip twin of the model (tp=1, SP off —
    the sharded program would need a bound mesh just to trace) is traced
    abstractly at the config's ``(micro_batch, seq)`` shape, its modeled
    non-comm time divided by ``tp*pp`` (each chip computes ~1/(tp*pp) of
    the model) and multiplied by the M microbatches whose backwards all
    run inside one sync window. Estimates feed a bucket-size *choice*,
    not a perf claim — candidates are powers of two, so only the order
    of magnitude matters. Deterministic for a given config + device
    spec (the resolved grid is a checkpoint-layout property: the ZeRO
    ``bucket_stamp`` persists it). Unpriceable models fall back loudly
    to ``DEFAULT_BUCKET_BYTES`` inside ``tune_bucket_bytes``."""
    from apex_tpu.observability.registry import get_registry
    from apex_tpu.pyprof import tune_bucket_bytes
    from apex_tpu.pyprof.model import model_program

    mesh_shape = dict(mesh.shape)
    dp = int(mesh_shape.get("data", 1))
    tp = int(mesh_shape.get("tensor", 1))
    pp = int(mesh_shape.get("pipe", 1))
    mb = cfg.batch.micro_batch_size
    num_micro = max(1, cfg.batch.global_batch_size // max(1, mb * dp))
    try:
        twin = type(model)(dataclasses.replace(
            model.cfg, tensor_model_parallel_size=1,
            sequence_parallel=False, tp_comm_overlap=False))
        pshapes = jax.eval_shape(twin.init, jax.random.PRNGKey(0))
        # per-chip on BOTH sides of the decision rule: each chip syncs
        # its own 1/(tp*pp) parameter shard over the dp ring, and hides
        # it under its own 1/(tp*pp) slice of the model's compute
        grad_bytes = 4.0 * sum(
            int(np.prod(l.shape)) if l.shape else 1
            for l in jax.tree_util.tree_leaves(pshapes)) / (tp * pp)
        seq = model.cfg.max_position_embeddings
        tokens = jax.ShapeDtypeStruct((mb, seq), jnp.int32)

        def fwd_bwd(params, tokens):
            return jax.grad(lambda p: twin.loss(p, tokens, tokens))(params)

        traced = jax.jit(fwd_bwd).trace(pshapes, tokens)
        cost = model_program(traced)
        hide_ms = sum(max(r.compute_ms, r.hbm_ms)
                      for r in cost.regions.values()) \
            * num_micro / (tp * pp)
        spec = cost.spec
    except Exception as e:
        # loud with the REAL reason — a swallowed pricing error would
        # leave every "auto" run on the default grid with a warning
        # blaming missing inputs instead of the actual failure
        import warnings

        from apex_tpu.parallel.distributed import DEFAULT_BUCKET_BYTES
        warnings.warn(
            f'ddp_bucket_bytes="auto": roofline pricing of the model '
            f"failed ({e!r}); falling back to DEFAULT_BUCKET_BYTES="
            f"{DEFAULT_BUCKET_BYTES}", stacklevel=2)
        resolved = DEFAULT_BUCKET_BYTES
    else:
        resolved = tune_bucket_bytes(grad_bytes=grad_bytes, axis_size=dp,
                                     hide_ms=hide_ms, spec=spec)
    get_registry().gauge("ddp/auto_bucket_bytes").set(float(resolved))
    return int(resolved)


def accumulate_gradients(ddp, loss_fn, params, microbatches):
    """Gradient accumulation with one DDP allreduce per window — the real
    implementation of ``DistributedDataParallel(delay_allreduce=True)``
    (apex's ``distributed.py:162`` flag; torch-DDP ``no_sync`` semantics).

    ``loss_fn(params, microbatch) -> scalar``; ``microbatches`` is a pytree
    of arrays with a leading accumulation axis ``K``. Each microbatch is
    differentiated with per-replica (unsynced) grads, the K grad trees are
    summed *locally* in a scan, and :meth:`ddp.sync_gradients
    <apex_tpu.parallel.distributed.DistributedDataParallel.sync_gradients>`
    fires exactly once on the mean — so the jaxpr carries one psum per
    window instead of K (asserted by
    ``tests/test_parallel.py::test_accumulate_gradients_single_psum``),
    cutting DP traffic by K× at identical numerics (grad of the mean loss
    over the window, then DDP's numeric policy).

    Must run where ``ddp.axis_name`` is bound (validated at trace time —
    an unbound axis or an empty window, ``num_micro == 0``, raises
    ``ValueError`` instead of tracing a silently-NaN program). With a
    bucketed ``ddp`` (``DistributedDataParallel(bucket_bytes=...)``) the
    window sync fires as B flat fp32 buckets in the scan epilogue — B
    independent collectives XLA can overlap with epilogue work that does
    not consume the synced grads. Returns ``(mean_loss, synced_grads)``;
    the loss is this replica's local window mean (pmean it over the data
    axis if a replicated value is needed).
    """
    leading = {jnp.shape(l)[0]
               for l in jax.tree_util.tree_leaves(microbatches)}
    if len(leading) != 1:
        raise ValueError(
            f"microbatch leaves disagree on the accumulation axis: "
            f"{sorted(leading)}")
    num_micro = leading.pop()
    if num_micro == 0:
        # without this the scan produces all-zero grads and the 0/0 window
        # mean is a silent NaN loss — fail loudly at trace time instead
        raise ValueError(
            "accumulate_gradients got an empty accumulation window "
            "(num_micro == 0); every microbatch leaf has leading dim 0")
    try:
        _compat_axis_size(ddp.axis_name)
    except Exception as e:
        # axis_size raises (NameError on most jax lines) when the name is
        # unbound; surface a trace-placement error, not a deep psum failure
        raise ValueError(
            f"accumulate_gradients must be traced where ddp.axis_name="
            f"{ddp.axis_name!r} is bound (inside shard_map/pmap over that "
            f"mesh axis); it is not bound here") from e
    params_v = jax.tree_util.tree_map(
        lambda p: cast_to_vma(p, frozenset({ddp.axis_name})), params)

    def body(carry, mb):
        acc, loss_sum = carry
        loss, grads = jax.value_and_grad(loss_fn)(params_v, mb)
        acc = jax.tree_util.tree_map(jnp.add, acc, grads)
        return (acc, loss_sum + loss), None

    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)), params_v)
    (acc, loss_sum), _ = scan_stable_vma(
        body, (zeros, jnp.zeros((), jnp.float32)), microbatches)
    mean_grads = jax.tree_util.tree_map(lambda g: g / num_micro, acc)
    return loss_sum / num_micro, ddp.sync_gradients(mean_grads)


class GPTHybridTrainer:
    """Everything needed to train the flagship GPT over a
    ``tp x pp x dp`` mesh from one :class:`~apex_tpu.config.TrainConfig`:

        trainer = GPTHybridTrainer(cfg, mesh)
        state = trainer.init_state(jax.random.PRNGKey(0))
        step = jax.jit(trainer.train_step)
        loss, *state = step(*state, tokens, targets)

    ``tokens``/``targets``: ``(M, dp*mb, seq)`` int arrays (sharded over
    ``data`` on axis 1). The step runs the pipelined schedule with the
    vocab-parallel embedding on stage 0 and the tied head + loss on the
    last stage, DP grad averaging, MP-synced dynamic loss scaling, and the
    config's optimizer over (stage, shared) params.
    """

    def __init__(self, cfg: TrainConfig, mesh, init_scale: float = 2.0 ** 8,
                 health=None):
        """``health`` is a
        :class:`~apex_tpu.observability.health.HealthConfig` (default:
        the config's ``cfg.build_health()``, itself defaulting to
        ``level="off"``). With any level above off, the numerics watchdog
        rides :meth:`train_step_with_metrics` — ``health/*`` metrics (and
        at ``level="full"`` the data-axis replica-agreement checks) land
        in the step's Metrics pytree; the uninstrumented
        :meth:`train_step` and the ``level="off"`` program stay
        jaxpr-identical to an unconfigured trainer (asserted in tests)."""
        self.mesh = mesh
        self.health = health if health is not None else cfg.build_health()
        self.pp = cfg.parallel.pipeline_model_parallel_size
        self.model = cfg.build_model()
        # DP-sync bucketing (None = per-leaf psums / monolithic ZeRO
        # collectives, provably identical to the pre-bucketing trainer).
        # "auto" resolves HERE, against this model/mesh via the pyprof
        # roofline, and the resolved int is stored back into the config —
        # to_dict()/checkpoint sidecars carry the concrete grid, and the
        # ZeRO bucket_stamp guard keys on the same value.
        bb = cfg.ddp_bucket_bytes
        if bb == "auto":
            cfg = dataclasses.replace(
                cfg, ddp_bucket_bytes=resolve_bucket_bytes(
                    cfg, self.model, mesh))
        elif not (bb is None or isinstance(bb, int)):
            raise ValueError(
                f'ddp_bucket_bytes must be None, an int, or "auto"; '
                f"got {bb!r}")
        self.cfg = cfg
        self.bucket_bytes = cfg.ddp_bucket_bytes
        # Activation-remat policy (apex_tpu/remat.py), resolved by the
        # model from ModelConfig.remat_policy / the deprecated remat bool.
        # The pipelined stage_fn is wrapped inside the model, so the
        # schedules' own remat flag stays False here; surfaced for
        # introspection and for the bench/report plumbing
        # (StepReporter.attach_memory_budget makes the policy's HBM trade
        # measurable as mem/* gauges).
        self.remat_policy = getattr(self.model, "remat_policy", None)
        if (getattr(self.model.cfg, "sequence_parallel", False)
                and not HAS_VMA):
            # The step runs under shard_map_unchecked, which relaxes
            # check_rep on pre-VMA 0.4.x — and with neither the VMA
            # replication rewrite nor the 0.4.x check_rep rewrite active,
            # the SP-split computation hands tensor-replicated params
            # (LNs, position embedding) and the SP boundary activations
            # per-rank PARTIAL cotangents: the loss is exact but the
            # gradients are silently wrong (the degradation class
            # documented in utils/compat.py). Refuse loudly instead.
            raise NotImplementedError(
                "sequence_parallel through GPTHybridTrainer requires "
                "VMA jax (the replication rewrite that supplies the "
                "tensor-axis psums of replicated-param cotangents); this "
                f"jax {jax.__version__} would train on silently wrong "
                "LN/position-embedding grads. Use the model-level SP path "
                "(plain shard_map, full checking) on this jax, or upgrade.")
        self.opt = cfg.build_optimizer()
        # ZeRO (OptimizerConfig.zero): DistributedFused* shards optimizer
        # state 1/dp over the data axis — its init/step run inside the
        # mesh'd region and its grad comm is the reduce_scatter itself
        # (reference:apex/contrib/optimizers/distributed_fused_adam.py:409)
        self.is_zero = isinstance(self.opt, _DistributedFusedBase)
        self.scaler = GradScaler(init_scale=init_scale)
        _, self.split_params = self.model.stage_fn(self.pp)

    # -- state ------------------------------------------------------------
    def init_state(self, key: jax.Array) -> Tuple[Any, Any, Any, Any]:
        params = self.model.init(key)
        stage_stack = self.split_params(params)
        shared = {"embedding": params["embedding"],
                  "final_ln": params["final_ln"]}
        if self.is_zero:
            sspec = self.stage_specs(stage_stack)
            opt = self.opt

            def init_inner(stage_stack, shared):
                return opt.init((stage_stack, shared))

            opt_state = jax.jit(shard_map_unchecked(
                init_inner, mesh=self.mesh,
                in_specs=(sspec, self.shared_specs),
                out_specs=self._zero_state_spec()))(stage_stack, shared)
        else:
            opt_state = self.opt.init((stage_stack, shared))
        return stage_stack, shared, opt_state, self.scaler.init()

    def _zero_state_spec(self):
        # every device owns a distinct flat shard (its pipe stage x its
        # tensor slice x its 1/dp chunk): fully sharded along dim 0
        flat = P(("pipe", "data", "tensor"))
        return ZeroAdamState(step=P(), master=flat, exp_avg=flat,
                             exp_avg_sq=flat, bucket_stamp=P())

    # -- shardings --------------------------------------------------------
    @staticmethod
    def stage_specs(stage_stack) -> Any:
        # per-layer TP stacks carry (pp, per, tp, ...); ln leaves don't
        return jax.tree_util.tree_map(
            lambda p: P("pipe", None, "tensor") if p.ndim >= 4
            else P("pipe"), stage_stack)

    shared_specs = {
        "embedding": {"word": {"weight": P("tensor")}, "position": P()},
        "final_ln": {"weight": P(), "bias": P()},
    }

    def state_specs(self, stage_stack):
        specs_p = (self.stage_specs(stage_stack), self.shared_specs)
        ospec = (self._zero_state_spec() if self.is_zero else
                 AdamState(step=P(), exp_avg=specs_p, exp_avg_sq=specs_p))
        return (specs_p[0], specs_p[1], ospec, P())

    # -- the step ---------------------------------------------------------
    def train_step(self, stage_stack, shared, opt_state, ls, tokens,
                   targets):
        return self._step_impl(False, stage_stack, shared, opt_state, ls,
                               tokens, targets)

    def jit_train_step(self, with_metrics: bool = False,
                       donate: bool = True,
                       verify_donation: bool = False):
        """``jax.jit`` of :meth:`train_step` (or
        :meth:`train_step_with_metrics`) with ``stage_stack``/``shared``/
        ``opt_state`` donated (``donate_argnums=(0, 1, 2)``): the step
        consumes each and returns its successor, so donation lets XLA
        update parameters and optimizer state in place instead of holding
        both generations live — the per-step HBM high-water drops by about
        a full parameter+optimizer copy (asserted on the compiled
        ``input_output_alias`` in tests). Callers must treat the passed
        state as consumed (standard donated-jit contract); pass
        ``donate=False`` to keep the old copy valid.

        On the ZeRO path the returned callable also validates the
        optimizer state's bucket-grid stamp on its FIRST dispatch — a
        checkpoint trained under a different ``ddp_bucket_bytes`` enters
        the step exactly there, and its bucket-major shard order would
        otherwise be silently permuted (see
        :meth:`~apex_tpu.optimizers.distributed_fused.
        _DistributedFusedBase.check_state`). First-call-only on purpose:
        reading the stamp forces a host sync, and every later state is
        this step's own output with the stamp threaded through unchanged
        — a per-step check would serialize the async dispatch pipeline
        for a constant. The ``.lower`` AOT surface is the raw jit's and
        does NOT validate — AOT callers restoring checkpoints must call
        ``trainer.opt.check_state(opt_state)`` themselves.

        ``verify_donation=True`` adds the donation-annotated-entry-point
        self-check (analysis rule ``jaxpr-donation``, docs/ANALYSIS.md)
        on the first dispatch: the step is AOT-compiled (sharded
        programs pair donations with outputs at XLA compile time, not at
        lowering) and every donated leaf must appear in the compiled
        ``input_output_alias``, with no buffer passed twice across the
        donated arguments — raises ``AnalysisError`` otherwise. The
        verified executable then serves every subsequent dispatch, so
        verification costs one AOT compile total, not one extra per
        step; requires ``donate=True`` (and, like any AOT program, the
        argument shapes/shardings of the first call).
        """
        if verify_donation and not donate:
            raise ValueError("verify_donation checks the donated "
                             "program; pass donate=True")
        fn = (self.train_step_with_metrics if with_metrics
              else self.train_step)
        jitted = jax.jit(fn, donate_argnums=(0, 1, 2) if donate else ())
        if not self.is_zero and not verify_donation:
            return jitted
        opt = self.opt if self.is_zero else None
        pending = [True]
        impl = [jitted]

        def checked(stage_stack, shared, opt_state, ls, tokens, targets):
            if pending:
                if opt is not None:
                    opt.check_state(opt_state)
                if verify_donation:
                    from apex_tpu.analysis.program import (
                        check_donation, verify_findings)
                    donated = (stage_stack, shared, opt_state)
                    expected = sum(
                        len(jax.tree_util.tree_leaves(t))
                        for t in donated)
                    compiled = jitted.lower(
                        stage_stack, shared, opt_state, ls, tokens,
                        targets).compile()
                    verify_findings(check_donation(
                        compiled, donated_args=donated,
                        expected_donated=expected,
                        label="GPTHybridTrainer.jit_train_step"),
                        "GPTHybridTrainer.jit_train_step donation")
                    impl[0] = compiled
                pending.clear()
            return impl[0](stage_stack, shared, opt_state, ls, tokens,
                           targets)

        checked.lower = jitted.lower  # raw AOT surface (no stamp check)
        return checked

    def attribution_report(self, stage_stack, shared, opt_state, ls,
                           tokens, targets, *, step_time_s=None, iters=3,
                           spec=None, regions=None, trace_dir=None,
                           spans=None, trace_steps=1):
        """Per-region step-time attribution of THIS trainer's jitted step
        (:mod:`apex_tpu.pyprof`): traces the step over the given state,
        prices every ``named_scope`` region against the chip roofline
        (FLOPs / HBM bytes / ICI bytes — the ``pipe x data x tensor``
        collectives priced ring-hop-aware), measures the wall step time
        when ``step_time_s`` is not supplied (``iters`` timed executions
        of the freshly compiled step, donation off so the caller's state
        stays valid), and returns the
        :class:`~apex_tpu.pyprof._attribute.AttributionReport` — markdown
        via ``.markdown()``, JSONL via ``.json_lines()``, and the
        ``perf/*`` gauges via ``StepReporter.attach_attribution``.
        ``trace_dir``/``spans`` upgrade the exposure accounting from
        modeled-share scaling to measured per-region walls
        (``trace_steps`` = steps the capture spans, so trace walls read
        per-step)."""
        args = (stage_stack, shared, opt_state, ls, tokens, targets)
        traced = jax.jit(self.train_step).trace(*args)
        compiled = traced.lower().compile()
        if step_time_s is None:
            import time as _time
            from apex_tpu.utils.timers import device_fence
            out = compiled(*args)
            device_fence(out)
            t0 = _time.perf_counter()
            for _ in range(max(1, iters)):
                out = compiled(*args)
            device_fence(out)
            step_time_s = (_time.perf_counter() - t0) / max(1, iters)
        from apex_tpu.pyprof import attribute
        kwargs = {} if regions is None else {"regions": regions}
        return attribute(traced, step_time_s, compiled=compiled,
                         spec=spec, trace_dir=trace_dir, spans=spans,
                         trace_steps=trace_steps, **kwargs)

    def train_step_with_metrics(self, stage_stack, shared, opt_state, ls,
                                tokens, targets):
        """:meth:`train_step` plus the step's telemetry: returns
        ``(loss, stage_stack, shared, opt_state, ls, metrics)`` where
        ``metrics`` is an
        :class:`~apex_tpu.observability.ingraph.Metrics` pytree of device
        scalars (``amp/*``, ``ddp/*``, ``pipeline/*``, ``optim/*``),
        already psum/pmean-aggregated over the whole mesh — hand it to a
        :class:`~apex_tpu.observability.report.StepReporter`. Compiles a
        separate program from :meth:`train_step`; the uninstrumented step
        stays byte-identical."""
        return self._step_impl(True, stage_stack, shared, opt_state, ls,
                               tokens, targets)

    def _step_impl(self, with_metrics, stage_stack, shared, opt_state, ls,
                   tokens, targets):
        model, opt, scaler, pp = self.model, self.opt, self.scaler, self.pp

        def body(stage_stack, shared, opt_state, ls, tokens, targets):
            # full-level watchdog: params enter the step data-replicated,
            # so any divergence across the data axis is silent replica
            # corruption; trace-time-gated no-op below level="full"
            _health.observe_replica_agreement((stage_stack, shared),
                                              "data", name="params")
            # rebuild the pipeline closures over THIS dp-rank's targets
            stage, embed_fn, head_fn, _, _ = model.pipeline_fns(pp, targets)
            if getattr(model.cfg, "tp_comm_overlap", False):
                # the pipelined path runs the layer stack via stage_fn (not
                # transform()), so the tp/* ring telemetry is recorded here:
                # M microbatch passes on a (mb, s/tp, h) activation shard
                mcfg = model.cfg
                model.record_tp_overlap(
                    (tokens.shape[1],
                     tokens.shape[2] // mcfg.tensor_model_parallel_size,
                     mcfg.hidden_size),
                    passes=tokens.shape[0])
            # DDP pattern: params enter the differentiated region
            # data-VARYING so AD yields per-replica grads, averaged
            # explicitly below (the instrumented DDP allreduce)
            vary = lambda t: jax.tree_util.tree_map(
                lambda x: cast_to_vma(x, frozenset({"data"})), t)
            my_stage = vary(jax.tree_util.tree_map(
                lambda p: p[0], stage_stack))
            loss, (sg, shg) = \
                forward_backward_pipelining_without_interleaving(
                    stage, tokens, my_stage, loss_fn=head_fn,
                    shared_params=vary(shared), embed_fn=embed_fn,
                    grad_scale=ls.loss_scale)
            grads = (jax.tree_util.tree_map(lambda g: g[None], sg), shg)
            # (ZeRO: the optimizer's psum_scatter/dp IS the DDP mean —
            # reduce_scatter replaces the allreduce, the ZeRO comm win.
            # With bucket_bytes set the apply is backward-interleaved:
            # each bucket's RS ravels span-locally from only its own
            # grad leaves, so the scheduler issues it under the tail of
            # the backward/accumulation window, and each param leaf
            # unravels from only its own buckets' gathers — bucket k's
            # AG rides under bucket k+1's RS + shard math. The finite
            # check below therefore consumes the LOCAL grads, never the
            # bucket collectives: the scale/skip select is one tiny
            # flag the transfers can run under.)
            if self.is_zero:
                # grads are still per-data-rank here, so the skip decision
                # must sync over data too (the reference's distributed
                # optimizer allreduces found_inf over the world,
                # distributed_fused_adam.py:409 region)
                from apex_tpu.amp.scaler import all_finite
                finite = all_finite(
                    grads, axis_names=(*scaler.model_parallel_axes, "data"))
            elif self.bucket_bytes is not None:
                # bucketed epilogue: the finite-check consumes the LOCAL
                # grads, pmin-synced over (mp axes + data) — the
                # reference's distributed found_inf allreduce — so the
                # loss-scale update and skip select depend on one tiny
                # flag, not on the bucket psums, and XLA can run them
                # under the bucket transfers. (A finite local tree whose
                # cross-replica SUM overflows fp32 is the one case this
                # decides differently from checking the synced grads;
                # the reference accepts the same trade.)
                from apex_tpu.amp.scaler import all_finite
                finite = all_finite(
                    grads, axis_names=(*scaler.model_parallel_axes, "data"))
                grads = allreduce_grads(grads, "data",
                                        bucket_bytes=self.bucket_bytes)
            else:
                grads = allreduce_grads(grads, "data")
                finite = scaler.all_finite_synced(grads)
            new_ls = scaler.update(ls, finite)
            new_p, new_s = opt.step(grads, opt_state,
                                    (stage_stack, shared),
                                    grads_finite=finite)
            return (jax.lax.pmean(loss, "data"), new_p[0], new_p[1],
                    new_s, new_ls)

        if with_metrics:
            def inner(*args):
                # reap INSIDE shard_map: the recorded scalars live at this
                # trace level; aggregation over every mesh axis makes them
                # replicated, so a prefix P() out_spec carries them out.
                # The health policy activates around the same trace so the
                # watchdog's trace-time gates see it.
                with _health.activate(self.health):
                    out, metrics = ingraph.reap(body)(*args)
                return out + (ingraph.aggregate(
                    metrics, tuple(self.mesh.axis_names)),)
        else:
            inner = body

        sspec = self.stage_specs(stage_stack)
        _, shspec, ospec, lspec = self.state_specs(stage_stack)
        out_specs = (P(), sspec, shspec, ospec, lspec)
        if with_metrics:
            out_specs = out_specs + (P(),)
        return shard_map_unchecked(
            inner, mesh=self.mesh,
            in_specs=(sspec, shspec, ospec, lspec,
                      P(None, "data"), P(None, "data")),
            out_specs=out_specs)(
                stage_stack, shared, opt_state, ls, tokens, targets)
