"""Roofline cost model: per-``named_scope``-region FLOP/byte accounting.

The reference pyprof's third stage attributes every traced CUDA kernel to
an annotated region and prices it with an analytic FLOP/byte model
(``reference:apex/pyprof/prof/``). The TPU-native rebuild prices the
*program* instead of a kernel trace: it walks a jaxpr (the one artifact
that survives every jax version, carries ``named_scope`` provenance on
each equation, and exists before the first device step runs) and buckets

- ``dot_general``/``conv_general_dilated`` -> FLOPs (XLA's convention:
  2 flops per MAC; transcendentals excluded, elementwise 1/elem) —
  so the totals are directly comparable to
  :func:`~apex_tpu.observability.costs.flops_budget` on programs XLA
  counts fully (no ``while`` bodies — scan with ``unroll=length``
  compiles to one; the walker itself is always scan-aware and multiplies
  by trip count);
- collectives -> ICI wire bytes per rank under the standard ring models:
  ``psum`` moves ``2(n-1)/n`` of its operand, ``all_gather``/
  ``psum_scatter`` ``(n-1)``x the shard / ``(n-1)/n`` of the input, and
  ``ppermute`` exactly one hop — which makes the model ring-hop-aware
  for the decomposed collective-matmul chains of
  ``tensor_parallel/collective_matmul.py`` (tp-1 scanned ppermutes price
  as tp-1 hops, the same traffic as the fused gather they replace);
- everything else -> HBM traffic, estimated as operand+result bytes per
  equation. This ignores fusion, so it is an upper estimate; regions it
  classifies ``compute``- or ``network``-bound are so despite the
  overestimate, and a ``memory`` verdict means "memory-bound even if
  XLA fuses nothing", to be confirmed against ``cost_analysis``'s
  ``bytes accessed``.

by the innermost *known region* on each equation's name stack. Known
regions are the ``scripts/check_annotations.py`` contract table
(mirrored in :data:`DEFAULT_REGIONS`): the model and parallel layers tag
their hot phases (``gpt_attention``, ``tp_row_linear``,
``apex_ddp_allreduce``, ...) and anything outside every known scope
lands in :data:`UNATTRIBUTED`.

Known blind spots (each walk records them in ``ProgramCost.notes``):
``while`` bodies with dynamic trip counts are priced once; ``cond``
branches price as their most expensive branch; Pallas kernels are priced
as kernel-body x grid (Mosaic custom calls report zero cost to XLA, so
this is strictly more information than ``cost_analysis`` has).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.observability.costs import DeviceSpec, device_spec

__all__ = ["DEFAULT_REGIONS", "UNATTRIBUTED", "RegionCost", "ProgramCost",
           "model_program", "jaxpr_of"]

# the attribution vocabulary — every name here is enforced to exist in
# source by scripts/check_annotations.py (and the pyprof smoke test
# asserts this tuple stays a subset of that contract table)
DEFAULT_REGIONS: Tuple[str, ...] = (
    # model phases
    "gpt_embed", "gpt_ln", "gpt_attention", "gpt_mlp", "gpt_head_loss",
    "rn50_stem", "rn50_body", "rn50_head",
    # kernels / parallel layers (nested inside the phases above; the
    # innermost match wins, so these carve their ops out when present)
    "flash_attention", "tp_column_linear", "tp_row_linear",
    # sync / schedule / optimizer machinery
    "apex_ddp_allreduce", "apex_ddp_bucketed_allreduce", "sync_bn_stats",
    "pipeline_tick", "optimizer_step",
    # serving fast path: the decode kernel carves out of gpt_attention;
    # the step scopes catch the non-model work (sampling, cache append)
    # and split prefill from decode from speculative verify programs in
    # a combined trace
    "decode_attention", "serve_prefill", "serve_decode", "serve_verify",
)

UNATTRIBUTED = "(unattributed)"

# ---------------------------------------------------------------------------
# per-equation pricing
# ---------------------------------------------------------------------------

# 1 flop per output element, matching HloCostAnalysis's elementwise
# convention (transcendentals are tracked separately by XLA and excluded
# from its "flops" — mirrored here so totals stay comparable)
_ELEMENTWISE = frozenset({
    "add", "add_any", "sub", "mul", "div", "rem", "max", "min", "neg",
    "abs", "sign", "floor", "ceil", "round", "nextafter", "is_finite",
    "and", "or", "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "eq", "ne", "lt", "le", "gt", "ge",
    "select_n", "clamp", "integer_pow", "square", "real", "imag",
    "conj", "population_count", "clz", "erf_inv",
})

_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "expm1", "log", "log1p", "log2", "sin", "cos", "tan",
    "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh", "asinh",
    "acosh", "atanh", "logistic", "erf", "erfc", "sqrt", "rsqrt", "cbrt",
    "pow", "digamma", "lgamma", "cumlogsumexp",
})

_REDUCERS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_precision",
    "cumsum", "cummax", "cummin", "cumprod",
})

# all-reduce-shaped collectives: ring cost 2(n-1)/n x operand bytes
# (psum2 is the jax-0.4.x lowering of psum inside a checked shard_map —
# the same fallback tests/_jaxpr_utils.py's collective census knows)
_ALLREDUCE = frozenset({"psum", "psum2", "pmax", "pmin"})


def _aval_bytes(aval) -> float:
    try:
        return float(aval.size) * aval.dtype.itemsize
    except Exception:
        return 0.0


def _eqn_io_bytes(eqn) -> float:
    total = 0.0
    for v in eqn.invars:
        total += _aval_bytes(v.aval)
    for v in eqn.outvars:
        total += _aval_bytes(v.aval)
    return total


def _dot_flops(eqn) -> float:
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    batch = 1
    for d in lb:
        batch *= lhs.shape[d]
    contract = 1
    for d in lc:
        contract *= lhs.shape[d]
    m = 1
    for i, s in enumerate(lhs.shape):
        if i not in lc and i not in lb:
            m *= s
    n = 1
    for i, s in enumerate(rhs.shape):
        if i not in rc and i not in _rb:
            n *= s
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    # HloCostAnalysis::HandleConvolution's exact MAC count: per spatial
    # dim, a (kernel tap, output position) pair is a real MAC only when
    # it lands on an actual input element — not padding, and not a
    # base-dilation hole (the transposed/strided-backward conv). The
    # naive out*kernel*in_features formula overcounts edge taps by
    # ~4/(3N) per 3x3-SAME dim, which is ~9% on RN50 at img=64.
    lhs = eqn.invars[0].aval
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    p = eqn.params
    dn = p["dimension_numbers"]
    nd = len(dn.lhs_spec) - 2
    strides = tuple(p.get("window_strides") or (1,) * nd)
    padding = tuple(p.get("padding") or ((0, 0),) * nd)
    lhs_dil = tuple(p.get("lhs_dilation") or (1,) * nd)
    rhs_dil = tuple(p.get("rhs_dilation") or (1,) * nd)
    valid = 1.0
    for i in range(nd):
        n = lhs.shape[dn.lhs_spec[2 + i]]
        k = rhs.shape[dn.rhs_spec[2 + i]]
        o = out.shape[dn.out_spec[2 + i]]
        s, (lo, _hi), b, w = strides[i], padding[i], lhs_dil[i], rhs_dil[i]
        count = 0
        for kidx in range(k):
            off = kidx * w - lo
            if s == 1 and b == 1:
                # contiguous run: 0 <= oidx + off < n
                count += max(0, min(o, n - off) - max(0, -off))
                continue
            for oidx in range(o):
                pos = oidx * s + off
                if pos >= 0 and pos % b == 0 and pos // b < n:
                    count += 1
        valid *= count
    batch = lhs.shape[dn.lhs_spec[0]] // p.get("batch_group_count", 1)
    in_features = rhs.shape[dn.rhs_spec[1]]  # already /groups in the aval
    out_features = out.shape[dn.out_spec[1]]
    return 2.0 * batch * out_features * in_features * valid


def _named_axes(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _axis_product(axes: Sequence[str], axis_env: Dict[str, int]) -> int:
    n = 1
    for a in axes:
        n *= axis_env.get(a, 1)
    return n


def _collective_wire_bytes(eqn, axis_env: Dict[str, int]
                           ) -> Optional[float]:
    """Per-rank ICI wire bytes of a collective equation under the ring
    model, or None when ``eqn`` is not a collective. Unknown axis sizes
    price as n=1 (zero traffic) — the walk notes it."""
    name = eqn.primitive.name
    if name in _ALLREDUCE:
        n = _axis_product(_named_axes(eqn), axis_env)
        bytes_in = sum(_aval_bytes(v.aval) for v in eqn.invars)
        return 2.0 * bytes_in * (n - 1) / n if n > 1 else 0.0
    if name == "all_gather":
        n = _axis_product(_named_axes(eqn), axis_env)
        shard = _aval_bytes(eqn.invars[0].aval)
        return shard * (n - 1)
    if name == "reduce_scatter":  # lax.psum_scatter
        n = _axis_product(_named_axes(eqn), axis_env)
        full = _aval_bytes(eqn.invars[0].aval)
        return full * (n - 1) / n if n > 1 else 0.0
    if name == "all_to_all":
        n = _axis_product(_named_axes(eqn), axis_env)
        full = _aval_bytes(eqn.invars[0].aval)
        return full * (n - 1) / n if n > 1 else 0.0
    if name == "ppermute":
        # one ring hop per call: the decomposed collective-matmul chains
        # (tp-1 scanned ppermutes) price as tp-1 hops via the scan
        # multiplier, not as one fused collective
        return sum(_aval_bytes(v.aval) for v in eqn.invars)
    return None


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return float(eqn.outvars[0].aval.size)
    if name in _TRANSCENDENTAL:
        return 0.0  # XLA books these as transcendentals, not flops
    if name in _REDUCERS:
        return float(eqn.invars[0].aval.size)
    if name in ("reduce_window_sum", "reduce_window_max",
                "reduce_window_min", "reduce_window"):
        out = eqn.outvars[0].aval
        window = 1
        for w in eqn.params.get("window_dimensions", ()):
            window *= w
        return float(out.size) * window
    if name in ("select_and_scatter_add", "select_and_scatter"):
        return 2.0 * float(eqn.invars[0].aval.size)
    if name in ("scatter-add", "scatter_add"):
        return float(eqn.invars[-1].aval.size)
    return 0.0


# ---------------------------------------------------------------------------
# region bucketing
# ---------------------------------------------------------------------------

_IDENT = re.compile(r"[A-Za-z0-9_]+")


def _region_of(stack_str: str, regions: Sequence[str]) -> str:
    """The innermost known region on a ``/``-joined name stack. Transform
    wrappers (``transpose(jvp(gpt_mlp))``, ``rematted_computation/...``)
    are seen through by matching identifiers inside each component; the
    innermost match wins so nested regions (``flash_attention`` inside
    ``gpt_attention``) carve out their own bucket."""
    if not stack_str:
        return UNATTRIBUTED
    known = set(regions)
    for component in reversed(stack_str.split("/")):
        for ident in reversed(_IDENT.findall(component)):
            if ident in known:
                return ident
    return UNATTRIBUTED


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RegionCost:
    """Modeled cost of one named region: raw counts plus, after
    :meth:`finalize`, the roofline times and the binding resource."""
    name: str
    flops: float = 0.0
    comm_bytes: float = 0.0
    hbm_bytes: float = 0.0
    compute_ms: float = 0.0
    hbm_ms: float = 0.0
    comm_ms: float = 0.0
    modeled_ms: float = 0.0
    bound: str = "compute"

    def finalize(self, spec: DeviceSpec) -> "RegionCost":
        self.compute_ms = spec.compute_ms(self.flops)
        self.hbm_ms = spec.hbm_ms(self.hbm_bytes)
        self.comm_ms = spec.comm_ms(self.comm_bytes)
        # roofline: the region takes at least as long as its most
        # contended resource (assumes perfect overlap of the other two)
        self.modeled_ms = max(self.compute_ms, self.hbm_ms, self.comm_ms)
        # ties resolve compute > memory > network (an all-zero region is
        # "compute"-bound, not spuriously "network")
        if self.modeled_ms == self.compute_ms:
            self.bound = "compute"
        elif self.modeled_ms == self.hbm_ms:
            self.bound = "memory"
        else:
            self.bound = "network"
        return self

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProgramCost:
    """Roofline model of a whole program, bucketed by region."""
    regions: Dict[str, RegionCost]
    spec: DeviceSpec
    notes: List[str]

    @property
    def flops(self) -> float:
        return sum(r.flops for r in self.regions.values())

    @property
    def comm_bytes(self) -> float:
        return sum(r.comm_bytes for r in self.regions.values())

    @property
    def hbm_bytes(self) -> float:
        return sum(r.hbm_bytes for r in self.regions.values())

    @property
    def modeled_ms(self) -> float:
        return sum(r.modeled_ms for r in self.regions.values())


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------

def jaxpr_of(program, args: Optional[tuple] = None):
    """The (closed) jaxpr behind ``program``: a ClosedJaxpr passes
    through, anything with a ``.jaxpr`` (``jax.jit(f).trace(*args)``)
    unwraps, and a callable traces via ``jax.make_jaxpr`` when ``args``
    are supplied. A bare ``Compiled``/``Lowered`` has already erased its
    jaxpr — hold the ``Traced`` stage instead (``jit(f).trace(*args)``
    still lowers/compiles to the identical executable)."""
    inner = getattr(program, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return program  # already a ClosedJaxpr
    if inner is not None:
        return jaxpr_of(inner)
    if callable(program) and args is not None:
        import jax
        return jax.make_jaxpr(program)(*args)
    raise TypeError(
        "cannot recover a jaxpr from "
        f"{type(program).__name__}: pass a ClosedJaxpr, a traced stage "
        "(jax.jit(f).trace(*args) — its .lower().compile() is the same "
        "executable), or a callable plus example args")


def _sub_jaxprs(value):
    """Yield every jaxpr reachable from one eqn param value."""
    items = value if isinstance(value, (list, tuple)) else (value,)
    for item in items:
        if hasattr(item, "eqns"):
            yield item
        elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
            yield item.jaxpr


def model_program(program, args: Optional[tuple] = None, *,
                  spec: Optional[DeviceSpec] = None,
                  regions: Sequence[str] = DEFAULT_REGIONS) -> ProgramCost:
    """Walk ``program``'s jaxpr and return the per-region roofline model.

    ``program`` is anything :func:`jaxpr_of` accepts. ``spec`` defaults
    to the first visible device's :func:`~apex_tpu.observability.costs.
    device_spec` (env-overridable). Per-rank convention: inside
    ``shard_map`` the avals are already the per-device shards, so every
    count is what ONE chip computes/moves — the per-chip roofline.
    """
    if spec is None:
        spec = device_spec()
    closed = jaxpr_of(program, args)
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    buckets: Dict[str, RegionCost] = {}
    notes: List[str] = []

    def bucket(region: str) -> RegionCost:
        if region not in buckets:
            buckets[region] = RegionCost(region)
        return buckets[region]

    def note(msg: str) -> None:
        if msg not in notes:
            notes.append(msg)

    def walk(jaxpr, mult: float, prefix: str,
             axis_env: Dict[str, int]) -> None:
        for eqn in jaxpr.eqns:
            own = str(eqn.source_info.name_stack)
            stack = f"{prefix}/{own}" if prefix and own else prefix or own
            name = eqn.primitive.name

            wire = _collective_wire_bytes(eqn, axis_env)
            if wire is not None:
                missing = [a for a in _named_axes(eqn)
                           if a not in axis_env]
                if missing and name != "ppermute":
                    note(f"axis size unknown for {missing} — its "
                         f"{name} priced as traffic-free")
                region = bucket(_region_of(stack, regions))
                region.comm_bytes += mult * wire
                # a collective also reads/writes HBM on both ends
                region.hbm_bytes += mult * _eqn_io_bytes(eqn)
                continue

            inner_mult = mult
            inner_env = axis_env
            if name == "scan":
                inner_mult = mult * int(eqn.params.get("length", 1))
            elif name == "while":
                note("while-loop body priced once (dynamic trip count)")
            elif name == "pallas_call":
                est = eqn.params.get("cost_estimate")
                if est is not None:
                    # the kernel author's own CostEstimate beats the
                    # body x grid heuristic — it can price data-bounded
                    # grids (e.g. paged decode, whose index maps clamp
                    # past-cursor steps so real traffic is O(actual
                    # context), which body x grid cannot see)
                    region = bucket(_region_of(stack, regions))
                    region.flops += mult * float(
                        getattr(est, "flops", 0) or 0)
                    region.hbm_bytes += mult * float(
                        getattr(est, "bytes_accessed", 0) or 0)
                    note("pallas kernels with a CostEstimate priced "
                         "from it")
                    continue
                grid = getattr(eqn.params.get("grid_mapping"), "grid", ())
                for g in grid:
                    if isinstance(g, int):
                        inner_mult *= g
                note("pallas kernels priced as kernel-body x grid")
            elif name == "shard_map":
                mesh = eqn.params.get("mesh")
                shape = getattr(mesh, "shape", None)
                if shape:
                    inner_env = dict(axis_env)
                    inner_env.update({str(k): int(v)
                                      for k, v in dict(shape).items()})

            subs = []
            if name == "cond":
                branches = eqn.params.get("branches", ())
            else:
                branches = ()
                for v in eqn.params.values():
                    subs.extend(_sub_jaxprs(v))

            if branches:
                # price the most expensive branch: exactly one executes
                best, best_cost = None, -1.0
                for br in branches:
                    probe = model_program(br, spec=spec, regions=regions)
                    cost = probe.flops + probe.hbm_bytes
                    if cost > best_cost:
                        best, best_cost = br, cost
                if best is not None:
                    for sub in _sub_jaxprs(best):
                        walk(sub, inner_mult, stack, inner_env)
                continue

            if subs:
                for sub in subs:
                    walk(sub, inner_mult, stack, inner_env)
                continue

            region = bucket(_region_of(stack, regions))
            region.flops += inner_mult * _eqn_flops(eqn)
            region.hbm_bytes += inner_mult * _eqn_io_bytes(eqn)

    walk(jaxpr, 1.0, "", {})
    for region in buckets.values():
        region.finalize(spec)
    ordered = dict(sorted(buckets.items(),
                          key=lambda kv: -kv[1].modeled_ms))
    return ProgramCost(ordered, spec, notes)
