"""Roofline-driven DP bucket autotuning (``ddp_bucket_bytes="auto"``).

The bucketed gradient-sync engine (:mod:`apex_tpu.parallel.distributed`)
trades two quantities against each other: *smaller* buckets mean more
independent collectives in flight — more overlap opportunity under the
backward — but each collective pays a fixed launch/rendezvous latency;
*larger* buckets amortize the latency but serialize more wire time behind
fewer dependency edges, and the tail bucket's transfer has nothing left
to hide under. The right size is the smallest bucket whose wire time is
fully hideable under the compute that runs concurrently with it — a
quantity the :mod:`~apex_tpu.pyprof.model` roofline already prices on
both sides:

- **wire side** — :func:`bucket_wire_ms`: the ring model's per-bucket
  traffic (reduce-scatter ``(n-1)/n`` + all-gather ``(n-1)/n`` of the
  bucket = ``2(n-1)/n`` — the ZeRO chain; the bucketed allreduce moves
  the same ``2(n-1)/n``) over the chip's per-link ICI bandwidth, plus a
  per-collective launch latency floor (the term that makes tiny buckets
  lose);
- **compute side** — the program's modeled non-comm time
  (``max(compute_ms, hbm_ms)`` per region, the roofline's "this work
  occupies the chip regardless of traffic"), which a step spreads
  uniformly over its B buckets: bucket k's transfer hides under the
  ~1/B of backward compute that runs while it is in flight.

:func:`tune_bucket_bytes` evaluates a candidate ladder (powers of two)
and picks the **smallest fully-hideable** candidate; when no candidate is
fully hideable (wire-starved programs) it picks the candidate with the
least total exposed wire time — deterministically, so the choice is
stable across restarts (the resolved size is a ZeRO *layout* property:
``bucket_stamp`` persists it into checkpoints). Programs the model
cannot price (no compute to hide under, a walk failure) fall back LOUDLY
(``warnings.warn``) to
:data:`~apex_tpu.parallel.distributed.DEFAULT_BUCKET_BYTES`.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence, Tuple

from apex_tpu.observability.costs import DeviceSpec, device_spec
from apex_tpu.pyprof.model import DEFAULT_REGIONS, model_program

__all__ = ["tune_bucket_bytes", "bucket_wire_ms", "DEFAULT_CANDIDATES",
           "DEFAULT_COLLECTIVE_LATENCY_US"]

# candidate ladder: 256 KiB .. 64 MiB powers of two. The floor keeps the
# per-collective latency term from dominating; the ceiling is past the
# point where a bucket's transfer can hide under any realistic backward
# slice (torch-DDP's default is 25 MB — inside this ladder).
DEFAULT_CANDIDATES: Tuple[int, ...] = tuple(
    1 << s for s in range(18, 27))  # 256KiB, 512KiB, ..., 64MiB

# per-collective launch/rendezvous latency floor (one-way, per
# collective). ICI collective setup is single-digit microseconds; the
# value only needs the right order of magnitude — it is the term that
# rules out pathologically small buckets, not a precision input.
DEFAULT_COLLECTIVE_LATENCY_US = 5.0


def bucket_wire_ms(bucket_bytes: float, axis_size: int,
                   spec: Optional[DeviceSpec] = None, *,
                   latency_us: float = DEFAULT_COLLECTIVE_LATENCY_US
                   ) -> float:
    """Modeled wire milliseconds of ONE bucket's sync chain over an
    ``axis_size``-rank ring: reduce-scatter + all-gather (the ZeRO
    RS→math→AG chain; the bucketed allreduce's ``2(n-1)/n`` ring psum
    prices identically) plus two collective-launch latencies. Strictly
    monotone in ``bucket_bytes`` and in ``axis_size``; zero at
    ``axis_size == 1`` (no wire, no launch)."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    n = int(axis_size)
    if n <= 1:
        return 0.0
    if spec is None:
        spec = device_spec()
    frac = 2.0 * (n - 1) / n          # RS (n-1)/n + AG (n-1)/n
    return spec.comm_ms(frac * float(bucket_bytes)) \
        + 2.0 * latency_us / 1e3


def _fallback(reason: str) -> int:
    from apex_tpu.parallel.distributed import DEFAULT_BUCKET_BYTES
    warnings.warn(
        f"tune_bucket_bytes: {reason}; falling back to "
        f"DEFAULT_BUCKET_BYTES={DEFAULT_BUCKET_BYTES} "
        f"({DEFAULT_BUCKET_BYTES >> 20} MiB)", stacklevel=3)
    return DEFAULT_BUCKET_BYTES


def tune_bucket_bytes(program=None, *, grad_bytes: float, axis_size: int,
                      spec: Optional[DeviceSpec] = None,
                      hide_ms: Optional[float] = None,
                      passes: int = 1,
                      args: Optional[tuple] = None,
                      regions: Sequence[str] = DEFAULT_REGIONS,
                      candidates: Sequence[int] = DEFAULT_CANDIDATES,
                      latency_us: float = DEFAULT_COLLECTIVE_LATENCY_US
                      ) -> int:
    """Resolve ``ddp_bucket_bytes="auto"``: the smallest candidate bucket
    whose RS+AG wire time is fully hideable under the program's modeled
    compute.

    ``program`` is anything :func:`~apex_tpu.pyprof.model.jaxpr_of`
    accepts (typically the traced per-microbatch fwd+bwd); its modeled
    non-comm time — ``sum(max(compute_ms, hbm_ms))`` over regions, times
    ``passes`` (microbatches per step: the sync fires once per window, so
    every pass's backward is hiding room) — is the hide window.
    ``hide_ms`` supplies that window directly and skips the pricing (the
    testable core). ``grad_bytes`` is the flat fp32 gradient size the
    sync moves (4 x param count); ``axis_size`` the DP ring.

    Decision rule, deterministic by construction: candidate c carves the
    gradient into ``B = ceil(grad_bytes / c)`` buckets, each allotted
    ``hide_ms / B`` of concurrent compute; c is *fully hideable* when
    :func:`bucket_wire_ms`\\(c) fits its allotment. The smallest hideable
    candidate wins (most overlap edges at no exposed wire); if none is
    hideable, the candidate with the least total exposed wire
    ``B x (wire - allotment)`` wins (ties to the smaller size). Returns
    a plain ``int``. Unpriceable inputs — no program and no ``hide_ms``,
    a model walk failure, a non-positive window or ``grad_bytes`` — fall
    back loudly to ``DEFAULT_BUCKET_BYTES`` via ``warnings.warn``.
    """
    if grad_bytes is None or grad_bytes <= 0:
        return _fallback(f"non-positive grad_bytes ({grad_bytes})")
    if hide_ms is None:
        if program is None:
            return _fallback("no program and no hide_ms to price against")
        try:
            cost = model_program(program, args, spec=spec, regions=regions)
        except Exception as e:
            return _fallback(f"program could not be priced ({e!r})")
        spec = cost.spec
        hide_ms = sum(max(r.compute_ms, r.hbm_ms)
                      for r in cost.regions.values()) * max(1, passes)
    if spec is None:
        spec = device_spec()
    if hide_ms <= 0.0:
        return _fallback(f"modeled hide window is {hide_ms} ms — nothing "
                         "to hide transfers under")
    ladder = sorted(int(c) for c in candidates)
    if not ladder or ladder[0] <= 0:
        raise ValueError(f"invalid candidate ladder {candidates!r}")
    best, best_exposed = None, None
    for c in ladder:
        n_buckets = max(1, -(-int(grad_bytes) // c))  # ceil div
        wire = bucket_wire_ms(min(c, grad_bytes), axis_size, spec,
                              latency_us=latency_us)
        allot = hide_ms / n_buckets
        if wire <= allot:
            return c                   # smallest fully-hideable candidate
        exposed = n_buckets * (wire - allot)
        if best_exposed is None or exposed < best_exposed:
            best, best_exposed = c, exposed
    return best
