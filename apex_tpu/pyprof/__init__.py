"""Deprecated stub (SURVEY §7.7): pyprof's NVTX profiling pipeline.

The reference (``reference:apex/pyprof/``, deprecated upstream) implements
annotate (NVTX monkey-patch) -> trace (nvprof) -> attribute (per-kernel
FLOP/byte analysis). The TPU-native workflow lives in
:mod:`apex_tpu.utils.timers`:

- annotate: ``jax.named_scope`` (hot paths in this library are
  pre-annotated — DDP allreduce, SyncBN stats, pipeline tick, flash
  attention);
- trace: :func:`apex_tpu.utils.timers.profile_trace` (``jax.profiler``);
- attribute: the trace viewer (tensorboard/xprof), or
  ``jit(f).lower(...).compile().cost_analysis()`` for static FLOP/byte
  budgets per program.

Any attribute access raises with this guidance.
"""

_MSG = ("apex_tpu.pyprof is a documented stub: use apex_tpu.utils.timers "
        "(profile_trace + jax.named_scope + cost_analysis) — see "
        "apex_tpu/pyprof/__init__.py for the annotate->trace->attribute "
        "mapping.")


def __getattr__(name):
    raise NotImplementedError(_MSG)
