"""pyprof reborn (SURVEY §7.7): annotate -> trace -> attribute for TPU.

The reference (``reference:apex/pyprof/``, deprecated upstream)
implements annotate (NVTX monkey-patch) -> trace (nvprof) -> attribute
(per-kernel FLOP/byte analysis). This package is the TPU-native rebuild
of the same three stages for JAX/XLA programs:

- **annotate** — :func:`annotate` is ``jax.named_scope`` (names reach
  HLO op metadata, jaxpr equations, and captured profiles); the
  library's hot paths are pre-annotated with the region vocabulary in
  :data:`~apex_tpu.pyprof.model.DEFAULT_REGIONS`, statically enforced by
  ``scripts/check_annotations.py``;
- **trace** — ``apex_tpu.utils.timers.profile_trace`` (``jax.profiler``)
  for device traces, or the host-side span buffer in
  :mod:`apex_tpu.observability.trace`; either joins back via
  :func:`~apex_tpu.pyprof._attribute.region_times_from_trace_dir` /
  :func:`~apex_tpu.pyprof._attribute.region_times_from_spans`;
- **attribute** — :func:`~apex_tpu.pyprof.model.model_program` prices
  every region against the chip's roofline
  (:class:`~apex_tpu.observability.costs.DeviceSpec`), and
  :func:`~apex_tpu.pyprof._attribute.attribute` joins the model with a
  measured step into an :class:`~apex_tpu.pyprof._attribute.
  AttributionReport` (markdown table, JSONL, and the
  ``perf/modeled_step_ms`` / ``perf/comm_exposed_ms`` /
  ``perf/overlap_efficiency`` gauges via
  ``StepReporter.attach_attribution``).

Entry points: ``scripts/attribute_step.py --model gpt|rn50`` for the
bench workloads, ``GPTHybridTrainer.attribution_report`` for the hybrid
trainer's own jitted step.

The NVTX-era module names (``pyprof.nvtx``, ``pyprof.prof``,
``pyprof.parse``) remain importable attributes that raise with a
migration pointer — the contract the old stub documented.

The attribution code lives in ``pyprof/_attribute.py`` (underscored ON
PURPOSE, names re-exported here): a ``pyprof/attribute.py`` submodule
would collide with the :func:`attribute` entry point — ``import
apex_tpu.pyprof.attribute`` makes the import system rebind the package
attribute to the module, silently clobbering the function process-wide
(the accepted-wart from PR 6, fixed in PR 11 with a regression test in
``tests/test_pyprof.py``).
"""

from jax import named_scope as annotate  # noqa: F401 — the annotate stage

from apex_tpu.pyprof.model import (  # noqa: F401
    DEFAULT_REGIONS, ProgramCost, RegionCost, UNATTRIBUTED, jaxpr_of,
    model_program)
from apex_tpu.pyprof._attribute import (  # noqa: F401
    AttributionReport, RegionAttribution, attribute,
    region_times_from_spans, region_times_from_trace_dir)
from apex_tpu.pyprof.tune import (  # noqa: F401
    bucket_wire_ms, tune_bucket_bytes)

__all__ = ["annotate", "attribute", "model_program", "jaxpr_of",
           "AttributionReport", "RegionAttribution", "ProgramCost",
           "RegionCost", "DEFAULT_REGIONS", "UNATTRIBUTED",
           "region_times_from_spans", "region_times_from_trace_dir",
           "tune_bucket_bytes", "bucket_wire_ms"]

# NVTX-era surface -> migration pointers (annotate -> trace -> attribute)
_DEPRECATED = {
    "nvtx": ("apex_tpu.pyprof.annotate (jax.named_scope) — hot paths are "
             "pre-annotated; profile_trace captures them"),
    "prof": ("apex_tpu.pyprof.attribute / model_program — the per-region "
             "FLOP/byte roofline attribution"),
    "parse": ("apex_tpu.pyprof.region_times_from_trace_dir — joins a "
              "jax.profiler capture back onto the annotated regions"),
}


def __getattr__(name):
    if name in _DEPRECATED:
        raise NotImplementedError(
            f"apex_tpu.pyprof.{name} is the deprecated NVTX-era surface; "
            f"use {_DEPRECATED[name]}. The TPU-native pipeline is "
            "annotate (jax.named_scope) -> trace "
            "(apex_tpu.utils.timers.profile_trace) -> attribute "
            "(apex_tpu.pyprof.attribute).")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
