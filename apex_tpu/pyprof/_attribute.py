"""Trace attribution: join the roofline model against a measured step.

The reference pyprof's ``prof`` stage joins the nvprof kernel trace
against its analytic model and prints per-kernel utilization
(``reference:apex/pyprof/prof/output.py``). Here the join runs at region
granularity: :func:`attribute` takes the program (for the
:func:`~apex_tpu.pyprof.model.model_program` roofline) plus a measured
step time — and, when available, per-region wall times from drained
:mod:`~apex_tpu.observability.trace` spans or a ``jax.profiler`` trace
directory — and produces an :class:`AttributionReport`:

- per region: modeled FLOPs/bytes, roofline milliseconds, the binding
  resource, the region's share of the step, and ``comm_exposed_ms`` —
  the measured time the region spent beyond max(modeled compute, modeled
  HBM), capped at the region's modeled comm time: communication the
  schedule failed to hide under compute;
- whole step: ``modeled_step_ms`` (the lower bound the tp/dp overlap
  machinery is tuned against), ``comm_exposed_ms`` (sum of the region
  exposures) and ``overlap_efficiency`` = 1 - exposed/modeled-comm (1.0
  = every modeled byte rode under compute; None on comm-free programs).

Without per-region walls the measured step is apportioned by modeled
share (``measured_source="scaled"``) — exposure then reads as each
region's share of the measured-vs-modeled gap, still capped by its
modeled comm. With walls (``measured_source="trace"``) the exposure is a
direct measurement. ``StepReporter.attach_attribution`` lifts the three
whole-step numbers into the ``perf/*`` gauge family.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.observability.costs import DeviceSpec, flops_budget
from apex_tpu.pyprof.model import (DEFAULT_REGIONS, UNATTRIBUTED,
                                   ProgramCost, _region_of, model_program)

__all__ = ["RegionAttribution", "AttributionReport", "attribute",
           "region_times_from_spans", "region_times_from_trace_dir"]


@dataclasses.dataclass
class RegionAttribution:
    name: str
    flops: float
    comm_bytes: float
    hbm_bytes: float
    compute_ms: float
    hbm_ms: float
    comm_ms: float
    modeled_ms: float
    bound: str
    share: float                      # of the whole-step modeled time
    measured_ms: Optional[float] = None
    comm_exposed_ms: Optional[float] = None

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AttributionReport:
    regions: List[RegionAttribution]
    spec: DeviceSpec
    modeled_step_ms: float
    step_time_ms: Optional[float]
    comm_exposed_ms: Optional[float]
    overlap_efficiency: Optional[float]
    flops: float
    xla_flops: Optional[float]        # flops_budget(compiled) when given
    measured_source: str              # "trace" | "scaled" | "none"
    notes: List[str]

    def as_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["spec"] = dataclasses.asdict(self.spec)
        return out

    def markdown(self) -> str:
        """The per-region attribution table, GitHub-markdown."""
        head = ("| region | flops | comm MB | hbm MB | modeled ms | bound "
                "| share | measured ms | comm exposed ms |")
        rule = "|---|---|---|---|---|---|---|---|---|"
        rows = [head, rule]
        for r in self.regions:
            rows.append(
                f"| {r.name} | {r.flops:.3g} | {r.comm_bytes / 1e6:.2f} "
                f"| {r.hbm_bytes / 1e6:.2f} | {r.modeled_ms:.3f} "
                f"| {r.bound} | {r.share:.1%} "
                f"| {'-' if r.measured_ms is None else f'{r.measured_ms:.3f}'} "
                f"| {'-' if r.comm_exposed_ms is None else f'{r.comm_exposed_ms:.3f}'} |")
        foot = [f"modeled_step_ms={self.modeled_step_ms:.3f}"]
        if self.step_time_ms is not None:
            foot.append(f"measured_step_ms={self.step_time_ms:.3f}"
                        f" ({self.measured_source})")
        if self.comm_exposed_ms is not None:
            foot.append(f"comm_exposed_ms={self.comm_exposed_ms:.3f}")
        if self.overlap_efficiency is not None:
            foot.append(f"overlap_efficiency={self.overlap_efficiency:.3f}")
        if self.xla_flops:
            delta = self.flops / self.xla_flops - 1.0
            foot.append(f"modeled_flops={self.flops:.4g} vs "
                        f"xla_flops={self.xla_flops:.4g} ({delta:+.1%})")
        rows.append("")
        rows.append("; ".join(foot))
        for n in self.notes:
            rows.append(f"note: {n}")
        return "\n".join(rows)

    def json_lines(self) -> str:
        """One JSON object per region plus a ``{"region": "_step"}``
        summary line — the JSONL twin of :meth:`markdown`."""
        lines = [json.dumps({"region": r.name, **r.as_dict()})
                 for r in self.regions]
        lines.append(json.dumps({
            "region": "_step", "modeled_step_ms": self.modeled_step_ms,
            "step_time_ms": self.step_time_ms,
            "comm_exposed_ms": self.comm_exposed_ms,
            "overlap_efficiency": self.overlap_efficiency,
            "flops": self.flops, "xla_flops": self.xla_flops,
            "measured_source": self.measured_source,
            "device": self.spec.name, "notes": self.notes}))
        return "\n".join(lines)


def region_times_from_spans(spans, regions: Sequence[str] = DEFAULT_REGIONS
                            ) -> Dict[str, float]:
    """Per-region wall milliseconds from drained
    :class:`~apex_tpu.observability.trace.Span` tuples: a span accrues to
    the innermost known region named in its span name — the same
    innermost-match rule the cost model buckets by, so measured walls and
    modeled costs land in the same region (a ``.../gpt_attention/
    flash_attention`` span accrues to ``flash_attention``, not the outer
    phase). Host-side timers wrap device work conservatively — treat
    these as upper bounds."""
    out: Dict[str, float] = {}
    for span in spans:
        region = _region_of(span.name, regions)
        if region != UNATTRIBUTED:
            out[region] = out.get(region, 0.0) \
                + (span.end - span.start) * 1e3
    return out


def region_times_from_trace_dir(trace_dir: str,
                                regions: Sequence[str] = DEFAULT_REGIONS,
                                steps: int = 1) -> Dict[str, float]:
    """Per-region wall milliseconds from a ``jax.profiler.trace`` log
    directory: sums the durations of Chrome-trace complete events (the
    ``*.trace.json.gz`` the profiler emits) whose name or args mention a
    known region. ``named_scope`` names reach the device events through
    HLO op metadata, so this attributes real kernel time — but fused ops
    carry only one representative name, so treat the split as
    approximate. Events accrue to the *innermost* known region on their
    scope path — the same innermost-match rule the cost model buckets
    by, so nested regions (``flash_attention`` inside ``gpt_attention``)
    carve out their own measured time exactly as they carve out their
    modeled time.

    Normalization — the roofline model is per-chip and per-step, so the
    walls must be too: durations sum *within* each Chrome-trace process
    track (``pid`` — one per device core or derived xprof plane) and
    average *across* tracks, so a multi-chip capture (or xprof's
    duplicate scope planes) reads as one chip's wall, not an
    n_devices-fold sum that would saturate every exposure cap. ``steps``
    is the number of profiled steps the capture spans
    (``profile_trace``-style captures record several): the per-track
    sums divide by it so the result is PER-STEP milliseconds. Returns {}
    when no trace files are found."""
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    acc: Dict[str, Dict[Any, float]] = {}
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    for path in sorted(glob.glob(pattern, recursive=True)):
        try:
            with gzip.open(path, "rt") as f:
                events = json.load(f).get("traceEvents", [])
        except (OSError, ValueError):
            continue
        for ev in events:
            if ev.get("ph") != "X":
                continue
            hay = ev.get("name", "")
            args = ev.get("args")
            if isinstance(args, dict):
                hay += "/" + "/".join(str(v) for v in args.values())
            region = _region_of(hay, regions)
            if region != UNATTRIBUTED:
                track = (path, ev.get("pid", 0))
                per_track = acc.setdefault(region, {})
                per_track[track] = per_track.get(track, 0.0) \
                    + float(ev.get("dur", 0.0)) / 1e3
    return {name: sum(tracks.values()) / len(tracks) / steps
            for name, tracks in acc.items()}


def attribute(program, step_time_s: Optional[float] = None, *,
              args: Optional[tuple] = None,
              compiled=None,
              spec: Optional[DeviceSpec] = None,
              regions: Sequence[str] = DEFAULT_REGIONS,
              region_times: Optional[Dict[str, float]] = None,
              trace_dir: Optional[str] = None,
              spans=None, trace_steps: int = 1) -> AttributionReport:
    """Model ``program`` (see :func:`~apex_tpu.pyprof.model.jaxpr_of` for
    accepted forms) and join it against a measured ``step_time_s``.

    ``compiled`` (the AOT executable, e.g. ``traced.lower().compile()``)
    adds the XLA ``flops_budget`` cross-check to the report.
    ``region_times``, ``spans``, and ``trace_dir`` supply per-region wall
    milliseconds, consulted in that order — the first source that yields
    any region wins, and a source that matches nothing (an empty span
    drain, a trace with no known-region events) falls through to the
    next rather than silently discarding it. Without any, the measured
    step is apportioned by modeled share. ``trace_steps`` is the number
    of steps a ``trace_dir`` capture spans (durations divide by it so
    the walls are per-step; see :func:`region_times_from_trace_dir`).
    """
    cost: ProgramCost = model_program(program, args, spec=spec,
                                      regions=regions)
    spec = cost.spec
    modeled_total = cost.modeled_ms
    step_ms = None if step_time_s is None else step_time_s * 1e3

    if not region_times and spans is not None:
        region_times = region_times_from_spans(spans, regions)
    if not region_times and trace_dir is not None:
        region_times = region_times_from_trace_dir(trace_dir, regions,
                                                   steps=trace_steps)
    if region_times:
        measured_source = "trace"
    elif step_ms is not None:
        measured_source = "scaled"
    else:
        measured_source = "none"

    regions_out: List[RegionAttribution] = []
    exposed_total = 0.0
    comm_total_ms = 0.0
    have_exposure = False
    unmeasured_comm: List[str] = []
    for rc in cost.regions.values():
        share = rc.modeled_ms / modeled_total if modeled_total > 0 else 0.0
        measured = None
        if region_times and rc.name in region_times:
            measured = region_times[rc.name]
        elif measured_source == "scaled" and step_ms is not None:
            measured = step_ms * share
        exposed = None
        if measured is not None:
            # time beyond the on-chip roofline, attributable to unhidden
            # communication — capped at the modeled comm time so a
            # comm-free region can never report exposure
            exposed = min(rc.comm_ms,
                          max(0.0, measured - max(rc.compute_ms,
                                                  rc.hbm_ms)))
            exposed_total += exposed
            have_exposure = True
            # only regions with a measured wall enter the
            # overlap_efficiency denominator: a partial trace (fusion
            # renamed a region's events away) must not let unobserved
            # comm inflate the ratio toward "everything hidden"
            comm_total_ms += rc.comm_ms
        elif rc.comm_ms > 0.0:
            unmeasured_comm.append(rc.name)
        regions_out.append(RegionAttribution(
            name=rc.name, flops=rc.flops, comm_bytes=rc.comm_bytes,
            hbm_bytes=rc.hbm_bytes, compute_ms=rc.compute_ms,
            hbm_ms=rc.hbm_ms, comm_ms=rc.comm_ms,
            modeled_ms=rc.modeled_ms, bound=rc.bound, share=share,
            measured_ms=measured, comm_exposed_ms=exposed))

    xla = flops_budget(compiled) if compiled is not None else None
    overlap = None
    if have_exposure and comm_total_ms > 0.0:
        overlap = min(1.0, max(0.0, 1.0 - exposed_total / comm_total_ms))
    notes = list(cost.notes)
    if have_exposure and unmeasured_comm:
        notes.append(
            "no measured wall for comm-bearing region(s) "
            f"{sorted(unmeasured_comm)} — their modeled comm is excluded "
            "from overlap_efficiency (a partial trace cannot claim their "
            "bytes were hidden)")
    return AttributionReport(
        regions=regions_out, spec=spec, modeled_step_ms=modeled_total,
        step_time_ms=step_ms,
        comm_exposed_ms=exposed_total if have_exposure else None,
        overlap_efficiency=overlap, flops=cost.flops, xla_flops=xla,
        measured_source=measured_source, notes=notes)
