"""apex_tpu — a TPU-native training-acceleration framework.

Ground-up JAX/XLA/Pallas re-design with the capabilities of NVIDIA Apex
(reference: krunt/apex). Package layout mirrors the reference's public surface
(``reference:apex/__init__.py:7-23``) where that surface is worth keeping:

  - :mod:`apex_tpu.amp`            — mixed-precision policies + loss scaling
  - :mod:`apex_tpu.optimizers`     — fused Adam/LAMB/SGD/NovoGrad/Adagrad, LARC
  - :mod:`apex_tpu.normalization`  — fused LayerNorm/RMSNorm (Pallas + XLA)
  - :mod:`apex_tpu.ops`            — fused softmax, cross-entropy, attention, …
  - :mod:`apex_tpu.parallel`       — data-parallel grad sync, SyncBatchNorm
  - :mod:`apex_tpu.transformer`    — Megatron-style TP/PP toolkit on a Mesh
  - :mod:`apex_tpu.contrib`        — sparsity (ASP), transducer, groupbn, …
  - :mod:`apex_tpu.utils`          — rank-aware logging, timers, checkpointing
  - :mod:`apex_tpu.observability`  — metrics registry, in-graph accumulators,
    step reporter + sinks (structured telemetry; see docs/OBSERVABILITY.md)

Unlike the reference there are no compiled extensions to feature-detect
(``reference:apex/__init__.py:13-19``): every op has an XLA path, and Pallas
kernels are selected by capability flags at call time.
"""

__version__ = "0.1.0"

from apex_tpu import amp  # noqa: F401
from apex_tpu.utils.logging import get_logger, setup_logging  # noqa: F401

# Keep heavier subpackages lazily importable: `import apex_tpu` stays cheap,
# while `apex_tpu.optimizers` etc. resolve on first attribute access.
import importlib as _importlib

_LAZY_SUBMODULES = (
    "optimizers", "normalization", "ops", "parallel", "transformer",
    "contrib", "utils", "fp16_utils", "models", "multi_tensor_apply",
    "RNN", "reparameterization", "checkpoint", "config", "pyprof",
    "observability", "remat",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        return _importlib.import_module(f"apex_tpu.{name}")
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY_SUBMODULES))
