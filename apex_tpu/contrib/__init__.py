"""apex_tpu.contrib — optional feature packages (reference:
``apex/contrib``): sparsity (ASP), transducer re-exports.

Unlike the reference there are no compiled extensions to feature-detect;
each subpackage imports on demand.
"""

import importlib as _importlib

_LAZY = ("sparsity",)


def __getattr__(name):
    if name in _LAZY:
        return _importlib.import_module(f"apex_tpu.contrib.{name}")
    raise AttributeError(f"module 'apex_tpu.contrib' has no attribute {name!r}")
