"""ASP — automatic 2:4 structured sparsity, functionally.

Reference: ``reference:apex/contrib/sparsity/asp.py:28-44`` —
``init_model_for_pruning`` attaches mask buffers to whitelisted
Linear/Conv modules, ``init_optimizer_for_pruning`` monkey-patches
``optimizer.step`` to re-apply masks after every update, and
``compute_sparse_masks`` fills the buffers with the "m4n2_1d" pattern
(``sparse_masklib.py:37-66``: per group of 4 consecutive weights along the
input dim, keep the 2 largest magnitudes). The permutation-search quality
recovery (``permutation_lib.py``) lives in
:mod:`apex_tpu.contrib.sparsity.permutation` and is enabled with
``ASP(permute=True)`` — the search math is device-independent; only the
Ampere-side physical relayout has no TPU meaning (masks are elementwise
here), so the permutation expresses itself purely in mask selection.

Functional shape: masks are a boolean pytree mirroring (a whitelisted
subset of) the params — they live beside the params, ride through
:mod:`apex_tpu.checkpoint` like any other state (the role of the buffer
registration + the checkpoint tests
``reference:apex/contrib/sparsity/test/checkpointing_test_part1/2.py``),
and the mask-reapplying optimizer step is a wrapper that zeroes the masked
entries of params (and grads) around the inner update.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ASP", "compute_sparse_masks", "apply_masks", "mn_1d_mask",
           "sparse_parameter_paths"]


def mn_1d_mask(w: jnp.ndarray, m: int = 4, n: int = 2) -> jnp.ndarray:
    """n:m mask along the last axis: in every group of ``m`` consecutive
    elements keep the ``n`` largest |w| (``sparse_masklib.py:37-49``
    ``mn_1d_best``/``m4n2_1d``; exact per-group top-n, not the heuristic
    pattern search)."""
    if w.shape[-1] % m:
        raise ValueError(f"last dim {w.shape[-1]} not divisible by m={m}")
    groups = jnp.abs(w).reshape(*w.shape[:-1], w.shape[-1] // m, m)
    # rank within each group; keep the n largest magnitudes
    order = jnp.argsort(groups, axis=-1)          # ascending
    ranks = jnp.argsort(order, axis=-1)
    keep = ranks >= (m - n)
    return keep.reshape(w.shape)


def _default_whitelist(path: Tuple, leaf: jnp.ndarray, m: int) -> bool:
    """The Linear/Conv whitelist, structurally: float weights with >= 2
    dims whose last dim is m-divisible and reasonably large (the reference
    skips tiny layers the same way)."""
    if not (hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                      jnp.floating)):
        return False
    if leaf.ndim < 2 or leaf.shape[-1] % m or leaf.shape[-1] < 16:
        return False
    name = jax.tree_util.keystr(path).lower()
    blocked = ("bias", "norm", "bn", "ln", "embedding")
    return not any(b in name for b in blocked)


def sparse_parameter_paths(params: Any, m: int = 4,
                           whitelist: Optional[Callable] = None) -> List[str]:
    """Which leaves ASP would prune (diagnostic; the role of
    ``__sparse_parameters``)."""
    wl = whitelist or _default_whitelist
    return [jax.tree_util.keystr(p)
            for p, l in jax.tree_util.tree_leaves_with_path(params)
            if wl(p, l, m)]


def compute_sparse_masks(params: Any, m: int = 4, n: int = 2,
                         whitelist: Optional[Callable] = None,
                         permute: bool = False, **permute_kw) -> Any:
    """Mask pytree: n:m boolean masks for whitelisted leaves, all-True for
    the rest (``ASP.compute_sparse_masks``).

    ``permute=True`` runs the channel-permutation search
    (:mod:`apex_tpu.contrib.sparsity.permutation`,
    ``reference:apex/contrib/sparsity/permutation_lib.py``) per leaf and
    selects each mask under the best found channel grouping — retained
    magnitude is then >= the unpermuted mask's."""
    wl = whitelist or _default_whitelist

    def one(path, leaf):
        if wl(path, leaf, m):
            if permute:
                from apex_tpu.contrib.sparsity.permutation import (
                    permuted_mn_1d_mask)
                return permuted_mn_1d_mask(leaf, m, n, **permute_kw)
            return mn_1d_mask(leaf, m, n)
        return jnp.ones(jnp.shape(leaf), bool)

    return jax.tree_util.tree_map_with_path(one, params)


def apply_masks(params: Any, masks: Any) -> Any:
    """Zero the pruned entries (applied after every optimizer step)."""
    return jax.tree_util.tree_map(
        lambda p, msk: jnp.where(msk, p, jnp.zeros_like(p))
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
        params, masks)


class ASP:
    """Workflow object (``asp.py:28-44``):

        asp = ASP()
        masks = asp.compute_sparse_masks(params)    # flip sparsity on
        opt = asp.init_optimizer_for_pruning(opt, masks)
        params = asp.prune(params, masks)           # one-time prune
        ... normal training; opt.step re-applies masks every update ...

    ``masks`` is ordinary state: checkpoint it next to the params
    (bool leaves survive :mod:`apex_tpu.checkpoint` untouched).
    """

    def __init__(self, m: int = 4, n: int = 2,
                 whitelist: Optional[Callable] = None,
                 permute: bool = False):
        self.m, self.n = m, n
        self.whitelist = whitelist
        self.permute = permute

    def compute_sparse_masks(self, params: Any, **permute_kw) -> Any:
        return compute_sparse_masks(params, self.m, self.n, self.whitelist,
                                    permute=self.permute, **permute_kw)

    def prune(self, params: Any, masks: Any) -> Any:
        return apply_masks(params, masks)

    def init_optimizer_for_pruning(self, optimizer: Any, masks: Any) -> Any:
        """Wrap ``optimizer.step`` so masked entries stay zero after every
        update (the monkey-patched ``step`` of
        ``reference:apex/contrib/sparsity/asp.py`` ``init_optimizer_for_
        pruning``). Grads of pruned entries are zeroed first so momentum
        never accumulates for dead weights."""
        return _MaskedOptimizer(optimizer, masks)


class _MaskedOptimizer:
    def __init__(self, inner: Any, masks: Any):
        self.inner = inner
        self.masks = masks

    def init(self, params: Any) -> Any:
        return self.inner.init(params)

    def step(self, grads: Any, state: Any, params: Any, **kw):
        grads = apply_masks(grads, self.masks)
        new_params, new_state = self.inner.step(grads, state, params, **kw)
        return apply_masks(new_params, self.masks), new_state
