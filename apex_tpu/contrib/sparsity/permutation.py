"""Channel-permutation search — the accuracy-recovery half of 2:4 ASP.

Reference: ``reference:apex/contrib/sparsity/permutation_lib.py`` (925 LoC
orchestration: find input-channel permutations that maximize the magnitude
kept by the n:m mask, then bake them into the graph) and
``reference:apex/contrib/sparsity/permutation_search_kernels/
exhaustive_search.py:371`` (bounded exhaustive over canonical group
partitions, plus greedy channel-swap refinement).

The math is device-independent: pruning groups are ``m`` consecutive
channels along the mask axis, and a permutation that co-locates channels
whose large magnitudes don't collide raises the retained magnitude
("efficacy"). This port keeps the two search kernels —

* **exhaustive** over canonical set-partitions of the channels into
  groups of ``m`` (identity-included, so the result is never worse), for
  small channel counts;
* **bounded greedy channel-swap**: repeated passes over sampled group
  pairs, applying the best single-channel swap per pair while it improves
  (the reference's ``Channel_Swap`` strategy), with optional row
  subsampling to bound cost on big convolutions

— and drops the CUDA-side part that has no TPU meaning: on Ampere the
permutation must be physically materialized so the 2:4 pattern lands in
sparse-tensor-core memory layout; XLA/TPU has no 2:4 MMA, masks are
elementwise, so here the permutation lives purely in *mask selection*
(``compute_sparse_masks(..., permute=True)`` returns masks in the
ORIGINAL channel order whose nonzeros follow the permuted grouping).
"""

from __future__ import annotations

import itertools
from typing import Any, Optional, Tuple

import numpy as np

__all__ = ["permutation_efficacy", "search_channel_permutation",
           "exhaustive_partition_search", "greedy_swap_search",
           "permuted_mn_1d_mask"]


def _as_2d(w: np.ndarray) -> np.ndarray:
    """Collapse every axis but the last (the mask axis) into rows."""
    w = np.abs(np.asarray(w, np.float64))
    return w.reshape(-1, w.shape[-1])


def _retained(w2d: np.ndarray, m: int, n: int) -> float:
    """Sum of magnitudes kept by the n:m mask over consecutive groups."""
    r, c = w2d.shape
    g = w2d.reshape(r, c // m, m)
    part = np.partition(g, m - n, axis=-1)[..., m - n:]
    return float(part.sum())


def permutation_efficacy(w: np.ndarray, perm: np.ndarray,
                         m: int = 4, n: int = 2) -> float:
    """Retained-magnitude sum of the n:m mask after permuting the mask
    axis by ``perm``."""
    return _retained(_as_2d(w)[:, np.asarray(perm)], m, n)


def exhaustive_partition_search(w2d: np.ndarray, m: int, n: int
                                ) -> np.ndarray:
    """Canonical exhaustive search (``exhaustive_search.py:371``): efficacy
    depends only on the *partition* of channels into groups (order within a
    group and of groups is irrelevant), so enumerate set partitions into
    blocks of ``m`` — identity included."""
    c = w2d.shape[1]

    def partitions(chans):
        if not chans:
            yield []
            return
        first, rest = chans[0], chans[1:]
        for combo in itertools.combinations(rest, m - 1):
            block = (first,) + combo
            remaining = [x for x in rest if x not in combo]
            for p in partitions(remaining):
                yield [block] + p

    best_perm, best_eff = np.arange(c), _retained(w2d, m, n)
    for part in partitions(list(range(c))):
        perm = np.asarray([ch for block in part for ch in block])
        eff = _retained(w2d[:, perm], m, n)
        if eff > best_eff:
            best_perm, best_eff = perm, eff
    return best_perm


def greedy_swap_search(w2d: np.ndarray, m: int, n: int,
                       max_passes: int = 10,
                       pairs_per_pass: Optional[int] = None,
                       seed: int = 0) -> np.ndarray:
    """Bounded greedy channel-swap refinement starting from identity: per
    sampled pair of groups, apply the best single-channel swap if it
    raises the two groups' combined retained magnitude; stop after a full
    pass with no improvement. Never worse than identity.

    ``pairs_per_pass`` defaults to ``8 * n_groups`` — all-pairs is
    O(n_groups^2) and takes minutes per pass at transformer widths, so the
    default samples a linear-size subset per pass (random each pass, so
    repeated passes still cover the space)."""
    rng = np.random.RandomState(seed)
    c = w2d.shape[1]
    n_groups = c // m
    if pairs_per_pass is None:
        pairs_per_pass = 8 * n_groups
    perm = np.arange(c)

    def group_eff(cols: np.ndarray) -> float:
        part = np.partition(cols, m - n, axis=-1)[..., m - n:]
        return float(part.sum())

    all_pairs = n_groups * (n_groups - 1) // 2
    for _ in range(max_passes):
        # sample group pairs directly — materializing the O(n_groups^2)
        # pair list would cost the quadratic work the sampling avoids
        if all_pairs <= pairs_per_pass:
            pairs = [(a, b) for a in range(n_groups)
                     for b in range(a + 1, n_groups)]
            rng.shuffle(pairs)
        else:
            ab = rng.randint(0, n_groups, (2 * pairs_per_pass + 16, 2))
            seen = set()
            pairs = []
            for a, b in ab:
                if a == b:
                    continue
                key = (int(min(a, b)), int(max(a, b)))
                if key in seen:
                    continue
                seen.add(key)
                pairs.append(key)
                if len(pairs) == pairs_per_pass:
                    break
        improved = False
        for a, b in pairs:
            ia = perm[a * m:(a + 1) * m].copy()
            ib = perm[b * m:(b + 1) * m].copy()
            cols_a, cols_b = w2d[:, ia], w2d[:, ib]
            base = group_eff(cols_a) + group_eff(cols_b)
            best_delta, best_swap = 0.0, None
            for i in range(m):
                for j in range(m):
                    na, nb = cols_a.copy(), cols_b.copy()
                    na[:, i], nb[:, j] = cols_b[:, j], cols_a[:, i]
                    delta = group_eff(na) + group_eff(nb) - base
                    if delta > best_delta + 1e-12:
                        best_delta, best_swap = delta, (i, j)
            if best_swap is not None:
                i, j = best_swap
                ia[i], ib[j] = ib[j], ia[i]
                perm[a * m:(a + 1) * m] = ia
                perm[b * m:(b + 1) * m] = ib
                improved = True
        if not improved:
            break
    return perm


def search_channel_permutation(w: Any, m: int = 4, n: int = 2,
                               method: str = "auto",
                               max_rows: int = 512,
                               seed: int = 0,
                               **kw) -> Tuple[np.ndarray, float, float]:
    """Find a mask-axis permutation maximizing n:m retained magnitude.

    Returns ``(perm, efficacy_identity, efficacy_permuted)`` with
    ``efficacy_permuted >= efficacy_identity`` guaranteed (identity is
    always a candidate). ``method``: ``"exhaustive"`` (canonical partition
    enumeration; feasible to ~3 groups), ``"greedy"``, or ``"auto"``
    (exhaustive for <= 2m channels, greedy otherwise, matching the
    reference's strategy dispatch). Rows beyond ``max_rows`` are
    subsampled for the SEARCH only (bounded cost on big convs); the
    returned efficacies are measured on the full matrix.
    """
    import jax

    if isinstance(w, jax.core.Tracer):
        raise TypeError(
            "permutation search is host-side numpy (like the reference's "
            "offline permutation_lib) — call compute_sparse_masks("
            "permute=True) outside jit, then feed the resulting masks "
            "into the jitted training step")
    w2d_full = _as_2d(w)
    c = w2d_full.shape[1]
    if c % m:
        raise ValueError(f"channels {c} not divisible by m={m}")
    w2d = w2d_full
    if w2d.shape[0] > max_rows:
        rng = np.random.RandomState(seed)
        w2d = w2d[rng.choice(w2d.shape[0], max_rows, replace=False)]
    if method == "auto":
        method = "exhaustive" if c <= 2 * m else "greedy"
    if method == "exhaustive":
        perm = exhaustive_partition_search(w2d, m, n)
    elif method == "greedy":
        perm = greedy_swap_search(w2d, m, n, seed=seed, **kw)
    else:
        raise ValueError(f"unknown method {method!r}")
    eff_id = _retained(w2d_full, m, n)
    eff_perm = _retained(w2d_full[:, perm], m, n)
    if eff_perm < eff_id:  # subsampled search can regress on full rows
        return np.arange(c), eff_id, eff_id
    return perm, eff_id, eff_perm


def permuted_mn_1d_mask(w, m: int = 4, n: int = 2, **search_kw):
    """n:m mask in ORIGINAL channel order whose nonzeros follow the best
    found permuted grouping — retained magnitude >= the unpermuted mask's.

    (On Ampere the permutation must be physically applied for the sparse
    MMA layout; on TPU masks are elementwise, so mask selection is the
    whole story.)"""
    import jax.numpy as jnp

    from apex_tpu.contrib.sparsity.asp import mn_1d_mask

    perm, _, _ = search_channel_permutation(w, m, n, **search_kw)
    wp = jnp.take(jnp.asarray(w), jnp.asarray(perm), axis=-1)
    mp = mn_1d_mask(wp, m, n)
    inv = np.argsort(perm)
    return jnp.take(mp, jnp.asarray(inv), axis=-1)
