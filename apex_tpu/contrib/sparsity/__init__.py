"""ASP — automatic structured (2:4) sparsity.

Reference: ``reference:apex/contrib/sparsity/asp.py:28-44`` and the mask
pattern library ``sparse_masklib.py``.
"""

from apex_tpu.contrib.sparsity.asp import (  # noqa: F401
    ASP, compute_sparse_masks, apply_masks, mn_1d_mask, sparse_parameter_paths)

__all__ = ["ASP", "compute_sparse_masks", "apply_masks", "mn_1d_mask",
           "sparse_parameter_paths"]
