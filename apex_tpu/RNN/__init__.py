"""Deprecated stub (SURVEY §7.7): ``apex.RNN`` has no TPU port.

The reference package (``reference:apex/RNN/``) is a deprecated
fp16-friendly RNN/LSTM/GRU/mLSTM reimplementation whose upstream docs say
"use torch.nn RNNs". The TPU-native migration:

- plain ``flax.linen.LSTMCell``/``GRUCell`` under ``jax.lax.scan`` —
  fp16/bf16-safe out of the box (XLA accumulates in fp32);
- per-op precision control via :func:`apex_tpu.amp.o1_context` if a cast
  policy is needed.

Any attribute access raises with this guidance.
"""

_MSG = ("apex_tpu.RNN is a documented stub: the reference package is "
        "deprecated. Use flax.linen LSTM/GRU cells under jax.lax.scan "
        "(bf16-safe natively); see apex_tpu/RNN/__init__.py for the "
        "migration notes.")


def __getattr__(name):
    raise NotImplementedError(_MSG)
