"""``apex.RNN`` — fp16/bf16-friendly RNN family, TPU-native.

Reference surface: ``reference:apex/RNN/__init__.py:1`` exports
``LSTM, GRU, ReLU, Tanh, mLSTM`` factories (``models.py:19-53``) built from
``stackedRNN``/``bidirectionalRNN``/``RNNCell`` (``RNNBackend.py:25,90,232``)
and the multiplicative-LSTM cell (``cells.py:55``). Cell math is the
torch-standard LSTM/GRU/RNN set (the reference imports
``torch.nn._functions.rnn`` cells) plus mLSTM:
``m = (x @ Wmih^T) * (h @ Wmhh^T); gates = x @ Wih^T + m @ Whh^T + b``.

TPU design — not a module-graph translation:

* The input-to-hidden projection for ALL timesteps is one big
  ``(T*B, in) x (in, G)`` matmul hoisted out of the recurrence (MXU-sized),
  so the ``lax.scan`` body only carries the unavoidable ``h @ Whh^T``.
* Mixed precision follows the house rule: gate matmuls accumulate fp32
  (``preferred_element_type``), activations/state stay in the input dtype,
  so bf16 sequences train without an analog of the reference's
  fused-pointwise fp16 kernels (``RNNBackend.py``'s fusedBackend).
* ``bidirectional`` runs the reversed scan and concatenates features;
  ``dropout`` applies between stacked layers (not after the last), matching
  torch/``stackedRNN`` semantics.

Protocol matches the repo's param-factory style::

    rnn = LSTM(input_size=32, hidden_size=64, num_layers=2)
    params = rnn.init(jax.random.PRNGKey(0))
    out, (h, c) = rnn(params, x)            # x: (T, B, in); out: (T, B, H)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "ApexRNN"]


def _linear(x: jnp.ndarray, w: jnp.ndarray,
            b: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """``x @ w.T (+ b)`` with fp32 MXU accumulation, cast back to x dtype."""
    y = jax.lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


# gate multiplier + #hidden-states per cell kind (RNNBackend.py:242
# gate_multiplier / n_hidden_states)
_CELLS = {
    "lstm": (4, 2),
    "gru": (3, 1),
    "relu": (1, 1),
    "tanh": (1, 1),
    "mlstm": (4, 2),
}


def _cell_step(kind: str, xg: jnp.ndarray, h: jnp.ndarray,
               c: Optional[jnp.ndarray], p: dict) -> Tuple[jnp.ndarray,
                                                           Optional[jnp.ndarray]]:
    """One recurrence step. ``xg`` is the precomputed input projection
    ``x @ Wih^T + b_ih`` for this timestep. Returns (h', c')."""
    f32 = jnp.float32
    if kind == "lstm" or kind == "mlstm":
        if kind == "mlstm":
            # cells.py:55 — multiplicative intermediate replaces h in the
            # hidden-to-hidden projection
            hm = p["xm"] * _linear(h, p["w_mhh"])
            gates = (xg + _linear(hm, p["w_hh"], p.get("b_hh"))).astype(f32)
        else:
            gates = (xg + _linear(h, p["w_hh"], p.get("b_hh"))).astype(f32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c.astype(f32) + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new.astype(h.dtype), c_new.astype(h.dtype)
    if kind == "gru":
        hg = _linear(h, p["w_hh"], p.get("b_hh")).astype(f32)
        xgf = xg.astype(f32)
        hdim = h.shape[-1]
        r = jax.nn.sigmoid(xgf[..., :hdim] + hg[..., :hdim])
        z = jax.nn.sigmoid(xgf[..., hdim:2 * hdim] + hg[..., hdim:2 * hdim])
        n = jnp.tanh(xgf[..., 2 * hdim:] + r * hg[..., 2 * hdim:])
        h_new = (1.0 - z) * n + z * h.astype(f32)
        return h_new.astype(h.dtype), None
    act = jax.nn.relu if kind == "relu" else jnp.tanh
    pre = (xg + _linear(h, p["w_hh"], p.get("b_hh"))).astype(f32)
    return act(pre).astype(h.dtype), None


@dataclasses.dataclass
class ApexRNN:
    """Stacked (optionally bidirectional) RNN over one cell kind."""

    kind: str
    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    batch_first: bool = False
    dropout: float = 0.0
    bidirectional: bool = False
    output_size: Optional[int] = None
    params_dtype: Any = jnp.float32

    def __post_init__(self):
        if self.kind not in _CELLS:
            raise ValueError(f"unknown cell kind {self.kind!r}")
        self.gate_mult, self.n_states = _CELLS[self.kind]
        # RNNBackend.py:232 RNNCell(output_size): h is projected by w_ho
        # when output_size != hidden_size
        self.proj = (self.output_size is not None
                     and self.output_size != self.hidden_size)
        if self.proj and self.kind == "gru":
            # torch's GRUCell mixes h into the candidate elementwise, so a
            # projected hidden of a different width cannot type-check (the
            # reference inherits the same limitation)
            raise ValueError("output_size projection is not defined for GRU")
        self.out_size = self.output_size if self.proj else self.hidden_size

    # -- params -------------------------------------------------------------

    def _layer_init(self, key, in_size: int) -> dict:
        h, g = self.hidden_size, self.gate_mult
        bound = 1.0 / (h ** 0.5)  # torch RNN reset_parameters
        ks = jax.random.split(key, 7)
        u = lambda k, shape: jax.random.uniform(
            k, shape, self.params_dtype, -bound, bound)
        p = {"w_ih": u(ks[0], (g * h, in_size)),
             "w_hh": u(ks[1], (g * h, self.out_size))}
        if self.bias:
            p["b_ih"] = u(ks[2], (g * h,))
            p["b_hh"] = u(ks[3], (g * h,))
        if self.kind == "mlstm":
            # cells.py mLSTMRNNCell sizes the multiplicative pair by
            # output_size so m matches w_hh's (gate, out_size) contraction
            p["w_mih"] = u(ks[4], (self.out_size, in_size))
            p["w_mhh"] = u(ks[5], (self.out_size, self.out_size))
        if self.proj:
            p["w_ho"] = u(ks[6], (self.out_size, h))
        return p

    def init(self, key: jax.Array) -> dict:
        dirs = 2 if self.bidirectional else 1
        keys = jax.random.split(key, self.num_layers * dirs)
        params = {}
        for layer in range(self.num_layers):
            in_size = (self.input_size if layer == 0
                       else self.out_size * dirs)
            for d in range(dirs):
                params[f"l{layer}{'_rev' if d else ''}"] = self._layer_init(
                    keys[layer * dirs + d], in_size)
        return params

    def init_hidden(self, batch: int, dtype=None) -> Any:
        """Zero hidden state, torch layout ``(layers*dirs, B, H)``
        (``RNNBackend.py:309`` init_hidden)."""
        dirs = 2 if self.bidirectional else 1
        dtype = dtype or self.params_dtype
        h = jnp.zeros((self.num_layers * dirs, batch, self.out_size), dtype)
        if self.n_states == 2:
            c = jnp.zeros((self.num_layers * dirs, batch, self.hidden_size),
                          dtype)
            return (h, c)
        return h

    # -- forward ------------------------------------------------------------

    def _run_layer(self, p: dict, x: jnp.ndarray, h0, c0,
                   reverse: bool) -> Tuple[jnp.ndarray, Any]:
        """x: (T, B, in) -> (T, B, out). The input projection for every
        timestep is one hoisted matmul; the scan carries only h (+ c)."""
        xg = _linear(x, p["w_ih"], p.get("b_ih"))       # (T, B, g*h)
        xm = _linear(x, p["w_mih"]) if self.kind == "mlstm" else None

        def step(carry, inputs):
            h, c = carry
            if self.kind == "mlstm":
                xg_t, xm_t = inputs
                pc = dict(p, xm=xm_t)
            else:
                xg_t, pc = inputs, p
            h_new, c_new = _cell_step(self.kind, xg_t, h, c, pc)
            if self.proj:
                h_new = _linear(h_new, p["w_ho"])
            return (h_new, c_new), h_new

        xs = (xg, xm) if self.kind == "mlstm" else xg
        (h_f, c_f), ys = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
        return ys, (h_f, c_f)

    def __call__(self, params: dict, x: jnp.ndarray, hidden: Any = None,
                 dropout_rng: Optional[jax.Array] = None
                 ) -> Tuple[jnp.ndarray, Any]:
        """Returns ``(output, h)`` or ``(output, (h, c))``; layouts follow
        torch (seq-major unless ``batch_first``)."""
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        T, B = x.shape[0], x.shape[1]
        dirs = 2 if self.bidirectional else 1
        if hidden is None:
            hidden = self.init_hidden(B, x.dtype)
        if self.n_states == 2:
            h_all, c_all = hidden
        else:
            h_all, c_all = hidden, None

        h_out, c_out = [], []
        for layer in range(self.num_layers):
            outs = []
            for d in range(dirs):
                idx = layer * dirs + d
                p = params[f"l{layer}{'_rev' if d else ''}"]
                c0 = (c_all[idx].astype(x.dtype)
                      if c_all is not None else None)
                ys, (h_f, c_f) = self._run_layer(
                    p, x, h_all[idx].astype(x.dtype), c0, reverse=bool(d))
                outs.append(ys)
                h_out.append(h_f)
                if c_f is not None:
                    c_out.append(c_f)
            x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
            if (self.dropout > 0.0 and dropout_rng is not None
                    and layer < self.num_layers - 1):
                key = jax.random.fold_in(dropout_rng, layer)
                keep = 1.0 - self.dropout
                mask = jax.random.bernoulli(key, keep, x.shape)
                x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

        out = jnp.swapaxes(x, 0, 1) if self.batch_first else x
        h_stack = jnp.stack(h_out)
        if self.n_states == 2:
            return out, (h_stack, jnp.stack(c_out))
        return out, h_stack


def LSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None, **kw):
    """``reference:apex/RNN/models.py:19``."""
    return ApexRNN("lstm", input_size, hidden_size, num_layers, bias,
                   batch_first, dropout, bidirectional, output_size, **kw)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, output_size=None, **kw):
    """``reference:apex/RNN/models.py:26``."""
    return ApexRNN("gru", input_size, hidden_size, num_layers, bias,
                   batch_first, dropout, bidirectional, output_size, **kw)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None, **kw):
    """``reference:apex/RNN/models.py:33``."""
    return ApexRNN("relu", input_size, hidden_size, num_layers, bias,
                   batch_first, dropout, bidirectional, output_size, **kw)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None, **kw):
    """``reference:apex/RNN/models.py:40``."""
    return ApexRNN("tanh", input_size, hidden_size, num_layers, bias,
                   batch_first, dropout, bidirectional, output_size, **kw)


def mLSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, output_size=None, **kw):
    """``reference:apex/RNN/models.py:47`` / ``cells.py:55`` — the
    multiplicative LSTM (Krause et al.)."""
    return ApexRNN("mlstm", input_size, hidden_size, num_layers, bias,
                   batch_first, dropout, bidirectional, output_size, **kw)
