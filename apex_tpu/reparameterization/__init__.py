"""Deprecated stub (SURVEY §7.7): weight-norm reparameterization.

The reference (``reference:apex/reparameterization/``) implements weight
normalization via forward pre-hooks — a mutation-based mechanism with no
functional analog needed: in JAX, reparameterize explicitly::

    def weight_norm(v, g):                  # v: direction, g: magnitude
        return g * v / jnp.linalg.norm(v, axis=-1, keepdims=True)

    w = weight_norm(params["v"], params["g"])   # inside the model fn

(or use ``flax.linen.WeightNorm``). Any attribute access raises with this
guidance.
"""

_MSG = ("apex_tpu.reparameterization is a documented stub: hooks-based "
        "weight norm has no functional analog. Reparameterize explicitly "
        "(w = g * v / ||v||) or use flax.linen.WeightNorm; see "
        "apex_tpu/reparameterization/__init__.py.")


def __getattr__(name):
    raise NotImplementedError(_MSG)
