"""Pallas TPU kernels for fused LayerNorm / RMSNorm forward + backward.

TPU re-design of ``reference:csrc/layer_norm_cuda_kernel.cu`` (Welford row
stats at :12-178, apply at :353-412, grads at :540-678) and the
``fast_layer_norm`` contrib kernels (``reference:apex/contrib/csrc/layer_norm/``,
hidden sizes to 64k). One grid row-block per program: stats are an in-VMEM
row reduction in fp32 (a single-pass mean/variance is numerically fine in
fp32 VMEM — Welford's streaming update exists to avoid multi-pass HBM reads,
which don't happen here), normalize + affine fuse into the same VMEM pass.
Backward emits per-block partial dgamma/dbeta tiles that the caller sums —
the TPU analog of the two-stage part-grad reduction in
``layer_norm_cuda_kernel.cu:540-678``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ln_fwd", "ln_bwd", "supports_pallas"]

_VMEM_BUDGET = 8 * 1024 * 1024  # conservative half of ~16MB VMEM


def _block_rows(n_rows: int, hidden: int) -> int:
    # ~5 fp32 row-buffers of width `hidden` live at once; keep under budget.
    # Mosaic requires the row-block to be a multiple of 8 (fp32 sublane
    # tile) or the full array, so the choices are: whole array if it fits,
    # else the largest multiple of 8 under budget that divides n_rows.
    per_row = hidden * 4 * 5
    cap = max(1, _VMEM_BUDGET // per_row)
    if n_rows <= cap:
        return n_rows
    rows = (min(n_rows, cap) // 8) * 8
    while rows >= 8 and n_rows % rows:
        rows -= 8
    if rows < 8:
        # no feasible block under budget (cap < 8, or nothing divides
        # n_rows): falling back to the whole array would blow the VMEM
        # budget this function exists to enforce — refuse loudly instead
        # (supports_pallas screens these shapes for the auto path)
        raise ValueError(
            f"no VMEM-feasible Pallas row block for rows={n_rows}, "
            f"hidden={hidden}; pass use_pallas=False")
    return rows


def prefer_pallas(n_rows: int, hidden: int) -> bool:
    """Auto-selection policy (capability is :func:`supports_pallas`; this is
    *preference*). Measured on v5e, bf16 fwd+bwd, 200-iteration device
    loops (round 5; pallas_ms vs xla_ms at constant 32M elements):

    ========  =========  ======  ======
    hidden    rows       Pallas  XLA
    ========  =========  ======  ======
    4096      8192       1.01    0.81
    8192      4096       1.19    0.65
    16384     2048       1.00    0.83
    32768     1024       1.14    0.72
    ========  =========  ======  ======

    XLA's native LN lowering wins at EVERY hidden size this kernel
    supports — its fusion into neighboring ops beats what a custom_vjp
    kernel-call boundary allows, including the large-hidden regime the
    reference's ``fast_layer_norm`` exists for
    (``reference:apex/contrib/csrc/layer_norm/ln_api.cpp:246``): on TPU
    the compiler's row reduction simply does not degrade the way the CUDA
    baseline's did. The measured answer is therefore *never* — the kernel
    is retained as the independent parity reference and for explicit
    ``use_pallas=True`` opt-in."""
    return False


def supports_pallas(n_rows: int, hidden: int) -> bool:
    """Kernel eligibility — the analog of ``is_kernel_available``
    (``reference:apex/transformer/functional/fused_softmax.py:159-179``)."""
    if jax.default_backend() != "tpu":
        return False
    if hidden % 128 or hidden * 4 * 5 > _VMEM_BUDGET:
        return False
    # a feasible block must exist: the whole array under budget, or an
    # 8-row-multiple tiling (which further requires >= 8 rows of budget —
    # at hidden >~ 52k the 8-row block itself exceeds it, see _block_rows)
    per_row = hidden * 4 * 5
    cap = _VMEM_BUDGET // per_row
    return n_rows <= cap or (cap >= 8 and n_rows % 8 == 0)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of ``like`` (see
    the flash-attention twin: pallas_call under shard_map needs it)."""
    from apex_tpu.utils.vma import leaf_vma
    vma = leaf_vma(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _stats(xf: jnp.ndarray, eps: float, rms: bool):
    if rms:
        ms = jnp.mean(xf * xf, axis=1, keepdims=True)
        invvar = jax.lax.rsqrt(ms + eps)
        return jnp.zeros_like(invvar), invvar, xf * invvar
    mean = jnp.mean(xf, axis=1, keepdims=True)
    centered = xf - mean
    var = jnp.mean(centered * centered, axis=1, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    return mean, invvar, centered * invvar


def _fwd_body(x_ref, w_ref, b_ref, o_ref, mean_ref, invvar_ref,
              eps: float, rms: bool):
    mean, invvar, xhat = _stats(x_ref[:].astype(jnp.float32), eps, rms)
    out = xhat
    if w_ref is not None:
        out = out * w_ref[:].astype(jnp.float32)
    if b_ref is not None:
        out = out + b_ref[:].astype(jnp.float32)
    o_ref[:] = out.astype(o_ref.dtype)
    mean_ref[:] = mean
    invvar_ref[:] = invvar


def _bwd_body(dy_ref, x_ref, mean_ref, invvar_ref, w_ref,
              dx_ref, dw_ref, db_ref, rms: bool):
    dy = dy_ref[:].astype(jnp.float32)
    xf = x_ref[:].astype(jnp.float32)
    invvar = invvar_ref[:]
    xhat = xf * invvar if rms else (xf - mean_ref[:]) * invvar
    dxhat = dy * w_ref[:].astype(jnp.float32) if w_ref is not None else dy
    # dx = invvar*(dxhat - mean(dxhat) - xhat*mean(dxhat*xhat))   [LN]
    # dx = invvar*(dxhat - xhat*mean(dxhat*xhat))                 [RMS]
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    if rms:
        dx = invvar * (dxhat - xhat * m2)
    else:
        m1 = jnp.mean(dxhat, axis=1, keepdims=True)
        dx = invvar * (dxhat - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dgamma/dbeta accumulate across the sequential grid into one resident
    # (1, h) VMEM block (constant index_map) — the TPU analog of the
    # two-stage part-grad reduction in layer_norm_cuda_kernel.cu:540-678,
    # with stage 2 done by Mosaic's revisit-in-VMEM rule instead of a
    # second kernel.
    first = pl.program_id(0) == 0
    if dw_ref is not None:
        part_w = jnp.sum(dy * xhat, axis=0, keepdims=True)

        @pl.when(first)
        def _():
            dw_ref[:] = jnp.zeros_like(dw_ref)

        dw_ref[:] += part_w
    if db_ref is not None:
        part_b = jnp.sum(dy, axis=0, keepdims=True)

        @pl.when(first)
        def _():
            db_ref[:] = jnp.zeros_like(db_ref)

        db_ref[:] += part_b


def ln_fwd(x2d: jnp.ndarray, weight: Optional[jnp.ndarray],
           bias: Optional[jnp.ndarray], *, eps: float, rms: bool,
           out_dtype) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns ``(out, mean, invvar)``; mean/invvar are ``(rows, 1)`` fp32
    (the saved stats of ``reference:apex/normalization/fused_layer_norm.py:32-56``)."""
    n, h = x2d.shape
    has_w, has_b = weight is not None, bias is not None
    br = _block_rows(n, h)
    row_spec = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)

    in_specs, args = [row_spec], [x2d]
    if has_w:
        in_specs.append(w_spec)
        args.append(weight.reshape(1, h))
    if has_b:
        in_specs.append(w_spec)
        args.append(bias.reshape(1, h))

    def kernel(x_ref, *refs):
        i = 0
        w_ref = refs[i] if has_w else None
        i += has_w
        b_ref = refs[i] if has_b else None
        i += has_b
        _fwd_body(x_ref, w_ref, b_ref, *refs[i:], eps=eps, rms=rms)

    return pl.pallas_call(
        kernel,
        grid=(n // br,),
        interpret=jax.default_backend() != "tpu",
        in_specs=in_specs,
        out_specs=(row_spec, stat_spec, stat_spec),
        out_shape=(
            _sds((n, h), out_dtype, x2d),
            _sds((n, 1), jnp.float32, x2d),
            _sds((n, 1), jnp.float32, x2d),
        ),
    )(*args)


def ln_bwd(dy2d: jnp.ndarray, x2d: jnp.ndarray, mean: jnp.ndarray,
           invvar: jnp.ndarray, weight: Optional[jnp.ndarray], *,
           rms: bool, has_bias: bool, x_dtype, w_dtype):
    """Returns ``(dx, dweight, dbias)``; dweight/dbias ``None`` when absent."""
    n, h = x2d.shape
    has_w = weight is not None
    br = _block_rows(n, h)
    grid_n = n // br
    row_spec = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    # dgamma/dbeta: one (1, h) block revisited by every program (see
    # _bwd_body's accumulation)
    acc_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)

    in_specs = [row_spec, row_spec, stat_spec, stat_spec]
    args = [dy2d, x2d, mean, invvar]
    if has_w:
        in_specs.append(w_spec)
        args.append(weight.reshape(1, h))

    out_specs = [row_spec]
    out_shape = [_sds((n, h), x_dtype, x2d)]
    if has_w:
        out_specs.append(acc_spec)
        out_shape.append(_sds((1, h), jnp.float32, x2d))
    if has_bias:
        out_specs.append(acc_spec)
        out_shape.append(_sds((1, h), jnp.float32, x2d))

    def kernel(dy_ref, x_ref, mean_ref, invvar_ref, *refs):
        i = 0
        w_ref = refs[i] if has_w else None
        i += has_w
        dx_ref = refs[i]
        i += 1
        dw_ref = refs[i] if has_w else None
        i += has_w
        db_ref = refs[i] if has_bias else None
        _bwd_body(dy_ref, x_ref, mean_ref, invvar_ref, w_ref,
                  dx_ref, dw_ref, db_ref, rms=rms)

    res = pl.pallas_call(
        kernel, grid=(grid_n,),
        in_specs=in_specs, out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=jax.default_backend() != "tpu",
    )(*args)
    if not isinstance(res, (tuple, list)):
        res = (res,)
    dx = res[0]
    dw = res[1][0].astype(w_dtype) if has_w else None
    db = res[-1][0].astype(w_dtype) if has_bias else None
    return dx, dw, db
