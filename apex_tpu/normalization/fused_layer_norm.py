"""Fused LayerNorm / RMSNorm — functional API + lightweight modules.

Reference surface: ``reference:apex/normalization/fused_layer_norm.py`` —
autograd Functions over the CUDA kernels (:32-119), module classes
``FusedLayerNorm`` (:204), ``FusedRMSNorm`` (:300), mixed-dtype Megatron
variants ``MixedFusedLayerNorm``/``MixedFusedRMSNorm`` (:398,420). Dtype
rules verified against ``reference:csrc/layer_norm_cuda.cpp``: the standard
affine path requires input/weight dtypes to match and outputs input dtype
(:183-189), while the ``*_mixed_dtypes`` path allows them to differ and
outputs **weight** dtype (:205 ``empty_like(input, gamma.options())``);
stats (mean, invvar) are always fp32 for half inputs (:161,184).

Two implementations sit behind one ``custom_vjp``: a Pallas kernel
(:mod:`apex_tpu.normalization._pallas`) when the backend is TPU and shapes
are tile-aligned, else plain jnp that XLA fuses. This replaces the
import-try feature detection of the reference (``fused_layer_norm.py:15-30``).
"""

from __future__ import annotations

import functools
import numbers
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.normalization import _pallas
from apex_tpu.utils.vma import reconcile_cotangent

__all__ = [
    "fused_layer_norm", "fused_layer_norm_affine",
    "fused_rms_norm", "fused_rms_norm_affine",
    "mixed_dtype_fused_layer_norm_affine", "mixed_dtype_fused_rms_norm_affine",
    "FusedLayerNorm", "FusedRMSNorm", "MixedFusedLayerNorm", "MixedFusedRMSNorm",
]


def _norm_shape(normalized_shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(d) for d in normalized_shape)


# ---------------------------------------------------------------------------
# core: custom_vjp per (rms, eps, out_dtype, use_pallas) configuration
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _make_core(rms: bool, eps: float, out_dtype_name: str, use_pallas: bool,
               has_weight: bool, has_bias: bool):
    out_dtype = jnp.dtype(out_dtype_name)

    def _xla_fwd(x2d, weight, bias):
        xf = x2d.astype(jnp.float32)
        if rms:
            ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
            invvar = jax.lax.rsqrt(ms + eps)
            mean = jnp.zeros_like(invvar)
            xhat = xf * invvar
        else:
            mean = jnp.mean(xf, axis=-1, keepdims=True)
            c = xf - mean
            var = jnp.mean(c * c, axis=-1, keepdims=True)
            invvar = jax.lax.rsqrt(var + eps)
            xhat = c * invvar
        out = xhat
        if has_weight:
            out = out * weight.astype(jnp.float32)
        if has_bias:
            out = out + bias.astype(jnp.float32)
        return out.astype(out_dtype), mean, invvar

    def fwd_impl(x2d, weight, bias):
        if use_pallas:
            return _pallas.ln_fwd(x2d, weight if has_weight else None,
                                  bias if has_bias else None,
                                  eps=eps, rms=rms, out_dtype=out_dtype)
        return _xla_fwd(x2d, weight, bias)

    def bwd_impl(dy, x2d, mean, invvar, weight):
        w_dtype = weight.dtype if has_weight else None
        if use_pallas:
            return _pallas.ln_bwd(dy, x2d, mean, invvar,
                                  weight if has_weight else None,
                                  rms=rms, has_bias=has_bias,
                                  x_dtype=x2d.dtype, w_dtype=w_dtype)
        dyf = dy.astype(jnp.float32)
        xf = x2d.astype(jnp.float32)
        xhat = xf * invvar if rms else (xf - mean) * invvar
        dxhat = dyf * weight.astype(jnp.float32) if has_weight else dyf
        m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
        if rms:
            dx = invvar * (dxhat - xhat * m2)
        else:
            m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
            dx = invvar * (dxhat - m1 - xhat * m2)
        dw = jnp.sum(dyf * xhat, axis=0).astype(w_dtype) if has_weight else None
        db = jnp.sum(dyf, axis=0).astype(w_dtype) if has_bias else None
        return dx.astype(x2d.dtype), dw, db

    @jax.custom_vjp
    def core(x2d, weight, bias):
        return fwd_impl(x2d, weight, bias)[0]

    def core_fwd(x2d, weight, bias):
        out, mean, invvar = fwd_impl(x2d, weight, bias)
        return out, (x2d, mean, invvar, weight, bias)

    def core_bwd(res, dy):
        x2d, mean, invvar, weight, bias = res
        dx, dw, db = bwd_impl(dy, x2d, mean, invvar, weight)
        # Under shard_map the bwd must hand back cotangents typed exactly
        # like the primals. Sequence parallelism is the live case: x2d is
        # sequence-sharded (tensor-varying) while weight/bias are replicated,
        # so dw/db emerge as per-rank partials — reconcile_cotangent psums
        # them over the tensor axis, matching what plain-op AD does for
        # replicated params (Megatron-LM instead defers this to a separate
        # allreduce of sequence_parallel-marked params).
        return (reconcile_cotangent(dx, x2d),
                reconcile_cotangent(
                    dw if has_weight else jnp.zeros((), jnp.float32), weight),
                reconcile_cotangent(
                    db if has_bias else jnp.zeros((), jnp.float32), bias))

    core.defvjp(core_fwd, core_bwd)
    return core


def _run(x, weight, bias, normalized_shape, eps, rms, out_dtype,
         use_pallas: Optional[bool]):
    shape = _norm_shape(normalized_shape)
    h = 1
    for d in shape:
        h *= d
    if tuple(x.shape[-len(shape):]) != shape:
        raise ValueError(
            f"normalized_shape {shape} does not match input tail {x.shape}")
    lead = x.shape[:-len(shape)]
    n = 1
    for d in lead:
        n *= d
    x2d = x.reshape(n, h)
    if use_pallas is None:
        use_pallas = _pallas.supports_pallas(n, h) and _pallas.prefer_pallas(
            n, h)
    core = _make_core(rms, float(eps), jnp.dtype(out_dtype).name,
                      bool(use_pallas), weight is not None, bias is not None)
    w2 = weight.reshape(h) if weight is not None else jnp.zeros((), jnp.float32)
    b2 = bias.reshape(h) if bias is not None else jnp.zeros((), jnp.float32)
    out = core(x2d, w2, b2)
    return out.reshape(*lead, *shape)


# ---------------------------------------------------------------------------
# functional API (mirrors the autograd Function entry points)
# ---------------------------------------------------------------------------

def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps=1e-5,
                            use_pallas: Optional[bool] = None):
    """``FusedLayerNormAffineFunction`` (``fused_layer_norm.py:32-56``):
    output dtype = input dtype."""
    return _run(x, weight, bias, normalized_shape, eps, rms=False,
                out_dtype=x.dtype, use_pallas=use_pallas)


def fused_layer_norm(x, normalized_shape, eps=1e-5,
                     use_pallas: Optional[bool] = None):
    """Non-affine LN (``fused_layer_norm.py:122-142``)."""
    return _run(x, None, None, normalized_shape, eps, rms=False,
                out_dtype=x.dtype, use_pallas=use_pallas)


def fused_rms_norm_affine(x, weight, normalized_shape, eps=1e-5,
                          use_pallas: Optional[bool] = None):
    """``FusedRMSNormAffineFunction`` (``fused_layer_norm.py:59-81``)."""
    return _run(x, weight, None, normalized_shape, eps, rms=True,
                out_dtype=x.dtype, use_pallas=use_pallas)


def fused_rms_norm(x, normalized_shape, eps=1e-5,
                   use_pallas: Optional[bool] = None):
    return _run(x, None, None, normalized_shape, eps, rms=True,
                out_dtype=x.dtype, use_pallas=use_pallas)


def mixed_dtype_fused_layer_norm_affine(x, weight, bias, normalized_shape,
                                        eps=1e-5,
                                        use_pallas: Optional[bool] = None):
    """Megatron-compat mixed-dtype LN: output dtype = **weight** dtype
    (``reference:csrc/layer_norm_cuda.cpp:205``)."""
    return _run(x, weight, bias, normalized_shape, eps, rms=False,
                out_dtype=weight.dtype, use_pallas=use_pallas)


def mixed_dtype_fused_rms_norm_affine(x, weight, normalized_shape, eps=1e-5,
                                      use_pallas: Optional[bool] = None):
    return _run(x, weight, None, normalized_shape, eps, rms=True,
                out_dtype=weight.dtype, use_pallas=use_pallas)


# ---------------------------------------------------------------------------
# module-style classes (param factories; functional apply)
# ---------------------------------------------------------------------------

class FusedLayerNorm:
    """``apex.normalization.FusedLayerNorm`` (``fused_layer_norm.py:204-297``)
    as a param-factory: ``params = m.init()``, ``y = m(params, x)``."""

    rms = False
    mixed = False

    def __init__(self, normalized_shape, eps: float = 1e-5,
                 elementwise_affine: bool = True, param_dtype=jnp.float32):
        self.normalized_shape = _norm_shape(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine
        self.param_dtype = param_dtype

    @property
    def _has_bias(self) -> bool:
        return not self.rms

    def init(self, key: Optional[jax.Array] = None) -> dict:
        if not self.elementwise_affine:
            return {}
        params = {"weight": jnp.ones(self.normalized_shape, self.param_dtype)}
        if self._has_bias:
            params["bias"] = jnp.zeros(self.normalized_shape, self.param_dtype)
        return params

    def __call__(self, params: dict, x, use_pallas: Optional[bool] = None):
        w = params.get("weight") if self.elementwise_affine else None
        b = params.get("bias") if (self.elementwise_affine and self._has_bias) else None
        out_dtype = (w.dtype if (self.mixed and w is not None) else x.dtype)
        return _run(x, w, b, self.normalized_shape, self.eps, rms=self.rms,
                    out_dtype=out_dtype, use_pallas=use_pallas)

    def __repr__(self):
        return (f"{type(self).__name__}({self.normalized_shape}, eps={self.eps}, "
                f"elementwise_affine={self.elementwise_affine})")


class FusedRMSNorm(FusedLayerNorm):
    """``fused_layer_norm.py:300-395`` — no bias term."""
    rms = True


class MixedFusedLayerNorm(FusedLayerNorm):
    """``fused_layer_norm.py:398-417`` — fp32 params with half inputs;
    output takes the weight dtype."""
    mixed = True


class MixedFusedRMSNorm(FusedRMSNorm):
    """``fused_layer_norm.py:420-437``."""
    mixed = True
