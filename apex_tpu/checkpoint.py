"""Checkpoint / resume subsystem.

The reference persists training state across several cooperating pieces:
amp scaler state via ``amp.state_dict`` (``reference:apex/amp/frontend.py:361-400``),
fp32-on-disk for O2-cast models via ``O2StateDictHook``
(``reference:apex/amp/_initialize.py:133-142,207-210``), sharded optimizer
``state_dict`` in the ZeRO optimizers
(``reference:apex/contrib/optimizers/distributed_fused_adam_v2.py``), RNG
streams via ``CudaRNGStatesTracker.get_states/set_states``
(``reference:apex/transformer/tensor_parallel/random.py:140-151``), and a
documented bitwise-resume recipe (``reference:README.md:57-97``).

TPU redesign: all device state here is already *explicit pytrees* (params,
optimizer state incl. ZeRO flat shards, :class:`~apex_tpu.amp.LossScaleState`,
RNG tracker key dict), so checkpointing collapses to one sharding-aware
pytree save/restore — backed by orbax, which writes each shard from the
device that owns it and restores onto the target's sharding (multi-host
safe). The reference's per-component ``state_dict`` choreography disappears.

Rules preserved from the reference:

- **fp32 on disk** (``O2StateDictHook``): with ``fp32_on_disk=True`` every
  half-precision (fp16/bf16) floating leaf is widened to fp32 before the
  bytes hit disk and narrowed back to the *target's* dtype on restore. Both
  casts are exact (fp32 superset), so resume stays bitwise while checkpoints
  remain loadable into an fp32 (O0) model — the interop the hook exists for.
- **bitwise resume**: save(state) → restore(state) is the identity for every
  leaf, including the loss-scaler scalars and RNG keys, so N steps + save +
  restore + M steps == N+M steps exactly (tested in
  ``tests/test_checkpoint.py``).
- **sharded optimizer state**: ZeRO shards (``ZeroAdamState`` flat vectors
  laid out over the ``data`` axis) and TP-sharded params save/restore with
  their shardings; each host writes only the shards it addresses.

Host-side scheduling state (microbatch calculator, consumed samples, python
step counters) rides in a JSON sidecar (``host_state=``), mirroring how the
reference stashes those in the torch checkpoint dict.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["save_checkpoint", "restore_checkpoint", "read_host_state",
           "latest_step", "all_steps", "torn_steps"]

_STEP_RE = re.compile(r"^step_(\d+)$")
_HOST_FILE = "host.json"
_COMMIT_FILE = "COMMITTED"


def _is_prng_key(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def _to_storage(tree: Any, fp32_on_disk: bool) -> Any:
    """Typed PRNG keys -> raw uint32 key data; half floats -> fp32."""

    def conv(x):
        if _is_prng_key(x):
            return jax.random.key_data(x)
        if fp32_on_disk and hasattr(x, "dtype") and x.dtype in (
                jnp.float16, jnp.bfloat16):
            return jnp.asarray(x, jnp.float32)
        return x

    return jax.tree_util.tree_map(conv, tree)


def _storage_target(target: Any, fp32_on_disk: bool) -> Any:
    """Abstract (shape/dtype/sharding) tree describing the on-disk layout of
    ``target``."""

    def conv(x):
        if _is_prng_key(x):
            data = jax.eval_shape(jax.random.key_data, x)
            return jax.ShapeDtypeStruct(data.shape, data.dtype)
        sharding = getattr(x, "sharding", None)
        if sharding is not None and not hasattr(sharding, "mesh"):
            sharding = None  # single-device placement: let orbax default
        dtype = x.dtype
        if fp32_on_disk and dtype in (jnp.float16, jnp.bfloat16):
            dtype = jnp.float32
        return jax.ShapeDtypeStruct(x.shape, dtype, sharding=sharding)

    return jax.tree_util.tree_map(conv, target, is_leaf=_is_prng_key)


def _from_storage(restored: Any, target: Any) -> Any:
    """Narrow each restored leaf back to the target leaf's dtype/key-type."""

    def conv(r, t):
        if _is_prng_key(t):
            return jax.random.wrap_key_data(
                r, impl=jax.random.key_impl(t))
        dtype = t.dtype if hasattr(t, "dtype") else None
        if dtype is not None and r.dtype != dtype:
            return jnp.asarray(r, dtype)
        return r

    return jax.tree_util.tree_map(conv, restored, target,
                                  is_leaf=_is_prng_key)


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:08d}")


def _barrier(name: str) -> None:
    """Cross-process sync point, a no-op in a single-process world.

    Multi-controller checkpointing needs two of these around the
    COMMITTED protocol: the orbax array save is collective (every process
    writes the shards it owns) but each process's ``save`` returns after
    only ITS shards are durable — without a barrier, process 0 could
    write COMMITTED while another process's shards are still in flight
    (a kill in that window yields the one thing the protocol promises
    never to produce: a COMMITTED-but-partial checkpoint), and a
    non-lead process could return from ``save_checkpoint`` and proceed to
    a restore before the marker exists (observed live as a spurious
    torn-dir fallback on the 2-process localhost mesh)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def all_steps(directory: str) -> list:
    """Committed checkpoint steps in ``directory``, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(
                os.path.join(directory, name, _COMMIT_FILE)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def torn_steps(directory: str) -> list:
    """Step numbers of TORN checkpoint dirs — present on disk but missing
    their COMMITTED marker (a writer died mid-save, or another process is
    still writing them), ascending. Invisible to :func:`all_steps` /
    :func:`latest_step`; :func:`restore_checkpoint` warns and skips them."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and not os.path.exists(
                os.path.join(directory, name, _COMMIT_FILE)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def save_checkpoint(directory: str, state: Any, step: int, *,
                    fp32_on_disk: bool = True,
                    host_state: Optional[Dict[str, Any]] = None,
                    keep: Optional[int] = None,
                    keep_last: Optional[int] = None) -> str:
    """Write ``state`` (any pytree of jax/numpy arrays) at ``step``.

    Returns the checkpoint path. ``host_state`` must be JSON-serializable.
    ``keep_last=N`` (N >= 1) prunes all but the newest N COMMITTED
    checkpoints after the new one commits; a torn/uncommitted dir — one
    another (possibly still-running) writer may own — is NEVER deleted by
    GC. ``keep=`` is the legacy spelling of the same parameter.

    Multi-host: the orbax array save is collective (every process calls
    ``save_checkpoint`` and writes the shards it owns); the directory
    bookkeeping here (rmtree/mkdir, host.json, COMMITTED marker, pruning)
    runs only on process 0, fenced by cross-process barriers
    (:func:`_barrier`): begin (no writer enters a dir the lead is still
    clearing), arrays-durable (COMMITTED cannot precede any process's
    shards), and commit (no process returns before the marker is
    visible). All three are no-ops in a single-process world.
    """
    import orbax.checkpoint as ocp

    if keep is not None and keep_last is not None and keep != keep_last:
        raise ValueError(
            f"keep={keep} and keep_last={keep_last} are the same parameter "
            "spelled twice; pass only keep_last")
    if keep_last is None:
        keep_last = keep
    if keep_last is not None and keep_last < 1:
        raise ValueError("keep_last must be >= 1")
    lead = jax.process_index() == 0
    path = _step_dir(directory, step)
    if lead:
        if os.path.exists(path):
            shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)
    # non-lead processes must not enter the collective save while the
    # lead is still clearing a previous generation of this step dir
    _barrier(f"apex_ckpt_begin_{step}")

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"),
                   _to_storage(state, fp32_on_disk))
        ckptr.wait_until_finished()

    # every process's shards are durable before COMMITTED can exist
    _barrier(f"apex_ckpt_arrays_{step}")
    if lead:
        meta = {"step": int(step), "fp32_on_disk": bool(fp32_on_disk),
                "host_state": host_state if host_state is not None else {}}
        tmp = os.path.join(path, _HOST_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, _HOST_FILE))
        # commit marker written last: a partially-written checkpoint is
        # never visible to latest_step/restore
        with open(os.path.join(path, _COMMIT_FILE), "w") as f:
            f.write("ok\n")

        if keep_last is not None:
            # all_steps lists only COMMITTED dirs, so a torn dir another
            # writer may still own is structurally exempt from GC
            steps = all_steps(directory)
            for old in steps[:max(len(steps) - keep_last, 0)]:
                shutil.rmtree(_step_dir(directory, old), ignore_errors=True)
    # no process returns before the marker is visible: the very next
    # thing a caller may do is resolve latest_step for a restore
    _barrier(f"apex_ckpt_commit_{step}")
    return path


def read_host_state(directory: str, step: Optional[int] = None
                    ) -> Tuple[int, Dict[str, Any]]:
    """``(step, host_state)`` of the checkpoint at ``step`` (default:
    latest COMMITTED) **without restoring any arrays** — the first half
    of the cross-world-size restore path: an elastic restart peeks at the
    saved world geometry (``host_state["world"]``, written by
    :class:`~apex_tpu.elastic.runner.ElasticRunner`) here to decide
    whether the on-disk ZeRO shard layout must be re-partitioned before
    it can build the orbax restore target at all (the saved flat-shard
    global shapes are a function of the OLD dp)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {directory!r}")
    path = _step_dir(directory, step)
    if not os.path.exists(os.path.join(path, _COMMIT_FILE)):
        raise FileNotFoundError(f"checkpoint at {path!r} is not committed")
    with open(os.path.join(path, _HOST_FILE)) as f:
        meta = json.load(f)
    return int(step), meta.get("host_state", {})


def restore_checkpoint(directory: str, target: Any,
                       step: Optional[int] = None
                       ) -> Tuple[Any, Dict[str, Any]]:
    """Restore the checkpoint at ``step`` (default: latest) onto the
    structure/dtypes/shardings of ``target``.

    ``target`` is a pytree of arrays or ``ShapeDtypeStruct``s (with optional
    shardings); restored leaves land sharded accordingly. Returns
    ``(state, host_state)``.

    Torn dirs (a ``step_*`` dir without its COMMITTED marker — a writer
    died mid-save) are SKIPPED, not an error: the latest-step resolution
    falls back to the newest COMMITTED step and a ``UserWarning`` names
    every torn step it skipped over. Only an *explicitly requested*
    ``step=`` that is torn raises.
    """
    import orbax.checkpoint as ocp

    if step is None:
        step = latest_step(directory)
        torn = torn_steps(directory)
        skipped = [s for s in torn if step is None or s > step]
        if skipped:
            warnings.warn(
                f"skipping torn (uncommitted) checkpoint dir(s) at step(s) "
                f"{skipped} under {directory!r}; "
                + (f"falling back to committed step {step}" if step
                   is not None else "no committed checkpoint remains"))
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {directory!r}"
                + (f" (only torn dirs at steps {torn})" if torn else ""))
    path = _step_dir(directory, step)
    if not os.path.exists(os.path.join(path, _COMMIT_FILE)):
        raise FileNotFoundError(f"checkpoint at {path!r} is not committed")

    with open(os.path.join(path, _HOST_FILE)) as f:
        meta = json.load(f)
    fp32_on_disk = bool(meta.get("fp32_on_disk", True))

    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.join(path, "state"),
                                 _storage_target(target, fp32_on_disk))
    return _from_storage(restored, target), meta.get("host_state", {})
