"""Numerics health watchdog tests: the fused per-leaf stats pass,
trace-time gating (the zero-cost-off contract, asserted on the jaxpr),
first-nonfinite attribution, replica-agreement detection on a multi-device
CPU mesh, crash dumps + the reporter hook, and the HealthConfig threading
through GPTHybridTrainer."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import observability as obs
from apex_tpu.observability import health, ingraph
from apex_tpu.utils.compat import shard_map


# ---------------------------------------------------------------------------
# tensor_stats: the fused per-leaf pass
# ---------------------------------------------------------------------------

class TestTensorStats:
    def test_per_leaf_stats(self):
        tree = {
            "a": jnp.asarray([1.0, -3.0, jnp.inf, 2.0], jnp.float32),
            "b": {"c": jnp.asarray([jnp.nan, 0.5], jnp.float32)},
            "ints": jnp.arange(5),  # non-float: ignored
        }
        stats = jax.jit(health.tensor_stats)(tree)
        assert stats.paths == ("['a']", "['b']['c']")
        assert stats.sizes == (4, 2)
        np.testing.assert_allclose(stats.finite_count, [3.0, 1.0])
        assert float(stats.nonfinite_count()) == 2.0
        # abs_max NaN-propagates: leaf a reads inf, leaf b reads NaN
        assert np.isinf(stats.abs_max[0])
        assert np.isnan(stats.abs_max[1])
        # sq_sum is over the FINITE elements (1+9+4, 0.25)
        np.testing.assert_allclose(stats.sq_sum, [14.0, 0.25])
        assert float(stats.first_nonfinite_index()) == 0.0

    def test_clean_tree_and_empty_tree(self):
        stats = health.tensor_stats({"w": jnp.ones((3, 2))})
        assert float(stats.nonfinite_count()) == 0.0
        assert float(stats.first_nonfinite_index()) == -1.0
        assert float(stats.l2()) == pytest.approx(np.sqrt(6.0))
        assert health.tensor_stats({"i": jnp.arange(3)}) is None
        assert health.tensor_stats({}) is None

    def test_underflow_fraction_half_only(self):
        # fp16 subnormal range is (0, 6.1e-5); f32 values there are normal
        tree = {
            "h": jnp.asarray([1e-6, 1.0, 0.0, 2e-5], jnp.float16),
            "f": jnp.asarray([1e-6, 1e-30], jnp.float32),
        }
        stats = health.tensor_stats(tree)
        # 2 of the 4 fp16 elements underflow; zeros don't count; f32
        # leaves contribute nothing to either side of the fraction
        assert float(stats.underflow_fraction()) == pytest.approx(0.5)
        assert stats.half_mask == (False, True)  # dict flattens sorted: f, h
        clean = health.tensor_stats({"f": jnp.ones(4, jnp.float32)})
        assert float(clean.underflow_fraction()) == 0.0

    def test_one_nan_in_a_huge_leaf_is_detected(self):
        """Counting must be int32-exact: an fp32 count is exact only to
        2^24, so one NaN in a 2^25-element leaf (a small embedding table)
        would round away and never be attributed."""
        big = jnp.zeros((2 ** 25,), jnp.bfloat16).at[12345].set(jnp.nan)
        stats = jax.jit(health.tensor_stats)({"emb": big})
        assert int(stats.finite_count[0]) == 2 ** 25 - 1
        assert float(stats.nonfinite_count()) == 1.0
        assert float(stats.first_nonfinite_index()) == 0.0

    def test_treestats_is_a_pytree(self):
        stats = health.tensor_stats({"a": jnp.ones(2)})
        leaves, treedef = jax.tree_util.tree_flatten(stats)
        assert len(leaves) == 4
        back = jax.tree_util.tree_unflatten(treedef, leaves)
        assert back.paths == stats.paths and back.sizes == stats.sizes


# ---------------------------------------------------------------------------
# gating: the zero-cost-off contract (acceptance criterion)
# ---------------------------------------------------------------------------

def _amp_opt_step():
    from apex_tpu.amp.scaler import DynamicLossScale, all_finite
    from apex_tpu.optimizers import FusedSGD

    scaler = DynamicLossScale()
    opt = FusedSGD(lr=0.1)

    def step(params, opt_state, ls, x):
        grads = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(params)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite)
        return params, opt_state, new_ls

    params = jnp.ones((4, 2))
    return step, (params, opt.init(params), scaler.init(), jnp.ones((3, 4)))


class TestZeroCostOff:
    def test_off_path_jaxpr_identical(self):
        """The instrumented amp+optimizer step must trace to the SAME
        jaxpr with (a) no active policy, (b) an explicit level="off"
        policy, (c) an active cheap policy but no collector — the two
        trace-time gates of observe_*, same style as the ingraph no-op
        contract."""
        step, args = _amp_opt_step()
        baseline = str(jax.make_jaxpr(step)(*args))
        with health.activate(health.HealthConfig(level="off")):
            assert str(jax.make_jaxpr(step)(*args)) == baseline
        with health.activate(health.HealthConfig(level="cheap")):
            assert health.active_level() == "cheap"
            assert str(jax.make_jaxpr(step)(*args)) == baseline
        assert health.active() is None

    def test_collector_without_policy_adds_nothing(self):
        step, args = _amp_opt_step()
        # reaping adds the amp/optim metrics but no health stats pass
        assert not any(k.startswith("health/")
                       for k in _reap_names(step, args))

    def test_cheap_level_adds_health_metrics(self):
        step, args = _amp_opt_step()

        def active_step(*a):
            with health.activate(health.HealthConfig(level="cheap")):
                return ingraph.reap(step)(*a)

        _, metrics = jax.jit(active_step)(*args)
        got = metrics.as_floats()
        for key in ("health/grads/nonfinite_count", "health/grads/abs_max",
                    "health/grads/l2", "health/grads/underflow_frac",
                    "health/grads/first_nonfinite_leaf"):
            assert key in got, key
        assert got["health/grads/nonfinite_count"] == 0.0
        assert got["health/grads/first_nonfinite_leaf"] == -1.0
        # cheap level does NOT run the full-tier observers
        assert not any(k.startswith(("health/optim_grads/",
                                     "health/params/")) for k in got)

    def test_full_level_adds_param_stats(self):
        step, args = _amp_opt_step()

        def active_step(*a):
            with health.activate(health.HealthConfig(level="full")):
                return ingraph.reap(step)(*a)

        _, metrics = jax.jit(active_step)(*args)
        got = metrics.as_floats()
        assert "health/optim_grads/nonfinite_count" in got
        assert "health/params/nonfinite_count" in got
        assert got["health/params/abs_max"] > 0.0


def _reap_names(step, args):
    _, metrics = ingraph.reap(step)(*args)
    return set(metrics.values)


# ---------------------------------------------------------------------------
# first-nonfinite attribution (acceptance criterion)
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_injected_inf_names_the_leaf(self):
        from apex_tpu.amp.scaler import DynamicLossScale, all_finite

        scaler = DynamicLossScale(init_scale=4.0)
        big = jnp.float32(3e38)

        def loss_fn(p, poison):
            inject = jnp.where(poison > 0, big * big, jnp.float32(0.0))
            return jnp.sum(p["aa"] ** 2) + jnp.sum(p["zz"]["bad"]) * inject

        def step(p, ls, poison):
            with health.activate(health.HealthConfig(level="cheap")):
                def body(p, ls, poison):
                    grads = jax.grad(loss_fn)(p, poison)
                    finite = all_finite(grads)
                    return scaler.update(ls, finite)
                return ingraph.reap(body)(p, ls, poison)

        p = {"aa": jnp.ones(3), "zz": {"bad": jnp.ones(2)}}
        ls = scaler.init()
        _, metrics = jax.jit(step)(p, ls, jnp.float32(1.0))
        got = metrics.as_floats()
        assert got["amp/overflow_count"] == 1.0
        assert got["health/grads/nonfinite_count"] == 2.0
        att = health.decode_attribution(got)
        assert att == {"grads": "['zz']['bad']"}
        # clean step: no attribution
        _, metrics = jax.jit(step)(p, ls, jnp.float32(0.0))
        assert health.decode_attribution(metrics.as_floats()) == {}

    def test_non_grad_finite_checks_do_not_pollute_grads(self):
        """all_finite is a shared chokepoint: finite-checks of non-grad
        trees (multi_tensor_apply outputs) must not sum into — or
        re-attribute — health/grads/*."""
        from apex_tpu.amp.scaler import all_finite
        from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
            multi_tensor_scale)

        def step(grads, params):
            scaled, _ = multi_tensor_scale(params, 2.0)  # observe=None
            finite = all_finite(grads)
            return jax.tree_util.tree_map(
                lambda s, g: s + 0.0 * g, scaled, grads), finite

        grads = {"g1": jnp.ones(2), "g2": jnp.asarray([jnp.inf])}
        params = {"g1": jnp.ones(2), "g2": jnp.ones(1)}
        with health.activate(health.HealthConfig(level="cheap")):
            _, m = jax.jit(ingraph.reap(step))(grads, params)
        got = m.as_floats()
        # only the GRAD check recorded: one inf total, not params' zero
        # summed in twice, and attribution points into the grads tree
        assert got["health/grads/nonfinite_count"] == 1.0
        assert health.decode_attribution(got) == {"grads": "['g2']"}

        def observed_names(observe):
            def s(t):
                return all_finite(t, observe=observe)
            with health.activate(health.HealthConfig(level="cheap")):
                _, m = ingraph.reap(s)({"x": jnp.ones(1)})
            return set(m.values)

        assert observed_names(None) == set()
        assert {n.split("/")[1] for n in observed_names("master")} \
            == {"master"}

    def test_two_same_name_checks_keep_separate_attribution(self):
        """A step with two all_finite calls (GAN pattern: D grads then G
        grads, both defaulting to "grads") must not overwrite the first
        check's attribution — the second records under grads#2."""
        from apex_tpu.amp.scaler import all_finite

        def step(gD, gG):
            return all_finite(gD), all_finite(gG)

        gD = {"d": jnp.asarray([jnp.inf])}
        gG = {"g": jnp.ones(2)}
        with health.activate(health.HealthConfig(level="cheap")):
            _, m = jax.jit(ingraph.reap(step))(gD, gG)
        got = m.as_floats()
        assert got["health/grads/nonfinite_count"] == 1.0
        assert got["health/grads#2/nonfinite_count"] == 0.0
        att = health.decode_attribution(got)
        assert att == {"grads": "['d']"}  # the inf stays attributed to D

    def test_leaf_paths_side_table(self):
        with health.activate(health.HealthConfig(level="cheap")):
            _, m = ingraph.reap(
                lambda: health.observe_tree(
                    {"x": jnp.ones(1), "y": jnp.ones(1)}, "sidetable")
                or jnp.zeros(()))()
        assert health.leaf_paths("sidetable") == ("['x']", "['y']")
        assert health.leaf_paths("never_observed") is None


# ---------------------------------------------------------------------------
# replica agreement (acceptance criterion: perturbed replica flagged)
# ---------------------------------------------------------------------------

class TestReplicaAgreement:
    def _run(self, stacked):
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))

        def inner(tree):
            def body(tree):
                local = jax.tree_util.tree_map(lambda l: l[0], tree)
                return health.check_replica_agreement(local, "data",
                                                      name="state")
            _, m = ingraph.reap(body)(tree)
            return ingraph.aggregate(m, "data")

        spec = jax.tree_util.tree_map(lambda _: P("data"), stacked)
        metrics = jax.jit(lambda t: shard_map(
            inner, mesh=mesh, in_specs=(spec,), out_specs=P())(t))(stacked)
        return metrics.as_floats()["health/state/replica_divergence"]

    def test_agreeing_replicas_read_zero(self):
        stacked = {"w": jnp.ones((4, 1, 8)), "b": jnp.zeros((4, 1, 2))}
        assert self._run(stacked) == 0.0

    def test_perturbed_replica_flagged(self):
        stacked = {"w": jnp.ones((4, 1, 8)), "b": jnp.zeros((4, 1, 2))}
        # corrupt one element on replica 1: mean moves by 0.5/4 = 0.125,
        # so the corrupted replica deviates by 0.375, the others by 0.125
        stacked["w"] = stacked["w"].at[1, 0, 3].add(0.5)
        assert self._run(stacked) == pytest.approx(0.375)

    def test_returns_scalar_outside_collector(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

        def inner(x):
            # zero-size and non-float leaves must be skipped, not crash
            tree = {"x": x, "empty": jnp.zeros((0,)), "i": jnp.arange(2)}
            # the returned divergence is PER-RANK (each replica's own
            # deviation from the mean); pmax it to cross a P() out_spec
            d = health.check_replica_agreement(tree, "data")
            return jax.lax.pmax(d, "data")

        out = jax.jit(lambda x: shard_map(
            inner, mesh=mesh, in_specs=P("data"), out_specs=P())(
                x))(jnp.ones(2))
        assert float(out) == 0.0


# ---------------------------------------------------------------------------
# crash dumps + the reporter hook
# ---------------------------------------------------------------------------

def _nonfinite_payload():
    """A payload as the attribution flow produces it (side table warmed)."""
    with health.activate(health.HealthConfig(level="cheap")):
        _, m = ingraph.reap(
            lambda: health.observe_tree(
                {"ok": jnp.ones(2),
                 "boom": jnp.asarray([jnp.inf])}, "grads")
            or jnp.zeros(()))()
    return m.as_floats()


class TestCrashDump:
    def test_dump_contents_and_roundtrip(self, tmp_path):
        payload = _nonfinite_payload()
        assert health.payload_nonfinite(payload)
        cfg = health.HealthConfig(level="cheap", on_nonfinite="dump",
                                  dump_dir=tmp_path)
        dump = health.CrashDump.from_payload(7, payload, cfg)
        assert dump.attribution == {"grads": "['boom']"}
        path = dump.write(tmp_path / "sub")
        text = open(path).read()
        # STRICT json: a bare Infinity literal (abs_max of an overflow
        # dump) would make the file unparsable by jq/JS/Go tooling
        doc = json.loads(text, parse_constant=lambda c: pytest.fail(
            f"non-standard JSON literal {c} in crash dump"))
        assert doc["step"] == 7
        assert doc["metrics"]["health/grads/nonfinite_count"] == 1.0
        assert doc["metrics"]["health/grads/abs_max"] == "Infinity"
        assert doc["attribution"] == {"grads": "['boom']"}
        assert doc["config"]["level"] == "cheap"
        assert doc["versions"]["jax"] == jax.__version__
        assert doc["wall_time"] > 0

    def test_monitor_dump_and_raise_and_skip(self, tmp_path):
        payload = _nonfinite_payload()
        clean = {"health/grads/nonfinite_count": 0.0,
                 "amp/overflow_count": 0.0}
        assert not health.payload_nonfinite(clean)

        dumper = health.HealthConfig(
            level="cheap", on_nonfinite="dump",
            dump_dir=tmp_path).reporter_hook()
        dumper(3, clean)
        assert dumper.dumps == []
        dumper(4, payload)
        assert len(dumper.dumps) == 1 and "step00000004" in dumper.dumps[0]

        raiser = health.HealthConfig(
            level="cheap", on_nonfinite="raise",
            dump_dir=tmp_path).reporter_hook()
        with pytest.raises(health.NonFiniteError) as exc:
            raiser(5, payload)
        assert exc.value.dump.step == 5
        assert exc.value.dump_path and "step00000005" in exc.value.dump_path
        assert "['boom']" in str(exc.value)

        skipper = health.HealthConfig(
            level="cheap", on_nonfinite="skip").reporter_hook()
        skipper(6, payload)  # no dump, no raise
        assert skipper.dumps == []

    def test_amp_overflow_alone_triggers(self):
        assert health.payload_nonfinite({"amp/overflow_count": 1.0})

    def test_reporter_runs_hooks_after_sinks(self, tmp_path):
        order = []

        class Spy(obs.JSONLSink):
            def __init__(self):
                pass

            def emit(self, step, metrics, spans=()):
                order.append("sink")

            def close(self):
                pass

        rep = obs.StepReporter([Spy()], registry=obs.MetricsRegistry(),
                               hooks=[lambda s, p: order.append("hook")])
        rep.report(0)
        assert order == ["sink", "hook"]

    def test_hooks_see_off_interval_steps(self):
        """interval=N samples the SINKS, not the watchdog: a transient
        non-finite step between reports must still reach the hooks."""
        seen, emitted = [], []

        class Spy(obs.JSONLSink):
            def __init__(self):
                pass

            def emit(self, step, metrics, spans=()):
                emitted.append(step)

            def close(self):
                pass

        rep = obs.StepReporter([Spy()], registry=obs.MetricsRegistry(),
                               interval=3,
                               hooks=[lambda s, p: seen.append((s, p))])
        for i in range(5):
            rep.report(i, metrics={"health/grads/nonfinite_count":
                                   1.0 if i == 1 else 0.0})
        assert emitted == [0, 3]
        assert [s for s, _ in seen] == [0, 1, 2, 3, 4]
        assert seen[1][1]["health/grads/nonfinite_count"] == 1.0
        # off-interval steps WITHOUT metrics stay fetch-free and unseen
        seen.clear()
        rep.report(7)
        assert seen == []

    def test_consecutive_tolerates_calibration_overflows(self, tmp_path):
        """consecutive=2 ignores isolated overflow reports (dynamic
        loss-scale calibration overflows by design every growth interval)
        and fires only when the streak shows real divergence."""
        payload = _nonfinite_payload()
        clean = {"amp/overflow_count": 0.0}
        hook = health.HealthConfig(
            level="cheap", on_nonfinite="raise", dump_dir=tmp_path,
            consecutive=2).reporter_hook()
        hook(0, payload)            # routine calibration overflow
        assert hook.streak == 1 and hook.dumps == []
        hook(1, clean)              # backoff cleared it -> streak resets
        assert hook.streak == 0
        hook(2, payload)
        with pytest.raises(health.NonFiniteError):
            hook(3, payload)        # second consecutive: real divergence
        assert len(hook.dumps) == 1

    def test_config_validation(self):
        with pytest.raises(ValueError):
            health.HealthConfig(level="loud")
        with pytest.raises(ValueError):
            health.HealthConfig(on_nonfinite="explode")
        with pytest.raises(ValueError):
            health.HealthConfig(consecutive=0)


# ---------------------------------------------------------------------------
# HealthConfig through GPTHybridTrainer (acceptance criterion)
# ---------------------------------------------------------------------------

def _small_cfg():
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    tp, pp, dp = 2, 2, 2
    M, mb, seq = 2, 2, 8
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2 * pp, num_attention_heads=4,
                          max_position_embeddings=seq),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0),
        opt_level="O0")
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    return cfg, tokens, targets


from _jaxpr_utils import jaxpr_str as _jaxpr_str  # noqa: E402


def test_trainer_health_off_is_jaxpr_identical_and_cheap_attributes():
    """level="off" leaves both trainer step programs identical to an
    unconfigured trainer's; level="cheap" surfaces the health metrics in
    the same Metrics pytree."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg, tokens, targets = _small_cfg()
    mesh = cfg.initialize_mesh(devices=jax.devices())
    try:
        base = GPTHybridTrainer(cfg, mesh)
        assert base.health.level == "off"  # from cfg.build_health()
        off = GPTHybridTrainer(cfg, mesh,
                               health=health.HealthConfig(level="off"))
        cheap = GPTHybridTrainer(
            cfg, mesh, health=health.HealthConfig(level="cheap"))
        state = base.init_state(jax.random.PRNGKey(0))
        args = state + (tokens, targets)

        base_plain = _jaxpr_str(base.train_step, *args)
        assert _jaxpr_str(off.train_step, *args) == base_plain
        # an active policy without a collector is also free: the plain
        # (uninstrumented) step of the CHEAP trainer matches too
        assert _jaxpr_str(cheap.train_step, *args) == base_plain
        base_metrics = _jaxpr_str(base.train_step_with_metrics, *args)
        assert _jaxpr_str(off.train_step_with_metrics, *args) \
            == base_metrics
        assert "health" not in base_metrics

        *_, metrics = jax.jit(cheap.train_step_with_metrics)(*args)
        got = metrics.as_floats()
        for key in ("health/grads/nonfinite_count",
                    "health/grads/first_nonfinite_leaf",
                    "amp/overflow_count"):
            assert key in got, key
        assert got["health/grads/nonfinite_count"] == 0.0
        assert got["health/grads/first_nonfinite_leaf"] == -1.0
    finally:
        parallel_state.destroy_model_parallel()


def test_trainer_full_level_replica_checks():
    """level="full" adds the data-axis replica-agreement checks on params
    and post-allreduce grads — both must read 0.0 on a healthy step."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg, tokens, targets = _small_cfg()
    mesh = cfg.initialize_mesh(devices=jax.devices())
    try:
        trainer = GPTHybridTrainer(
            cfg, mesh, health=health.HealthConfig(level="full"))
        state = trainer.init_state(jax.random.PRNGKey(0))
        *_, metrics = jax.jit(trainer.train_step_with_metrics)(
            *state, tokens, targets)
        got = metrics.as_floats()
        # ~0, not exactly 0: the pmean reduction order can leave an ulp
        # of residue on replicated state (see check_replica_agreement)
        assert got["health/params/replica_divergence"] <= 1e-6
        assert got["health/ddp_grads/replica_divergence"] <= 1e-6
        assert "health/optim_grads/nonfinite_count" in got
    finally:
        parallel_state.destroy_model_parallel()


def test_trainconfig_builds_health():
    from apex_tpu.config import TrainConfig

    cfg = TrainConfig(health_level="cheap", health_on_nonfinite="dump",
                      health_consecutive=3, health_dump_dir="dumps")
    h = cfg.build_health()
    assert h.level == "cheap" and h.on_nonfinite == "dump"
    assert h.consecutive == 3 and h.dump_dir == "dumps"
    # serialization round-trips the new fields
    assert TrainConfig.from_dict(cfg.to_dict()) == cfg
    assert TrainConfig().build_health().level == "off"
