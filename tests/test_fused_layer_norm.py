"""LayerNorm/RMSNorm parity tests.

Model: ``reference:tests/L0/run_fused_layer_norm/test_fused_layer_norm.py`` —
forward/backward vs ``torch.nn.LayerNorm`` (and manual RMS), per-dtype
tolerances, both the XLA path and the Pallas kernel (run in interpreter mode
on CPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import normalization as norm


def _data(shape=(4, 6, 512), seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.randn(*shape).astype(dtype)


@pytest.mark.parametrize("use_pallas", [False, True])
@pytest.mark.parametrize("hidden", [512, 384])
def test_layer_norm_affine_fwd_bwd_vs_torch(use_pallas, hidden):
    x_np = _data((8, hidden))
    w_np = _data((hidden,), 1) * 0.1 + 1.0
    b_np = _data((hidden,), 2) * 0.1
    dy_np = _data((8, hidden), 3)

    def f(x, w, b):
        out = norm.fused_layer_norm_affine(x, w, b, hidden,
                                           use_pallas=use_pallas)
        return jnp.sum(out * jnp.asarray(dy_np))

    out = norm.fused_layer_norm_affine(
        jnp.asarray(x_np), jnp.asarray(w_np), jnp.asarray(b_np), hidden,
        use_pallas=use_pallas)
    dx, dw, db = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(x_np), jnp.asarray(w_np), jnp.asarray(b_np))

    tx = torch.tensor(x_np, requires_grad=True)
    tw = torch.tensor(w_np, requires_grad=True)
    tb = torch.tensor(b_np, requires_grad=True)
    tout = torch.nn.functional.layer_norm(tx, (hidden,), tw, tb, eps=1e-5)
    tout.backward(torch.tensor(dy_np))

    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw), tw.grad.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(db), tb.grad.numpy(), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_rms_norm_affine_fwd_bwd(use_pallas):
    hidden = 256
    x_np = _data((16, hidden), 4)
    w_np = _data((hidden,), 5) * 0.1 + 1.0
    dy_np = _data((16, hidden), 6)

    out = norm.fused_rms_norm_affine(
        jnp.asarray(x_np), jnp.asarray(w_np), hidden, use_pallas=use_pallas)

    # manual torch RMS reference (fused_layer_norm.py:381-388 fallback math)
    tx = torch.tensor(x_np, requires_grad=True)
    tw = torch.tensor(w_np, requires_grad=True)
    trms = torch.rsqrt(tx.pow(2).mean(-1, keepdim=True) + 1e-5)
    tout = tx * trms * tw
    tout.backward(torch.tensor(dy_np))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=2e-5, atol=2e-5)

    def f(x, w):
        o = norm.fused_rms_norm_affine(x, w, hidden, use_pallas=use_pallas)
        return jnp.sum(o * jnp.asarray(dy_np))

    dx, dw = jax.grad(f, argnums=(0, 1))(jnp.asarray(x_np), jnp.asarray(w_np))
    np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw), tw.grad.numpy(), rtol=2e-4, atol=2e-4)


def test_no_affine_paths():
    x = jnp.asarray(_data((4, 128), 7))
    out = norm.fused_layer_norm(x, 128)
    tout = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)), (128,))
    np.testing.assert_allclose(np.asarray(out), tout.numpy(), rtol=2e-5, atol=2e-5)
    out = norm.fused_rms_norm(x, 128)
    assert out.shape == (4, 128)


def test_multidim_normalized_shape():
    x = jnp.asarray(_data((3, 4, 8, 16), 8))
    m = norm.FusedLayerNorm((8, 16))
    params = m.init()
    out = m(params, x)
    tout = torch.nn.functional.layer_norm(
        torch.tensor(np.asarray(x)), (8, 16),
        torch.ones(8, 16), torch.zeros(8, 16))
    np.testing.assert_allclose(np.asarray(out), tout.numpy(), rtol=2e-5, atol=2e-5)


def test_mixed_dtype_output_rule():
    """Standard: out dtype = input dtype; Mixed: out dtype = weight dtype
    (csrc/layer_norm_cuda.cpp:183-189 vs :205)."""
    hidden = 128
    x = jnp.asarray(_data((4, hidden), 9), jnp.bfloat16)
    w = jnp.ones(hidden, jnp.float32)
    b = jnp.zeros(hidden, jnp.float32)

    out_std = norm.fused_layer_norm_affine(x, w.astype(jnp.bfloat16),
                                           b.astype(jnp.bfloat16), hidden)
    assert out_std.dtype == jnp.bfloat16

    out_mixed = norm.mixed_dtype_fused_layer_norm_affine(x, w, b, hidden)
    assert out_mixed.dtype == jnp.float32

    m = norm.MixedFusedRMSNorm(hidden)
    out = m(m.init(), x)
    assert out.dtype == jnp.float32

    m2 = norm.FusedRMSNorm(hidden, param_dtype=jnp.bfloat16)
    assert m2(m2.init(), x).dtype == jnp.bfloat16


def test_bf16_stats_in_fp32():
    """bf16 input must not lose the mean to rounding: stats are fp32
    (csrc/layer_norm_cuda.cpp:161)."""
    hidden = 256
    x32 = _data((8, hidden), 10) * 3.0 + 100.0  # large offset stresses stats
    x16 = jnp.asarray(x32, jnp.bfloat16)
    out = norm.fused_layer_norm(x16, hidden)
    ref = torch.nn.functional.layer_norm(
        torch.tensor(np.asarray(x16, np.float32)), (hidden,))
    np.testing.assert_allclose(np.asarray(out, np.float32), ref.numpy(),
                               rtol=0.05, atol=0.05)


def test_shape_mismatch_raises():
    x = jnp.zeros((4, 100))
    with pytest.raises(ValueError):
        norm.fused_layer_norm(x, 128)


def test_jit_and_grad_through_module():
    m = norm.FusedRMSNorm(128)
    params = m.init()
    x = jnp.asarray(_data((4, 128), 11))

    @jax.jit
    def loss(p, x):
        return jnp.mean(m(p, x) ** 2)

    g = jax.grad(loss)(params, x)
    assert g["weight"].shape == (128,)
    assert np.isfinite(np.asarray(g["weight"])).all()


def test_pallas_block_sizing_respects_vmem_budget():
    """Code-review r3: huge-hidden shapes where no 8-row block fits the
    VMEM budget must be screened out of the auto path and refused loudly
    on the explicit path — not silently compiled with a budget-busting
    whole-array block."""
    import unittest.mock as mock

    from apex_tpu.normalization import _pallas

    with mock.patch.object(_pallas.jax, "default_backend",
                           return_value="tpu"):
        # hidden=65536: per-row working set 1.25MB -> cap = 6 rows < 8
        assert not _pallas.supports_pallas(1024, 65536)
        # small row counts still fit whole
        assert _pallas.supports_pallas(4, 65536)
        # normal regime unchanged
        assert _pallas.supports_pallas(8192, 4096)
    with pytest.raises(ValueError):
        _pallas._block_rows(1024, 65536)
    assert _pallas._block_rows(4, 65536) == 4
    assert _pallas._block_rows(8192, 4096) % 8 == 0
