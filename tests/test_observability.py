"""Telemetry subsystem tests: registry, in-graph accumulators (mesh
aggregation under shard_map), sinks, StepReporter, runtime introspection,
and the amp/DDP/pipeline/optimizer hot-path instrumentation — including
the zero-cost-when-inactive contract asserted on the traced program."""

import io
import json
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import observability as obs
from apex_tpu.observability import ingraph
from apex_tpu.utils.compat import shard_map


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram(self):
        r = obs.MetricsRegistry()
        r.counter("c").inc()
        r.counter("c").inc(2.5)
        r.gauge("g").set(7)
        h = r.histogram("h", buckets=[1.0, 10.0])
        for v in (0.5, 5.0, 50.0, 0.2):
            h.observe(v)
        snap = r.snapshot()
        assert snap["c"] == 3.5
        assert snap["g"] == 7.0
        assert snap["h_count"] == 4.0
        assert snap["h_sum"] == pytest.approx(55.7)
        # Prometheus le contract: cumulative counts, le_inf == count
        assert snap["h_bucket_le_1"] == 2.0
        assert snap["h_bucket_le_10"] == 3.0
        assert snap["h_bucket_le_inf"] == 4.0

    def test_get_or_create_and_kind_conflict(self):
        r = obs.MetricsRegistry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_unset_gauge_skipped_and_reset(self):
        r = obs.MetricsRegistry()
        r.gauge("never_set")
        r.counter("c").inc(5)
        assert "never_set" not in r.snapshot()
        r.reset()
        assert r.snapshot()["c"] == 0.0

    def test_gauge_set_to_nan_is_reported(self):
        """"Unset" is a flag, not a NaN sentinel: a gauge explicitly set
        to NaN (a legitimate health value — NaN abs-max IS the signal)
        must survive into the snapshot."""
        import math

        r = obs.MetricsRegistry()
        g = r.gauge("g")
        assert not g.is_set and math.isnan(g.value)
        g.set(float("nan"))
        assert g.is_set
        assert math.isnan(r.snapshot()["g"])
        g.reset()
        assert not g.is_set and "g" not in r.snapshot()

    def test_default_registry_singleton(self):
        assert obs.get_registry() is obs.get_registry()


# ---------------------------------------------------------------------------
# in-graph accumulators
# ---------------------------------------------------------------------------

class TestInGraph:
    def test_record_is_noop_without_collector(self):
        evaluated = []
        ingraph.record("m", lambda: evaluated.append(1) or 1.0)
        assert not evaluated and not ingraph.recording()

    def test_reap_returns_metrics(self):
        def fn(x):
            ingraph.record("a", x.sum(), reduce="sum")
            ingraph.record("b", lambda: x.max(), reduce="max")
            return x * 2

        out, metrics = jax.jit(ingraph.reap(fn))(jnp.arange(4.0))
        assert np.allclose(out, [0, 2, 4, 6])
        got = metrics.as_floats()
        assert got == {"a": 6.0, "b": 3.0}
        assert metrics.modes["a"] == "sum"

    def test_sum_rerecord_accumulates_others_overwrite(self):
        def fn():
            ingraph.record("s", 1.0, reduce="sum")
            ingraph.record("s", 2.0, reduce="sum")
            ingraph.record("g", 1.0, reduce="mean")
            ingraph.record("g", 5.0, reduce="mean")
            return jnp.zeros(())

        _, m = ingraph.reap(fn)()
        assert m.as_floats() == {"s": 3.0, "g": 5.0}

    def test_mode_conflict_and_bad_inputs(self):
        with ingraph.collecting():
            ingraph.record("m", 1.0, reduce="sum")
            with pytest.raises(ValueError):
                ingraph.record("m", 1.0, reduce="mean")
            with pytest.raises(ValueError):
                ingraph.record("vec", jnp.ones(3))
            with pytest.raises(ValueError):
                ingraph.record("m2", 1.0, reduce="median")

    def test_metrics_is_a_pytree(self):
        m = ingraph.Metrics({"a": jnp.asarray(1.0)}, {"a": "sum"})
        leaves, treedef = jax.tree_util.tree_flatten(m)
        assert len(leaves) == 1
        m2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert m2.modes == {"a": "sum"} and "a" in m2

    def test_mesh_aggregation_under_shard_map(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))

        def body(x):
            rank = jax.lax.axis_index("data").astype(jnp.float32)
            ingraph.record("r/sum", rank, reduce="sum")
            ingraph.record("r/mean", rank, reduce="mean")
            ingraph.record("r/max", rank, reduce="max")
            ingraph.record("r/min", rank, reduce="min")
            return x

        def inner(x):
            out, metrics = ingraph.reap(body)(x)
            return out, ingraph.aggregate(metrics, "data")

        _, metrics = jax.jit(lambda x: shard_map(
            inner, mesh=mesh, in_specs=P("data"),
            out_specs=(P("data"), P()))(x))(jnp.arange(8.0))
        got = metrics.as_floats()
        assert got == {"r/sum": 6.0, "r/mean": 1.5, "r/max": 3.0,
                       "r/min": 0.0}

    def test_aggregate_identity_without_axes(self):
        _, m = ingraph.reap(lambda: ingraph.record("a", 2.0) or jnp.zeros(()))()
        assert ingraph.aggregate(m, None).as_floats() == {"a": 2.0}


# ---------------------------------------------------------------------------
# zero-cost-when-inactive contract (acceptance criterion)
# ---------------------------------------------------------------------------

class TestZeroCost:
    def _instrumented_step(self):
        from apex_tpu.amp.scaler import DynamicLossScale, all_finite
        from apex_tpu.optimizers import FusedSGD

        scaler = DynamicLossScale()
        opt = FusedSGD(lr=0.1)

        def step(params, opt_state, ls, x):
            grads = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(params)
            finite = all_finite(grads)
            new_ls = scaler.update(ls, finite)
            params, opt_state = opt.step(grads, opt_state, params,
                                         grads_finite=finite)
            return params, opt_state, new_ls

        params = jnp.ones((4, 2))
        opt = FusedSGD(lr=0.1)
        return step, (params, opt.init(params), scaler.init(),
                      jnp.ones((3, 4)))

    def test_no_collector_no_collectives_no_extra_outputs(self):
        """With no collector the instrumented amp+optimizer step must add
        no device collectives, no telemetry math (the grad-norm sqrt), and
        no extra outputs — i.e. no per-step host transfers beyond the
        step's own results."""
        step, args = self._instrumented_step()
        jaxpr = jax.make_jaxpr(step)(*args)
        txt = str(jaxpr)
        for collective in ("psum", "pmean", "pmax", "pmin", "all_reduce"):
            assert collective not in txt
        assert "sqrt" not in txt  # optim/grad_norm's reduction is absent
        n_plain_outputs = len(jax.tree_util.tree_leaves(
            jax.eval_shape(step, *args)))

        reaped = ingraph.reap(step)
        jaxpr_on = jax.make_jaxpr(reaped)(*args)
        assert "sqrt" in str(jaxpr_on)  # grad norm present when collecting
        n_on_outputs = len(jax.tree_util.tree_leaves(
            jax.eval_shape(reaped, *args)))
        assert n_on_outputs > n_plain_outputs

    def test_ddp_allreduce_hlo_unchanged_without_collector(self):
        """The instrumented DDP sync compiles to the same collective count
        as ever when telemetry is off (its metrics are trace-time
        constants, so even with it on, only aggregation adds psums)."""
        from apex_tpu.parallel.distributed import allreduce_grads

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

        def step(g):
            return shard_map(
                lambda g: allreduce_grads({"w": g, "b": g[0]}, "data"),
                mesh=mesh, in_specs=P("data"),
                out_specs={"w": P("data"), "b": P("data")})(g)

        txt = jax.jit(step).lower(jnp.ones((2, 4))).as_text()
        # one collective per grad leaf, no more (spelling differs between
        # StableHLO and HLO renderings across jax versions)
        assert txt.count("all-reduce") + txt.count("all_reduce") == 2


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

class TestSinks:
    def test_jsonl_shape(self):
        buf = io.StringIO()
        sink = obs.JSONLSink(buf)
        sink.emit(3, {"b": 2.0, "a": 1.0})
        line = json.loads(buf.getvalue())
        assert line["step"] == 3
        assert isinstance(line["time"], float)
        assert line["metrics"] == {"a": 1.0, "b": 2.0}
        assert list(line["metrics"]) == ["a", "b"]  # sorted, grep-stable

    def test_jsonl_nonfinite_values_stay_strict_json(self):
        """NaN/inf payload values (legitimate health metrics, NaN-set
        gauges) must serialize as strings, not bare NaN/Infinity
        literals that strict parsers (jq, JSON.parse, Go) reject."""
        buf = io.StringIO()
        obs.JSONLSink(buf).emit(0, {"nan": float("nan"),
                                    "inf": float("inf"),
                                    "ninf": float("-inf"), "ok": 1.5})
        line = json.loads(buf.getvalue(), parse_constant=lambda c:
                          pytest.fail(f"non-standard literal {c}"))
        assert line["metrics"] == {"nan": "NaN", "inf": "Infinity",
                                   "ninf": "-Infinity", "ok": 1.5}

    def test_chrome_counters_nonfinite_safe(self, tmp_path):
        p = tmp_path / "t.json"
        sink = obs.ChromeTraceSink(p)
        sink.emit(0, {"bad": float("inf")})
        sink.close()
        doc = json.loads(p.read_text(), parse_constant=lambda c:
                         pytest.fail(f"non-standard literal {c}"))
        counter = [e for e in doc["traceEvents"] if e["ph"] == "C"][0]
        assert counter["args"]["bad"] == "Infinity"

    def test_jsonl_appends_to_path(self, tmp_path):
        p = tmp_path / "events.jsonl"
        with obs.JSONLSink(p) as sink:
            sink.emit(0, {"x": 1.0})
            sink.emit(1, {"x": 2.0})
        lines = [json.loads(l) for l in p.read_text().splitlines()]
        assert [l["step"] for l in lines] == [0, 1]

    def test_tensorboard_protocol(self):
        calls = []

        class Writer:
            def add_scalar(self, tag, value, step):
                calls.append((tag, value, step))

        obs.TensorBoardSink(Writer()).emit(7, {"b": 2.0, "a": 1.0})
        assert calls == [("a", 1.0, 7), ("b", 2.0, 7)]
        with pytest.raises(TypeError):
            obs.TensorBoardSink(object())

    def test_chrome_trace_spans_and_counters(self, tmp_path):
        p = tmp_path / "trace.json"
        sink = obs.ChromeTraceSink(p, pid=5)
        spans = [obs.Span("fwd", 1.0, 1.5), obs.Span("opt", 1.5, 1.6)]
        sink.emit(2, {"loss": 0.5}, spans)
        sink.close()
        doc = json.loads(p.read_text())
        events = doc["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        assert [e["name"] for e in complete] == ["fwd", "opt"]
        assert complete[0]["dur"] == pytest.approx(0.5e6)
        assert complete[0]["pid"] == 5
        assert complete[0]["args"]["step"] == 2
        counters = [e for e in events if e["ph"] == "C"]
        assert counters and counters[0]["args"] == {"loss": 0.5}

    def test_chrome_trace_interop_spans_plus_perf_gauges(self, tmp_path):
        """Drained spans and in-graph metrics render into ONE Chrome
        trace: span events for the timers, counter events carrying the
        pyprof `perf/*` attribution gauges next to the step metrics —
        well-formed strict JSON."""
        from apex_tpu.pyprof import attribute
        from apex_tpu.utils.timers import Timers

        p = tmp_path / "trace.json"
        timers = Timers()
        reg = obs.MetricsRegistry()
        with obs.StepReporter([obs.ChromeTraceSink(p, pid=3)],
                              registry=reg, timers=timers,
                              capture_spans=True) as rep:
            report = attribute(
                lambda x, w: jnp.sum(x @ w), 0.004,
                args=(jnp.ones((8, 8)), jnp.ones((8, 8))))
            rep.attach_attribution(report)
            with timers("fwd")():
                time.sleep(0.001)
            _, metrics = ingraph.reap(
                lambda: ingraph.record("m", 2.5) or jnp.zeros(()))()
            rep.report(0, metrics=metrics)
        doc = json.loads(p.read_text(), parse_constant=lambda c:
                         pytest.fail(f"non-standard literal {c}"))
        events = doc["traceEvents"]
        spans = [e for e in events if e["ph"] == "X"]
        assert [e["name"] for e in spans] == ["fwd"]
        counters = {k: v for e in events if e["ph"] == "C"
                    for k, v in e["args"].items()}
        assert counters["m"] == 2.5
        assert counters["perf/modeled_step_ms"] == pytest.approx(
            report.modeled_step_ms)
        assert counters["perf/comm_exposed_ms"] == 0.0


# ---------------------------------------------------------------------------
# StepReporter + timer spans
# ---------------------------------------------------------------------------

class TestStepReporter:
    def test_merges_ingraph_registry_timers_extra(self):
        from apex_tpu.utils.timers import Timers

        reg = obs.MetricsRegistry()
        reg.counter("host/c").inc(4)
        timers = Timers()
        timers("fwd").start()
        time.sleep(0.002)
        timers("fwd").stop()
        buf = io.StringIO()
        rep = obs.StepReporter([obs.JSONLSink(buf)], registry=reg,
                               timers=timers)
        _, metrics = ingraph.reap(
            lambda: ingraph.record("m", 1.5) or jnp.zeros(()))()
        payload = rep.report(0, metrics=metrics, extra={"loss": 2.0})
        assert payload["m"] == 1.5
        assert payload["host/c"] == 4.0
        assert payload["loss"] == 2.0
        assert payload["time/fwd_ms"] >= 2.0
        assert json.loads(buf.getvalue())["metrics"]["m"] == 1.5
        # reset_timers=True drained the timer
        assert timers("fwd").elapsed(reset=False) == 0.0

    def test_interval_gating(self):
        emitted = []

        class Spy(obs.JSONLSink):
            def __init__(self):
                pass

            def emit(self, step, metrics, spans=()):
                emitted.append(step)

            def close(self):
                pass

        rep = obs.StepReporter([Spy()], registry=obs.MetricsRegistry(),
                               interval=3)
        for s in range(7):
            rep.report(s)
        assert emitted == [0, 3, 6]

    def test_timer_spans_reach_chrome_sink(self, tmp_path):
        from apex_tpu.utils.timers import Timers

        p = tmp_path / "t.json"
        timers = Timers()
        with obs.StepReporter([obs.ChromeTraceSink(p)],
                              registry=obs.MetricsRegistry(),
                              timers=timers, capture_spans=True) as rep:
            with timers("step")():
                time.sleep(0.001)
            rep.report(0)
        assert not obs.spans_enabled()  # close() restored the default
        events = json.loads(p.read_text())["traceEvents"]
        spans = [e for e in events if e["ph"] == "X"]
        assert [e["name"] for e in spans] == ["step"]

    def test_mfu_gauge_from_flops_budget(self):
        """With a flops budget attached, consecutive reports carry a
        perf/mfu gauge computed from the wall time between them."""
        emitted = []

        class Spy(obs.JSONLSink):
            def __init__(self):
                pass

            def emit(self, step, metrics, spans=()):
                emitted.append(dict(metrics))

            def close(self):
                pass

        rep = obs.StepReporter([Spy()], registry=obs.MetricsRegistry())
        with pytest.raises(ValueError):
            rep.attach_flops_budget(1e6, peak=0.0)  # fail at config time
        with pytest.raises(ValueError):
            rep.attach_flops_budget(-1.0)
        assert rep.attach_flops_budget(1e6, peak=1e9) is rep
        rep.report(0)
        assert "perf/mfu" not in emitted[0]  # no prior report to diff
        time.sleep(0.005)
        rep.report(2)
        # 2 steps x 1e6 flops over >= 5ms against a 1e9 peak
        assert 0.0 < emitted[1]["perf/mfu"] <= 2e6 / 0.005 / 1e9
        # the gauge also lands in the registry for later snapshots
        assert rep.registry.snapshot()["perf/mfu"] == emitted[1]["perf/mfu"]

    def test_memory_budget_gauges(self):
        """attach_memory_budget sets the mem/* gauge family — from a
        budget dict or straight from a compiled executable — and a
        None-budget backend leaves the gauges unset (no fabricated
        zeros)."""
        rep = obs.StepReporter([], registry=obs.MetricsRegistry())
        budget = {"argument_bytes": 100, "output_bytes": 10,
                  "temp_bytes": 50, "alias_bytes": 0,
                  "generated_code_bytes": 1, "host_temp_bytes": 0,
                  "peak_hbm_bytes": 161}
        assert rep.attach_memory_budget(budget) is rep
        snap = rep.registry.snapshot()
        assert snap["mem/peak_hbm_bytes"] == 161.0
        assert snap["mem/temp_bytes"] == 50.0
        assert snap["mem/argument_bytes"] == 100.0
        assert snap["mem/output_bytes"] == 10.0
        assert snap["mem/host_temp_bytes"] == 0.0

        # straight from a compiled executable (skip silently if the
        # backend reports no analysis — then nothing may be set)
        rep2 = obs.StepReporter([], registry=obs.MetricsRegistry())
        compiled = jax.jit(lambda x: jnp.sum(x * x)).lower(
            jnp.ones((32, 32))).compile()
        rep2.attach_memory_budget(compiled)
        snap2 = rep2.registry.snapshot()
        if obs.memory_budget(compiled) is not None:
            assert snap2["mem/peak_hbm_bytes"] > 0
        # an analysis-less object must leave the family unset
        rep3 = obs.StepReporter([], registry=obs.MetricsRegistry())
        rep3.attach_memory_budget(object())
        assert not any(k.startswith("mem/")
                       for k in rep3.registry.snapshot())

    def test_null_reporter_default(self):
        obs.detach_reporter()
        rep = obs.get_reporter()
        assert not rep
        assert rep.report(0, extra={"x": 1}) is None
        real = obs.attach_reporter(
            obs.StepReporter([], registry=obs.MetricsRegistry()))
        try:
            assert obs.get_reporter() is real
        finally:
            obs.detach_reporter()
        assert not obs.get_reporter()


# ---------------------------------------------------------------------------
# trace span buffer under concurrency
# ---------------------------------------------------------------------------

class TestTraceConcurrency:
    def test_concurrent_record_and_drain_loses_nothing(self):
        """Producer threads hammer record_span while a drainer races
        drain_spans: every span must come out exactly once (the _SPANS
        buffer swap is lock-protected on both sides)."""
        import threading

        from apex_tpu.observability import trace

        n_producers, n_spans = 4, 300
        drained = []
        stop = threading.Event()

        def produce(k):
            for i in range(n_spans):
                trace.record_span(f"p{k}-{i}", float(i), float(i) + 1.0)

        def drain():
            while not stop.is_set():
                drained.extend(trace.drain_spans())

        trace.enable_spans()
        try:
            threads = [threading.Thread(target=produce, args=(k,))
                       for k in range(n_producers)]
            drainer = threading.Thread(target=drain)
            drainer.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stop.set()
            drainer.join()
            drained.extend(trace.drain_spans())
        finally:
            trace.disable_spans()
        names = [s.name for s in drained]
        assert len(names) == n_producers * n_spans
        assert len(set(names)) == len(names)  # no duplicates either

    def test_disable_drops_undrained_spans(self):
        from apex_tpu.observability import trace

        trace.enable_spans()
        trace.record_span("stale", 0.0, 1.0)
        trace.disable_spans()
        trace.enable_spans()
        try:
            assert trace.drain_spans() == []
        finally:
            trace.disable_spans()


# ---------------------------------------------------------------------------
# costs: peak-flops table + MFU math (shared with bench.py)
# ---------------------------------------------------------------------------

class TestCosts:
    def test_peak_flops_table_and_fallback(self):
        class Fake:
            def __init__(self, kind):
                self.device_kind = kind

        assert obs.peak_flops(Fake("TPU v4 something")) == 275e12
        assert obs.peak_flops(Fake("TPU v5e")) == 197e12
        from apex_tpu.observability.costs import DEFAULT_PEAK_FLOPS
        assert obs.peak_flops(Fake("cpu")) == DEFAULT_PEAK_FLOPS
        assert obs.peak_flops() == DEFAULT_PEAK_FLOPS  # CPU test host

    def test_flops_budget_from_compiled(self):
        compiled = jax.jit(lambda x: x @ x).lower(
            jnp.ones((8, 8))).compile()
        budget = obs.flops_budget(compiled)
        # the CPU backend reports a real flop count for a matmul; a
        # backend without cost analysis must yield None, not raise
        assert budget is None or budget > 0
        assert obs.flops_budget(object()) is None

    def test_mfu_math(self):
        assert obs.mfu(10.0, 2.0, peak=1.0) == 5.0
        # zero/negative step time returns NaN (gauge stays unset) rather
        # than raising mid-report — the first-report wall delta can be
        # ~0 on a fast host (regression: tests/test_pyprof.py pins the
        # reporter-level behavior)
        import math
        assert math.isnan(obs.mfu(1.0, 0.0, peak=1.0))
        assert math.isnan(obs.mfu(1.0, -1.0, peak=1.0))
        assert math.isnan(obs.mfu(1.0, 1.0, peak=0.0))

    def test_bench_imports_from_costs(self):
        """bench.py must not regrow its own table — one source of truth."""
        import ast
        src = ast.parse(open("bench.py").read())
        assigned = {t.id for node in ast.walk(src)
                    if isinstance(node, ast.Assign)
                    for t in node.targets if isinstance(t, ast.Name)}
        assert "_PEAK_BF16" not in assigned
        imports = [n for node in ast.walk(src)
                   if isinstance(node, ast.ImportFrom)
                   and node.module == "apex_tpu.observability.costs"
                   for n in node.names]
        assert {a.name for a in imports} >= {"flops_budget", "peak_flops",
                                             "memory_budget"}

    def test_memory_budget_from_compiled(self):
        """memory_analysis() extraction: real bytes on backends that report
        (the CPU backend does), None — never a raise — otherwise."""
        compiled = jax.jit(
            lambda x, w: jnp.sum(jnp.tanh(x @ w) @ w)).lower(
            jnp.ones((64, 64)), jnp.ones((64, 64))).compile()
        budget = obs.memory_budget(compiled)
        assert obs.memory_budget(object()) is None
        if budget is None:  # backend without memory analysis
            return
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "generated_code_bytes",
                    "host_temp_bytes", "peak_hbm_bytes"):
            assert key in budget and budget[key] >= 0, key
        # two 64x64 fp32 args, and the high-water covers them
        assert budget["argument_bytes"] == 2 * 64 * 64 * 4
        assert budget["peak_hbm_bytes"] >= budget["argument_bytes"]


# ---------------------------------------------------------------------------
# runtime introspection
# ---------------------------------------------------------------------------

class TestRuntime:
    def test_compile_listener_counts_fresh_compile(self):
        reg = obs.MetricsRegistry()
        assert obs.install_compile_listeners(reg) is reg
        obs.install_compile_listeners(reg)  # idempotent: no double count
        before = reg.counter("jax/compiles").value
        salt = np.random.default_rng().integers(1 << 30)
        jax.jit(lambda x: x * float(salt))(jnp.ones(3)).block_until_ready()
        after = reg.counter("jax/compiles").value
        assert after == before + 1
        assert reg.counter("jax/traces").value >= after
        snap = reg.snapshot()
        assert snap["jax/compile_seconds_count"] == after

    def test_uninstall_and_reinstall(self):
        """Listener lifecycles are reversible: an uninstalled registry's
        counters stop moving, a reinstalled one counts again — repeated
        StepReporter-style lifecycles cannot double-count."""
        def fresh_compile():
            salt = np.random.default_rng().integers(1 << 30)
            jax.jit(lambda x: x + float(salt))(
                jnp.ones(3)).block_until_ready()

        reg = obs.MetricsRegistry()
        obs.install_compile_listeners(reg)
        fresh_compile()
        counted = reg.counter("jax/compiles").value
        assert counted >= 1
        assert obs.uninstall_compile_listeners(reg)
        assert not obs.uninstall_compile_listeners(reg)  # already gone
        fresh_compile()
        assert reg.counter("jax/compiles").value == counted  # frozen
        obs.install_compile_listeners(reg)
        fresh_compile()
        assert reg.counter("jax/compiles").value == counted + 1

    def test_reset_detaches_everything(self):
        regs = [obs.MetricsRegistry(), obs.MetricsRegistry()]
        for r in regs:
            obs.install_compile_listeners(r)
        obs.reset_compile_listeners()
        salt = np.random.default_rng().integers(1 << 30)
        jax.jit(lambda x: x - float(salt))(jnp.ones(3)).block_until_ready()
        for r in regs:
            assert r.counter("jax/compiles").value == 0

    def test_memory_stats_sampler(self):
        reg = obs.MetricsRegistry()
        out = obs.sample_memory_stats(reg)
        # CPU backends expose no allocator stats; on TPU/GPU each device
        # contributes bytes_in_use
        for name, value in out.items():
            assert name.startswith("memory/")
            assert value >= 0
            assert reg.snapshot()[name] == value


# ---------------------------------------------------------------------------
# hot-path instrumentation
# ---------------------------------------------------------------------------

class TestHotPaths:
    def test_amp_scaler_metrics_on_overflow(self):
        from apex_tpu.amp.scaler import DynamicLossScale

        scaler = DynamicLossScale(init_scale=16.0)

        def update(ls, finite):
            return scaler.update(ls, finite)

        reaped = jax.jit(ingraph.reap(update))
        _, m = reaped(scaler.init(), jnp.asarray(False))
        got = m.as_floats()
        assert got["amp/loss_scale"] == 8.0  # halved on overflow
        assert got["amp/overflow_count"] == 1.0
        assert got["amp/skipped_steps"] == 1.0
        _, m = reaped(scaler.init(), jnp.asarray(True))
        got = m.as_floats()
        assert got["amp/loss_scale"] == 16.0
        assert got["amp/overflow_count"] == 0.0

    def test_static_scaler_also_reports(self):
        from apex_tpu.amp.scaler import StaticLossScale

        scaler = StaticLossScale(scale=4.0)
        _, m = ingraph.reap(scaler.update)(scaler.init(),
                                           jnp.asarray(False))
        got = m.as_floats()
        assert got["amp/loss_scale"] == 4.0
        assert got["amp/skipped_steps"] == 1.0

    def test_ddp_allreduce_bytes_mesh_aggregated(self):
        from apex_tpu.parallel.distributed import allreduce_grads

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        grads = {"w": jnp.ones((2, 8, 4)), "b": jnp.ones((2, 4))}
        per_rank = 8 * 4 * 4 + 4 * 4  # f32 leaf bytes on one rank

        def inner(g):
            out, m = ingraph.reap(
                lambda g: allreduce_grads(g, "data"))(g)
            return out, ingraph.aggregate(m, "data")

        _, m = jax.jit(lambda g: shard_map(
            inner, mesh=mesh,
            in_specs=({"w": P("data"), "b": P("data")},),
            out_specs=({"w": P("data"), "b": P("data")}, P()))(g))(grads)
        got = m.as_floats()
        assert got["ddp/allreduce_bytes"] == 2 * per_rank  # psum over mesh
        assert got["ddp/buckets"] == 2.0

    def test_ddp_fp32_upcast_counts_fp32_bytes(self):
        from apex_tpu.parallel.distributed import allreduce_grads

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        g16 = jnp.ones((2, 8), jnp.bfloat16)

        def inner(g):
            out, m = ingraph.reap(lambda g: allreduce_grads(
                g, "data", allreduce_always_fp32=True))(g)
            return out, ingraph.aggregate(m, "data")

        _, m = jax.jit(lambda g: shard_map(
            inner, mesh=mesh, in_specs=P("data"),
            out_specs=(P("data"), P()))(g))(g16)
        assert m.as_floats()["ddp/allreduce_bytes"] == 2 * 8 * 4

    def test_optimizer_grad_norm(self):
        from apex_tpu.optimizers import FusedSGD

        opt = FusedSGD(lr=0.0)  # lr 0: params unchanged, norm still real
        params = {"a": jnp.ones(3), "b": jnp.zeros(2)}
        grads = {"a": jnp.full(3, 2.0), "b": jnp.zeros(2)}

        def step(g, s, p):
            return opt.step(g, s, p)

        _, m = jax.jit(ingraph.reap(step))(grads, opt.init(params), params)
        assert m.as_floats()["optim/grad_norm"] == pytest.approx(
            float(np.sqrt(12.0)))

    def test_pipeline_no_pipelining_reports_zero_bubble(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_no_pipelining)

        batch = jnp.ones((4, 2, 3))
        params = {"w": jnp.ones((3,))}

        def fwd(p, mb):
            return jnp.mean(mb * p["w"])

        def run(params):
            return forward_backward_no_pipelining(fwd, batch, params)

        _, m = jax.jit(ingraph.reap(run))(params)
        got = m.as_floats()
        assert got["pipeline/bubble_fraction"] == 0.0
        assert got["pipeline/num_microbatches"] == 4.0
        assert got["pipeline/ticks"] == 4.0

    def test_pipeline_1f1b_bubble_fraction(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_without_interleaving)

        mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
        pp, M, D = 2, 4, 4
        ws = jnp.ones((pp, D, D)) * 0.1
        micro = jnp.ones((M, 2, D))

        def stage(p, x, s):
            return jnp.tanh(x @ p["w"])

        def inner(ws):
            def body(ws):
                return forward_backward_pipelining_without_interleaving(
                    stage, micro, {"w": ws[0]},
                    loss_fn=lambda y, m: jnp.mean(y ** 2))
            out, m = ingraph.reap(body)(ws)
            return out, ingraph.aggregate(m, "pipe")

        (_, _), m = jax.jit(lambda w: shard_map(
            inner, mesh=mesh, in_specs=(P("pipe"),),
            out_specs=((P(), {"w": P("pipe")}), P()))(w))(ws)
        got = m.as_floats()
        # fwd+bwd 1F1B scan: T = M + 2L - 1 = 7 ticks, M useful -> 3/7
        assert got["pipeline/ticks"] == 7.0
        assert got["pipeline/bubble_fraction"] == pytest.approx(3.0 / 7.0)


# ---------------------------------------------------------------------------
# the acceptance toy run: 3 steps, full stream, mesh-aggregated
# ---------------------------------------------------------------------------

def test_three_step_toy_run_emits_full_stream(tmp_path):
    """amp + DDP + pipelined schedule + fused optimizer on a pipe x data
    CPU mesh for 3 steps: the JSONL stream must carry the whole documented
    metric surface with per-rank values psum-aggregated across the mesh."""
    from apex_tpu.amp.scaler import DynamicLossScale, all_finite
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.optimizers.fused_sgd import SGDState
    from apex_tpu.parallel.distributed import allreduce_grads
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("pipe", "data"))
    pp, M, mb, D = 2, 4, 2, 8
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(pp, D, D) * 0.3, jnp.float32)
    micro = jnp.asarray(rng.randn(M, 2 * mb, D), jnp.float32)
    scaler = DynamicLossScale(init_scale=2.0 ** 4, growth_interval=2)
    opt = FusedSGD(lr=1e-2, momentum=0.9)
    opt_state = opt.init(ws)
    ls = scaler.init()

    def stage(p, x, s):
        return jnp.tanh(x @ p["w"])

    def body(ws, opt_state, ls, micro):
        loss, grads = forward_backward_pipelining_without_interleaving(
            stage, micro, {"w": ws[0]},
            loss_fn=lambda y, m: jnp.mean(y ** 2),
            grad_scale=ls.loss_scale)
        grads = allreduce_grads(grads["w"][None], "data")
        finite = all_finite(grads, axis_names=("pipe",))
        new_ls = scaler.update(ls, finite)
        new_w, new_s = opt.step(grads, opt_state, ws, grads_finite=finite)
        return jax.lax.pmean(loss, "data"), new_w, new_s, new_ls

    def inner(*args):
        out, metrics = ingraph.reap(body)(*args)
        return out + (ingraph.aggregate(metrics, ("pipe", "data")),)

    ospec = SGDState(step=P(), momentum_buf=P("pipe"))
    step = jax.jit(lambda w, s, l, m: shard_map(
        inner, mesh=mesh,
        in_specs=(P("pipe"), ospec, P(), P(None, "data")),
        out_specs=(P(), P("pipe"), ospec, P(), P()))(w, s, l, m))

    path = tmp_path / "telemetry.jsonl"
    with obs.StepReporter([obs.JSONLSink(path)],
                          registry=obs.MetricsRegistry()) as rep:
        for i in range(3):
            loss, ws, opt_state, ls, metrics = step(ws, opt_state, ls,
                                                    micro)
            rep.report(i, metrics=metrics, extra={"loss": float(loss)})

    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["step"] for l in lines] == [0, 1, 2]
    for line in lines:
        m = line["metrics"]
        for key in ("amp/loss_scale", "amp/overflow_count",
                    "amp/skipped_steps", "ddp/allreduce_bytes",
                    "ddp/buckets", "optim/grad_norm",
                    "pipeline/bubble_fraction", "pipeline/ticks",
                    "pipeline/num_microbatches", "loss"):
            assert key in m, key
    last = lines[-1]["metrics"]
    # psum-aggregation across the 4-device mesh: each rank contributes its
    # (1, D, D) f32 grad leaf per sync
    assert last["ddp/allreduce_bytes"] == 4 * D * D * 4
    # growth_interval=2, 3 clean steps -> one doubling of 2**4
    assert last["amp/loss_scale"] == 32.0
    assert last["pipeline/bubble_fraction"] == pytest.approx(3.0 / 7.0)
    assert last["optim/grad_norm"] > 0.0


def test_hybrid_trainer_step_with_metrics():
    """GPTHybridTrainer.train_step_with_metrics must produce the same loss
    as train_step plus the full mesh-aggregated telemetry surface."""
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    tp, pp, dp = 2, 2, 2
    M, mb, seq = 4, 2, 8
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2 * pp, num_attention_heads=4,
                          max_position_embeddings=seq),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0),
        opt_level="O0")
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    mesh = cfg.initialize_mesh(devices=jax.devices())
    try:
        trainer = GPTHybridTrainer(cfg, mesh)
        state = trainer.init_state(jax.random.PRNGKey(0))
        loss, *_ = jax.jit(trainer.train_step)(*state, tokens, targets)
        loss_m, _, _, _, _, metrics = jax.jit(
            trainer.train_step_with_metrics)(*state, tokens, targets)
    finally:
        parallel_state.destroy_model_parallel()
    assert float(loss) == pytest.approx(float(loss_m), abs=1e-6)
    got = metrics.as_floats()
    for key in ("amp/loss_scale", "amp/overflow_count", "amp/skipped_steps",
                "ddp/allreduce_bytes", "ddp/buckets", "optim/grad_norm",
                "pipeline/bubble_fraction", "pipeline/ticks"):
        assert key in got, key
    assert got["ddp/allreduce_bytes"] > 0
    # 1F1B over pp=2, M=4: T = 7 ticks, bubble 3/7
    assert got["pipeline/bubble_fraction"] == pytest.approx(3.0 / 7.0)


# ---------------------------------------------------------------------------
# static contract checks
# ---------------------------------------------------------------------------
# The six per-script test classes that used to live here (annotations,
# collectives, metrics-doc, remat-names, elastic-exits, bench-configs)
# moved to tests/test_analysis.py as ONE parametrized planted-violation
# suite over the unified engine (apex_tpu.analysis, PR 11). What remains
# here is the back-compat contract: the scripts/ shims still expose the
# historical check(repo) -> (ok, lines) surface and pass on this tree.

_SHIM_SCRIPTS = ("check_annotations", "check_collectives",
                 "check_metrics_doc", "check_remat_names",
                 "check_elastic_exits", "check_bench_configs")


@pytest.mark.parametrize("script", _SHIM_SCRIPTS)
def test_check_script_shim_passes_on_this_tree(script):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        script, f"scripts/{script}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ok, lines = mod.check()
    assert ok, "\n".join(lines)
    assert lines  # the report still enumerates what was checked
    assert callable(mod.main)
