"""Paged serving path (docs/SERVING.md "Paged serving"): the bounded
paged decode kernel vs the cache oracle, PagedKVCache pool writes, the
block allocator's refcount/COW/prefix-hash lifecycle, and the
PagedServingEngine contracts — prefill+decode parity vs the one-shot
forward, prefix-shared stream identity, zero-recompile across
admit/COW/retire, pool-exhaustion admission control."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.ops.flash_attention import (decode_attention, mha_reference,
                                          paged_decode_attention,
                                          supports_paged)
from apex_tpu.serving import (BlockAllocator, PagedKVCache,
                              PagedServingEngine, PoolExhausted, Rejection,
                              Request, ServingEngine, SlotScheduler,
                              paged_block_bytes)


def _quantize_ref(x):
    scale = np.maximum(np.abs(x).max(-1) / 127.0, 1e-8)
    q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


# ---------------------------------------------------------------------------
# the paged decode kernel vs the mha_reference cache oracle
# ---------------------------------------------------------------------------

class TestPagedDecodeKernel:
    B, H, BS, NBS, D = 4, 4, 32, 8, 32      # per-slot span 256
    NB = 34                                  # pool blocks (0 = null)
    LENGTHS = [0, 1, 100, 256]               # empty, single, partial, full

    def _layout(self, rng):
        """Random pool layout: each slot's blocks scattered through the
        pool (never block 0), plus the dense gather for the oracle."""
        perm = rng.permutation(np.arange(1, self.NB))
        tables = perm[: self.B * self.NBS].reshape(self.B, self.NBS)
        return tables.astype(np.int32)

    def _dense_of(self, pool, tables):
        g = np.asarray(pool)[tables]              # (B, NBS, H, BS, D)
        return g.transpose(0, 2, 1, 3, 4).reshape(
            self.B, self.H, self.NBS * self.BS, self.D)

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-6),
                                           (jnp.bfloat16, 2e-2)])
    def test_parity_vs_cache_oracle(self, dtype, tol):
        rng = np.random.RandomState(0)
        tables = self._layout(rng)
        lengths = jnp.asarray(self.LENGTHS, jnp.int32)
        q = jnp.asarray(rng.randn(self.B, self.H, self.D), dtype)
        kp = jnp.asarray(rng.randn(self.NB, self.H, self.BS, self.D), dtype)
        vp = jnp.asarray(rng.randn(self.NB, self.H, self.BS, self.D), dtype)
        k_new = jnp.asarray(rng.randn(self.B, self.H, self.D), dtype)
        v_new = jnp.asarray(rng.randn(self.B, self.H, self.D), dtype)
        out = paged_decode_attention(q, kp, vp, jnp.asarray(tables),
                                     lengths, k_new=k_new, v_new=v_new)
        # oracle: dense-gather the pool and write the current token at
        # each row's CURSOR (kv_length masks everything past it)
        kd = np.concatenate([self._dense_of(kp, tables),
                             np.zeros((self.B, self.H, 1, self.D),
                                      np.float32)], axis=2)
        vd = np.concatenate([self._dense_of(vp, tables),
                             np.zeros((self.B, self.H, 1, self.D),
                                      np.float32)], axis=2)
        for i, ln in enumerate(self.LENGTHS):
            kd[i, :, ln] = np.asarray(k_new, np.float32)[i]
            vd[i, :, ln] = np.asarray(v_new, np.float32)[i]
        ref = mha_reference(
            q[:, :, None].astype(jnp.float32), jnp.asarray(kd),
            jnp.asarray(vd), kv_length=lengths + 1)[:, :, 0]
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32), atol=tol)

    def test_parity_int8(self):
        rng = np.random.RandomState(1)
        tables = self._layout(rng)
        lengths = jnp.asarray(self.LENGTHS, jnp.int32)
        q = jnp.asarray(rng.randn(self.B, self.H, self.D), jnp.float32)
        kf = rng.randn(self.NB, self.H, self.BS, self.D).astype(np.float32)
        vf = rng.randn(self.NB, self.H, self.BS, self.D).astype(np.float32)
        # pool scales are per-(block-position, head): quantize on the
        # (NB, H, BS) leading axes
        kq, ksc = _quantize_ref(kf)
        vq, vsc = _quantize_ref(vf)
        out = paged_decode_attention(
            q, jnp.asarray(kq), jnp.asarray(vq), jnp.asarray(tables),
            lengths, k_scale=jnp.asarray(ksc), v_scale=jnp.asarray(vsc))
        kd = self._dense_of(kq.astype(np.float32) * ksc[..., None], tables)
        vd = self._dense_of(vq.astype(np.float32) * vsc[..., None], tables)
        ref = mha_reference(q[:, :, None], jnp.asarray(kd),
                            jnp.asarray(vd), kv_length=lengths)[:, :, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2)

    def test_pallas_matches_xla_fallback(self):
        rng = np.random.RandomState(2)
        tables = self._layout(rng)
        lengths = jnp.asarray([7, 63, 128, 200], jnp.int32)
        q = jnp.asarray(rng.randn(self.B, self.H, self.D), jnp.float32)
        kp = jnp.asarray(rng.randn(self.NB, self.H, self.BS, self.D),
                         jnp.float32)
        vp = jnp.asarray(rng.randn(self.NB, self.H, self.BS, self.D),
                         jnp.float32)
        a = paged_decode_attention(q, kp, vp, jnp.asarray(tables), lengths,
                                   use_pallas=True)
        b = paged_decode_attention(q, kp, vp, jnp.asarray(tables), lengths,
                                   use_pallas=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)

    def test_unmapped_tail_blocks_never_pollute(self):
        """Table entries past ceil(length/block) may be garbage (null or
        stale) — the clamped index map / length mask must keep them out
        of the math."""
        rng = np.random.RandomState(3)
        tables = self._layout(rng)
        lengths = jnp.asarray([40, 40, 40, 40], jnp.int32)  # 2 blocks
        q = jnp.asarray(rng.randn(self.B, self.H, self.D), jnp.float32)
        kp = rng.randn(self.NB, self.H, self.BS, self.D).astype(np.float32)
        vp = rng.randn(self.NB, self.H, self.BS, self.D).astype(np.float32)
        out1 = paged_decode_attention(q, jnp.asarray(kp), jnp.asarray(vp),
                                      jnp.asarray(tables), lengths)
        # poison every block the cursor doesn't cover
        used = set(tables[:, :2].ravel().tolist())
        for blk in range(self.NB):
            if blk not in used:
                kp[blk] = 1e6
                vp[blk] = 1e6
        out2 = paged_decode_attention(q, jnp.asarray(kp), jnp.asarray(vp),
                                      jnp.asarray(tables), lengths)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# PagedKVCache pool writes
# ---------------------------------------------------------------------------

class TestPagedKVCache:
    def test_append_and_null_masking(self):
        pool = PagedKVCache.create(2, 6, 3, 4, 5, dtype=jnp.float32)
        kn = jnp.arange(2 * 2 * 3 * 5, dtype=jnp.float32).reshape(2, 2, 3, 5)
        pool = pool.append(kn, kn + 100, jnp.asarray([2, 3]),
                           jnp.asarray([1, 0]))
        np.testing.assert_allclose(np.asarray(pool.k)[:, 2, :, 1, :],
                                   np.asarray(kn)[:, 0])
        np.testing.assert_allclose(np.asarray(pool.v)[:, 3, :, 0, :],
                                   np.asarray(kn)[:, 1] + 100)
        # a null-targeted append (masked slot) lands in block 0 only
        pool2 = pool.append(kn * 0 - 7, kn * 0 - 7, jnp.asarray([0, 0]),
                            jnp.asarray([0, 0]))
        np.testing.assert_allclose(np.asarray(pool2.k)[:, 2, :, 1, :],
                                   np.asarray(kn)[:, 0])

    def test_write_prompt_blocks_layout(self):
        L, H, P, D, bs = 2, 3, 8, 5, 4
        pool = PagedKVCache.create(L, 6, H, bs, D, dtype=jnp.float32)
        kp = jnp.arange(L * H * P * D, dtype=jnp.float32).reshape(L, H, P, D)
        pool = pool.write_prompt_blocks(kp, kp + 5, jnp.asarray([4, 5]))
        # block 4 holds positions 0..3, block 5 positions 4..7
        np.testing.assert_allclose(np.asarray(pool.k)[:, 4],
                                   np.asarray(kp)[:, :, 0:4, :])
        np.testing.assert_allclose(np.asarray(pool.v)[:, 5],
                                   np.asarray(kp)[:, :, 4:8, :] + 5)

    def test_cow_copy_and_null_noop(self):
        pool = PagedKVCache.create(1, 4, 2, 4, 3, dtype=jnp.float32)
        kn = jnp.ones((1, 1, 2, 3))
        pool = pool.append(kn, 2 * kn, jnp.asarray([2]), jnp.asarray([0]))
        pool = pool.cow_copy(jnp.asarray([2]), jnp.asarray([3]))
        np.testing.assert_allclose(np.asarray(pool.k)[:, 3],
                                   np.asarray(pool.k)[:, 2])
        # the all-null pair is the no-op every COW-free step runs
        pool2 = pool.cow_copy(jnp.asarray([0]), jnp.asarray([0]))
        np.testing.assert_allclose(np.asarray(pool2.k), np.asarray(pool.k))

    def test_int8_pool_roundtrip_and_pytree(self):
        pool = PagedKVCache.create(1, 3, 2, 4, 8, dtype=jnp.int8)
        assert pool.quantized
        x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 2, 8),
                        jnp.float32)
        pool = pool.append(x, x, jnp.asarray([1]), jnp.asarray([2]))
        deq = (pool.k[0, 1, :, 2].astype(jnp.float32)
               * pool.k_scale[0, 1, :, 2, None])
        np.testing.assert_allclose(np.asarray(deq), np.asarray(x[0, 0]),
                                   atol=float(jnp.max(jnp.abs(x)) / 127.0)
                                   + 1e-6)
        leaves, treedef = jax.tree_util.tree_flatten(pool)
        assert len(leaves) == 4
        assert jax.tree_util.tree_unflatten(treedef, leaves).quantized
        fp = PagedKVCache.create(1, 3, 2, 4, 8)
        assert len(jax.tree_util.tree_leaves(fp)) == 2

    def test_block_bytes(self):
        assert paged_block_bytes(12, 12, 16, 64, jnp.bfloat16) == \
            2 * 12 * 12 * 64 * 2 * 16
        pool = PagedKVCache.create(12, 4, 12, 16, 64, dtype=jnp.bfloat16)
        assert pool.nbytes() == 4 * paged_block_bytes(12, 12, 16, 64,
                                                      jnp.bfloat16)


# ---------------------------------------------------------------------------
# the host-side block allocator
# ---------------------------------------------------------------------------

class TestBlockAllocator:
    def _alloc(self, num_blocks=10, block_size=4, blocks_per_slot=4,
               max_seqs=3):
        return BlockAllocator(num_blocks, block_size, blocks_per_slot,
                              max_seqs)

    def test_refcount_lifecycle_admit_share_cow_retire_free(self):
        a = self._alloc()
        prompt = list(range(8))                  # exactly 2 blocks
        plan = a.admit(0, prompt, prefill_blocks=2)
        assert plan.prefill and len(plan.block_row) == 2
        a.register_prefix(0, prompt)
        b0, b1 = int(a.tables[0, 0]), int(a.tables[0, 1])
        assert a.refcount[b0] == 1 and a.refcount[b1] == 1
        # share: full-cover hit maps both blocks, refcount++
        plan2 = a.admit(1, prompt, prefill_blocks=2)
        assert not plan2.prefill and plan2.cow_pending
        assert plan2.shared_tokens == 7 and len(plan2.suffix) == 1
        assert a.refcount[b0] == 2 and a.refcount[b1] == 2
        # COW: the cursor (7) is inside the last shared block
        step = a.prepare_step([1])
        new = int(step.cow_dst[1])
        assert int(step.cow_src[1]) == b1 and new not in (0, b1)
        assert a.cow_copies == 1
        assert a.refcount[b1] == 1 and a.refcount[new] == 1
        assert int(a.tables[1, 1]) == new
        a.advance([1])
        # retire the sharer: its private COW block frees, the shared
        # b0 drops to slot 0's reference
        a.release(1)
        assert a.refcount[b0] == 1 and a.refcount[new] == 0
        # retire the owner: registered blocks PARK in the prefix cache
        # (refcount 0, still indexed) instead of freeing outright
        a.release(0)
        assert a.refcount[b0] == 0 and a.refcount[b1] == 0
        assert a.free_blocks == 9                # everything reusable
        # the parked prefix still hits
        plan3 = a.admit(2, prompt, prefill_blocks=2)
        assert not plan3.prefill and a.refcount[b0] == 1

    def test_pool_exhaustion_rejects_and_rolls_back(self):
        a = self._alloc(num_blocks=4, blocks_per_slot=3)
        a.admit(0, list(range(8)), prefill_blocks=3)     # takes 2 of 3
        free_before = a.free_blocks
        with pytest.raises(PoolExhausted):
            a.admit(1, list(range(100, 108)), prefill_blocks=3)
        assert a.free_blocks == free_before              # rolled back
        assert not a.tables[1].any()

    def test_prefix_hash_collision_falls_back_to_full_prefill(self,
                                                              monkeypatch):
        a = self._alloc()
        monkeypatch.setattr(BlockAllocator, "_digest",
                            staticmethod(lambda parent, chunk: b"COLLIDE"))
        a.admit(0, list(range(8)), prefill_blocks=2)
        a.register_prefix(0, list(range(8)))
        # every digest collides now — the stored-chunk verification must
        # read a DIFFERENT prompt as a miss, never serve slot 0's KV
        assert a.lookup(list(range(100, 108))) == []
        plan = a.admit(1, list(range(100, 108)), prefill_blocks=2)
        assert plan.prefill
        # the identical prompt still verifies and hits (only the FIRST
        # chunk: under a total collision the second chunk's digest is
        # already taken, so it was never registered — sharing degrades,
        # correctness doesn't)
        assert len(a.lookup(list(range(8)))) == 1

    def test_lru_eviction_unregisters_oldest(self):
        a = self._alloc(num_blocks=5, blocks_per_slot=3, max_seqs=4)
        a.admit(0, list(range(4)), prefill_blocks=1)
        a.register_prefix(0, list(range(4)))
        a.release(0)                              # 1 cached block
        a.admit(0, list(range(10, 14)), prefill_blocks=1)
        a.register_prefix(0, list(range(10, 14)))
        a.release(0)                              # 2 cached blocks
        assert len(a.lookup(list(range(4)))) == 1
        # demand 3 fresh blocks: free list has 2, so the OLDEST cached
        # block (prompt 0..3) is evicted and unregistered
        a.admit(1, list(range(20, 32)), prefill_blocks=3)
        assert a.lookup(list(range(4))) == []
        assert len(a.lookup(list(range(10, 14)))) == 1

    def test_append_targets_mask_inactive_and_saturated(self):
        a = self._alloc(num_blocks=10, block_size=2, blocks_per_slot=2,
                        max_seqs=3)
        a.admit(0, [1, 2], prefill_blocks=1)
        a.admit(1, [3, 4, 5], prefill_blocks=2)
        a.lengths[1] = 4                          # saturated
        bid, off = a.append_targets(np.asarray([True, True, True]))
        assert bid[0] == a.tables[0, 1] or bid[0] == a.tables[0, 0]
        assert bid[1] == 0                        # saturated -> null
        assert bid[2] == 0                        # inactive slot -> null


# ---------------------------------------------------------------------------
# PagedServingEngine contracts
# ---------------------------------------------------------------------------

def _tiny_model():
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype=jnp.float32)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _paged_engine(model, params, **kw):
    kw.setdefault("max_seqs", 2)
    kw.setdefault("max_len", 24)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("block_size", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    return PagedServingEngine(model, params, **kw)


class TestPagedEngine:
    @pytest.mark.parametrize("cache_dtype,tol", [
        (jnp.float32, 2e-4), (jnp.bfloat16, 0.1), (jnp.int8, 0.25)])
    def test_prefill_decode_parity_vs_one_shot(self, cache_dtype, tol):
        model, params = _tiny_model()
        eng = _paged_engine(model, params, cache_dtype=cache_dtype)
        rng = np.random.RandomState(0)
        prompt = [int(t) for t in rng.randint(1, 97, 7)]
        tok = eng.prefill(prompt, 0)
        toks = np.zeros(2, np.int32)
        temps = np.zeros(2, np.float32)
        active = np.asarray([True, False])
        seq = list(prompt) + [tok]
        for _ in range(4):
            toks[0] = seq[-1]
            out = eng.decode(toks, temps, active=active)
            one_shot = model(params, jnp.asarray(seq, jnp.int32)[None])
            # greedy parity: the engine's sampled token must equal the
            # one-shot argmax whenever the cache noise doesn't flip a
            # near-tie — assert on logit closeness via the argmax
            seq.append(int(out[0]))
        ref = model(params, jnp.asarray(seq[:-1], jnp.int32)[None])
        assert int(jnp.argmax(ref[0, -1])) == seq[-1]

    def test_prefix_shared_stream_identical_to_unshared(self):
        model, params = _tiny_model()
        eng = _paged_engine(model, params)
        prompt = [5, 9, 1, 33, 7, 21, 2, 40]
        t0 = eng.prefill(prompt, 0)
        assert eng.last_admit.prefill
        cold = [t0]
        toks = np.zeros(2, np.int32)
        temps = np.zeros(2, np.float32)
        for _ in range(5):
            toks[0] = cold[-1]
            out = eng.decode(toks, temps,
                             active=np.asarray([True, False]))
            cold.append(int(out[0]))
        # the same prompt admits into slot 1 as a prefix HIT and must
        # produce the identical greedy stream
        t1 = eng.prefill(prompt, 1)
        plan = eng.last_admit
        assert not plan.prefill and plan.shared_tokens == len(prompt) - 1
        assert eng.allocator.prefix_hits == 1
        shared = [t1]
        for _ in range(5):
            toks[1] = shared[-1]
            out = eng.decode(toks, temps,
                             active=np.asarray([False, True]))
            shared.append(int(out[1]))
        assert shared == cold

    def test_zero_recompile_across_admit_cow_retire(self):
        from apex_tpu.analysis.program import recompile_guard
        model, params = _tiny_model()
        eng = _paged_engine(model, params)
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        reg = MetricsRegistry()
        sched = SlotScheduler(eng, registry=reg)
        with recompile_guard("paged admit/COW/retire") as guard:
            # warmup: first dispatch of the three programs is legit
            sched.run([Request(prompt=prompt, max_new_tokens=2)])
            guard.rebase()
            # steady state: cold admissions, prefix hits (COW), decode
            # grid steps, retirements — all on the same three programs
            reqs = [Request(prompt=prompt, max_new_tokens=3),
                    Request(prompt=prompt, max_new_tokens=3),
                    Request(prompt=[7, 7, 7], max_new_tokens=2)]
            sched.run(reqs)
        assert eng.allocator.prefix_hits >= 1
        assert eng.allocator.cow_copies >= 1
        snap = dict(reg.snapshot())
        assert snap.get("serve/prefix_hits", 0) >= 1
        assert snap.get("serve/blocks_cow_copied", 0) >= 1
        assert snap.get("serve/pool_blocks_free", 0) > 0
        assert snap.get("serve/ttft_prefix_ms_count", 0) >= 1

    def test_donation_lint_passes_and_swap_params(self):
        # construction runs lint_serving_engine (donation + aliasing on
        # all three programs); swap re-runs it
        model, params = _tiny_model()
        eng = _paged_engine(model, params)
        eng.swap_params(jax.tree_util.tree_map(lambda x: x * 1.01, params))
        assert eng.swaps == 1

    def test_pool_exhausted_submit_rejection_and_queueing(self):
        model, params = _tiny_model()
        # pool of 3 allocatable blocks; the prefill window admits up to
        # 16 tokens (4 blocks) so the pool is the binding constraint
        eng = _paged_engine(model, params, num_blocks=4, max_len=16,
                            prefill_len=16)
        sched = SlotScheduler(eng, registry=MetricsRegistry())
        # a prompt that could NEVER fit the pool: typed rejection
        r = sched.submit(Request(prompt=list(range(1, 17)),
                                 max_new_tokens=1))
        assert isinstance(r, Rejection) and r.reason == "pool_exhausted"
        # transient pressure queues instead: two 8-token prompts want
        # 2 blocks each + a decode block, pool has 3
        a = sched.submit(Request(prompt=[1, 2, 3, 4, 5, 6, 7, 8],
                                 max_new_tokens=2))
        b = sched.submit(Request(prompt=[11, 12, 13, 14, 15, 16, 17, 18],
                                 max_new_tokens=2))
        assert not isinstance(a, Rejection) and not isinstance(b, Rejection)
        for _ in range(30):
            if not sched.pending:
                break
            sched.step()
        assert {c.request_id for c in sched.completed} == {a, b}
        assert all(len(c.tokens) >= 1 for c in sched.completed)

    def test_pool_exhaustion_mid_decode_retires_capacity(self):
        model, params = _tiny_model()
        # 2 allocatable blocks of 4: one 4-token prompt takes 1 block,
        # decode grows into the 2nd, then the pool is dry
        eng = _paged_engine(model, params, num_blocks=3, max_len=16,
                            prefill_len=4, max_seqs=1)
        sched = SlotScheduler(eng, registry=MetricsRegistry())
        rid = sched.submit(Request(prompt=[1, 2, 3, 4],
                                   max_new_tokens=12))
        for _ in range(20):
            if not sched.pending:
                break
            sched.step()
        (comp,) = sched.completed
        assert comp.request_id == rid
        # ran out of pool before max_new_tokens: loud capacity retire,
        # not silent corruption
        assert comp.finish_reason == "capacity"
        assert 1 <= len(comp.tokens) < 12

    def test_suggest_pool_blocks_capacity_math(self):
        model, params = _tiny_model()
        eng = _paged_engine(model, params)
        hbm = 16 * 2 ** 30
        blocks = eng.suggest_pool_blocks(hbm, mean_len=128)
        assert blocks > 0
        # monotonic in HBM, and the per-block unit is honest
        assert eng.suggest_pool_blocks(2 * hbm, mean_len=128) >= blocks
        assert eng.block_bytes() == paged_block_bytes(
            model.cfg.num_layers, model.cfg.num_attention_heads,
            eng.block_size, model.cfg.head_dim, jnp.float32)
        # mean-length math: more blocks -> more concurrent sequences
        assert eng.suggest_max_seqs_for_pool(129, mean_len=128.0) == 4
        assert eng.suggest_max_seqs_for_pool(129, mean_len=256.0) == 2


# ---------------------------------------------------------------------------
# the pyprof cost model prices paged decode O(actual context)
# ---------------------------------------------------------------------------

class TestPagedCostModel:
    def test_paged_decode_prices_mean_context_not_max_len(self):
        from apex_tpu.pyprof.model import model_program
        model, params = _tiny_model()
        MAX_LEN, MEAN = 64, 8
        dense = ServingEngine(model, params, max_seqs=2, max_len=MAX_LEN,
                              prefill_len=8, cache_dtype=jnp.float32)
        paged = _paged_engine(model, params, max_len=MAX_LEN,
                              num_blocks=40, mean_context=MEAN)
        da = model_program(dense.decode_traced).regions["decode_attention"]
        pa = model_program(paged.decode_traced).regions["decode_attention"]
        ratio = pa.hbm_bytes / da.hbm_bytes
        # the paged program's modeled HBM is ~mean/max of the dense
        # leg's — the O(max_len) gap, closed
        assert ratio <= (MEAN / MAX_LEN) * 1.5, ratio
        # and it scales WITH the context, not the pool span
        paged2 = _paged_engine(model, params, max_len=MAX_LEN,
                               num_blocks=40, mean_context=4 * MEAN)
        pa2 = model_program(paged2.decode_traced).regions[
            "decode_attention"]
        assert pa2.hbm_bytes > 2 * pa.hbm_bytes
