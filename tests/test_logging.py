"""Rank-aware logging tests (``apex_tpu/utils/logging.py`` —
``reference:apex/transformer/log_util.py:5-20`` and amp's ``maybe_print``
rank gating, ``reference:apex/amp/_amp_state.py:39-51``)."""

import io
import logging

import pytest

from apex_tpu.utils import logging as apex_logging


@pytest.fixture()
def fresh_logger(monkeypatch):
    """An isolated apex_tpu logger: reset the module's configured flag and
    strip handlers so each test installs its own stream."""
    logger = logging.getLogger(apex_logging._ROOT_NAME)
    old_handlers = list(logger.handlers)
    old_level = logger.level
    for h in old_handlers:
        logger.removeHandler(h)
    monkeypatch.setattr(apex_logging, "_configured", False)
    yield logger
    for h in list(logger.handlers):
        logger.removeHandler(h)
    for h in old_handlers:
        logger.addHandler(h)
    logger.setLevel(old_level)


def test_rank_info_formatter_prefixes_records(fresh_logger):
    stream = io.StringIO()
    apex_logging.setup_logging(stream=stream)
    apex_logging.get_logger("unit").info("hello")
    out = stream.getvalue()
    assert "hello" in out
    assert "apex_tpu.unit" in out
    # single-process test rig: the fallback (proc N) prefix
    assert "(proc 0)" in out


def test_rank_info_formatter_standalone():
    fmt = apex_logging.RankInfoFormatter("%(rank_info)s %(message)s")
    rec = logging.LogRecord("apex_tpu", logging.INFO, __file__, 1,
                            "msg", (), None)
    line = fmt.format(rec)
    assert line.endswith("msg")
    assert line.startswith("(")  # either (proc N) or the rank tuple


def test_setup_logging_idempotent_and_level_preserving(fresh_logger):
    stream = io.StringIO()
    logger = apex_logging.setup_logging(stream=stream,
                                        level=logging.WARNING)
    n_handlers = len(logger.handlers)
    # implicit re-setup (what get_logger does) must not stack handlers or
    # reset the chosen level
    apex_logging.setup_logging()
    assert len(logger.handlers) == n_handlers
    assert logger.level == logging.WARNING


def test_set_verbosity(fresh_logger):
    stream = io.StringIO()
    apex_logging.setup_logging(stream=stream)
    log = apex_logging.get_logger("v")
    apex_logging.set_verbosity(logging.ERROR)
    log.info("quiet")
    assert "quiet" not in stream.getvalue()
    apex_logging.set_verbosity(logging.DEBUG)
    log.debug("loud")
    assert "loud" in stream.getvalue()


def test_rank_zero_only_runs_on_rank0(monkeypatch):
    calls = []

    @apex_logging.rank_zero_only
    def fn(x):
        calls.append(x)
        return x * 2

    monkeypatch.setattr(apex_logging, "_process_index", lambda: 0)
    assert fn(3) == 6
    monkeypatch.setattr(apex_logging, "_process_index", lambda: 1)
    assert fn(4) is None
    assert calls == [3]


def test_process_index_env_fallback(monkeypatch):
    """Without a working jax import path the env var decides the rank."""
    import builtins
    real_import = builtins.__import__

    def no_jax(name, *a, **k):
        if name == "jax":
            raise ImportError("jax disabled for test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_jax)
    monkeypatch.setenv("JAX_PROCESS_INDEX", "3")
    assert apex_logging._process_index() == 3
