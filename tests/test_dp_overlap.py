"""Bucketed DP gradient sync + ZeRO-through-the-trainer tests.

Covers the overlap PR's contracts on the 8-virtual-CPU-device mesh:

- the bucket grid (``optimizers/_flatten.bucket_bounds``) is exact:
  covering, ordered, shard-divisible;
- the bucketed allreduce (``parallel/distributed.py``) matches the
  per-leaf path numerically and compiles to exactly B psums;
- ``accumulate_gradients`` windows fire B bucket psums (vs one per leaf),
  and its new guards (empty window, unbound axis) raise loudly;
- trainer-level ZeRO parity: ``zero=1`` reproduces the replicated
  ``FusedAdam`` trainer bit-for-bit-to-tolerance, with the jaxpr holding
  exactly B data-axis reduce-scatters and B gathers, and no full-tree
  psum of the flat gradient;
- ``zero=off`` + bucketing-off is provably the pre-bucketing program
  (no reduce_scatter / bucket machinery in the jaxpr; old-style config
  dicts round-trip);
- ``jit_train_step`` donation aliases the state buffers and leaves
  numerics unchanged;
- the new ``ddp/*`` / ``zero/*`` metrics surface through
  ``train_step_with_metrics``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from _jaxpr_utils import (collective_census, count_eqns, eqn_axes,
                          jaxpr_str)
from apex_tpu.optimizers._flatten import bucket_bounds, build_layout
from apex_tpu.parallel import DistributedDataParallel, allreduce_grads
from apex_tpu.utils.compat import shard_map


def _mesh(n=None):
    devs = jax.devices() if n is None else jax.devices()[:n]
    return Mesh(np.array(devs), ("data",))


def _grad_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(8, 100, 7), jnp.float32),
            "b": jnp.asarray(rng.randn(8, 13), jnp.float32),
            "emb": jnp.asarray(rng.randn(8, 5, 16), jnp.float32)}


# ---------------------------------------------------------------------------
# bucket grid
# ---------------------------------------------------------------------------

def test_bucket_bounds_cover_and_divide():
    lay = build_layout({"a": jnp.zeros(1000), "b": jnp.zeros(23)}, chunks=4)
    for bb in (4, 256, 1024, 10 ** 9):
        bounds = bucket_bounds(lay, bb)
        # covering, ordered, disjoint
        off = 0
        for o, n in bounds:
            assert o == off and n > 0
            assert n % 4 == 0  # every bucket reduce-scatters over 4 ranks
            off += n
        assert off == lay.padded
    # None = monolithic single span
    assert bucket_bounds(lay, None) == ((0, lay.padded),)
    with pytest.raises(ValueError, match="positive"):
        bucket_bounds(lay, 0)


def test_ravel_span_unravel_parts_roundtrip():
    """Span-local ravel/unravel (the backward-interleave building blocks)
    are element-identical to the monolithic ravel/unravel over any
    bucket grid — including scalar leaves, dtype casts, and the padding
    tail."""
    from apex_tpu.optimizers._flatten import (bucket_bounds, build_layout,
                                              ravel, ravel_span, unravel,
                                              unravel_parts)

    rng = np.random.RandomState(3)
    tree = {"w": jnp.asarray(rng.randn(7, 5), jnp.float32),
            "s": jnp.asarray(1.5, jnp.float32),
            "z": jnp.zeros((0,), jnp.float32),   # zero-size leaf
            "h": jnp.asarray(rng.randn(9), jnp.bfloat16)}
    lay = build_layout(tree, chunks=4)
    assert lay.padded > lay.total  # the padding tail is exercised
    flat = np.asarray(ravel(tree, lay))
    for bb in (16, 40, 1 << 20, None):
        bounds = bucket_bounds(lay, bb)
        parts = [ravel_span(tree, lay, o, n) for o, n in bounds]
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(p) for p in parts]), flat)
        ref = unravel(jnp.asarray(flat), lay)
        got = unravel_parts(parts, bounds, lay)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(got)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
    with pytest.raises(ValueError, match="outside"):
        ravel_span(tree, lay, lay.padded - 2, 4)
    with pytest.raises(ValueError, match="parts"):
        unravel_parts([flat[:4]], ((0, 4), (4, lay.padded - 4)), lay)
    with pytest.raises(ValueError, match="cover"):
        unravel_parts([jnp.asarray(flat[:4])], ((0, 4),), lay)
    with pytest.raises(ValueError, match="tile"):
        unravel_parts([jnp.asarray(flat[:4]), jnp.asarray(flat[8:])],
                      ((0, 4), (8, lay.padded - 8)), lay)


def test_build_layout_is_memoized_with_identical_jaxpr():
    """Satellite: the FlatLayout is cached across steps/calls (the
    per-call rebuild was measurable host overhead at 512 leaves), and
    the cached path traces a byte-identical program."""
    from apex_tpu.optimizers import FlatOptimizer, FusedAdam
    from apex_tpu.optimizers._flatten import (build_layout,
                                              clear_layout_cache,
                                              layout_cache_stats,
                                              segment_ids)

    clear_layout_cache()
    tree = {f"p{i}": jnp.ones((4, 3), jnp.float32) for i in range(5)}
    l1 = build_layout(tree, chunks=2)
    l2 = build_layout(tree, chunks=2)
    assert l1 is l2  # the hit returns the identical object
    stats = layout_cache_stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert build_layout(tree, chunks=4) is not l1  # chunks key in the id
    np.testing.assert_array_equal(np.asarray(segment_ids(l1)),
                                  np.asarray(segment_ids(l1)))

    def step_txt():
        opt = FlatOptimizer(FusedAdam(lr=1e-3))
        state = opt.init(tree)
        grads = jax.tree_util.tree_map(jnp.ones_like, tree)
        return jaxpr_str(lambda g, s, p: opt._step(g, s, p),
                         grads, state, tree)

    clear_layout_cache()
    cold = step_txt()             # builds the layout
    warm = step_txt()             # second optimizer, cache warm
    assert layout_cache_stats()["hits"] >= 1
    assert cold == warm           # cached path is program-identical
    clear_layout_cache()


# ---------------------------------------------------------------------------
# bucketed allreduce
# ---------------------------------------------------------------------------

def _run_allreduce(grads, mesh, **kw):
    def inner(w, b, emb):
        return allreduce_grads({"w": w, "b": b, "emb": emb}, "data", **kw)
    return shard_map(inner, mesh=mesh,
                     in_specs=(P("data"), P("data"), P("data")),
                     out_specs=P("data"))


def test_bucketed_allreduce_matches_per_leaf():
    mesh = _mesh()
    g = _grad_tree()
    args = (g["w"], g["b"], g["emb"])
    ref = jax.jit(_run_allreduce(g, mesh))(*args)
    for bb in (512, 4096, 1 << 20):
        out = jax.jit(_run_allreduce(g, mesh, bucket_bytes=bb))(*args)
        for k in ref:
            np.testing.assert_allclose(np.asarray(out[k]),
                                       np.asarray(ref[k]),
                                       rtol=1e-6, atol=1e-6)


def test_bucketed_allreduce_predivide_numerics():
    mesh = _mesh()
    g = _grad_tree(1)
    args = (g["w"], g["b"], g["emb"])
    plain = jax.jit(_run_allreduce(g, mesh, bucket_bytes=512))(*args)
    pre = jax.jit(_run_allreduce(g, mesh, bucket_bytes=512,
                                 gradient_predivide_factor=8.0))(*args)
    for k in plain:
        np.testing.assert_allclose(np.asarray(pre[k]),
                                   np.asarray(plain[k]),
                                   rtol=1e-5, atol=1e-6)


def test_bucketed_allreduce_jaxpr_holds_b_psums():
    """The bucketing is real: exactly B psums, no fused all-reduce of the
    whole tree, one per bucket of the flat layout."""
    mesh = _mesh()
    g = _grad_tree()
    lay = build_layout(
        {k: v[0] for k, v in g.items()}, chunks=1)
    args = (g["w"], g["b"], g["emb"])
    from _jaxpr_utils import flat_materializations
    for bb in (512, 1600):
        B = len(bucket_bounds(lay, bb))
        assert B > 1
        # one trace serves both assertions
        jaxpr = jax.make_jaxpr(_run_allreduce(g, mesh, bucket_bytes=bb))(
            *args)
        assert str(jaxpr).count("psum") == B, (bb, B)
        # span-local assembly: the full padded flat vector never
        # materializes — each bucket ravels from its own leaves only
        assert not flat_materializations(jaxpr.jaxpr, lay.padded)
    # a bucket larger than the whole tree degenerates to ONE flat psum
    txt = jaxpr_str(_run_allreduce(g, mesh, bucket_bytes=1 << 20), *args)
    assert txt.count("psum") == 1
    # and the per-leaf path: one psum per leaf
    txt = jaxpr_str(_run_allreduce(g, mesh), *args)
    assert txt.count("psum") == 3


def test_bucketed_allreduce_rejects_groups():
    from apex_tpu.parallel import Reducer

    with pytest.raises(ValueError, match="mutually exclusive"):
        allreduce_grads({"w": jnp.zeros(4)}, "data",
                        axis_index_groups=[[0, 1]], bucket_bytes=512)
    with pytest.raises(ValueError, match="mutually exclusive"):
        Reducer("data", axis_index_groups=[[0, 1]], bucket_bytes=512)


def test_bucketed_reducer_matches_pmean():
    from apex_tpu.parallel import Reducer

    mesh = _mesh()
    tree = {"a": jnp.arange(8 * 40, dtype=jnp.float32).reshape(8, 40),
            "b": jnp.ones((8, 3), jnp.float32)}

    def run(red):
        return jax.jit(shard_map(
            lambda a, b: red.reduce({"a": a, "b": b}),
            mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data")))(tree["a"], tree["b"])

    ref = run(Reducer("data"))
    out = run(Reducer("data", bucket_bytes=64))
    for k in ref:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# DDP + accumulation window
# ---------------------------------------------------------------------------

def test_accumulate_gradients_bucketed_window():
    """A bucketed DDP fires B bucket psums once per window (not per
    microbatch) and reproduces the per-leaf window grads."""
    from apex_tpu.training import accumulate_gradients

    mesh = _mesh()
    rng = np.random.RandomState(6)
    K = 3
    params = {"w1": jnp.asarray(rng.randn(4, 33), jnp.float32),
              "w2": jnp.asarray(rng.randn(33, 2), jnp.float32)}
    xs = jnp.asarray(rng.randn(K, 16, 4), jnp.float32)
    ys = jnp.asarray(rng.randn(K, 16, 2), jnp.float32)

    def loss_fn(p, mb):
        x, y = mb
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)

    def run(ddp):
        def inner(p, xs, ys):
            _, grads = accumulate_gradients(ddp, loss_fn, p, (xs, ys))
            return grads
        def wrapped(p, xs, ys):
            return shard_map(
                inner, mesh=mesh,
                in_specs=(P(), P(None, "data"), P(None, "data")),
                out_specs=P())(p, xs, ys)
        return wrapped

    bb = 256
    lay = build_layout(params, chunks=1)
    B = len(bucket_bounds(lay, bb))
    assert B > 1
    mono = run(DistributedDataParallel("data", delay_allreduce=True))
    buck = run(DistributedDataParallel("data", delay_allreduce=True,
                                       bucket_bytes=bb))
    assert jaxpr_str(mono, params, xs, ys).count("psum") == 2  # per leaf
    assert jaxpr_str(buck, params, xs, ys).count("psum") == B
    g_m = jax.jit(mono)(params, xs, ys)
    g_b = jax.jit(buck)(params, xs, ys)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_b[k]), np.asarray(g_m[k]),
                                   rtol=1e-6, atol=1e-6)


def test_accumulate_gradients_empty_window_raises():
    from apex_tpu.training import accumulate_gradients

    ddp = DistributedDataParallel("data", delay_allreduce=True)
    with pytest.raises(ValueError, match="num_micro == 0"):
        accumulate_gradients(ddp, lambda p, mb: jnp.sum(p),
                             jnp.zeros((2, 2)), jnp.zeros((0, 4)))


def test_accumulate_gradients_unbound_axis_raises():
    from apex_tpu.training import accumulate_gradients

    ddp = DistributedDataParallel("nonexistent_axis", delay_allreduce=True)
    with pytest.raises(ValueError, match="is not bound"):
        accumulate_gradients(ddp, lambda p, mb: jnp.sum(p),
                             jnp.zeros((2, 2)), jnp.zeros((3, 4)))


# ---------------------------------------------------------------------------
# trainer-level ZeRO parity + program shape (satellite + acceptance)
# ---------------------------------------------------------------------------

DP = 4


def _trainer_cfg(zero=False, bucket_bytes=None):
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    M, mb, seq = 2, 2, 8
    return TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2, num_attention_heads=4,
                          max_position_embeddings=seq),
        parallel=ParallelConfig(tensor_model_parallel_size=1,
                                pipeline_model_parallel_size=1),
        batch=BatchConfig(global_batch_size=M * mb * DP,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0,
                                  zero=zero),
        opt_level="O0", ddp_bucket_bytes=bucket_bytes)


def _trainer_data(seed=0):
    rng = np.random.RandomState(seed)
    M, mb, seq = 2, 2, 8
    return (jnp.asarray(rng.randint(0, 64, (M, DP * mb, seq))),
            jnp.asarray(rng.randint(0, 64, (M, DP * mb, seq))))


def _run_trainer(cfg, steps=3):
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    tokens, targets = _trainer_data()
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        step = jax.jit(tr.train_step)
        losses = []
        for _ in range(steps):
            loss, *state = step(*state, tokens, targets)
            losses.append(float(loss))
        return tr, losses, state
    finally:
        parallel_state.destroy_model_parallel()


def test_trainer_zero_parity_with_replicated_adam():
    """zero=1 on the dp=4 mesh: loss trajectory and post-3-step params
    match the replicated FusedAdam trainer. The ZeRO update math is the
    same fp32 elementwise program over a flat view; the only reassociation
    is reduce_scatter's ring order vs psum's, so tolerance is a few ULPs
    (documented; bit-identity holds on this mesh in practice for the loss,
    asserted exactly)."""
    _, l_ref, s_ref = _run_trainer(_trainer_cfg(zero=False))
    _, l_z, s_z = _run_trainer(_trainer_cfg(zero=1, bucket_bytes=1024))
    assert l_ref == l_z, (l_ref, l_z)
    for pa, pb in zip(jax.tree_util.tree_leaves((s_ref[0], s_ref[1])),
                      jax.tree_util.tree_leaves((s_z[0], s_z[1]))):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=3e-6, atol=3e-6)


def test_trainer_zero_jaxpr_per_bucket_collectives():
    """The bucketed ZeRO step holds exactly B data-axis reduce-scatters and
    B gathers — and no full-tree psum of the flat gradient (the monolithic
    pattern this PR removes)."""
    from apex_tpu.optimizers._flatten import bucket_bounds as bbounds
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    bb = 1024
    cfg = _trainer_cfg(zero=1, bucket_bytes=bb)
    tokens, targets = _trainer_data()
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        lay = tr.opt._layout
        assert lay is not None  # init traced the layout
        B = len(bbounds(lay, bb))
        assert B > 1

        def data_axis(eqn):
            return "data" in eqn_axes(eqn)

        jaxpr = jax.make_jaxpr(tr.train_step)(*state, tokens, targets)
        n_rs = count_eqns(jaxpr, "reduce_scatter", where=data_axis)
        assert n_rs == B, (n_rs, B)
        # gather leg: B invariant gathers where this jax has them, else the
        # documented psum fallback (utils/vma.invariant_all_gather) — B
        # bucket-sized psums either way, never one padded-size reduction
        n_ag = count_eqns(
            jaxpr, "all_gather", where=data_axis) + count_eqns(
            jaxpr, "all_gather_invariant", where=data_axis)
        sizes = {n for _, n in bbounds(lay, bb)}

        def is_flat_psum(eqn):
            return data_axis(eqn) and any(
                v.aval.size == lay.padded and v.aval.ndim == 1
                for v in eqn.invars)

        n_fallback = count_eqns(
            jaxpr, "psum", where=lambda e: data_axis(e) and any(
                v.aval.ndim == 1 and v.aval.size in sizes
                for v in e.invars))
        assert n_ag == B or n_fallback >= B, (n_ag, n_fallback, B)
        # no monolithic full-tree psum of the flat gradient
        assert count_eqns(jaxpr, "psum", where=is_flat_psum) == 0
    finally:
        parallel_state.destroy_model_parallel()


def test_trainer_zero_off_unbucketed_is_pre_pr_program():
    """zero=off + bucketing off: the step jaxpr carries no reduce_scatter
    and no bucket machinery, and is identical to a trainer built from an
    old-style config dict that predates the new fields — the same
    provably-unchanged contract as health level="off"."""
    from apex_tpu.config import TrainConfig
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg = _trainer_cfg(zero=False, bucket_bytes=None)
    d = cfg.to_dict()
    # a config dict from before this PR: no ddp_bucket_bytes, bool zero
    del d["ddp_bucket_bytes"]
    assert d["optimizer"]["zero"] is False
    old_cfg = TrainConfig.from_dict(d)
    tokens, targets = _trainer_data()
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        tr_old = GPTHybridTrainer(old_cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        args = state + (tokens, targets)
        txt = jaxpr_str(tr.train_step, *args)
        assert collective_census(txt)["reduce_scatter"] == 0
        assert jaxpr_str(tr_old.train_step, *args) == txt
    finally:
        parallel_state.destroy_model_parallel()


def test_config_zero_spellings():
    from apex_tpu.config import OptimizerConfig, TrainConfig
    from apex_tpu.optimizers import DistributedFusedAdam, FusedAdam

    def build(z):
        return TrainConfig(
            optimizer=OptimizerConfig(name="adam", zero=z)).build_optimizer()

    for z in (False, 0, "off"):
        assert isinstance(build(z), FusedAdam)
    for z in (True, 1, "1"):
        assert isinstance(build(z), DistributedFusedAdam)
    with pytest.raises(ValueError, match="zero"):
        build("2")
    # bucket size threads from the train config into the ZeRO optimizer
    opt = TrainConfig(
        optimizer=OptimizerConfig(name="adam", zero=1),
        ddp_bucket_bytes=4096).build_optimizer()
    assert opt.bucket_bytes == 4096


def test_trainer_zero_rejects_mismatched_restored_state():
    """The restored-checkpoint boundary: a ZeRO state trained under one
    ddp_bucket_bytes entering jit_train_step of a trainer configured with
    another fails loudly before dispatch (the bucket-major shard order
    would otherwise be silently permuted)."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    tokens, targets = _trainer_data()
    cfg_a = _trainer_cfg(zero=1, bucket_bytes=None)
    mesh = cfg_a.initialize_mesh(devices=jax.devices()[:DP])
    try:
        state = GPTHybridTrainer(cfg_a, mesh).init_state(
            jax.random.PRNGKey(0))
    finally:
        parallel_state.destroy_model_parallel()
    cfg_b = _trainer_cfg(zero=1, bucket_bytes=2048)
    mesh = cfg_b.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr_b = GPTHybridTrainer(cfg_b, mesh)
        with pytest.raises(ValueError, match="bucket_bytes"):
            tr_b.jit_train_step()(*state, tokens, targets)
    finally:
        parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# donated state buffers (perf satellite)
# ---------------------------------------------------------------------------

def test_jit_train_step_donates_state():
    """jit_train_step aliases stage_stack/shared/opt_state into their
    outputs (input_output_alias in the compiled module) so the live-buffer
    high-water drops by a state generation; numerics are unchanged."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg = _trainer_cfg(zero=False)
    tokens, targets = _trainer_data()
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        args = state + (tokens, targets)
        plain = jax.jit(tr.train_step).lower(*args).compile()
        donated = tr.jit_train_step().lower(*args).compile()
        assert "input_output_alias" not in plain.as_text()
        assert "input_output_alias" in donated.as_text()
        # the aliasing must cover the whole donated state, not one buffer:
        # every stage/shared/opt_state leaf has an alias entry
        n_state_leaves = len(jax.tree_util.tree_leaves(state[:3]))
        n_aliases = donated.as_text().count("may-alias")
        assert n_aliases >= n_state_leaves, (n_aliases, n_state_leaves)
        # live-buffer math: peak-ish footprint is args + outputs + temps
        # minus bytes the runtime reuses via aliasing — donation must
        # cover (almost) the whole donated state and shrink the total
        ma_p, ma_d = plain.memory_analysis(), donated.memory_analysis()
        if ma_p is not None and ma_d is not None:
            state_bytes = sum(
                l.size * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(state[:3]))
            assert ma_p.alias_size_in_bytes == 0
            assert ma_d.alias_size_in_bytes >= 0.9 * state_bytes

            def live(ma):
                return (ma.argument_size_in_bytes + ma.output_size_in_bytes
                        + ma.temp_size_in_bytes - ma.alias_size_in_bytes)

            assert live(ma_d) <= live(ma_p)
        loss_p, *out_p = plain(*args)
        # donated call consumes its args: pass fresh copies
        fresh = jax.tree_util.tree_map(jnp.copy, state)
        loss_d, *out_d = donated(*fresh, tokens, targets)
        assert float(loss_p) == float(loss_d)
        for a, b in zip(jax.tree_util.tree_leaves(out_p),
                        jax.tree_util.tree_leaves(out_d)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------

def test_bucketing_metrics_surface():
    from apex_tpu.optimizers._flatten import bucket_bounds as bbounds
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    bb = 1024
    tokens, targets = _trainer_data()

    # ZeRO leg: reduce-scatter + shard metrics
    cfg = _trainer_cfg(zero=1, bucket_bytes=bb)
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        *_, metrics = jax.jit(tr.train_step_with_metrics)(
            *state, tokens, targets)
        got = metrics.as_floats()
        lay = tr.opt._layout
        B = len(bbounds(lay, bb))
        assert got["ddp/num_buckets"] == float(B)
        assert got["ddp/reduce_scatter_bytes"] > 0
        assert got["zero/shard_bytes"] == float(4 * lay.chunk)
        assert got["ddp/bucket_bytes"] == float(
            4 * max(n for _, n in bbounds(lay, bb)))
    finally:
        parallel_state.destroy_model_parallel()

    # replicated leg: bucketed allreduce metrics
    cfg = _trainer_cfg(zero=False, bucket_bytes=bb)
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        *_, metrics = jax.jit(tr.train_step_with_metrics)(
            *state, tokens, targets)
        got = metrics.as_floats()
        assert got["ddp/num_buckets"] >= 2
        assert got["ddp/allreduce_bytes"] > 0
    finally:
        parallel_state.destroy_model_parallel()


def test_jit_train_step_verify_donation_self_check():
    """jit_train_step(verify_donation=True): the first dispatch runs the
    analysis engine's jaxpr-donation rule on the compiled step (every
    donated leaf aliased, no double-donated buffer) and then dispatches
    through the verified executable (PR 11)."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg = _trainer_cfg(zero=True)
    tokens, targets = _trainer_data()
    mesh = cfg.initialize_mesh(devices=jax.devices()[:DP])
    try:
        tr = GPTHybridTrainer(cfg, mesh)
        state = tr.init_state(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="donate=True"):
            tr.jit_train_step(donate=False, verify_donation=True)
        step = tr.jit_train_step(verify_donation=True)
        loss1, *state = step(*state, tokens, targets)
        loss2, *_ = step(*state, tokens, targets)  # verified executable
        assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    finally:
        parallel_state.destroy_model_parallel()
