"""ASP 2:4 sparsity tests (``reference:apex/contrib/sparsity/test/``:
``toy_problem.py`` + ``checkpointing_test_part1/2.py`` roles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity import (ASP, apply_masks,
                                       compute_sparse_masks, mn_1d_mask,
                                       sparse_parameter_paths)
from apex_tpu.optimizers import FusedAdam


def test_mn_1d_mask_keeps_top2_of_4():
    w = jnp.asarray([[0.1, -0.9, 0.5, 0.01, 4.0, 1.0, -2.0, 3.0]])
    mask = np.asarray(mn_1d_mask(w))
    assert mask.tolist() == [[False, True, True, False,
                              True, False, False, True]]
    # exactly n per group, always
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(7, 32))
    m = np.asarray(mn_1d_mask(w)).reshape(7, 8, 4)
    assert np.all(m.sum(-1) == 2)


def test_whitelist_skips_bias_norm_and_small():
    params = {
        "dense": {"weight": jnp.ones((16, 32)), "bias": jnp.ones(32)},
        "ln": {"weight": jnp.ones((4, 32))},
        "tiny": jnp.ones((4, 8)),
    }
    paths = sparse_parameter_paths(params)
    assert any("dense" in p and "weight" in p for p in paths)
    assert not any("bias" in p or "ln" in p or "tiny" in p for p in paths)

    masks = compute_sparse_masks(params)
    assert np.asarray(masks["dense"]["bias"]).all()
    pruned = apply_masks(params, masks)
    dw = np.asarray(pruned["dense"]["weight"]).reshape(16, 8, 4)
    assert np.all((dw != 0).sum(-1) == 2)


def test_masked_optimizer_keeps_sparsity_and_converges():
    """Toy problem (``toy_problem.py`` role): prune, finetune with the
    mask-reapplying step, and check sparsity is invariant while loss
    drops."""
    rng = np.random.RandomState(1)
    params = {"w": jnp.asarray(rng.randn(32, 32) * 0.5)}
    x = jnp.asarray(rng.randn(64, 32))
    y = jnp.asarray(rng.randn(64, 32))

    asp = ASP()
    masks = asp.compute_sparse_masks(params)
    params = asp.prune(params, masks)
    opt = asp.init_optimizer_for_pruning(FusedAdam(lr=1e-2), masks)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((x @ p["w"] - y) ** 2)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.step(g, s, p)
        return p, s, loss

    losses = []
    for _ in range(60):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    w = np.asarray(params["w"]).reshape(32, 8, 4)
    assert np.all((w != 0).sum(-1) <= 2)  # 2:4 pattern held every step
    assert losses[-1] < losses[0] * 0.7


def test_masks_survive_checkpoint(tmp_path):
    """``checkpointing_test_part1/2.py``: masks ride the checkpoint as
    ordinary state and resume bit-identically."""
    from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint

    params = {"w": jnp.asarray(np.random.RandomState(2).randn(16, 16))}
    masks = compute_sparse_masks(params)
    save_checkpoint(str(tmp_path), {"params": params, "masks": masks},
                    step=0)
    restored, _ = restore_checkpoint(str(tmp_path),
                                     {"params": params, "masks": masks})
    np.testing.assert_array_equal(np.asarray(restored["masks"]["w"]),
                                  np.asarray(masks["w"]))
    pruned = apply_masks(restored["params"], restored["masks"])
    np.testing.assert_array_equal(np.asarray(pruned["w"]),
                                  np.asarray(apply_masks(params, masks)["w"]))
