"""ASP 2:4 sparsity tests (``reference:apex/contrib/sparsity/test/``:
``toy_problem.py`` + ``checkpointing_test_part1/2.py`` roles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.sparsity import (ASP, apply_masks,
                                       compute_sparse_masks, mn_1d_mask,
                                       sparse_parameter_paths)
from apex_tpu.optimizers import FusedAdam


def test_mn_1d_mask_keeps_top2_of_4():
    w = jnp.asarray([[0.1, -0.9, 0.5, 0.01, 4.0, 1.0, -2.0, 3.0]])
    mask = np.asarray(mn_1d_mask(w))
    assert mask.tolist() == [[False, True, True, False,
                              True, False, False, True]]
    # exactly n per group, always
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(7, 32))
    m = np.asarray(mn_1d_mask(w)).reshape(7, 8, 4)
    assert np.all(m.sum(-1) == 2)


def test_whitelist_skips_bias_norm_and_small():
    params = {
        "dense": {"weight": jnp.ones((16, 32)), "bias": jnp.ones(32)},
        "ln": {"weight": jnp.ones((4, 32))},
        "tiny": jnp.ones((4, 8)),
    }
    paths = sparse_parameter_paths(params)
    assert any("dense" in p and "weight" in p for p in paths)
    assert not any("bias" in p or "ln" in p or "tiny" in p for p in paths)

    masks = compute_sparse_masks(params)
    assert np.asarray(masks["dense"]["bias"]).all()
    pruned = apply_masks(params, masks)
    dw = np.asarray(pruned["dense"]["weight"]).reshape(16, 8, 4)
    assert np.all((dw != 0).sum(-1) == 2)


def test_masked_optimizer_keeps_sparsity_and_converges():
    """Toy problem (``toy_problem.py`` role): prune, finetune with the
    mask-reapplying step, and check sparsity is invariant while loss
    drops."""
    rng = np.random.RandomState(1)
    params = {"w": jnp.asarray(rng.randn(32, 32) * 0.5)}
    x = jnp.asarray(rng.randn(64, 32))
    y = jnp.asarray(rng.randn(64, 32))

    asp = ASP()
    masks = asp.compute_sparse_masks(params)
    params = asp.prune(params, masks)
    opt = asp.init_optimizer_for_pruning(FusedAdam(lr=1e-2), masks)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((x @ p["w"] - y) ** 2)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.step(g, s, p)
        return p, s, loss

    losses = []
    for _ in range(60):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    w = np.asarray(params["w"]).reshape(32, 8, 4)
    assert np.all((w != 0).sum(-1) <= 2)  # 2:4 pattern held every step
    assert losses[-1] < losses[0] * 0.7


def test_masks_survive_checkpoint(tmp_path):
    """``checkpointing_test_part1/2.py``: masks ride the checkpoint as
    ordinary state and resume bit-identically."""
    from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint

    params = {"w": jnp.asarray(np.random.RandomState(2).randn(16, 16))}
    masks = compute_sparse_masks(params)
    save_checkpoint(str(tmp_path), {"params": params, "masks": masks},
                    step=0)
    restored, _ = restore_checkpoint(str(tmp_path),
                                     {"params": params, "masks": masks})
    np.testing.assert_array_equal(np.asarray(restored["masks"]["w"]),
                                  np.asarray(masks["w"]))
    pruned = apply_masks(restored["params"], restored["masks"])
    np.testing.assert_array_equal(np.asarray(pruned["w"]),
                                  np.asarray(apply_masks(params, masks)["w"]))


# ---------------------------------------------------------------------------
# channel-permutation search (permutation_lib port)
# ---------------------------------------------------------------------------

def _adversarial(rows, c, seed=0):
    """Matrix whose large-magnitude channels are packed into the same
    groups, so the identity grouping wastes magnitude and a permutation
    provably helps."""
    rng = np.random.RandomState(seed)
    w = rng.rand(rows, c) * 0.1
    # every channel in the first group is huge: 2:4 must drop two of them
    w[:, :4] += 10.0
    return w


def test_permutation_search_beats_identity_on_adversarial():
    from apex_tpu.contrib.sparsity.permutation import (
        permutation_efficacy, search_channel_permutation)

    w = _adversarial(32, 16)
    perm, eff_id, eff_perm = search_channel_permutation(w, method="greedy")
    assert sorted(perm.tolist()) == list(range(16))
    assert eff_perm > eff_id * 1.2  # genuinely spreads the big channels
    np.testing.assert_allclose(
        eff_perm, permutation_efficacy(w, perm), rtol=1e-12)


def test_exhaustive_matches_or_beats_greedy_and_identity():
    from apex_tpu.contrib.sparsity.permutation import (
        exhaustive_partition_search, greedy_swap_search, _retained)

    rng = np.random.RandomState(3)
    w = np.abs(rng.randn(16, 8))
    ex = exhaustive_partition_search(w, 4, 2)
    gr = greedy_swap_search(w, 4, 2)
    eff_id = _retained(w, 4, 2)
    eff_ex = _retained(w[:, ex], 4, 2)
    eff_gr = _retained(w[:, gr], 4, 2)
    assert eff_ex >= eff_gr - 1e-12 >= 0
    assert eff_ex >= eff_id
    assert eff_gr >= eff_id


def test_permuted_mask_is_valid_and_retains_more():
    from apex_tpu.contrib.sparsity.asp import mn_1d_mask
    from apex_tpu.contrib.sparsity.permutation import (
        permuted_mn_1d_mask, search_channel_permutation)

    w = jnp.asarray(_adversarial(8, 16, seed=1), jnp.float32)
    base = mn_1d_mask(w)
    perm_mask = permuted_mn_1d_mask(w)
    # same shape, same total density (2:4 keeps exactly half)
    assert perm_mask.shape == w.shape
    assert int(perm_mask.sum()) == int(base.sum())
    # the nonzeros follow the permuted grouping: 2 kept per permuted group
    perm, _, _ = search_channel_permutation(w)
    regrouped = np.asarray(perm_mask)[:, perm].reshape(8, 4, 4)
    np.testing.assert_array_equal(regrouped.sum(-1), 2)
    # retained magnitude >= the unpermuted mask's
    kept_base = float(jnp.sum(jnp.abs(w) * base))
    kept_perm = float(jnp.sum(jnp.abs(w) * perm_mask))
    assert kept_perm >= kept_base


def test_asp_permute_workflow():
    from apex_tpu.contrib.sparsity.asp import ASP

    params = {"w": jnp.asarray(_adversarial(16, 32, seed=2), jnp.float32),
              "bias": jnp.zeros(32, jnp.float32)}
    masks_plain = ASP().compute_sparse_masks(params)
    masks_perm = ASP(permute=True).compute_sparse_masks(params)
    assert bool(masks_perm["bias"].all())  # non-whitelisted untouched
    kept = lambda ms: float(jnp.sum(jnp.abs(params["w"]) * ms["w"]))
    assert kept(masks_perm) >= kept(masks_plain)
    assert int(masks_perm["w"].sum()) == int(masks_plain["w"].sum())
