"""Batch samplers (``reference:tests/L0/run_transformer/test_batch_sampler.py``
role) + the unified config tree (SURVEY §5 item 3)."""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                             ParallelConfig, TrainConfig)
from apex_tpu.transformer._data import (MegatronPretrainingRandomSampler,
                                        MegatronPretrainingSampler)


# ---------------------------------------------------------------------------
# sequential sampler
# ---------------------------------------------------------------------------

def test_sequential_sampler_shards_disjointly():
    total, lmb, dp = 64, 4, 2
    per_rank = [list(MegatronPretrainingSampler(
        total, 0, lmb, rank, dp)) for rank in range(dp)]
    # same number of batches per rank; each global batch partitions its
    # index range between the ranks
    assert len(per_rank[0]) == len(per_rank[1]) == total // (lmb * dp)
    for b0, b1 in zip(*per_rank):
        assert len(b0) == len(b1) == lmb
        assert not set(b0) & set(b1)
        assert sorted(b0 + b1) == list(range(min(b0), min(b0) + lmb * dp))
    covered = sorted(i for b in per_rank[0] + per_rank[1] for i in b)
    assert covered == list(range(total))


def test_sequential_sampler_resumes_from_consumed():
    total, lmb, dp = 32, 4, 1
    full = list(MegatronPretrainingSampler(total, 0, lmb, 0, dp))
    resumed = list(MegatronPretrainingSampler(total, 16, lmb, 0, dp))
    assert resumed == full[16 // (lmb * dp):]


def test_sequential_sampler_drop_last():
    total, lmb, dp = 10, 4, 1
    dropped = list(MegatronPretrainingSampler(total, 0, lmb, 0, dp))
    kept = list(MegatronPretrainingSampler(total, 0, lmb, 0, dp,
                                           drop_last=False))
    assert len(dropped) == 2
    assert len(kept) == 3 and kept[-1] == [8, 9]


def test_sequential_sampler_validation():
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(0, 0, 4, 0, 1)
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(8, 8, 4, 0, 1)
    with pytest.raises(RuntimeError):
        MegatronPretrainingSampler(8, 0, 4, 2, 2)


# ---------------------------------------------------------------------------
# random sampler
# ---------------------------------------------------------------------------

def test_random_sampler_epoch_determinism_and_disjoint_ranks():
    total, lmb, dp = 64, 4, 2
    r0a = list(MegatronPretrainingRandomSampler(total, 0, lmb, 0, dp))
    r0b = list(MegatronPretrainingRandomSampler(total, 0, lmb, 0, dp))
    r1 = list(MegatronPretrainingRandomSampler(total, 0, lmb, 1, dp))
    assert r0a == r0b  # same epoch -> same permutation
    flat0 = {i for b in r0a for i in b}
    flat1 = {i for b in r1 for i in b}
    assert not flat0 & flat1  # bucket sharding is disjoint
    assert len(flat0) == len(flat1) == total // dp
    # shuffled, not sequential
    assert [i for b in r0a for i in b] != sorted(flat0)


def test_random_sampler_resume_skips_consumed():
    total, lmb, dp = 64, 4, 2
    full = list(MegatronPretrainingRandomSampler(total, 0, lmb, 0, dp))
    consumed = 2 * lmb * dp  # two global batches into epoch 0
    resumed = list(MegatronPretrainingRandomSampler(
        total, consumed, lmb, 0, dp))
    assert resumed == full[2:]


def test_random_sampler_advances_epoch():
    total, lmb, dp = 32, 4, 1
    e0 = list(MegatronPretrainingRandomSampler(total, 0, lmb, 0, dp))
    e1 = list(MegatronPretrainingRandomSampler(total, total, lmb, 0, dp))
    assert e0 != e1  # different epoch seed -> different order
    assert {i for b in e0 for i in b} == {i for b in e1 for i in b}


# ---------------------------------------------------------------------------
# config tree
# ---------------------------------------------------------------------------

def test_config_roundtrip_and_builders():
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=128, hidden_size=32,
                          num_layers=2, num_attention_heads=4,
                          max_position_embeddings=16),
        parallel=ParallelConfig(tensor_model_parallel_size=1),
        batch=BatchConfig(global_batch_size=16, micro_batch_size=4),
        optimizer=OptimizerConfig(name="adamw", lr=3e-4, flat=True),
        opt_level="O2")

    # JSON-serializable roundtrip (checkpoint host_state sidecar)
    import json
    d = json.loads(json.dumps(cfg.to_dict()))
    assert TrainConfig.from_dict(d) == cfg

    pol = cfg.build_policy()
    assert pol.name == "O2" and pol.compute_dtype == jnp.bfloat16

    model = cfg.build_model()
    import jax
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    assert np.isfinite(float(model.loss(params, tokens, tokens)))

    opt = cfg.build_optimizer()
    from apex_tpu.optimizers import FlatOptimizer
    assert isinstance(opt, FlatOptimizer)
    state = opt.init(params)
    new_p, _ = opt.step(jax.tree_util.tree_map(jnp.zeros_like, params),
                        state, params)

    calc = cfg.build_microbatch_calculator(data_parallel_size=2)
    assert calc.get() == 16 // (4 * 2)

    sampler = cfg.build_sampler(total_samples=64, consumed_samples=0,
                                data_parallel_rank=0, data_parallel_size=2)
    first = next(iter(sampler))
    assert len(first) == 16 // 2

    scaler = cfg.build_scaler()
    ls = scaler.init()
    assert ls is not None


def test_config_zero_and_errors():
    cfg = TrainConfig(optimizer=OptimizerConfig(name="adam", zero=True))
    from apex_tpu.optimizers import DistributedFusedAdam
    assert isinstance(cfg.build_optimizer(), DistributedFusedAdam)
    with pytest.raises(ValueError):
        TrainConfig(optimizer=OptimizerConfig(name="sgd", zero=True)
                    ).build_optimizer()
    with pytest.raises(ValueError):
        TrainConfig(model=ModelConfig(name="vgg")).build_model()
