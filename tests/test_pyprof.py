"""pyprof reborn: the per-region step-time attribution engine.

Covers the roofline cost model (`pyprof/model.py`) — per-primitive FLOP
pricing against XLA's counting conventions, ring-model collective wire
bytes, scan/pallas multipliers, `named_scope` region bucketing — the
trace-join layer (`pyprof/_attribute.py`), the `StepReporter.
attach_attribution` gauge surface, the bench/script wiring, and the
acceptance smoke: a real (tiny) GPT train step whose modeled FLOPs must
match `costs.flops_budget(compiled)` and whose every region is known to
the `scripts/check_annotations.py` contract.
"""

import ast
import gzip
import importlib.util
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import observability as obs
from apex_tpu import pyprof
from apex_tpu.observability.costs import (DEFAULT_DEVICE_SPEC, DeviceSpec,
                                          device_spec, flops_budget)
from apex_tpu.pyprof import (DEFAULT_REGIONS, UNATTRIBUTED,
                             AttributionReport, attribute, model_program)
from apex_tpu.utils.compat import shard_map

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mesh(n, axis="x"):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), (axis,))


# ---------------------------------------------------------------------------
# DeviceSpec table
# ---------------------------------------------------------------------------

class TestDeviceSpec:
    def test_table_lookup_by_kind_prefix(self):
        class Fake:
            def __init__(self, kind):
                self.device_kind = kind

        v5p = device_spec(Fake("TPU v5p"))
        assert v5p.peak_flops == 459e12 and v5p.hbm_gbps == 2765.0
        v5e = device_spec(Fake("TPU v5 lite something"))
        assert v5e.peak_flops == 197e12
        # CPU hosts fall back to the conservative v5e-class default
        assert device_spec(Fake("cpu")) is DEFAULT_DEVICE_SPEC
        assert device_spec() is DEFAULT_DEVICE_SPEC  # CPU test host

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_HBM_GBPS", "100.0")
        spec = device_spec()
        assert spec.hbm_gbps == 100.0
        assert spec.peak_flops == DEFAULT_DEVICE_SPEC.peak_flops
        assert "env-tuned" in spec.name
        monkeypatch.setenv("APEX_TPU_HBM_GBPS", "-3")
        with pytest.raises(ValueError):
            device_spec()

    def test_roofline_ms(self):
        spec = DeviceSpec("t", peak_flops=1e12, hbm_gbps=1.0, ici_gbps=2.0)
        assert spec.compute_ms(1e12) == pytest.approx(1e3)
        assert spec.hbm_ms(1e9) == pytest.approx(1e3)
        assert spec.comm_ms(1e9) == pytest.approx(500.0)


# ---------------------------------------------------------------------------
# the roofline walker
# ---------------------------------------------------------------------------

class TestModelProgram:
    def test_dot_general_flops_and_hbm(self):
        a, b = jnp.ones((8, 16)), jnp.ones((16, 4))
        cost = model_program(lambda a, b: a @ b, (a, b))
        assert cost.flops == 2 * 8 * 16 * 4
        # operands + result, fp32
        assert cost.hbm_bytes == (8 * 16 + 16 * 4 + 8 * 4) * 4
        assert list(cost.regions) == [UNATTRIBUTED]

    def test_named_scope_bucketing_innermost_wins(self):
        def f(x, w):
            with jax.named_scope("gpt_attention"):
                x = x @ w
                with jax.named_scope("flash_attention"):
                    x = x @ w
            with jax.named_scope("gpt_mlp"):
                return x @ w

        x, w = jnp.ones((4, 8)), jnp.ones((8, 8))
        cost = model_program(f, (x, w))
        per_mm = 2 * 4 * 8 * 8
        assert cost.regions["gpt_attention"].flops == per_mm
        assert cost.regions["flash_attention"].flops == per_mm  # carved out
        assert cost.regions["gpt_mlp"].flops == per_mm

    def test_region_names_survive_grad_transform(self):
        def loss(w, x):
            with jax.named_scope("gpt_mlp"):
                return jnp.sum((x @ w) ** 2)

        w, x = jnp.ones((8, 8)), jnp.ones((4, 8))
        cost = model_program(jax.grad(loss), (w, x))
        # the fwd matmul AND the transposed dW matmul both bucket to the
        # region through the transpose(jvp(...)) name-stack wrappers
        assert cost.regions["gpt_mlp"].flops >= 2 * (2 * 4 * 8 * 8)

    def test_scan_multiplies_by_trip_count(self):
        w = jnp.ones((8, 8))

        def scanned(x):
            return jax.lax.scan(lambda c, _: (c @ w, None), x,
                                None, length=5)[0]

        x = jnp.ones((4, 8))
        cost = model_program(scanned, (x,))
        once = model_program(lambda x: x @ w, (x,))
        assert cost.flops == 5 * once.flops

    def test_transcendentals_excluded_elementwise_counted(self):
        x = jnp.ones((16, 16))
        cost = model_program(lambda x: jnp.tanh(x + x), (x,))
        assert cost.flops == 16 * 16  # the add; tanh books zero

    def test_bound_classification(self):
        a, b = jnp.ones((64, 64)), jnp.ones((64, 64))
        starved = DeviceSpec("starved", peak_flops=1.0, hbm_gbps=1e9,
                             ici_gbps=1e9)
        cost = model_program(lambda a, b: a @ b, (a, b), spec=starved)
        assert cost.regions[UNATTRIBUTED].bound == "compute"
        choked = DeviceSpec("choked", peak_flops=1e30, hbm_gbps=1e-9,
                            ici_gbps=1e9)
        cost = model_program(lambda a, b: a @ b, (a, b), spec=choked)
        assert cost.regions[UNATTRIBUTED].bound == "memory"

    def test_callable_without_args_raises(self):
        with pytest.raises(TypeError):
            model_program(lambda x: x)


class TestCollectivePricing:
    """Ring-model ICI wire bytes per rank, axis sizes read off the
    enclosing shard_map's mesh."""

    def test_psum_prices_two_n_minus_one_over_n(self):
        mesh = _mesh(4)
        g = shard_map(lambda x: jax.lax.psum(x, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P())
        cost = model_program(jax.make_jaxpr(g)(jnp.ones((8, 4))))
        shard_bytes = 2 * 4 * 4
        assert cost.comm_bytes == pytest.approx(2 * shard_bytes * 3 / 4)

    def test_all_gather_prices_n_minus_one_shards(self):
        mesh = _mesh(4)
        g = shard_map(lambda x: jax.lax.all_gather(x, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P(), check_rep=False)
        cost = model_program(jax.make_jaxpr(g)(jnp.ones((8, 4))))
        assert cost.comm_bytes == pytest.approx((2 * 4 * 4) * 3)

    def test_psum_scatter_prices_n_minus_one_over_n(self):
        mesh = _mesh(4)
        g = shard_map(lambda x: jax.lax.psum_scatter(x, "x"), mesh=mesh,
                      in_specs=P(), out_specs=P("x"), check_rep=False)
        cost = model_program(jax.make_jaxpr(g)(jnp.ones((4, 8))))
        assert cost.comm_bytes == pytest.approx((4 * 8 * 4) * 3 / 4)

    def test_ppermute_prices_one_hop(self):
        mesh = _mesh(4)
        perm = [(i, (i + 1) % 4) for i in range(4)]
        g = shard_map(lambda x: jax.lax.ppermute(x, "x", perm), mesh=mesh,
                      in_specs=P("x"), out_specs=P("x"))
        cost = model_program(jax.make_jaxpr(g)(jnp.ones((8, 4))))
        assert cost.comm_bytes == pytest.approx(2 * 4 * 4)  # one shard

    def test_ring_chain_prices_hop_by_hop(self):
        """tp-1 scanned ppermutes (the PR-2 collective-matmul shape)
        price as tp-1 hops — the same traffic as the fused gather they
        replace."""
        mesh = _mesh(4)
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def ring(x):
            def body(c, _):
                return jax.lax.ppermute(c, "x", perm), None
            return jax.lax.scan(body, x, None, length=3)[0]

        g = shard_map(ring, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
        chain = model_program(jax.make_jaxpr(g)(jnp.ones((8, 4))))
        gather = model_program(jax.make_jaxpr(
            shard_map(lambda x: jax.lax.all_gather(x, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P(), check_rep=False)
        )(jnp.ones((8, 4))))
        assert chain.comm_bytes == pytest.approx(gather.comm_bytes)

    def test_collective_hbm_endpoints_counted(self):
        mesh = _mesh(4)
        g = shard_map(lambda x: jax.lax.psum(x, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P())
        cost = model_program(jax.make_jaxpr(g)(jnp.ones((8, 4))))
        assert cost.hbm_bytes == pytest.approx(2 * (2 * 4 * 4))


# ---------------------------------------------------------------------------
# region vocabulary <-> annotation contract
# ---------------------------------------------------------------------------

class TestRegionContract:
    def test_default_regions_subset_of_annotations_table(self):
        """Every region the attribution report can name must be a
        named_scope the check_annotations contract proves exists."""
        mod = _load_script("check_annotations")
        assert set(DEFAULT_REGIONS) <= set(mod.ANNOTATIONS)

    def test_annotation_script_passes(self):
        proc = subprocess.run(
            [sys.executable, "scripts/check_annotations.py"], cwd=REPO,
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# attribution join
# ---------------------------------------------------------------------------

def _small_report(step_time_s=0.01, **kw):
    def f(x, w):
        with jax.named_scope("gpt_mlp"):
            h = jnp.tanh(x @ w)
        with jax.named_scope("gpt_head_loss"):
            return jnp.sum(h @ w)

    args = (jnp.ones((16, 32)), jnp.ones((32, 32)))
    return attribute(f, step_time_s, args=args, **kw)


class TestAttribute:
    def test_scaled_apportionment_and_shares(self):
        rep = _small_report()
        assert rep.measured_source == "scaled"
        assert rep.step_time_ms == pytest.approx(10.0)
        assert sum(r.share for r in rep.regions) == pytest.approx(1.0)
        assert sum(r.measured_ms for r in rep.regions) \
            == pytest.approx(10.0)
        # comm-free program: zero exposure, overlap undefined
        assert rep.comm_exposed_ms == 0.0
        assert rep.overlap_efficiency is None
        assert all(r.comm_exposed_ms == 0.0 for r in rep.regions)

    def test_no_step_time_no_measured_columns(self):
        rep = _small_report(step_time_s=None)
        assert rep.measured_source == "none"
        assert rep.step_time_ms is None and rep.comm_exposed_ms is None
        assert all(r.measured_ms is None for r in rep.regions)

    def test_exposure_capped_by_modeled_comm(self):
        """A region measured far beyond its roofline can only blame its
        modeled comm traffic — a comm-free region never reports
        exposure, however slow it measured."""
        mesh = _mesh(4)
        g = shard_map(lambda x: jax.lax.psum(jnp.tanh(x), "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P())
        jaxpr = jax.make_jaxpr(g)(jnp.ones((8, 4)))
        rep = attribute(jaxpr, 1.0)  # 1000 ms for a microscopic program
        (region,) = [r for r in rep.regions if r.comm_bytes > 0]
        assert region.comm_exposed_ms == pytest.approx(region.comm_ms)
        assert rep.overlap_efficiency == 0.0  # nothing was hidden
        free = _small_report(step_time_s=5.0)
        assert free.comm_exposed_ms == 0.0

    def test_markdown_and_jsonl_render(self):
        rep = _small_report()
        md = rep.markdown()
        assert md.splitlines()[0].startswith("| region |")
        assert "gpt_mlp" in md and "modeled_step_ms=" in md
        lines = rep.json_lines().splitlines()
        objs = [json.loads(l, parse_constant=pytest.fail) for l in lines]
        step = [o for o in objs if o["region"] == "_step"]
        assert len(step) == 1
        assert step[0]["modeled_step_ms"] == pytest.approx(
            rep.modeled_step_ms)
        assert {o["region"] for o in objs} \
            >= {"gpt_mlp", "gpt_head_loss", "_step"}

    def test_xla_flops_cross_check_field(self):
        def f(x, w):
            return jnp.sum(x @ w)

        args = (jnp.ones((16, 32)), jnp.ones((32, 32)))
        traced = jax.jit(f).trace(*args)
        compiled = traced.lower().compile()
        rep = attribute(traced, 0.001, compiled=compiled)
        if rep.xla_flops:  # backend-dependent
            assert rep.flops == pytest.approx(rep.xla_flops, rel=0.05)

    def test_region_times_from_spans(self):
        spans = [obs.Span("step/gpt_mlp", 1.0, 1.25),
                 obs.Span("gpt_mlp", 2.0, 2.05),
                 obs.Span("unrelated", 0.0, 9.0)]
        times = pyprof.region_times_from_spans(spans)
        assert times == {"gpt_mlp": pytest.approx(300.0)}

    def test_region_times_from_trace_dir(self, tmp_path):
        events = {"traceEvents": [
            {"name": "fusion.1", "ph": "X", "ts": 0, "dur": 1500,
             "args": {"tf_op": "gpt_attention/dot_general"}},
            {"name": "gpt_attention.2", "ph": "X", "ts": 0, "dur": 500},
            {"name": "ignored", "ph": "C", "ts": 0, "dur": 999},
        ]}
        sub = tmp_path / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump(events, f)
        times = pyprof.region_times_from_trace_dir(str(tmp_path))
        assert times == {"gpt_attention": pytest.approx(2.0)}
        assert pyprof.region_times_from_trace_dir(
            str(tmp_path / "empty")) == {}

    def test_trace_region_times_win_over_scaling(self):
        rep = _small_report(step_time_s=0.01,
                            region_times={"gpt_mlp": 7.5})
        assert rep.measured_source == "trace"
        by_name = {r.name: r for r in rep.regions}
        assert by_name["gpt_mlp"].measured_ms == 7.5
        assert by_name["gpt_head_loss"].measured_ms is None

    def test_span_join_buckets_by_innermost_region(self):
        """The trace/span join must bucket by the INNERMOST known region
        — the same rule the cost model uses — so measured walls land in
        the region that carries the modeled cost (flash_attention inside
        gpt_attention, not the outer phase)."""
        spans = [obs.Span("gpt_attention/flash_attention", 0.0, 0.1),
                 obs.Span("gpt_attention/proj", 0.2, 0.25)]
        times = pyprof.region_times_from_spans(spans)
        assert times == {"flash_attention": pytest.approx(100.0),
                         "gpt_attention": pytest.approx(50.0)}

    def test_trace_dir_join_buckets_by_innermost_region(self, tmp_path):
        events = {"traceEvents": [
            {"name": "fusion.7", "ph": "X", "ts": 0, "dur": 2000,
             "args": {"tf_op": "gpt_attention/flash_attention/custom"}},
        ]}
        sub = tmp_path / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump(events, f)
        times = pyprof.region_times_from_trace_dir(str(tmp_path))
        assert times == {"flash_attention": pytest.approx(2.0)}

    def test_trace_dir_steps_normalizes_multi_step_captures(self,
                                                            tmp_path):
        """A profile_trace capture spans several steps; ``steps=`` must
        divide the summed durations so the walls are per-step and the
        exposure cap isn't saturated by a 5x-inflated measurement."""
        events = {"traceEvents": [
            {"name": f"gpt_mlp.{i}", "ph": "X", "ts": i, "dur": 1000}
            for i in range(5)]}
        sub = tmp_path / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump(events, f)
        assert pyprof.region_times_from_trace_dir(str(tmp_path)) \
            == {"gpt_mlp": pytest.approx(5.0)}
        assert pyprof.region_times_from_trace_dir(
            str(tmp_path), steps=5) == {"gpt_mlp": pytest.approx(1.0)}
        with pytest.raises(ValueError):
            pyprof.region_times_from_trace_dir(str(tmp_path), steps=0)

    def test_trace_dir_averages_across_device_tracks(self, tmp_path):
        """A multi-chip capture has one process track (pid) per device
        core; the per-chip roofline must join against ONE chip's wall —
        averaged across tracks — not an n_devices-fold sum."""
        events = {"traceEvents": [
            {"name": "gpt_mlp.1", "ph": "X", "ts": 0, "dur": 1000,
             "pid": 1},
            {"name": "gpt_mlp.2", "ph": "X", "ts": 5, "dur": 1000,
             "pid": 1},
            {"name": "gpt_mlp.3", "ph": "X", "ts": 0, "dur": 1400,
             "pid": 2},
        ]}
        sub = tmp_path / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump(events, f)
        # pid 1 sums to 2.0 ms, pid 2 to 1.4 ms -> per-chip mean 1.7 ms
        assert pyprof.region_times_from_trace_dir(str(tmp_path)) \
            == {"gpt_mlp": pytest.approx(1.7)}

    def test_empty_spans_fall_through_to_trace_dir(self, tmp_path):
        """A span drain that matches no region (capture off, unrelated
        spans) must not swallow a real --trace-dir capture."""
        events = {"traceEvents": [
            {"name": "gpt_mlp.1", "ph": "X", "ts": 0, "dur": 4000}]}
        sub = tmp_path / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump(events, f)
        rep = _small_report(step_time_s=0.01, spans=[],
                            trace_dir=str(tmp_path))
        assert rep.measured_source == "trace"
        by_name = {r.name: r for r in rep.regions}
        assert by_name["gpt_mlp"].measured_ms == pytest.approx(4.0)

    def test_partial_trace_excludes_unmeasured_comm_from_overlap(self):
        """A partial trace (a comm-bearing region's events fused away)
        must not inflate overlap_efficiency: the unmeasured region's
        modeled comm leaves the denominator and the report says so."""
        mesh = _mesh(4)

        def g(x):
            with jax.named_scope("apex_ddp_allreduce"):
                a = jax.lax.psum(jnp.tanh(x), "x")
            with jax.named_scope("tp_row_linear"):
                b = jax.lax.psum(x * x, "x")
            return a + b

        jaxpr = jax.make_jaxpr(shard_map(
            g, mesh=mesh, in_specs=P("x"), out_specs=P()))(
                jnp.ones((8, 4)))
        # walls only for the allreduce region, measured fully exposed;
        # tp_row_linear's events were "fused away"
        full = attribute(jaxpr, 1.0)
        by = {r.name: r for r in full.regions}
        wall = {"apex_ddp_allreduce":
                by["apex_ddp_allreduce"].comm_ms + 1.0}
        rep = attribute(jaxpr, 1.0, region_times=wall)
        assert rep.measured_source == "trace"
        # everything measured was exposed -> 0.0, not diluted toward 1
        # by tp_row_linear's unobserved bytes
        assert rep.overlap_efficiency == pytest.approx(0.0)
        assert any("tp_row_linear" in n for n in rep.notes)


class TestAttachAttribution:
    def test_gauges_set_from_report(self):
        rep = obs.StepReporter([], registry=obs.MetricsRegistry())
        report = _small_report()
        assert rep.attach_attribution(report) is rep
        snap = rep.registry.snapshot()
        assert snap["perf/modeled_step_ms"] == pytest.approx(
            report.modeled_step_ms)
        assert snap["perf/comm_exposed_ms"] == 0.0
        # comm-free program: overlap_efficiency stays unset, not 0/1
        assert "perf/overlap_efficiency" not in snap

    def test_unmeasured_report_leaves_exposure_unset(self):
        rep = obs.StepReporter([], registry=obs.MetricsRegistry())
        rep.attach_attribution(_small_report(step_time_s=None))
        snap = rep.registry.snapshot()
        assert "perf/modeled_step_ms" in snap
        assert "perf/comm_exposed_ms" not in snap


# ---------------------------------------------------------------------------
# mfu zero-step-time guard (regression: first-report wall delta ~0)
# ---------------------------------------------------------------------------

class TestMfuGuard:
    def test_mfu_returns_nan_not_raise(self):
        assert obs.mfu(10.0, 2.0, peak=1.0) == 5.0
        assert math.isnan(obs.mfu(1.0, 0.0, peak=1.0))
        assert math.isnan(obs.mfu(1.0, -0.5, peak=1.0))
        assert math.isnan(obs.mfu(1.0, 1.0, peak=0.0))

    def test_zero_wall_delta_leaves_gauge_unset(self, monkeypatch):
        """Two reports inside one perf_counter tick (fast host) must not
        emit a fabricated utilization — and must not crash the loop."""
        from apex_tpu.observability import report as report_mod

        monkeypatch.setattr(report_mod.time, "perf_counter", lambda: 42.0)
        rep = obs.StepReporter([], registry=obs.MetricsRegistry())
        rep.attach_flops_budget(1e6, peak=1e9)
        p0 = rep.report(0)
        p1 = rep.report(1)  # dt == 0.0 exactly
        assert "perf/mfu" not in p0 and "perf/mfu" not in p1

    def test_attach_flops_budget_still_validates_at_config_time(self):
        rep = obs.StepReporter([], registry=obs.MetricsRegistry())
        with pytest.raises(ValueError):
            rep.attach_flops_budget(0.0)
        with pytest.raises(ValueError):
            rep.attach_flops_budget(1e6, peak=-1.0)


# ---------------------------------------------------------------------------
# the acceptance smoke: a real (tiny) GPT train step
# ---------------------------------------------------------------------------

TINY_GPT = {"hidden_size": 64, "num_layers": 2, "vocab_size": 256,
            "num_attention_heads": 2, "batch": 2, "seq": 32}


@pytest.fixture(scope="module")
def tiny_gpt_attribution():
    attr = _load_script("attribute_step")
    traced, compiled, args, _wrapped = attr.build_gpt(TINY_GPT, False)
    return attribute(traced, 0.05, compiled=compiled)


class TestGPTSmoke:
    def test_modeled_flops_match_xla_budget(self, tiny_gpt_attribution):
        rep = tiny_gpt_attribution
        if not rep.xla_flops:
            pytest.skip("backend reports no cost analysis")
        assert rep.flops == pytest.approx(rep.xla_flops, rel=0.05)

    def test_every_region_is_contract_known(self, tiny_gpt_attribution):
        known = set(_load_script("check_annotations").ANNOTATIONS)
        for r in tiny_gpt_attribution.regions:
            assert r.name == UNATTRIBUTED or r.name in known, r.name

    def test_expected_phases_present_and_dominant(self,
                                                  tiny_gpt_attribution):
        by_name = {r.name: r for r in tiny_gpt_attribution.regions}
        for phase in ("gpt_embed", "gpt_ln", "gpt_attention", "gpt_mlp",
                      "gpt_head_loss", "optimizer_step"):
            assert phase in by_name, phase
        # the unattributed residue (scaler/donation glue) stays small
        total = tiny_gpt_attribution.modeled_step_ms
        resid = by_name.get(UNATTRIBUTED)
        assert resid is None or resid.modeled_ms < 0.25 * total

    def test_region_flops_sum_to_report_total(self, tiny_gpt_attribution):
        rep = tiny_gpt_attribution
        assert sum(r.flops for r in rep.regions) == pytest.approx(
            rep.flops)


def test_attribute_step_script_validates():
    """`python scripts/attribute_step.py --model gpt` (tiny config):
    prints the per-region table and its self-validation against
    flops_budget passes within tolerance."""
    proc = subprocess.run(
        [sys.executable, "scripts/attribute_step.py", "--model", "gpt",
         "--config", json.dumps(TINY_GPT), "--iters", "1",
         "--warmup", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "| region |" in proc.stdout
    assert "validation ok" in proc.stdout


# ---------------------------------------------------------------------------
# bench wiring
# ---------------------------------------------------------------------------

class TestBenchWiring:
    def test_attrib_extra_emits_the_two_columns(self):
        sys.path.insert(0, REPO)
        try:
            import bench
        finally:
            sys.path.remove(REPO)

        def f(x, w):
            with jax.named_scope("gpt_mlp"):
                return jnp.sum(x @ w)

        traced = jax.jit(f).trace(jnp.ones((1024, 1024)),
                                  jnp.ones((1024, 1024)))
        extra = bench._attrib_extra(traced, 5.0)
        assert extra["modeled_step_ms"] > 0
        assert extra["comm_exposed_ms"] == 0.0  # comm-free on one chip
        # never fabricates numbers for an unpriceable program
        assert bench._attrib_extra(object(), 5.0) == {}

    def test_gpt_and_headline_benches_carry_attribution(self):
        """Structural: every headline/GPT _emit call site reaches
        _attrib_extra — the bench lines carry modeled_step_ms."""
        src = ast.parse(open(os.path.join(REPO, "bench.py")).read())
        want = {"bench_headline", "bench_gpt", "bench_gpt_remat",
                "bench_gpt_sp_overlap"}
        seen = set()
        for node in ast.walk(src):
            if isinstance(node, ast.FunctionDef) and node.name in want:
                calls = {c.func.id for c in ast.walk(node)
                         if isinstance(c, ast.Call)
                         and isinstance(c.func, ast.Name)}
                if "_attrib_extra" in calls:
                    seen.add(node.name)
        assert seen == want


# ---------------------------------------------------------------------------
# trainer surface
# ---------------------------------------------------------------------------

def test_hybrid_trainer_attribution_report():
    """GPTHybridTrainer.attribution_report prices the trainer's own
    tp x pp x dp step: every pipeline/TP/DP region shows up and the
    collectives carry wire bytes."""
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    tp, pp, dp = 2, 2, 2
    M, mb, seq = 2, 2, 8
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2 * pp, num_attention_heads=4,
                          max_position_embeddings=seq),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0),
        opt_level="O0")
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    mesh = cfg.initialize_mesh(devices=jax.devices())
    try:
        trainer = GPTHybridTrainer(cfg, mesh)
        state = trainer.init_state(jax.random.PRNGKey(0))
        rep = trainer.attribution_report(*state, tokens, targets,
                                         iters=1)
    finally:
        parallel_state.destroy_model_parallel()
    assert isinstance(rep, AttributionReport)
    assert rep.step_time_ms and rep.step_time_ms > 0
    assert rep.measured_source == "scaled"
    # the sharded step moves real collective traffic (grad psum at
    # minimum), and the model prices it
    assert sum(r.comm_bytes for r in rep.regions) > 0
    names = {r.name for r in rep.regions}
    assert "optimizer_step" in names
    known = set(_load_script("check_annotations").ANNOTATIONS)
    assert names <= known | {UNATTRIBUTED}


# ---------------------------------------------------------------------------
# the attribute shadow (PR 6 accepted-wart, fixed in PR 11)
# ---------------------------------------------------------------------------

def test_attribute_function_not_shadowed_by_submodule():
    """pyprof.attribute must stay the FUNCTION even after the attribution
    submodule is imported. The old pyprof/attribute.py made ``import
    apex_tpu.pyprof.attribute`` rebind the package attribute to the
    module, clobbering the entry point process-wide; the submodule now
    lives at pyprof/_attribute.py with its names re-exported."""
    import importlib

    import apex_tpu.pyprof as pp

    sub = importlib.import_module("apex_tpu.pyprof._attribute")
    assert callable(pp.attribute)
    assert pp.attribute is sub.attribute
    assert pp.AttributionReport is sub.AttributionReport
    # the shadowing module path is gone for good
    with pytest.raises(ModuleNotFoundError):
        importlib.import_module("apex_tpu.pyprof.attribute")
    # and the from-package import keeps resolving to the function
    from apex_tpu.pyprof import attribute as fn
    assert fn is sub.attribute
