"""Dropout semantics tests (VERDICT r1 item 4).

- hidden/embedding dropout masks are IDENTICAL across TP ranks (replicated
  activations; the reference's default RNG stream), so a TP=4 run with
  hidden dropout matches the dense run with the same key;
- attention-probability dropout folds in the TP rank (sharded heads; the
  reference's tensor-parallel stream), so TP ranks draw independent masks;
- recompute under ``remat`` replays identical masks (keys are explicit
  inputs — the property CheckpointFunction stashes RNG state for in
  ``reference:apex/transformer/tensor_parallel/random.py:233-304``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops.dropout import dropout
from apex_tpu.transformer import parallel_state


@pytest.fixture
def mesh_tp4():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=4)
    yield mesh
    parallel_state.destroy_model_parallel()


def _cfg(tp=1, **kw):
    return GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     tensor_model_parallel_size=tp,
                     compute_dtype=jnp.float32, use_flash=False, **kw)


def _tokens(b=2, s=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, 128, (b, s)))


def test_dropout_op_basics():
    x = jnp.ones((4, 100))
    key = jax.random.PRNGKey(0)
    y = dropout(x, 0.5, key)
    kept = np.asarray(y) != 0
    assert abs(kept.mean() - 0.5) < 0.1
    np.testing.assert_allclose(np.asarray(y)[kept], 2.0)  # inverted scaling
    np.testing.assert_array_equal(np.asarray(dropout(x, 0.5, None)),
                                  np.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(dropout(x, 0.5, key, deterministic=True)), np.asarray(x))


def test_gpt_dropout_changes_loss_and_is_deterministic():
    model = GPTModel(_cfg(hidden_dropout=0.2, attention_dropout=0.1))
    params = model.init(jax.random.PRNGKey(0))
    toks = _tokens()
    rng = jax.random.PRNGKey(42)
    l_eval = model.loss(params, toks, toks)
    l1 = model.loss(params, toks, toks, dropout_rng=rng)
    l2 = model.loss(params, toks, toks, dropout_rng=rng)
    l3 = model.loss(params, toks, toks, dropout_rng=jax.random.PRNGKey(43))
    assert float(l1) == float(l2)            # same key, same masks
    assert float(l1) != float(l_eval)        # dropout actually fires
    assert float(l1) != float(l3)            # key-dependent




def _tp_specs():
    specs = {
        "embedding": {"word": {"weight": P("tensor")}, "position": P()},
        "final_ln": {"weight": P(), "bias": P()},
        "layers": {
            "ln1": {"weight": P(), "bias": P()},
            "ln2": {"weight": P(), "bias": P()},
            "qkv": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
            "fc1": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
            "proj": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
            "fc2": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
        },
    }
    return specs

def test_hidden_dropout_tp_matches_dense(mesh_tp4):
    """With attention_dropout=0, hidden+embedding dropout draws only from
    the TP-replicated stream: the TP=4 loss equals the dense loss with the
    same key (mask identity across ranks, reference random.py:200-230)."""
    mesh = parallel_state.get_mesh()
    toks = _tokens()
    rng = jax.random.PRNGKey(7)

    dense = GPTModel(_cfg(hidden_dropout=0.3))
    params = dense.init(jax.random.PRNGKey(0))
    l_dense = dense.loss(params, toks, toks, dropout_rng=rng)

    tp_model = GPTModel(_cfg(tp=4, hidden_dropout=0.3))
    tp_params = tp_model.init(jax.random.PRNGKey(0))

    def run(tp_params, toks):
        def inner(tp_params, toks):
            l = tp_model.loss(tp_params, toks, toks, dropout_rng=rng)
            return jax.lax.pmean(l, "tensor")
        return shard_map(inner, mesh=mesh, in_specs=(_tp_specs(), P()),
                         out_specs=P())(tp_params, toks)

    l_tp = jax.jit(run)(tp_params, toks)
    np.testing.assert_allclose(float(l_tp), float(l_dense), rtol=2e-5)


def test_attention_dropout_tp_rank_streams(mesh_tp4):
    """Attention dropout folds in the TP rank, so the TP result differs from
    the dense run with the same key (independent masks per head shard) but
    stays deterministic."""
    mesh = parallel_state.get_mesh()
    toks = _tokens()
    rng = jax.random.PRNGKey(7)

    dense = GPTModel(_cfg(attention_dropout=0.4))
    params = dense.init(jax.random.PRNGKey(0))
    l_dense = dense.loss(params, toks, toks, dropout_rng=rng)

    tp_model = GPTModel(_cfg(tp=4, attention_dropout=0.4))
    tp_params = tp_model.init(jax.random.PRNGKey(0))

    def run(tp_params, toks):
        def inner(tp_params, toks):
            l = tp_model.loss(tp_params, toks, toks, dropout_rng=rng)
            return jax.lax.pmean(l, "tensor")
        return shard_map(inner, mesh=mesh, in_specs=(_tp_specs(), P()),
                         out_specs=P())(tp_params, toks)

    l_tp1 = jax.jit(run)(tp_params, toks)
    l_tp2 = jax.jit(run)(tp_params, toks)
    assert float(l_tp1) == float(l_tp2)      # deterministic
    assert float(l_tp1) != float(l_dense)    # rank-folded masks differ


def test_remat_replays_dropout_masks():
    """remat recomputes the forward in backward; explicit keys make the
    recomputed dropout masks identical, so loss AND grads match the
    non-remat run exactly."""
    toks = _tokens()
    rng = jax.random.PRNGKey(11)
    losses, grads = [], []
    for remat in (False, True):
        model = GPTModel(_cfg(hidden_dropout=0.2, attention_dropout=0.1,
                              remat=remat))
        params = model.init(jax.random.PRNGKey(0))
        l, g = jax.value_and_grad(
            lambda p: model.loss(p, toks, toks, dropout_rng=rng))(params)
        losses.append(float(l))
        grads.append(g)
    assert losses[0] == losses[1]
    for a, b in zip(jax.tree_util.tree_leaves(grads[0]),
                    jax.tree_util.tree_leaves(grads[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_flash_kernel_dropout_in_model():
    """The Pallas in-kernel dropout path wires through GPT (shapes eligible
    for flash) and matches the XLA fallback with the same seed."""
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=1,
                    num_attention_heads=1, max_position_embeddings=128,
                    compute_dtype=jnp.float32, attention_dropout=0.3,
                    use_flash=True)
    cfg_ref = dataclasses_replace(cfg, use_flash=False)
    toks = _tokens(b=1, s=128)
    rng = jax.random.PRNGKey(5)
    m1, m2 = GPTModel(cfg), GPTModel(cfg_ref)
    params = m1.init(jax.random.PRNGKey(0))
    l_pallas = m1.loss(params, toks, toks, dropout_rng=rng)
    l_ref = m2.loss(params, toks, toks, dropout_rng=rng)
    np.testing.assert_allclose(float(l_pallas), float(l_ref), rtol=2e-5)


def dataclasses_replace(cfg, **kw):
    import dataclasses
    return dataclasses.replace(cfg, **kw)
