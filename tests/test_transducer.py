"""Transducer joint/loss parity tests
(``reference:apex/contrib/test/transducer/test_transducer_{joint,loss}.py``
role, vs ``transducer_ref.py`` semantics).

The loss reference here is an *independent* naive implementation: the
textbook RNN-T recursion written with unrolled Python loops over jnp
scalars, differentiated by JAX AD — it shares no code with the scan/
associative-scan implementation or its hand-written backward, so agreement
checks both the forward DP and the analytic gradient."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.transducer import (TransducerJoint, TransducerLoss,
                                     transducer_joint, transducer_loss)


def _naive_loss(x, label, f_len, y_len, blank_idx):
    """Unrolled-textbook RNN-T NLL for one batch element (host loops)."""
    x_log = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    B = x.shape[0]
    losses = []
    for b in range(B):
        T, U = int(f_len[b]), int(y_len[b])
        alpha = {}
        alpha[(0, 0)] = 0.0
        for t in range(1, T):
            alpha[(t, 0)] = alpha[(t - 1, 0)] + x_log[b, t - 1, 0, blank_idx]
        for u in range(1, U + 1):
            alpha[(0, u)] = alpha[(0, u - 1)] + \
                x_log[b, 0, u - 1, label[b, u - 1]]
        for t in range(1, T):
            for u in range(1, U + 1):
                stay = alpha[(t - 1, u)] + x_log[b, t - 1, u, blank_idx]
                move = alpha[(t, u - 1)] + x_log[b, t, u - 1, label[b, u - 1]]
                alpha[(t, u)] = jnp.logaddexp(stay, move)
        losses.append(-(alpha[(T - 1, U)] + x_log[b, T - 1, U, blank_idx]))
    return jnp.stack(losses)


@pytest.mark.parametrize("blank_idx", [0, 3])
def test_loss_and_grad_match_naive_reference(blank_idx):
    rng = np.random.RandomState(0)
    B, T, U, V = 2, 4, 3, 6
    x = jnp.asarray(rng.randn(B, T, U + 1, V), jnp.float32)
    label_pool = [v for v in range(V) if v != blank_idx]
    label = jnp.asarray(rng.choice(label_pool, (B, U)))
    f_len = jnp.asarray([T, T - 1])
    y_len = jnp.asarray([U, U - 1])

    loss = transducer_loss(x, label, f_len, y_len, blank_idx)
    ref = _naive_loss(x, label, f_len, y_len, blank_idx)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)

    w = jnp.asarray(rng.randn(B), jnp.float32)  # nontrivial upstream grads
    g = jax.grad(lambda x: jnp.sum(
        w * transducer_loss(x, label, f_len, y_len, blank_idx)))(x)
    g_ref = jax.grad(lambda x: jnp.sum(
        w * _naive_loss(x, label, f_len, y_len, blank_idx)))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_loss_grad_zero_outside_valid_region():
    rng = np.random.RandomState(1)
    B, T, U, V = 2, 5, 3, 5
    x = jnp.asarray(rng.randn(B, T, U + 1, V), jnp.float32)
    label = jnp.asarray(rng.randint(1, V, (B, U)))
    f_len = jnp.asarray([3, 5])
    y_len = jnp.asarray([2, 3])
    g = jax.grad(lambda x: jnp.sum(
        transducer_loss(x, label, f_len, y_len, 0)))(x)
    g = np.asarray(g)
    # no gradient flows to padded time/label cells
    assert np.all(g[0, 3:] == 0.0)
    assert np.all(g[0, :, 3:] == 0.0)
    assert np.all(g[1, :, 4:] == 0.0)
    assert np.any(g[0, :3, :3] != 0.0)


def test_loss_is_jittable_and_batched():
    rng = np.random.RandomState(2)
    B, T, U, V = 3, 6, 4, 8
    x = jnp.asarray(rng.randn(B, T, U + 1, V), jnp.float32)
    label = jnp.asarray(rng.randint(1, V, (B, U)))
    f_len = jnp.asarray([6, 4, 5])
    y_len = jnp.asarray([4, 2, 3])
    fn = jax.jit(lambda x: transducer_loss(x, label, f_len, y_len, 0))
    loss = fn(x)
    assert loss.shape == (B,)
    assert np.all(np.isfinite(np.asarray(loss)))
    np.testing.assert_allclose(
        np.asarray(loss),
        np.asarray(_naive_loss(x, label, f_len, y_len, 0)), rtol=1e-5)


def test_joint_matches_manual_and_masks_padding():
    rng = np.random.RandomState(3)
    B, T, U, H = 2, 4, 3, 8
    f = jnp.asarray(rng.randn(B, T, H), jnp.float32)
    g = jnp.asarray(rng.randn(B, U, H), jnp.float32)
    f_len = jnp.asarray([4, 2])
    g_len = jnp.asarray([3, 1])

    h = transducer_joint(f, g, f_len, g_len, relu=True)
    manual = jax.nn.relu(f[:, :, None, :] + g[:, None, :, :])
    np.testing.assert_allclose(np.asarray(h[0]), np.asarray(manual[0]),
                               rtol=1e-6)
    assert np.all(np.asarray(h[1, 2:]) == 0.0)       # t >= f_len
    assert np.all(np.asarray(h[1, :, 1:]) == 0.0)    # u >= g_len


def test_joint_dropout_and_module_wrappers():
    rng = np.random.RandomState(4)
    f = jnp.asarray(rng.randn(2, 3, 16), jnp.float32)
    g = jnp.asarray(rng.randn(2, 2, 16), jnp.float32)
    joint = TransducerJoint(relu=False, dropout=True, dropout_prob=0.5)
    h = joint(f, g, dropout_rng=jax.random.PRNGKey(0))
    frac_zero = float(np.mean(np.asarray(h) == 0.0))
    assert 0.3 < frac_zero < 0.7

    with pytest.raises(NotImplementedError):
        TransducerJoint(pack_output=True)
    with pytest.raises(NotImplementedError):
        TransducerLoss(packed_input=True)

    loss_mod = TransducerLoss()
    x = jnp.asarray(rng.randn(2, 3, 3, 5), jnp.float32)
    label = jnp.asarray(rng.randint(1, 5, (2, 2)))
    out = loss_mod(x, label, jnp.asarray([3, 3]), jnp.asarray([2, 2]))
    assert out.shape == (2,)
