"""Selective activation rematerialization (apex_tpu/remat.py).

Pins the four contracts of the policy subsystem:

1. **Back-compat**: the deprecated ``remat: bool`` maps to
   ``remat_policy="full"|"none"`` (DeprecationWarning on True), configs
   round-trip through the JSON sidecar form, and ``policy="full"`` traces
   a program *identical* to the legacy ``remat=True`` one — with zero
   ``name`` equations, so it cannot have drifted from the pre-policy
   program (which had no tag machinery at all).
2. **Structure**: under ``selective`` the jaxpr census shows exactly the
   registry-named residuals tagged in the forward, none of the saved
   names recomputed, and the flash-attention *forward* kernel absent
   from the remat region (its backward kernels stay, by construction).
3. **Determinism**: the recomputed forward regenerates bit-identical
   dropout keep masks under every policy — both the in-kernel
   (counter-based, seed-keyed) flash dropout and the key-threaded hidden
   dropout — asserted as grad equality against the unrematerialized
   program.
4. **Memory**: ``memory_budget`` temp bytes order
   ``none > selective > full`` on a GPT train step — the trade the
   policies exist to navigate.
"""

import dataclasses
import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _jaxpr_utils import iter_eqns, jaxpr_str
from apex_tpu import remat
from apex_tpu.remat import RematPolicy


# ---------------------------------------------------------------------------
# policy object: validation + resolution
# ---------------------------------------------------------------------------

class TestRematPolicy:
    def test_modes_and_validation(self):
        assert RematPolicy().mode == "none"
        for mode in ("none", "full", "selective", "offload"):
            RematPolicy(mode=mode)
        with pytest.raises(ValueError):
            RematPolicy(mode="everything")
        with pytest.raises(ValueError):            # unregistered name
            RematPolicy(mode="selective", names=("rogue",))
        with pytest.raises(ValueError):            # names need a name mode
            RematPolicy(mode="full", names=("qkv_out",))
        p = RematPolicy(mode="selective", names=["qkv_out", "ln_out"])
        assert p.names == ("qkv_out", "ln_out")    # normalized to tuple
        assert p.save_names == ("qkv_out", "ln_out")
        assert RematPolicy(mode="selective").save_names \
            == remat.SELECTIVE_SAVE

    def test_resolve_spellings(self):
        assert RematPolicy.resolve(None).mode == "none"
        assert RematPolicy.resolve(False).mode == "none"
        assert RematPolicy.resolve(True).mode == "full"   # schedules flag
        assert RematPolicy.resolve("selective").mode == "selective"
        p = RematPolicy(mode="offload")
        assert RematPolicy.resolve(p) is p
        with pytest.raises(TypeError):
            RematPolicy.resolve(3.14)

    def test_legacy_bool_warns(self):
        with pytest.warns(DeprecationWarning, match="remat_policy"):
            p = RematPolicy.resolve(None, legacy_bool=True, owner="X")
        assert p.mode == "full"
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # False must stay silent
            assert RematPolicy.resolve(
                None, legacy_bool=False).mode == "none"

    def test_uses_names_gate(self):
        assert not RematPolicy(mode="none").uses_names
        assert not RematPolicy(mode="full").uses_names
        assert RematPolicy(mode="selective").uses_names
        assert RematPolicy(mode="offload").uses_names


# ---------------------------------------------------------------------------
# config threading + round-trip (satellite: back-compat)
# ---------------------------------------------------------------------------

class TestConfigRoundTrip:
    def test_legacy_bool_round_trips_and_warns(self):
        from apex_tpu.config import ModelConfig, TrainConfig

        cfg = TrainConfig(model=ModelConfig(
            name="gpt", vocab_size=64, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_position_embeddings=16, remat=True))
        cfg2 = TrainConfig.from_dict(json.loads(json.dumps(cfg.to_dict())))
        assert cfg2.model.remat is True and cfg2.model.remat_policy is None
        with pytest.warns(DeprecationWarning):
            model = cfg2.build_model()
        assert model.remat_policy.mode == "full"

    def test_policy_and_names_round_trip(self):
        from apex_tpu.config import ModelConfig, TrainConfig

        cfg = TrainConfig(model=ModelConfig(
            name="gpt", vocab_size=64, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_position_embeddings=16,
            remat_policy="selective", remat_names=("qkv_out", "flash_ctx")))
        cfg2 = TrainConfig.from_dict(json.loads(json.dumps(cfg.to_dict())))
        assert cfg2.model.remat_policy == "selective"
        assert cfg2.model.remat_names == ("qkv_out", "flash_ctx")
        with warnings.catch_warnings():
            warnings.simplefilter("error", DeprecationWarning)
            model = cfg2.build_model()
        assert model.remat_policy.mode == "selective"
        assert model.remat_policy.save_names == ("qkv_out", "flash_ctx")

    def test_default_stays_silent_and_none(self):
        from apex_tpu.config import ModelConfig, TrainConfig

        cfg = TrainConfig(model=ModelConfig(
            name="gpt", vocab_size=64, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_position_embeddings=16))
        with warnings.catch_warnings():
            warnings.simplefilter("error", DeprecationWarning)
            model = cfg.build_model()
        assert model.remat_policy.mode == "none"

    def test_names_without_name_policy_rejected(self):
        from apex_tpu.models import GPTConfig, GPTModel

        with pytest.raises(ValueError, match="remat_names"):
            GPTModel(GPTConfig(
                vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, remat_policy="full",
                remat_names=("qkv_out",)))


# ---------------------------------------------------------------------------
# jaxpr structure: identity + selective census (acceptance criteria)
# ---------------------------------------------------------------------------

# pallas-eligible shapes: seq % 128 == 0, head_dim % 8 == 0 — the flash
# kernel (interpret mode on CPU) must be in the program for the census
_GPT_KW = dict(vocab_size=256, hidden_size=64, num_layers=2,
               num_attention_heads=4, max_position_embeddings=128,
               compute_dtype=jnp.float32, params_dtype=jnp.float32,
               use_flash=True)


def _gpt_grad_jaxpr(policy=None, legacy=False, dropout=False, **kw):
    from apex_tpu.models import GPTConfig, GPTModel

    model = GPTModel(GPTConfig(**{**_GPT_KW, **kw}, remat=legacy,
                               remat_policy=policy))
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (1, 128)))
    rng = jax.random.PRNGKey(1) if dropout else None
    fn = jax.grad(lambda p: model.loss(p, tokens, tokens, dropout_rng=rng))
    return jax.make_jaxpr(fn)(params), model, params, tokens


def _names_in(jaxpr) -> set:
    return {e.params["name"] for e in iter_eqns(jaxpr.jaxpr)
            if e.primitive.name == "name"}


def _remat_bodies(jaxpr):
    return [e.params["jaxpr"] for e in iter_eqns(jaxpr.jaxpr)
            if e.primitive.name in ("remat2", "checkpoint")]


def _count_in(jaxpr_like, prim: str) -> int:
    return sum(1 for e in iter_eqns(getattr(jaxpr_like, "jaxpr",
                                            jaxpr_like))
               if e.primitive.name == prim)


class TestJaxprStructure:
    def test_full_identical_to_legacy_and_tagfree(self):
        """policy="full" IS the pre-policy remat=True program: same jaxpr
        as the legacy bool spelling, and zero name equations (the tag
        machinery provably absent)."""
        j_full, model, params, tokens = _gpt_grad_jaxpr("full")
        with pytest.warns(DeprecationWarning):
            j_legacy, lmodel, lparams, _ = _gpt_grad_jaxpr(None,
                                                           legacy=True)
        f = jax.grad(lambda p: model.loss(p, tokens, tokens))
        lf = jax.grad(lambda p: lmodel.loss(p, tokens, tokens))
        assert jaxpr_str(f, params) == jaxpr_str(lf, lparams)
        assert not _names_in(j_full)
        assert _remat_bodies(j_full)

    def test_none_identical_to_default_and_rematfree(self):
        j_none, model, params, tokens = _gpt_grad_jaxpr("none")
        j_default, dmodel, dparams, _ = _gpt_grad_jaxpr(None)
        f = jax.grad(lambda p: model.loss(p, tokens, tokens))
        df = jax.grad(lambda p: dmodel.loss(p, tokens, tokens))
        assert jaxpr_str(f, params) == jaxpr_str(df, dparams)
        assert not _names_in(j_none)
        assert not _remat_bodies(j_none)

    def test_selective_census(self):
        """The acceptance census: every registry tag emitted in the
        forward; saved names NOT recomputed inside the remat region; the
        flash *forward* kernel absent from the recompute (only the two
        backward kernels remain), while full remat reruns it there."""
        j_sel, *_ = _gpt_grad_jaxpr("selective")
        # every registry name is emitted (the flash pair comes from the
        # kernel's custom_vjp fwd rule)
        assert _names_in(j_sel) == set(remat.CHECKPOINT_NAMES)
        bodies = _remat_bodies(j_sel)
        assert bodies
        body_names = set().union(*[{e.params["name"] for e in iter_eqns(b)
                                    if e.primitive.name == "name"}
                                   for b in bodies])
        # saved residuals are dropped from the recompute by DCE; only the
        # deliberately-recomputed LN tier may reappear
        assert body_names <= {"ln_out"}, body_names
        sel_kernels = sum(_count_in(b, "pallas_call") for b in bodies)

        j_full, *_ = _gpt_grad_jaxpr("full")
        full_kernels = sum(_count_in(b, "pallas_call")
                           for b in _remat_bodies(j_full))
        # full: fwd recompute + dq + dkv kernels; selective: dq + dkv only
        assert full_kernels == 3 and sel_kernels == 2, \
            (full_kernels, sel_kernels)
        # both programs run the real forward kernel exactly once outside
        assert _count_in(j_sel, "pallas_call") - sel_kernels == 1
        assert _count_in(j_full, "pallas_call") - full_kernels == 1

    def test_offload_inserts_host_transfers(self):
        j_off, *_ = _gpt_grad_jaxpr("offload")
        assert _names_in(j_off) == set(remat.CHECKPOINT_NAMES)
        # each offloaded residual crosses to host and back
        n_dput = _count_in(j_off, "device_put")
        assert n_dput >= 2 * (len(remat.SELECTIVE_SAVE) - 1), n_dput

    def test_custom_names_narrow_the_saved_set(self):
        j, *_ = _gpt_grad_jaxpr("selective",
                                remat_names=("mlp_fc1_out", "mlp_fc2_out"))
        bodies = _remat_bodies(j)
        body_names = set().union(*[{e.params["name"] for e in iter_eqns(b)
                                    if e.primitive.name == "name"}
                                   for b in bodies])
        # everything outside the custom save-list is now fair recompute
        assert "qkv_out" in body_names and "ln_out" in body_names
        assert "mlp_fc1_out" not in body_names
        # flash residuals unsaved -> the fwd kernel is BACK in the remat
        # region (the failure mode the default save-list exists to avoid)
        assert sum(_count_in(b, "pallas_call") for b in bodies) == 3

    def test_bert_selective_traces_with_tags(self):
        from apex_tpu.models import BertConfig, BertModel

        model = BertModel(BertConfig(
            vocab_size=256, hidden_size=64, num_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            compute_dtype=jnp.float32, params_dtype=jnp.float32,
            use_flash=True, remat_policy="selective"))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (1, 128)))
        jaxpr = jax.make_jaxpr(jax.grad(
            lambda p: model.loss(p, tokens, tokens)))(params)
        assert {"qkv_out", "attn_proj_out", "flash_ctx", "flash_lse",
                "mlp_fc1_out", "mlp_fc2_out", "ln_out"} <= _names_in(jaxpr)
        assert _remat_bodies(jaxpr)


# ---------------------------------------------------------------------------
# schedules accept policies (bool | str | RematPolicy)
# ---------------------------------------------------------------------------

class TestSchedulesRemat:
    def _setup(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_no_pipelining)

        w = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)

        def stage(params, x, idx):
            h = remat.tag(jnp.tanh(x @ params), "qkv_out")
            return h @ params

        def loss_fn(y, m):
            return jnp.mean(y ** 2)

        batch = jnp.asarray(
            np.random.RandomState(1).randn(2, 4, 8), jnp.float32)
        run = lambda r: forward_backward_no_pipelining(
            stage, batch, w, loss_fn=loss_fn, remat=r)
        return run

    def test_policy_spellings_agree_numerically(self):
        run = self._setup()
        base_loss, base_grads = run(False)
        for r in (True, "full", "selective",
                  RematPolicy(mode="selective", names=("qkv_out",))):
            loss, grads = run(r)
            np.testing.assert_allclose(loss, base_loss, rtol=1e-6)
            np.testing.assert_allclose(grads, base_grads, rtol=1e-6)

    def test_bool_true_is_full(self):
        import functools
        run = self._setup()
        assert jaxpr_str(functools.partial(run, True)) \
            == jaxpr_str(functools.partial(run, "full"))
        assert jaxpr_str(functools.partial(run, False)) \
            == jaxpr_str(functools.partial(run, "none"))


# ---------------------------------------------------------------------------
# dropout determinism under recompute (satellite)
# ---------------------------------------------------------------------------

_POLICIES = ("full", "selective", "offload")


class TestDropoutUnderRemat:
    def test_flash_inkernel_dropout_bit_identical(self):
        """The in-kernel (counter-based, seed-keyed) flash dropout must
        regenerate the SAME keep mask when the forward is recomputed:
        grads through a checkpointed call equal the unrematerialized
        grads bitwise. A single flipped mask bit would shift entries by
        O(grad), not epsilon."""
        from apex_tpu.ops.flash_attention import flash_attention

        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2, 128, 16), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, 128, 16), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, 128, 16), jnp.float32)
        dy = jnp.asarray(rng.randn(1, 2, 128, 16), jnp.float32)

        def f(q, k, v):
            out = flash_attention(q, k, v, causal=True, use_pallas=True,
                                  dropout_rate=0.3, dropout_seed=7,
                                  checkpoint_names=True)
            return jnp.sum(out * dy)

        base = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v)
        for mode in _POLICIES:
            wrapped = RematPolicy(mode=mode).wrap(f)
            got = jax.jit(jax.grad(wrapped, argnums=(0, 1, 2)))(q, k, v)
            for b, g in zip(base, got):
                np.testing.assert_array_equal(np.asarray(b), np.asarray(g),
                                              err_msg=mode)

    def test_gpt_dropout_masks_stable_across_policies(self):
        """Model level: hidden + embedding dropout (key-threaded) and
        flash attention dropout (in-kernel) together. Grads under every
        policy match the unrematerialized program far below the O(1)
        signature of a regenerated-differently mask."""
        from apex_tpu.models import GPTConfig, GPTModel

        kw = {**_GPT_KW, "hidden_dropout": 0.1, "attention_dropout": 0.1}
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (1, 128)))
        rng = jax.random.PRNGKey(3)

        def grads_for(policy):
            model = GPTModel(GPTConfig(**kw, remat_policy=policy))
            params = model.init(jax.random.PRNGKey(0))
            return params, jax.jit(jax.grad(
                lambda p: model.loss(p, tokens, tokens,
                                     dropout_rng=rng)))(params)

        p_base, base = grads_for("none")
        for mode in _POLICIES:
            p_got, got = grads_for(mode)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-7,
                    err_msg=mode), base, got)


# ---------------------------------------------------------------------------
# memory accounting: the frontier the policies navigate
# ---------------------------------------------------------------------------

def test_temp_bytes_ordering_none_selective_full():
    """The acceptance ordering on a GPT train-shaped program:
    save-everything > save-GEMM/flash-outputs > save-carry-only. Measured
    off the compiled executables' memory_analysis — the same numbers
    bench_gpt_remat and StepReporter.attach_memory_budget report."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.observability.costs import memory_budget

    kw = dict(vocab_size=512, hidden_size=128, num_layers=2,
              num_attention_heads=4, max_position_embeddings=256,
              compute_dtype=jnp.float32, params_dtype=jnp.float32,
              use_flash=True)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 512, (4, 256)))

    def temp_bytes(policy):
        model = GPTModel(GPTConfig(**kw, remat_policy=policy))
        params = model.init(jax.random.PRNGKey(0))
        compiled = jax.jit(jax.grad(
            lambda p: model.loss(p, tokens, tokens))).lower(
            params).compile()
        budget = memory_budget(compiled)
        if budget is None:
            pytest.skip("backend exposes no memory analysis")
        return budget["temp_bytes"]

    none_b, sel_b, full_b = (temp_bytes(p)
                             for p in ("none", "selective", "full"))
    assert none_b > sel_b > full_b, (none_b, sel_b, full_b)


# ---------------------------------------------------------------------------
# hybrid trainer: policy threads through the whole tp x pp x dp step
# ---------------------------------------------------------------------------

def _trainer_cfg(**model_overrides):
    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    tp, pp, dp = 2, 2, 2
    M, mb, seq = 2, 2, 8
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=64, hidden_size=32,
                          num_layers=2 * pp, num_attention_heads=4,
                          max_position_embeddings=seq, **model_overrides),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0),
        opt_level="O0")
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, dp * mb, seq)))
    return cfg, tokens, targets


def test_trainer_full_policy_jaxpr_identical_to_legacy_bool():
    """The PR 3/4-style identity assertion at the trainer level:
    remat_policy="full" traces the same hybrid train step as the
    deprecated remat=True, with zero name equations; and a selective
    trainer's step carries the registry tags + a remat region."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg_full, tokens, targets = _trainer_cfg(remat_policy="full")
    cfg_legacy, _, _ = _trainer_cfg(remat=True)
    cfg_sel, _, _ = _trainer_cfg(remat_policy="selective")
    mesh = cfg_full.initialize_mesh(devices=jax.devices())
    try:
        full = GPTHybridTrainer(cfg_full, mesh)
        with pytest.warns(DeprecationWarning):
            legacy = GPTHybridTrainer(cfg_legacy, mesh)
        assert full.remat_policy.mode == "full"
        assert legacy.remat_policy.mode == "full"
        state = full.init_state(jax.random.PRNGKey(0))
        args = state + (tokens, targets)
        j_full = jaxpr_str(full.train_step, *args)
        assert jaxpr_str(legacy.train_step, *args) == j_full
        assert " name[" not in j_full and "remat2" in j_full

        sel = GPTHybridTrainer(cfg_sel, mesh)
        assert sel.remat_policy.uses_names
        j_sel = jaxpr_str(sel.train_step, *args)
        assert "remat2" in j_sel
        # seq=8 takes the XLA attention fallback, which still tags the
        # context; the GEMM/LN tags come from the layer body
        for name in ("qkv_out", "attn_proj_out", "mlp_fc1_out",
                     "mlp_fc2_out", "ln_out", "flash_ctx"):
            assert f"name[name={name}]" in j_sel, name
    finally:
        parallel_state.destroy_model_parallel()


def test_trainer_selective_step_runs_and_matches_none():
    """One real optimizer step under selective remat on the 8-device
    mesh reproduces the unrematerialized step's loss and updated params
    (recompute changes schedule, not math)."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state

    cfg_none, tokens, targets = _trainer_cfg()
    cfg_sel, _, _ = _trainer_cfg(remat_policy="selective")
    mesh = cfg_none.initialize_mesh(devices=jax.devices())
    try:
        t_none = GPTHybridTrainer(cfg_none, mesh)
        t_sel = GPTHybridTrainer(cfg_sel, mesh)
        s0 = t_none.init_state(jax.random.PRNGKey(0))
        s1 = t_sel.init_state(jax.random.PRNGKey(0))
        loss0, *out0 = jax.jit(t_none.train_step)(*s0, tokens, targets)
        loss1, *out1 = jax.jit(t_sel.train_step)(*s1, tokens, targets)
        np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            out0[0], out1[0])
    finally:
        parallel_state.destroy_model_parallel()
