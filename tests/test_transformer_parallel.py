"""Transformer parallel toolkit tests on the 8-device CPU mesh.

Models: ``reference:tests/L0/run_transformer/`` — ``test_parallel_state.py``,
``test_mapping.py``, ``test_layers.py``, ``test_cross_entropy.py``,
``test_data.py``, ``test_random.py``, ``test_microbatches.py``,
``test_pipeline_parallel_fwd_bwd.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.pipeline_parallel import (
    ConstantNumMicroBatches, RampupBatchsizeNumMicroBatches,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    get_forward_backward_func, get_ltor_masks_and_position_ids,
    pipelined_apply)


@pytest.fixture
def mesh_tp2_pp2():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


@pytest.fixture
def mesh_tp4():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=4)
    yield mesh
    parallel_state.destroy_model_parallel()


@pytest.fixture
def mesh_pp4():
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=4)
    yield mesh
    parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# parallel_state (test_parallel_state.py)
# ---------------------------------------------------------------------------

def test_parallel_state_sizes_and_groups(mesh_tp2_pp2):
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_data_parallel_world_size() == 2
    # group membership matches reference rank math (tp fastest, dp, pp)
    assert parallel_state.get_tensor_model_parallel_groups() == [
        [0, 1], [2, 3], [4, 5], [6, 7]]
    assert parallel_state.get_data_parallel_groups() == [
        [0, 2], [1, 3], [4, 6], [5, 7]]
    assert parallel_state.get_pipeline_model_parallel_groups() == [
        [0, 4], [1, 5], [2, 6], [3, 7]]
    assert parallel_state.get_embedding_ranks() == [
        [0, 4], [1, 5], [2, 6], [3, 7]]


def test_parallel_state_validation():
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(tensor_model_parallel_size=3)
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2)
    assert not parallel_state.model_parallel_is_initialized()


# ---------------------------------------------------------------------------
# mappings (test_mapping.py)
# ---------------------------------------------------------------------------

def test_mappings_roundtrip_and_grads(mesh_tp4):
    mesh = parallel_state.get_mesh()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)

    def body(x):
        # scatter then gather is identity (test_mapping.py parity); the
        # gathered value is device-varying-but-equal, so cross the shard_map
        # boundary with a pmean (no-op on equal values)
        s = tp.scatter_to_tensor_model_parallel_region(x)
        g = tp.gather_from_tensor_model_parallel_region(s)
        return jax.lax.pmean(g, "tensor")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(), out_specs=P()))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    # copy fwd is identity; bwd is psum: grad of sum over ranks = tp * ones
    def loss(x):
        def inner(x):
            y = tp.copy_to_tensor_model_parallel_region(x)
            return jax.lax.psum(jnp.sum(y), "tensor") / 4.0
        return shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P())(x)

    g = jax.jit(jax.grad(loss))(x)
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x), rtol=1e-6)


# ---------------------------------------------------------------------------
# TP layers (test_layers.py): sharded == unsharded
# ---------------------------------------------------------------------------

def test_column_row_parallel_linear_match_dense(mesh_tp4):
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(6, 16), jnp.float32)

    col = tp.ColumnParallelLinear(16, 32, gather_output=True)
    row = tp.RowParallelLinear(32, 16, input_is_parallel=False)
    cp = col.init(jax.random.PRNGKey(0))
    rp = row.init(jax.random.PRNGKey(1))

    def fwd(cp, rp, x):
        def inner(cp, rp, x):
            h, _ = col(cp, x)
            out, _ = row(rp, h)
            # varying-but-equal (per-rank bias copies); pmean to cross out
            return jax.lax.pmean(out, "tensor")
        return shard_map(
            inner, mesh=mesh,
            in_specs=(P("tensor"), P("tensor"), P()), out_specs=P())(cp, rp, x)

    out = jax.jit(fwd)(cp, rp, x)

    # dense reference from the full stacked weights
    w_col = np.asarray(cp["weight"]).reshape(32, 16)
    b_col = np.asarray(cp["bias"]).reshape(32)
    w_row = np.concatenate(list(np.asarray(rp["weight"])), axis=1)  # (16,32)
    b_row = np.asarray(rp["bias"])[0]
    ref = np.asarray(x) @ w_col.T + b_col
    ref = ref @ w_row.T + b_row
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)

    # grads flow through both layers
    def loss(cp, rp):
        return jnp.sum(fwd(cp, rp, x) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1)))(cp, rp)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_row_parallel_grads_match_dense(mesh_tp4):
    """TP=4 weight AND bias grads equal the dense (TP=1) grads on every rank
    (ADVICE r1: the bias copies used to receive grad/tp)."""
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 16), jnp.float32)

    row = tp.RowParallelLinear(16, 8, input_is_parallel=False, world_size=4)
    params = row.init(jax.random.PRNGKey(0))
    params = {"weight": params["weight"], "bias": params["bias"] + 0.3}

    def loss_tp(params, x):
        y, _ = row(params, x)
        return jnp.sum(y ** 2)

    def run(params, x):
        def inner(params, x):
            l, g = jax.value_and_grad(loss_tp)(params, x)
            return jax.lax.pmean(l, "tensor"), g
        specs = {"weight": P("tensor"), "bias": P("tensor")}
        return shard_map(inner, mesh=mesh, in_specs=(specs, P()),
                         out_specs=(P(), specs))(params, x)

    l, g = jax.jit(run)(params, x)

    w_full = jnp.concatenate([params["weight"][i] for i in range(4)], axis=1)
    b_full = params["bias"][0]

    def loss_dense(w, b, x):
        return jnp.sum((x @ w.T + b) ** 2)

    ld, (gw, gb) = jax.value_and_grad(loss_dense, argnums=(0, 1))(
        w_full, b_full, x)
    np.testing.assert_allclose(float(l), float(ld), rtol=1e-5)
    for i in range(4):
        # every replicated bias copy gets the FULL dense grad, not grad/tp
        np.testing.assert_allclose(np.asarray(g["bias"][i]), np.asarray(gb),
                                   rtol=1e-5)
    gw_tp = jnp.concatenate([g["weight"][i] for i in range(4)], axis=1)
    np.testing.assert_allclose(np.asarray(gw_tp), np.asarray(gw),
                               rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding(mesh_tp4):
    mesh = parallel_state.get_mesh()
    emb = tp.VocabParallelEmbedding(64, 16)
    ep = emb.init(jax.random.PRNGKey(2))
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 64, (4, 10)))

    out = jax.jit(shard_map(
        lambda p, i: jax.lax.pmean(emb(p, i), "tensor"), mesh=mesh,
        in_specs=(P("tensor"), P()), out_specs=P()))(ep, ids)

    full = np.asarray(ep["weight"]).reshape(64, 16)
    np.testing.assert_allclose(np.asarray(out), full[np.asarray(ids)],
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# vocab-parallel cross entropy (test_cross_entropy.py)
# ---------------------------------------------------------------------------

def test_vocab_parallel_cross_entropy_vs_torch(mesh_tp4):
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(4)
    logits = rng.randn(5, 7, 32).astype(np.float32)
    target = rng.randint(0, 32, (5, 7))

    # shard logits along vocab: (5,7,32) -> per-rank (5,7,8)
    def run(logits, target):
        return shard_map(
            lambda l, t: tp.vocab_parallel_cross_entropy(l, t),
            mesh=mesh, in_specs=(P(None, None, "tensor"), P()),
            out_specs=P())(logits, target)

    loss = jax.jit(run)(jnp.asarray(logits), jnp.asarray(target))
    ref = torch.nn.functional.cross_entropy(
        torch.tensor(logits).reshape(-1, 32), torch.tensor(target).reshape(-1),
        reduction="none").reshape(5, 7)
    np.testing.assert_allclose(np.asarray(loss), ref.numpy(), rtol=1e-5,
                               atol=1e-5)

    # grads match dense softmax-CE
    def j_loss(l):
        return jnp.sum(run(l, jnp.asarray(target)))

    g = jax.jit(jax.grad(j_loss))(jnp.asarray(logits))
    tl = torch.tensor(logits, requires_grad=True)
    torch.nn.functional.cross_entropy(
        tl.reshape(-1, 32), torch.tensor(target).reshape(-1),
        reduction="sum").backward()
    np.testing.assert_allclose(np.asarray(g), tl.grad.numpy(), rtol=1e-4,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# data broadcast (test_data.py)
# ---------------------------------------------------------------------------

def test_broadcast_data(mesh_tp4):
    mesh = parallel_state.get_mesh()
    # rank-varying input: only rank 0's survives
    data = jnp.arange(4 * 3, dtype=jnp.float32).reshape(4, 3)

    def body(x):
        # x arrives sharded over tensor: each rank has (1, 3) — its "own" data
        out = tp.broadcast_data(["k"], {"k": x})["k"]
        return out

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("tensor"), out_specs=P("tensor")))(data)
    # every rank's slot now holds rank 0's row
    np.testing.assert_allclose(np.asarray(out),
                               np.tile(np.asarray(data[0:1]), (4, 1)),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# RNG (test_random.py)
# ---------------------------------------------------------------------------

def test_rng_tracker_semantics():
    tp.model_parallel_seed(1234, tensor_rank=0)
    tracker = tp.get_rng_tracker()
    states0 = tracker.get_states()
    with tracker.fork() as key_a:
        pass
    with tracker.fork() as key_b:
        pass
    assert not np.array_equal(np.asarray(key_a), np.asarray(key_b))
    # restore replays the stream
    tracker.set_states(states0)
    with tracker.fork() as key_a2:
        pass
    np.testing.assert_array_equal(np.asarray(key_a), np.asarray(key_a2))
    # tp ranks get distinct streams; same seed reproduces
    tp.model_parallel_seed(1234, tensor_rank=1)
    with tp.get_rng_tracker().fork() as key_r1:
        pass
    assert not np.array_equal(np.asarray(key_a), np.asarray(key_r1))
    with pytest.raises(Exception):
        tp.get_rng_tracker().add("default", 1)
    with pytest.raises(Exception):
        tp.get_rng_tracker().make_key("nonexistent")


# ---------------------------------------------------------------------------
# microbatches (test_microbatches.py)
# ---------------------------------------------------------------------------

def test_microbatch_calculators():
    const = ConstantNumMicroBatches(64, 2, 4)
    assert const.get() == 8
    ramp = RampupBatchsizeNumMicroBatches(
        start_batch_size=8, batch_size_increment=8, ramup_samples=80,
        global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
    assert ramp.get() == 2  # 8/(2*2)
    ramp.update(40, False)
    assert ramp.get_current_global_batch_size() == 16
    ramp.update(1000, False)
    assert ramp.get() == 8  # 32/(2*2)


# ---------------------------------------------------------------------------
# pipeline schedules (test_pipeline_parallel_fwd_bwd.py)
# ---------------------------------------------------------------------------

def _stage_fn(chunk_params, x, stage_idx):
    """Uniform affine stage: y = tanh(x @ w + b)."""
    return jnp.tanh(x @ chunk_params["w"] + chunk_params["b"])


def test_pipelined_apply_matches_sequential(mesh_pp4):
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(5)
    d = 8
    # per-stage params, stacked (pp=4, d, d)
    ws = jnp.asarray(rng.randn(4, d, d) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(4, d) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(6, 2, d), jnp.float32)  # M=6, mb=2

    def run(ws, bs, micro):
        def inner(ws, bs, micro):
            # local stage params arrive sharded: (1, d, d) -> chunk axis
            params = {"w": ws[0][None], "b": bs[0][None]}
            params = jax.tree_util.tree_map(lambda p: p, params)
            out = pipelined_apply(
                lambda cp, x, s: _stage_fn(
                    {"w": cp["w"], "b": cp["b"]}, x, s),
                {"w": ws, "b": bs}, micro, num_chunks=1)
            # conservatively varying-but-equal over data/tensor: pmean out
            return jax.lax.pmean(jax.lax.pmean(out, "data"), "tensor")
        return shard_map(
            inner, mesh=mesh,
            in_specs=(P("pipe"), P("pipe"), P()), out_specs=P())(ws, bs, micro)

    out = jax.jit(run)(ws, bs, micro)

    # sequential reference
    ref = np.asarray(micro)
    for s in range(4):
        ref = np.tanh(ref @ np.asarray(ws[s]) + np.asarray(bs[s]))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_pipeline_fwd_bwd_matches_no_pipelining(mesh_pp4):
    """All three schedules produce the same loss and equivalent grads
    (the cross-schedule consistency the reference test sweeps)."""
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(6)
    d = 8
    ws = jnp.asarray(rng.randn(4, d, d) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(4, d) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(6, 2, d), jnp.float32)
    targets = jnp.asarray(rng.randn(6, 2, d), jnp.float32)

    def loss_fn_of(targets):
        def loss_fn(y, m):
            t = jax.lax.dynamic_index_in_dim(targets, m, 0, keepdims=False)
            return jnp.mean((y - t) ** 2)
        return loss_fn

    # pipelined over pipe axis
    def run_pipe(ws, bs):
        def inner(ws, bs):
            loss, grads = forward_backward_pipelining_without_interleaving(
                _stage_fn, micro, {"w": ws[0], "b": bs[0]},
                loss_fn=loss_fn_of(targets))
            pm = lambda x: jax.lax.pmean(jax.lax.pmean(x, "data"), "tensor")
            return pm(loss), jax.tree_util.tree_map(pm, grads)
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"), P("pipe")),
                         out_specs=(P(), P("pipe")))(ws, bs)

    loss_pipe, grads_pipe = jax.jit(run_pipe)(ws, bs)

    # sequential reference: no pipelining, full model on one device
    def full_model(params, mb):
        x, t = mb
        for s in range(4):
            x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x, s)
        return jnp.mean((x - t) ** 2)

    loss_ref, grads_ref = forward_backward_no_pipelining(
        full_model, (micro, targets), {"w": ws, "b": bs})

    np.testing.assert_allclose(float(loss_pipe), float(loss_ref), rtol=1e-5)
    # out_specs=P("pipe") concatenates per-stage grads on axis 0
    np.testing.assert_allclose(
        np.asarray(grads_pipe["w"]).reshape(4, d, d),
        np.asarray(grads_ref["w"]), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads_pipe["b"]).reshape(4, d),
        np.asarray(grads_ref["b"]), rtol=1e-4, atol=1e-5)


def test_interleaved_schedule(mesh_pp4):
    """vpp=2: 8 global stages round-robin over 4 devices; must equal the
    sequential 8-layer model."""
    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(7)
    d = 8
    # global stage g = c*4 + dev -> device holds chunks stacked on axis 0
    ws_global = jnp.asarray(rng.randn(8, d, d) * 0.2, jnp.float32)
    bs_global = jnp.asarray(rng.randn(8, d) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.randn(5, 2, d), jnp.float32)
    targets = jnp.asarray(rng.randn(5, 2, d), jnp.float32)

    # rearrange to (dev, chunk, ...): dev d gets stages [d, d+4]
    ws_dev = jnp.stack([jnp.stack([ws_global[c * 4 + dev] for c in range(2)])
                        for dev in range(4)])
    bs_dev = jnp.stack([jnp.stack([bs_global[c * 4 + dev] for c in range(2)])
                        for dev in range(4)])

    def loss_fn(y, m):
        t = jax.lax.dynamic_index_in_dim(targets, m, 0, keepdims=False)
        return jnp.mean((y - t) ** 2)

    def run(ws, bs):
        def inner(ws, bs):
            loss, grads = forward_backward_pipelining_with_interleaving(
                _stage_fn, micro, {"w": ws[0], "b": bs[0]},
                loss_fn=loss_fn, num_model_chunks=2)
            pm = lambda x: jax.lax.pmean(jax.lax.pmean(x, "data"), "tensor")
            return pm(loss), jax.tree_util.tree_map(pm, grads)
        return shard_map(inner, mesh=mesh, in_specs=(P("pipe"), P("pipe")),
                         out_specs=(P(), P("pipe")))(ws, bs)

    loss_pipe, grads = jax.jit(run)(ws_dev, bs_dev)

    # sequential reference
    def full_model(params, mb):
        x, t = mb
        for g in range(8):
            x = _stage_fn({"w": params["w"][g], "b": params["b"][g]}, x, g)
        return jnp.mean((x - t) ** 2)

    loss_ref, grads_ref = forward_backward_no_pipelining(
        full_model, (micro, targets), {"w": ws_global, "b": bs_global})
    np.testing.assert_allclose(float(loss_pipe), float(loss_ref), rtol=1e-5)
    # grads: out_specs P("pipe") stacks per-device chunk grads, so entry
    # [dev*2 + c] is global stage c*4 + dev — must match the sequential ref
    gw = np.asarray(grads["w"]).reshape(4, 2, d, d)
    gb = np.asarray(grads["b"]).reshape(4, 2, d)
    for dev in range(4):
        for c in range(2):
            g = c * 4 + dev
            np.testing.assert_allclose(
                gw[dev, c], np.asarray(grads_ref["w"])[g],
                rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(
                gb[dev, c], np.asarray(grads_ref["b"])[g],
                rtol=1e-4, atol=1e-5)


def test_get_forward_backward_func_dispatch():
    assert get_forward_backward_func(None, 1) is forward_backward_no_pipelining
    assert (get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving)
    assert (get_forward_backward_func(2, 4)
            is forward_backward_pipelining_with_interleaving)


def test_ltor_masks_and_position_ids():
    data = jnp.asarray([[5, 1, 9, 1, 3]])  # eod=1
    mask, loss_mask, pos = get_ltor_masks_and_position_ids(
        data, eod_token=1, reset_position_ids=True,
        reset_attention_mask=True, eod_mask_loss=True)
    # loss masked at eod positions
    np.testing.assert_array_equal(np.asarray(loss_mask[0]),
                                  [1, 0, 1, 0, 1])
    # position ids reset after eod: docs are [5,1], [9,1], [3]
    np.testing.assert_array_equal(np.asarray(pos[0]), [0, 1, 0, 1, 0])
    # attention cannot cross document boundaries: pos 2 can't see pos 0
    assert bool(mask[0, 0, 2, 0])
    assert not bool(mask[0, 0, 3, 2])


def test_dispatch_uniform_call_shape():
    """The dispatcher's pp=1 branch accepts the pipelined call shape."""
    rng = np.random.RandomState(9)
    d = 8
    params = {"w": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32),
              "b": jnp.zeros(d)}
    micro = jnp.asarray(rng.randn(3, 2, d), jnp.float32)
    targets = jnp.asarray(rng.randn(3, 2, d), jnp.float32)

    def loss_fn(y, m):
        t = jax.lax.dynamic_index_in_dim(targets, m, 0, keepdims=False)
        return jnp.mean((y - t) ** 2)

    f = get_forward_backward_func(None, 1)
    loss, grads = f(_stage_fn, micro, params, loss_fn=loss_fn)
    # direct reference
    def full(params, mb):
        x, t = mb
        return jnp.mean((_stage_fn(params, x, 0) - t) ** 2)
    loss_ref, grads_ref = forward_backward_no_pipelining(
        full, (micro, targets), params)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(grads_ref["w"]), rtol=1e-5)


def test_gpt_pipelined_embedding_and_tied_head(mesh_pp4):
    """The full-model pipeline decomposition (embedding on stage 0, final
    LN + tied logits + LM loss on the last stage) reproduces the single-chip
    GPT loss AND grads — including the tied embedding's grad, which receives
    both the stage-0 lookup contribution and the last-stage logit
    contribution via the pipe-axis psum (the reference's embedding-group
    allreduce, ``reference:apex/transformer/parallel_state.py:215-247``)."""
    from apex_tpu.models import GPTConfig, GPTModel

    mesh = parallel_state.get_mesh()
    PP, M, mb, seq = 4, 8, 2, 8
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_attention_heads=4, max_position_embeddings=seq,
                    compute_dtype=jnp.float32, use_flash=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, (M, mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, mb, seq)))

    stage, embed_fn, head_fn, split_params, shared_of = model.pipeline_fns(
        PP, targets)
    stage_stack = split_params(params)      # leaves (PP, per, ...)
    shared = shared_of(params)

    def run_pipe(stage_stack, shared):
        def inner(stage_stack, shared):
            my_stage = jax.tree_util.tree_map(lambda p: p[0], stage_stack)
            loss, (sg, shg) = \
                forward_backward_pipelining_without_interleaving(
                    stage, tokens, my_stage, loss_fn=head_fn,
                    shared_params=shared, embed_fn=embed_fn)
            pm = lambda x: jax.lax.pmean(jax.lax.pmean(x, "data"), "tensor")
            sg = jax.tree_util.tree_map(lambda g: pm(g)[None], sg)
            return pm(loss), sg, jax.tree_util.tree_map(pm, shg)
        spec = jax.tree_util.tree_map(lambda _: P("pipe"), stage_stack)
        shspec = jax.tree_util.tree_map(lambda _: P(), shared)
        return shard_map(inner, mesh=mesh,
                         in_specs=(spec, shspec),
                         out_specs=(P(), spec, shspec))(stage_stack, shared)

    loss_pipe, stage_grads, shared_grads = jax.jit(run_pipe)(
        stage_stack, shared)

    # single-chip reference: same loss = mean over microbatches
    def ref_loss(params):
        losses = jax.vmap(
            lambda tok, tgt: model.loss(params, tok, tgt))(tokens, targets)
        return jnp.mean(losses)

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)

    np.testing.assert_allclose(float(loss_pipe), float(loss_ref), rtol=2e-5)
    # layer grads: pipelined (PP, per, ...) vs reference (num_layers, ...)
    ref_layers = split_params(grads_ref)
    for a, b in zip(jax.tree_util.tree_leaves(stage_grads),
                    jax.tree_util.tree_leaves(ref_layers)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    # shared grads: embedding (tied: lookup + logits contributions) + final ln
    ref_shared = shared_of(grads_ref)
    for (ka, a), b in zip(
            jax.tree_util.tree_leaves_with_path(shared_grads),
            jax.tree_util.tree_leaves(ref_shared)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=str(ka))
    # the tied embedding grad must actually mix both contributions: it is
    # nonzero (lookup path) and differs from an untied-head run's grad
    emb = np.asarray(shared_grads["embedding"]["word"]["weight"])
    assert np.abs(emb).max() > 0


def test_stage_predicates_with_explicit_virtual_rank():
    """Virtual-chunk predicates take the chunk index explicitly (traced or
    host); the module-global remains reference-API compat only."""
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=4,
        virtual_pipeline_model_parallel_size=2)
    try:
        def inner():
            first = parallel_state.is_pipeline_first_stage(virtual_rank=0)
            not_first = parallel_state.is_pipeline_first_stage(
                virtual_rank=1)
            last = parallel_state.is_pipeline_last_stage(virtual_rank=1)
            not_last = parallel_state.is_pipeline_last_stage(virtual_rank=0)
            return tuple(
                jnp.reshape(v.astype(jnp.int32), (1,))
                for v in (first, not_first, last, not_last))

        outs = shard_map(inner, mesh=mesh, in_specs=(),
                         out_specs=(P("pipe"),) * 4)()
        first, not_first, last, not_last = (np.asarray(o) for o in outs)
        assert first.tolist() == [1, 0, 0, 0]
        assert not_first.tolist() == [0, 0, 0, 0]
        assert last.tolist() == [0, 0, 0, 1]
        assert not_last.tolist() == [0, 0, 0, 0]
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_interleaved_pipeline_with_embedding_head(mesh_pp4):
    """Virtual-pipeline (vpp=2) GPT with the pipelined embedding + tied
    head: Megatron chunk layout (chunk c on device d = global stage
    c*S + d), loss and shared grads matching single-chip."""
    from apex_tpu.models import GPTConfig, GPTModel

    mesh = parallel_state.get_mesh()
    S, VPP, M, mb, seq = 4, 2, 8, 2, 8
    L = S * VPP  # one layer per global stage
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=L,
                    num_attention_heads=4, max_position_embeddings=seq,
                    compute_dtype=jnp.float32, use_flash=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(3))
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, 64, (M, mb, seq)))
    targets = jnp.asarray(rng.randint(0, 64, (M, mb, seq)))

    stage, embed_fn, head_fn, split_params, shared_of = model.pipeline_fns(
        L, targets)
    # (L, per=1, ...) -> (VPP, S, per, ...): axis 1 shards over pipe
    chunked = jax.tree_util.tree_map(
        lambda p: p.reshape(VPP, S, *p.shape[1:]), split_params(params))
    shared = shared_of(params)

    def run(chunked, shared):
        def inner(chunked, shared):
            mine = jax.tree_util.tree_map(lambda p: p[:, 0], chunked)
            loss, (sg, shg) = forward_backward_pipelining_with_interleaving(
                stage, tokens, mine, loss_fn=head_fn,
                num_model_chunks=VPP, shared_params=shared,
                embed_fn=embed_fn)
            pm = lambda x: jax.lax.pmean(jax.lax.pmean(x, "data"), "tensor")
            sg = jax.tree_util.tree_map(lambda g: pm(g)[:, None], sg)
            return pm(loss), sg, jax.tree_util.tree_map(pm, shg)
        spec = jax.tree_util.tree_map(lambda _: P(None, "pipe"), chunked)
        shspec = jax.tree_util.tree_map(lambda _: P(), shared)
        return shard_map(inner, mesh=mesh, in_specs=(spec, shspec),
                         out_specs=(P(), spec, shspec))(chunked, shared)

    loss_pipe, chunk_grads, shared_grads = jax.jit(run)(chunked, shared)

    def ref_loss(params):
        return jnp.mean(jax.vmap(
            lambda tok, tgt: model.loss(params, tok, tgt))(tokens, targets))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss_pipe), float(loss_ref), rtol=2e-5)

    # chunk grads back to (L, ...) layer order: global stage g = c*S + d
    for a, b in zip(jax.tree_util.tree_leaves(chunk_grads),
                    jax.tree_util.tree_leaves(
                        split_params(grads_ref))):
        a = np.asarray(a)           # (VPP, S, per, ...)
        a = a.reshape(L, *a.shape[2:])
        np.testing.assert_allclose(a, np.asarray(b), rtol=5e-4, atol=5e-5)
    for (ka, a), b in zip(
            jax.tree_util.tree_leaves_with_path(shared_grads),
            jax.tree_util.tree_leaves(shared_of(grads_ref))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=str(ka))
