"""Optimizer parity tests.

Model: ``reference:tests/L0/run_optimizers/test_fused_optimizer.py`` /
``test_lamb.py`` — each fused optimizer is checked against an independent
reference implementation over random parameter sets. Here the references are
``torch.optim`` (CPU) where one exists, plus hand-written numpy for LAMB/
NovoGrad semantics that torch doesn't ship.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opt_mod
from apex_tpu.amp.scaler import all_finite
from apex_tpu.multi_tensor_apply import (
    flatten, multi_tensor_axpby, multi_tensor_l2norm, multi_tensor_scale,
    unflatten)


def _rand_tree(seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.randn(17, 9).astype(dtype),
        "b": rng.randn(9).astype(dtype),
        "emb": {"table": rng.randn(31, 7).astype(dtype)},
    }


def _to_torch(tree):
    return jax.tree_util.tree_map(
        lambda x: torch.tensor(np.asarray(x, np.float32), requires_grad=True), tree)


def _assign_grads(tparams, grads):
    for tp, g in zip(jax.tree_util.tree_leaves(tparams),
                     jax.tree_util.tree_leaves(grads)):
        tp.grad = torch.tensor(np.asarray(g, np.float32))


def _assert_close(jtree, ttree, rtol=1e-5, atol=1e-6):
    for j, t in zip(jax.tree_util.tree_leaves(jtree),
                    jax.tree_util.tree_leaves(ttree)):
        np.testing.assert_allclose(np.asarray(j), t.detach().numpy(),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("adam_w_mode", [True, False])
@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_fused_adam_vs_torch(adam_w_mode, wd):
    params = _rand_tree(1)
    opt = opt_mod.FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=adam_w_mode)
    state = opt.init(params)

    tparams = _to_torch(params)
    tcls = torch.optim.AdamW if adam_w_mode else torch.optim.Adam
    topt = tcls(jax.tree_util.tree_leaves(tparams), lr=1e-2, weight_decay=wd,
                eps=1e-8)

    jp = params
    for step in range(5):
        grads = _rand_tree(100 + step)
        jp, state = opt.step(grads, state, jp)
        _assign_grads(tparams, grads)
        topt.step()
    _assert_close(jp, tparams, rtol=2e-5, atol=1e-6)


def test_fused_sgd_vs_torch():
    params = _rand_tree(2)
    opt = opt_mod.FusedSGD(lr=0.05, momentum=0.9, weight_decay=0.01)
    state = opt.init(params)
    tparams = _to_torch(params)
    topt = torch.optim.SGD(jax.tree_util.tree_leaves(tparams), lr=0.05,
                           momentum=0.9, weight_decay=0.01)
    jp = params
    for step in range(5):
        grads = _rand_tree(200 + step)
        jp, state = opt.step(grads, state, jp)
        _assign_grads(tparams, grads)
        topt.step()
    _assert_close(jp, tparams, rtol=1e-5, atol=1e-6)


def test_fused_adagrad_vs_torch():
    params = _rand_tree(3)
    opt = opt_mod.FusedAdagrad(lr=0.02, eps=1e-10, weight_decay=0.05)
    state = opt.init(params)
    tparams = _to_torch(params)
    topt = torch.optim.Adagrad(jax.tree_util.tree_leaves(tparams), lr=0.02,
                               eps=1e-10, weight_decay=0.05)
    jp = params
    for step in range(4):
        grads = _rand_tree(300 + step)
        jp, state = opt.step(grads, state, jp)
        _assign_grads(tparams, grads)
        topt.step()
    _assert_close(jp, tparams, rtol=1e-5, atol=1e-6)


def _ref_lamb_step(params, grads, m, v, t, *, lr, b1, b2, eps, wd,
                   adam_w_mode=True, max_grad_norm=1.0, use_nvlamb=False,
                   grad_averaging=True):
    """Numpy LAMB mirroring multi_tensor_lamb.cu exactly."""
    flat = np.concatenate([g.ravel() for g in grads])
    gn = np.sqrt((flat.astype(np.float64) ** 2).sum())
    clip = gn / max_grad_norm if gn > max_grad_norm else 1.0
    bc1 = 1 - b1 ** t
    bc2 = 1 - b2 ** t
    beta3 = 1 - b1 if grad_averaging else 1.0
    out_p, out_m, out_v = [], [], []
    for p, g, mm, vv in zip(params, grads, m, v):
        sg = g / clip
        if not adam_w_mode:
            sg = sg + wd * p
        mm = b1 * mm + beta3 * sg
        vv = b2 * vv + (1 - b2) * sg * sg
        upd = (mm / bc1) / (np.sqrt(vv / bc2) + eps)
        if adam_w_mode:
            upd = upd + wd * p
        if use_nvlamb or wd != 0.0:
            pn = np.sqrt((p ** 2).sum())
            un = np.sqrt((upd ** 2).sum())
            ratio = lr * pn / un if (pn != 0 and un != 0) else lr
        else:
            ratio = lr
        out_p.append(p - ratio * upd)
        out_m.append(mm)
        out_v.append(vv)
    return out_p, out_m, out_v


@pytest.mark.parametrize("wd,use_nvlamb", [(0.0, False), (0.01, False), (0.01, True)])
def test_fused_lamb_vs_numpy(wd, use_nvlamb):
    params = _rand_tree(4)
    opt = opt_mod.FusedLAMB(lr=1e-2, weight_decay=wd, use_nvlamb=use_nvlamb)
    state = opt.init(params)

    leaves, treedef = jax.tree_util.tree_flatten(params)
    np_p = [np.asarray(l, np.float64) for l in leaves]
    np_m = [np.zeros_like(p) for p in np_p]
    np_v = [np.zeros_like(p) for p in np_p]

    jp = params
    for step in range(1, 4):
        grads = _rand_tree(400 + step)
        jp, state = opt.step(grads, state, jp)
        gl = [np.asarray(g, np.float64)
              for g in jax.tree_util.tree_leaves(grads)]
        np_p, np_m, np_v = _ref_lamb_step(
            np_p, gl, np_m, np_v, step, lr=1e-2, b1=0.9, b2=0.999, eps=1e-6,
            wd=wd, use_nvlamb=use_nvlamb)
    for j, n in zip(jax.tree_util.tree_leaves(jp), np_p):
        np.testing.assert_allclose(np.asarray(j), n, rtol=3e-5, atol=1e-6)


def test_novograd_moves_and_norm_seeding():
    params = _rand_tree(5)
    opt = opt_mod.FusedNovoGrad(lr=1e-2, weight_decay=0.01)
    state = opt.init(params)
    grads = _rand_tree(500)
    jp, state = opt.step(grads, state, params)
    # first step seeds v = ||g|| per tensor
    for g, v in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(state.exp_avg_sq)):
        np.testing.assert_allclose(
            float(v), float(np.sqrt((np.asarray(g) ** 2).sum())), rtol=1e-5)
    assert not np.allclose(np.asarray(jp["w"]), params["w"])


def test_larc_clips_rate():
    params = _rand_tree(6)
    inner = opt_mod.FusedSGD(lr=0.1, momentum=0.0, weight_decay=0.1)
    larc = opt_mod.LARC(inner, trust_coefficient=0.02)
    state = larc.init(params)
    grads = _rand_tree(600)
    jp, state = larc.step(grads, state, params)
    # torch reference: LARC.py grad rewrite then vanilla SGD with wd=0
    tleaves = [torch.tensor(np.asarray(p), requires_grad=True)
               for p in jax.tree_util.tree_leaves(params)]
    gleaves = [torch.tensor(np.asarray(g))
               for g in jax.tree_util.tree_leaves(grads)]
    for p, g in zip(tleaves, gleaves):
        pn, gn = p.detach().norm(), g.norm()
        alr = 0.02 * pn / (gn + pn * 0.1 + 1e-8)
        alr = torch.clamp(alr / 0.1, max=1.0)
        p.grad = (g + 0.1 * p.detach()) * alr
    topt = torch.optim.SGD(tleaves, lr=0.1)
    topt.step()
    _assert_close(jp, tleaves, rtol=1e-5, atol=1e-6)


def test_overflow_skip_keeps_params_and_step():
    params = _rand_tree(7)
    opt = opt_mod.FusedAdam(lr=1e-2)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, _rand_tree(700))
    grads = dict(grads, w=grads["w"].at[0, 0].set(jnp.inf))
    finite = all_finite(grads)
    assert not bool(finite)
    new_p, new_state = opt.step(grads, state, params, grads_finite=finite)
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(new_state.step) == 0


def test_step_is_jittable():
    params = jax.tree_util.tree_map(jnp.asarray, _rand_tree(8))
    opt = opt_mod.FusedLAMB(lr=1e-3)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, _rand_tree(800))

    @jax.jit
    def train_step(g, s, p):
        return opt.step(g, s, p, grads_finite=all_finite(g))

    new_p, new_s = train_step(grads, state, params)
    assert int(new_s.step) == 1


def test_multi_tensor_ops():
    tree = jax.tree_util.tree_map(jnp.asarray, _rand_tree(9))
    scaled, finite = multi_tensor_scale(tree, 0.5)
    assert bool(finite)
    np.testing.assert_allclose(np.asarray(scaled["w"]),
                               np.asarray(tree["w"]) * 0.5, rtol=1e-6)
    bad = dict(tree, w=tree["w"].at[0, 0].set(jnp.nan))
    _, finite = multi_tensor_scale(bad, 1.0)
    assert not bool(finite)

    out, finite = multi_tensor_axpby(2.0, tree, 3.0, tree)
    np.testing.assert_allclose(np.asarray(out["b"]),
                               np.asarray(tree["b"]) * 5.0, rtol=1e-6)

    gnorm, per = multi_tensor_l2norm(tree, per_tensor=True)
    flat, unravel = flatten(tree)
    np.testing.assert_allclose(
        float(gnorm), float(jnp.sqrt((flat ** 2).sum())), rtol=1e-6)
    rt = unflatten(flat, unravel)
    np.testing.assert_allclose(np.asarray(rt["w"]), np.asarray(tree["w"]))


def test_optax_adapter():
    import optax
    params = jax.tree_util.tree_map(jnp.asarray, _rand_tree(10))
    tx = opt_mod.FusedAdam(lr=1e-2).as_optax()
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, _rand_tree(1000))
    updates, state = tx.update(grads, state, params)
    new_p = optax.apply_updates(params, updates)
    direct_p, _ = opt_mod.FusedAdam(lr=1e-2).step(
        grads, opt_mod.FusedAdam(lr=1e-2).init(params), params)
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(direct_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.parametrize("inner_cls,kw", [
    (opt_mod.FusedSGD, dict(lr=0.1, momentum=0.9, weight_decay=1e-4)),
    (opt_mod.FusedAdam, dict(lr=1e-2, weight_decay=0.1)),
    (opt_mod.FusedAdagrad, dict(lr=1e-2)),
])
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
def test_flat_optimizer_parity(inner_cls, kw, dtype):
    """FlatOptimizer(inner) == inner over a multi-leaf tree, for fp32 and
    bf16 params. Both paths widen (grad, param) to fp32 inside the update and
    cast back to the param dtype, so flattening commutes with the elementwise
    math and parity is essentially bitwise."""
    dtype = jnp.bfloat16 if dtype == "bfloat16" else dtype
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, dtype), _rand_tree(11))
    grads = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, dtype), _rand_tree(1100))

    ref_opt = inner_cls(**kw)
    ref_state = ref_opt.init(params)
    flat_opt = opt_mod.FlatOptimizer(inner_cls(**kw))
    flat_state = flat_opt.init(params)

    rp = fp = params
    for step in range(3):
        g = jax.tree_util.tree_map(lambda x: x * (step + 1.0), grads)
        rp, ref_state = ref_opt.step(g, ref_state, rp)
        fp, flat_state = flat_opt.step(g, flat_state, fp)
    for a, b in zip(jax.tree_util.tree_leaves(fp),
                    jax.tree_util.tree_leaves(rp)):
        assert a.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-7)


def test_flat_optimizer_overflow_skip_and_jit():
    params = jax.tree_util.tree_map(jnp.asarray, _rand_tree(12))
    opt = opt_mod.FlatOptimizer(opt_mod.FusedSGD(lr=0.1, momentum=0.9))
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, _rand_tree(1200))
    bad = dict(grads, w=grads["w"].at[0, 0].set(jnp.inf))

    @jax.jit
    def train_step(g, s, p):
        return opt.step(g, s, p, grads_finite=all_finite(g))

    new_p, new_s = train_step(bad, state, params)
    for a, b in zip(jax.tree_util.tree_leaves(new_p),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    new_p, new_s = train_step(grads, new_s, new_p)
    assert not np.allclose(np.asarray(new_p["w"]), np.asarray(params["w"]))


def test_flat_optimizer_persistent_flat_tier():
    """The performance tier: params live flat across steps, grads are taken
    w.r.t. the flat buffer through ``unflatten`` views, and ``flat_step``
    updates everything in one fused pass. Must match the per-leaf optimizer
    exactly, including the overflow skip."""
    params = jax.tree_util.tree_map(jnp.asarray, _rand_tree(21))
    data = jnp.asarray(np.random.RandomState(7).randn(5, 17), jnp.float32)

    def loss_from_tree(p, x):
        h = jnp.tanh(x @ p["w"] + p["b"])
        return jnp.sum(h ** 2) + jnp.sum(p["emb"]["table"] ** 2)

    ref_opt = opt_mod.FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    ref_state = ref_opt.init(params)
    rp = params

    opt = opt_mod.FlatOptimizer(
        opt_mod.FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
    fstate = opt.init_flat(params)

    @jax.jit
    def flat_train_step(fstate, x):
        g = jax.grad(lambda f: loss_from_tree(opt.unflatten(f), x))(
            fstate.flat_params)
        return opt.flat_step(g, fstate, grads_finite=all_finite(g))

    for step in range(3):
        x = data * (step + 1.0)
        g = jax.grad(loss_from_tree)(rp, x)
        rp, ref_state = ref_opt.step(g, ref_state, rp)
        fstate = flat_train_step(fstate, x)

    for a, b in zip(jax.tree_util.tree_leaves(opt.params_of(fstate)),
                    jax.tree_util.tree_leaves(rp)):
        # jit fuses the flat-grad path differently (reassociation noise)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # overflow skip: a non-finite flat grad leaves the state untouched
    before = fstate
    bad = jnp.full_like(fstate.flat_params, jnp.nan)
    after = opt.flat_step(bad, fstate, grads_finite=all_finite(bad))
    np.testing.assert_array_equal(np.asarray(after.flat_params),
                                  np.asarray(before.flat_params))


def test_flat_optimizer_rejects_structure_change():
    params = jax.tree_util.tree_map(jnp.asarray, _rand_tree(13))
    opt = opt_mod.FlatOptimizer(opt_mod.FusedSGD(lr=0.1))
    opt.init(params)
    with pytest.raises(ValueError):
        opt.init({"w": params["w"]})
