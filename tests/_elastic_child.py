"""Subprocess child for the elastic kill-and-resume e2e tests.

Runs the real :class:`~apex_tpu.training.GPTHybridTrainer` under
:class:`~apex_tpu.elastic.runner.ElasticRunner` on its own virtual
2-device CPU mesh (tp=1, pp=1, dp=2) and prints machine-readable
progress lines:

- ``STEP <k>`` after each completed step (the parent keys external
  SIGTERM delivery on these),
- ``RESTORED <n>`` when the run resumed from a checkpoint,
- ``DIGEST <hex>`` when the run COMPLETES all steps: a sha256 over the
  bitwise content of every state leaf (params, optimizer moments,
  loss-scale scalars) plus the completed-step count and the data
  cursor — the equality the bitwise-resume contract is judged on.

A run preempted mid-way (external ``kill -TERM`` or a
:class:`~apex_tpu.elastic.faults.FaultPlan` self-SIGTERM) drains the
in-flight save, writes a final checkpoint, and exits 0 via
``AutoResume.request_resume`` — so it never prints ``DIGEST``; the
parent relaunches the same command line and the resumed run finishes
the remaining steps. The parent also imports this module directly to
produce the uninterrupted reference digest in-process (one source for
the model/data recipe, so child and reference cannot drift).
"""

import argparse
import hashlib
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# tiny-but-real hybrid GPT: tp=1 pp=1 dp=2 on 2 virtual CPU devices
VOCAB, HIDDEN, LAYERS, HEADS, SEQ = 32, 16, 1, 2, 8
M, MB = 2, 1  # microbatches x micro-batch rows (per dp rank)
DATA_ROWS, DATA_SEED = 64, 1


def build_trainer_and_data(devices, fastpath=True):
    """(trainer, data_iterator, mesh) on the FIRST ``len(devices)`` of the
    caller's jax devices — shared by the child (2-device process) and the
    parent's in-process reference run (first 2 of its 8). The trainer
    runs the COMPOUND fastpath configuration (TrainConfig.fastpath:
    ZeRO-1 with the backward-interleaved per-bucket RS/AG chains +
    selective remat) with a pinned small bucket grid, so the
    kill-and-resume contract is proven on the interleaved-apply program
    with a real multi-bucket (bucket-major) shard layout — the plain
    trainer's elastic loop stays covered in-process by tests/
    test_elastic.py and the dryrun gate's elastic leg. ``fastpath=False``
    keeps the plain config reachable for debugging."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from apex_tpu.elastic import (PrefetchingIterator, ShardedIndexIterator,
                                  token_batch_fetcher)
    from apex_tpu.training import GPTHybridTrainer

    dp = len(devices)
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=VOCAB, hidden_size=HIDDEN,
                          num_layers=LAYERS, num_attention_heads=HEADS,
                          max_position_embeddings=SEQ),
        parallel=ParallelConfig(tensor_model_parallel_size=1,
                                pipeline_model_parallel_size=1),
        batch=BatchConfig(global_batch_size=M * MB * dp,
                          micro_batch_size=MB),
        optimizer=OptimizerConfig(name="adam", lr=1e-2, weight_decay=0.0),
        opt_level="O0")
    if fastpath:
        # pinned grid: the tiny model sits below the roofline candidate
        # ladder ("auto" would resolve to one bucket and skip the
        # bucket-major layout this leg exists to prove)
        cfg = cfg.fastpath(bucket_bytes=2048)
    mesh = cfg.initialize_mesh(devices=devices)
    trainer = GPTHybridTrainer(cfg, mesh)

    data = np.random.RandomState(0).randint(0, VOCAB, (DATA_ROWS, SEQ + 1))
    sampler = ShardedIndexIterator(DATA_ROWS, M * dp * MB, seed=DATA_SEED)
    fetch = token_batch_fetcher(data, M, dp * MB, SEQ)
    it = PrefetchingIterator(
        sampler, fetch, depth=2,
        sharding=NamedSharding(mesh, P(None, "data")))
    return trainer, it, mesh


def state_digest(state, step, cursor):
    """sha256 of the bitwise content of every leaf + step + data cursor."""
    import jax
    import numpy as np

    from apex_tpu.elastic.ckpt import host_snapshot

    h = hashlib.sha256()
    h.update(f"step={int(step)};cursor={int(cursor)};".encode())
    for leaf in jax.tree_util.tree_leaves(host_snapshot(state)):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--fp32-on-disk", type=int, default=1)
    ap.add_argument("--fault-json", default=None)
    ap.add_argument("--save-interval", type=int, default=1)
    args = ap.parse_args(argv)

    from apex_tpu.utils.hostmesh import force_virtual_cpu_devices
    force_virtual_cpu_devices(2)
    import jax

    # match the parent test process (tests/conftest.py) so the in-process
    # reference digest and the child digests are comparable
    jax.config.update("jax_threefry_partitionable", True)

    from apex_tpu.elastic import ElasticRunner, FaultPlan
    from apex_tpu.transformer import parallel_state

    plan = (FaultPlan.from_json(args.fault_json)
            if args.fault_json else None)
    trainer, it, _ = build_trainer_and_data(jax.devices()[:2])
    try:
        runner = ElasticRunner(
            trainer, it, args.ckpt_dir,
            save_interval=args.save_interval, keep_last=3,
            fp32_on_disk=bool(args.fp32_on_disk), fault_plan=plan,
            on_step=lambda k, _loss: print(f"STEP {k}", flush=True))
        res = runner.fit(args.steps, key=jax.random.PRNGKey(0))
        if res.restored_from is not None:
            print(f"RESTORED {res.restored_from}", flush=True)
        print(f"DIGEST {state_digest(res.state, res.step, it.consumed)}",
              flush=True)
    finally:
        parallel_state.destroy_model_parallel()


if __name__ == "__main__":
    main()
