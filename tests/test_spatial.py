"""Spatial-parallel conv tests (``reference:apex/contrib/bottleneck``
SpatialBottleneck halo-exchange role)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.spatial import halo_exchange, spatial_conv2d

SP = 4


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:SP]), ("spatial",))


def test_halo_exchange_rows(mesh):
    x = jnp.arange(SP * 2 * 3, dtype=jnp.float32).reshape(1, SP * 2, 3, 1)

    def run(x):
        return shard_map(
            lambda x: halo_exchange(x, "spatial", 1),
            mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial"))(x)

    out = np.asarray(jax.jit(run)(x))  # (1, SP*(2+2), 3, 1)
    per = out.reshape(SP, 4, 3)
    full = np.asarray(x).reshape(SP * 2, 3)
    for r in range(SP):
        np.testing.assert_array_equal(per[r, 1:3], full[2 * r:2 * r + 2])
        if r > 0:
            np.testing.assert_array_equal(per[r, 0], full[2 * r - 1])
        else:
            assert np.all(per[r, 0] == 0)
        if r < SP - 1:
            np.testing.assert_array_equal(per[r, 3], full[2 * r + 2])
        else:
            assert np.all(per[r, 3] == 0)


@pytest.mark.parametrize("stride", [1, 2])
def test_spatial_conv_matches_dense(mesh, stride):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, SP * 4, 10, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 5) * 0.2, jnp.float32)

    def run(x, w):
        return shard_map(
            lambda x, w: spatial_conv2d(x, w, "spatial", stride=stride),
            mesh=mesh, in_specs=(P(None, "spatial"), P()),
            out_specs=P(None, "spatial"))(x, w)

    out = np.asarray(jax.jit(run)(x, w))
    ref = np.asarray(jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_spatial_conv_grads_cross_shards(mesh):
    """Halo gradients must flow back to the neighboring shard's owner."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, SP * 2, 6, 2), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 2, 2) * 0.2, jnp.float32)

    def loss(x, w):
        def inner(x, w):
            out = spatial_conv2d(x, w, "spatial")
            return jax.lax.psum(jnp.sum(out ** 2), "spatial")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(None, "spatial"), P()),
                         out_specs=P())(x, w)

    gx, gw = jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)

    def dense_loss(x, w):
        out = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum(out ** 2)

    gx_ref, gw_ref = jax.grad(dense_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=2e-5, atol=2e-5)
