"""Serving fast path: decode kernel parity, KV-cached prefill/decode vs
the one-shot forward, AOT donation + zero-recompile contracts, and the
continuous slot batcher (docs/SERVING.md)."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops.flash_attention import decode_attention, mha_reference
from apex_tpu.serving import (KVCache, Request, ServingEngine,
                              SlotScheduler, cache_bytes_per_slot,
                              sample_tokens)
from apex_tpu.observability.registry import MetricsRegistry


def _quantize_ref(x):
    """Host-side mirror of the cache's symmetric per-(position, head)
    int8 quantization."""
    scale = np.maximum(np.abs(x).max(-1) / 127.0, 1e-8)
    q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


# ---------------------------------------------------------------------------
# decode kernel vs the mha_reference cache oracle
# ---------------------------------------------------------------------------

class TestDecodeKernel:
    B, H, T, D = 4, 4, 256, 32
    LENGTHS = [0, 1, 100, 256]  # empty, single, partial, full

    def _rand(self, rng, shape, dtype):
        return jnp.asarray(rng.randn(*shape), dtype)

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-6),
                                           (jnp.bfloat16, 2e-2)])
    def test_parity_vs_cache_oracle(self, dtype, tol):
        rng = np.random.RandomState(0)
        q = self._rand(rng, (self.B, self.H, self.D), dtype)
        k = self._rand(rng, (self.B, self.H, self.T, self.D), dtype)
        v = self._rand(rng, (self.B, self.H, self.T, self.D), dtype)
        lengths = jnp.asarray(self.LENGTHS, jnp.int32)
        out = decode_attention(q, k, v, lengths)
        ref = mha_reference(q[:, :, None], k, v, kv_length=lengths)[:, :, 0]
        np.testing.assert_allclose(out.astype(np.float32),
                                   ref.astype(np.float32), atol=tol)
        # the empty row is exactly zero on both paths
        assert np.all(np.asarray(out[0]) == 0.0)

    def test_current_token_merge_matches_in_cache_oracle(self):
        """decode_attention(k_new=...) over an L-length prefix must equal
        the oracle over an (L+1)-length cache with the token written at
        the cursor — the exactness the write-after-read decode step
        relies on."""
        rng = np.random.RandomState(1)
        q = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        k = self._rand(rng, (self.B, self.H, self.T, self.D), jnp.float32)
        v = self._rand(rng, (self.B, self.H, self.T, self.D), jnp.float32)
        kn = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        vn = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        prefix = [0, 1, 100, 255]
        k2, v2 = k, v
        for i, L in enumerate(prefix):
            k2 = k2.at[i, :, L].set(kn[i])
            v2 = v2.at[i, :, L].set(vn[i])
        out = decode_attention(q, k, v, jnp.asarray(prefix), k_new=kn,
                               v_new=vn)
        ref = mha_reference(q[:, :, None], k2, v2,
                            kv_length=jnp.asarray(prefix) + 1)[:, :, 0]
        np.testing.assert_allclose(out, ref, atol=2e-6)
        # empty prefix == softmax over one position == exactly v_new
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(vn[0]))

    def test_int8_cache_parity(self):
        rng = np.random.RandomState(2)
        q = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        kf = rng.randn(self.B, self.H, self.T, self.D).astype(np.float32)
        vf = rng.randn(self.B, self.H, self.T, self.D).astype(np.float32)
        ki, ks = _quantize_ref(kf)
        vi, vs = _quantize_ref(vf)
        lengths = jnp.asarray([3, 50, 200, 256], jnp.int32)
        out = decode_attention(q, jnp.asarray(ki), jnp.asarray(vi),
                               lengths, k_scale=jnp.asarray(ks),
                               v_scale=jnp.asarray(vs))
        # oracle over the DEQUANTIZED cache: the kernel's only error
        # budget is fp roundoff, not quantization (same int8 values in)
        ref = mha_reference(q[:, :, None],
                            jnp.asarray(ki.astype(np.float32)
                                        * ks[..., None]),
                            jnp.asarray(vi.astype(np.float32)
                                        * vs[..., None]),
                            kv_length=lengths)[:, :, 0]
        np.testing.assert_allclose(out, ref, atol=2e-6)
        # and vs the unquantized truth the int8 error stays bounded
        full = mha_reference(q[:, :, None], jnp.asarray(kf),
                             jnp.asarray(vf), kv_length=lengths)[:, :, 0]
        assert np.max(np.abs(out - full)) < 0.05

    def test_pallas_and_fallback_agree(self):
        rng = np.random.RandomState(3)
        q = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        k = self._rand(rng, (self.B, self.H, self.T, self.D), jnp.float32)
        v = self._rand(rng, (self.B, self.H, self.T, self.D), jnp.float32)
        kn = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        vn = self._rand(rng, (self.B, self.H, self.D), jnp.float32)
        lengths = jnp.asarray(self.LENGTHS, jnp.int32)
        a = decode_attention(q, k, v, lengths, k_new=kn, v_new=vn,
                             use_pallas=True)
        b = decode_attention(q, k, v, lengths, k_new=kn, v_new=vn,
                             use_pallas=False)
        np.testing.assert_allclose(a, b, atol=2e-6)

    def test_int8_requires_scales(self):
        z8 = jnp.zeros((1, 1, 128, 8), jnp.int8)
        with pytest.raises(ValueError, match="k_scale"):
            decode_attention(jnp.zeros((1, 1, 8)), z8, z8,
                             jnp.zeros(1, jnp.int32))

    def test_forced_pallas_on_misaligned_cache_refused(self):
        """use_pallas=True on a misaligned max_len would silently drop
        the T % block_k tail (or never write the output at
        T < block_k) — it must raise, not decode garbage; the auto path
        falls back and stays correct."""
        rng = np.random.RandomState(5)
        for T in (192, 64):  # tail-dropping and empty-grid cases
            q = jnp.asarray(rng.randn(2, 2, 32), jnp.float32)
            k = jnp.asarray(rng.randn(2, 2, T, 32), jnp.float32)
            v = jnp.asarray(rng.randn(2, 2, T, 32), jnp.float32)
            lengths = jnp.asarray([T, T // 2], jnp.int32)
            with pytest.raises(ValueError, match="tile-aligned"):
                decode_attention(q, k, v, lengths, use_pallas=True)
            auto = decode_attention(q, k, v, lengths)
            ref = mha_reference(q[:, :, None], k, v,
                                kv_length=lengths)[:, :, 0]
            np.testing.assert_allclose(auto, ref, atol=2e-6)

    def test_kv_length_oracle_masks_garbage(self):
        """mha_reference's kv_length path must be insensitive to cache
        content past the cursor — the property that makes it a valid
        oracle for a preallocated cache."""
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(2, 2, 1, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
        lengths = jnp.asarray([5, 20])
        ref = mha_reference(q, k, v, kv_length=lengths)
        trash = mha_reference(
            q, k.at[0, :, 5:].set(1e4).at[1, :, 20:].set(-1e4),
            v.at[0, :, 5:].set(7.0), kv_length=lengths)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(trash))


# ---------------------------------------------------------------------------
# KV cache pytree
# ---------------------------------------------------------------------------

class TestKVCache:
    def test_append_and_write_prompt(self):
        cache = KVCache.create(2, 3, 2, 8, 4, dtype=jnp.float32)
        k_p = jnp.ones((2, 2, 5, 4))
        cache = cache.write_prompt(k_p, 2 * k_p, slot=1, true_len=3)
        assert int(cache.lengths[1]) == 3 and int(cache.lengths[0]) == 0
        k_n = jnp.full((2, 3, 2, 4), 9.0)
        cache = cache.append(k_n, k_n)
        # slot 1 appended at its cursor (3); slot 0 at 0
        assert float(cache.k[0, 1, 0, 3, 0]) == 9.0
        assert float(cache.k[0, 1, 0, 2, 0]) == 1.0   # prompt intact
        assert float(cache.k[0, 0, 0, 0, 0]) == 9.0
        assert cache.lengths.tolist() == [1, 4, 1]

    def test_append_saturates_at_max_len(self):
        cache = KVCache.create(1, 1, 1, 2, 4, dtype=jnp.float32)
        u = jnp.ones((1, 1, 1, 4))
        cache = cache.append(u, u)
        cache = cache.append(2 * u, 2 * u)      # fills max_len
        for _ in range(2):
            cache = cache.append(9 * u, 9 * u)  # saturated appends
        assert int(cache.lengths[0]) == 2  # clamped, no OOB write
        # a saturated slot writes NOTHING: the last position keeps its
        # value (the old semantics silently overwrote position
        # max_len-1 with each newest token's KV — the scheduler now
        # retires at capacity BEFORE the dispatch, and the cache write
        # is a no-op even if one slips through)
        assert float(cache.k[0, 0, 0, 1, 0]) == 2.0
        assert float(cache.v[0, 0, 0, 1, 0]) == 2.0

    def test_int8_roundtrip(self):
        cache = KVCache.create(1, 1, 2, 4, 8, dtype=jnp.int8)
        assert cache.quantized
        x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 2, 8),
                        jnp.float32)
        cache = cache.append(x, x)
        deq = (cache.k[0, 0, :, 0].astype(jnp.float32)
               * cache.k_scale[0, 0, :, 0, None])
        np.testing.assert_allclose(deq, x[0, 0], atol=float(
            jnp.max(jnp.abs(x)) / 127.0) + 1e-6)
        # pytree roundtrip preserves the quantized layout
        leaves, treedef = jax.tree_util.tree_flatten(cache)
        assert len(leaves) == 5
        assert jax.tree_util.tree_unflatten(treedef, leaves).quantized

    def test_bytes_per_slot(self):
        bf16 = cache_bytes_per_slot(12, 12, 1024, 64, jnp.bfloat16)
        assert bf16 == 2 * 12 * 12 * 64 * 2 * 1024
        i8 = cache_bytes_per_slot(12, 12, 1024, 64, jnp.int8)
        assert i8 == (2 * 12 * 12 * 64 + 2 * 12 * 12 * 4) * 1024
        cache = KVCache.create(12, 3, 12, 1024, 64, dtype=jnp.int8)
        assert cache.nbytes() == 3 * i8 + 3 * 4  # + the (S,) cursor


# ---------------------------------------------------------------------------
# prefill + N decode steps vs the one-shot causal forward
# ---------------------------------------------------------------------------

def _tiny_model(compute_dtype):
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype=compute_dtype)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


class TestPrefillDecodeParity:
    @pytest.mark.parametrize("compute,cache_dtype,tol", [
        # fp32 end to end: the decode path agrees with the one-shot
        # forward to fp32 roundoff (the reduction ORDER differs — block
        # streaming + two-way merge vs one softmax — so bitwise identity
        # is not the contract; docs/SERVING.md pins this tolerance)
        (jnp.float32, jnp.float32, 1e-5),
        # bf16 compute, bf16 cache: one bf16 rounding per cache write on
        # top of bf16 matmul noise
        (jnp.bfloat16, jnp.bfloat16, 0.05),
    ])
    def test_matches_oneshot_logits(self, compute, cache_dtype, tol):
        model, params = _tiny_model(compute)
        rng = np.random.RandomState(0)
        n, P, S = 12, 8, 3
        tokens = jnp.asarray(rng.randint(0, 97, (1, n)))
        oneshot = np.asarray(model(params, tokens), np.float32)

        cache = KVCache.create(2, S, 4, 16, 8, dtype=cache_dtype)
        logits_p, cache = model.forward(params, tokens[:, :P],
                                        kv_cache=cache, slot=1)
        np.testing.assert_allclose(np.asarray(logits_p[0], np.float32),
                                   oneshot[0, :P], atol=tol)
        # teacher-forced decode of the remaining positions on slot 1 (the
        # other slots stay empty and step along — the fixed-shape grid)
        for t in range(P, n):
            dt = jnp.zeros((S, 1), tokens.dtype).at[1, 0].set(tokens[0, t])
            logits_d, cache = model.forward(params, dt, kv_cache=cache)
            np.testing.assert_allclose(np.asarray(logits_d[1], np.float32),
                                       oneshot[0, t], atol=tol)
        assert int(cache.lengths[1]) == n

    def test_int8_cache_stays_close(self):
        """int8 cache: quantization error bounded, ranking mostly
        preserved on the tiny model (argmax agreement is the serving
        quantity that matters)."""
        model, params = _tiny_model(jnp.float32)
        rng = np.random.RandomState(1)
        n, P = 10, 6
        tokens = jnp.asarray(rng.randint(0, 97, (1, n)))
        oneshot = np.asarray(model(params, tokens), np.float32)
        cache = KVCache.create(2, 1, 4, 16, 8, dtype=jnp.int8)
        _, cache = model.forward(params, tokens[:, :P], kv_cache=cache,
                                 slot=0)
        agree = 0
        for t in range(P, n):
            logits_d, cache = model.forward(params, tokens[:, t][:, None],
                                            kv_cache=cache)
            agree += int(np.argmax(np.asarray(logits_d[0]))
                         == np.argmax(oneshot[0, t]))
        assert agree >= (n - P) - 1

    def test_prompt_padding_is_invisible(self):
        """A right-padded prompt (prompt_len < window) must produce the
        same decode trajectory as an exact-width prefill: the cursor
        masks the pad garbage and the appends overwrite it."""
        model, params = _tiny_model(jnp.float32)
        toks = [5, 6, 7]

        def run(window):
            cache = KVCache.create(2, 1, 4, 16, 8, dtype=jnp.float32)
            padded = np.zeros((1, window), np.int32)
            padded[0, : len(toks)] = toks
            _, cache = model.forward(params, jnp.asarray(padded),
                                     kv_cache=cache, slot=0,
                                     prompt_len=len(toks))
            out, _ = model.forward(params, jnp.asarray([[9]]),
                                   kv_cache=cache)
            return np.asarray(out)

        np.testing.assert_allclose(run(3), run(8), atol=1e-5)

    def test_prompt_len_outside_window_guarded(self):
        """A cursor past the written window would make every later
        decode read stale cache: static prompt_len is rejected, a
        traced one (the AOT engine path) is clamped."""
        model, params = _tiny_model(jnp.float32)
        tokens = jnp.asarray([[1, 2, 3, 4]])
        cache = KVCache.create(2, 1, 4, 16, 8, dtype=jnp.float32)
        with pytest.raises(ValueError, match="written window"):
            model.forward(params, tokens, kv_cache=cache, slot=0,
                          prompt_len=7)
        _, out_cache = jax.jit(
            lambda p, c, pl: model.forward(p, tokens, kv_cache=c,
                                           slot=0, prompt_len=pl)
        )(params, cache, jnp.asarray(7, jnp.int32))
        assert int(out_cache.lengths[0]) == 4  # clamped to the window

    def test_forward_without_cache_is_call(self):
        model, params = _tiny_model(jnp.float32)
        tokens = jnp.asarray([[1, 2, 3]])
        np.testing.assert_array_equal(
            np.asarray(model.forward(params, tokens)),
            np.asarray(model(params, tokens)))

    def test_tp_refused(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_attention_heads=2, max_position_embeddings=8,
                        tensor_model_parallel_size=2)
        model = GPTModel(cfg)
        with pytest.raises(NotImplementedError, match="tp=1"):
            model.forward({}, jnp.zeros((1, 4), jnp.int32),
                          kv_cache=KVCache.create(1, 1, 2, 8, 8))


# ---------------------------------------------------------------------------
# AOT engine: donation, live buffers, zero recompiles
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    model, params = _tiny_model(jnp.float32)
    return ServingEngine(model, params, max_seqs=2, max_len=16,
                         prefill_len=8)


class TestEngineContracts:
    def test_cache_donation_aliased(self, engine):
        """Every cache leaf must be input/output-aliased in BOTH compiled
        programs: alias_bytes covers the whole cache, so decode steps do
        zero cache allocation (the PR 4 donation-test methodology)."""
        for compiled in (engine.decode_compiled, engine.prefill_compiled):
            assert "input_output_alias" in compiled.as_text()
            ma = compiled.memory_analysis()
            assert int(ma.alias_size_in_bytes) >= engine.cache.nbytes()

    def test_live_buffers_consumed(self, engine):
        """The donated cache buffers die at each call — the step updates
        in place instead of copying."""
        old = jax.tree_util.tree_leaves(engine.cache)
        engine.prefill([1, 2, 3], slot=0)
        assert all(leaf.is_deleted() for leaf in old)
        old = jax.tree_util.tree_leaves(engine.cache)
        engine.decode(np.zeros(2, np.int32), np.zeros(2, np.float32))
        assert all(leaf.is_deleted() for leaf in old)

    def test_zero_recompiles_across_steps(self, engine):
        """After one warm call of each program, admissions/decodes/
        retirements must never trace or compile again — the compile-storm
        counters (PR 1) stay flat."""
        from apex_tpu import observability as obs
        reg = MetricsRegistry()
        # warm every host path once (prefill, decode, release, rng
        # split, asarray)
        engine.prefill([1, 2], slot=0)
        engine.decode(np.zeros(2, np.int32), np.zeros(2, np.float32))
        engine.release_slot(0)
        obs.install_compile_listeners(reg)
        try:
            before = dict(reg.snapshot())
            for i in range(4):
                engine.prefill([1, 2, 3], slot=i % 2)
                engine.decode(np.asarray([i, i + 1], np.int32),
                              np.asarray([0.0, 0.7], np.float32))
                engine.release_slot(i % 2)
            after = reg.snapshot()
        finally:
            obs.uninstall_compile_listeners(reg)
        for name in ("jax/compiles", "jax/traces", "jax/lowerings"):
            assert after.get(name, 0.0) == before.get(name, 0.0), (
                name, before, after)

    def test_capacity_math(self, engine):
        per_slot = engine.bytes_per_slot()
        # the engine default cache dtype is bf16 regardless of compute
        assert per_slot == cache_bytes_per_slot(2, 4, 16, 8, jnp.bfloat16)
        overhead = engine.overhead_bytes()
        hbm = 1 << 30
        suggested = engine.suggest_max_seqs(hbm, reserve_fraction=0.1)
        if overhead is not None:
            assert suggested == (int(hbm * 0.9) - overhead) // per_slot
        assert engine.suggest_max_seqs(0) == 0  # no HBM, no slots
        # monotone in memory
        assert engine.suggest_max_seqs(2 * hbm) >= suggested

    def test_prompt_too_long_rejected(self, engine):
        with pytest.raises(ValueError, match="prefill window"):
            engine.prefill(list(range(9)), slot=0)

    def test_out_of_range_slot_rejected(self, engine):
        """An out-of-range slot would CLAMP inside the compiled
        dynamic_update_slice and silently clobber the last valid slot's
        in-flight sequence — it must bounce at the host boundary."""
        before = np.asarray(engine.cache.lengths)
        for slot in (engine.max_seqs, -1):
            with pytest.raises(ValueError, match="out of range"):
                engine.prefill([1, 2], slot=slot)
        np.testing.assert_array_equal(np.asarray(engine.cache.lengths),
                                      before)

    def test_prefill_last_logit_only_matches_full_head(self):
        """The engine's single-row head projection equals the full-head
        logits at prompt_len - 1 (the head is per-position, so gathering
        the hidden row first changes nothing but the FLOPs)."""
        model, params = _tiny_model(jnp.float32)
        tokens = jnp.asarray([[3, 1, 4, 1, 5, 0, 0, 0]])

        def run(last_only):
            cache = KVCache.create(2, 1, 4, 16, 8, dtype=jnp.float32)
            lg, _ = model.forward(params, tokens, kv_cache=cache, slot=0,
                                  prompt_len=5, last_logit_only=last_only)
            return np.asarray(lg)

        full, last = run(False), run(True)
        assert last.shape == (1, 1, full.shape[-1])
        np.testing.assert_allclose(last[0, 0], full[0, 4], atol=1e-6)

    def test_rng_varies_sampling(self):
        """Two stochastic decodes of the same state draw different rngs
        (the engine splits its key per call)."""
        model, params = _tiny_model(jnp.float32)
        eng = ServingEngine(model, params, max_seqs=1, max_len=16,
                            prefill_len=4)
        k1 = eng._next_key()
        k2 = eng._next_key()
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

class TestSampling:
    def test_greedy_and_topk1(self):
        rng = jax.random.PRNGKey(0)
        logits = jnp.asarray(np.random.RandomState(0).randn(5, 33),
                             jnp.float32)
        greedy = sample_tokens(logits, rng, jnp.zeros(5))
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.argmax(np.asarray(logits), -1))
        topk1 = sample_tokens(logits, rng, jnp.full(5, 1.0), top_k=1)
        np.testing.assert_array_equal(np.asarray(topk1),
                                      np.asarray(greedy))

    def test_topk_restricts_support(self):
        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]] * 64, jnp.float32)
        toks = sample_tokens(logits, jax.random.PRNGKey(1),
                             jnp.full(64, 5.0), top_k=2)
        assert set(np.asarray(toks).tolist()) <= {2, 3}

    def test_mixed_batch_greedy_rows_deterministic(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(4, 16),
                             jnp.float32)
        temps = jnp.asarray([0.0, 1.0, 0.0, 1.0])
        a = sample_tokens(logits, jax.random.PRNGKey(2), temps)
        b = sample_tokens(logits, jax.random.PRNGKey(3), temps)
        np.testing.assert_array_equal(np.asarray(a)[[0, 2]],
                                      np.asarray(b)[[0, 2]])


# ---------------------------------------------------------------------------
# continuous slot batching
# ---------------------------------------------------------------------------

def _sched(max_seqs=2, max_len=32, prefill_len=8, **kw):
    model, params = _tiny_model(jnp.float32)
    eng = ServingEngine(model, params, max_seqs=max_seqs, max_len=max_len,
                        prefill_len=prefill_len, **kw)
    reg = MetricsRegistry()
    return SlotScheduler(eng, registry=reg), reg


class TestSlotScheduler:
    def test_all_requests_complete_with_exact_lengths(self):
        sched, reg = _sched()
        reqs = [Request(prompt=[1 + i, 2, 3], max_new_tokens=2 + i)
                for i in range(5)]
        out = sched.run(reqs)
        assert sorted(out) == list(range(5))
        for i, c in sorted(out.items()):
            assert c.finish_reason == "length"
            assert len(c.tokens) == 2 + i
            # completions carry the measured request-lifecycle latencies
            # (the full tracing/SLO surface: tests/test_reqtrace.py)
            assert c.queue_wait_ms >= 0.0
            assert c.ttft_ms >= c.queue_wait_ms
            assert c.e2e_ms >= c.ttft_ms and c.tpot_ms > 0.0
        snap = reg.snapshot()
        assert snap["serve/ttft_ms_count"] == 5.0
        assert snap["serve/e2e_ms_count"] == 5.0
        assert snap["serve/admitted"] == 5.0
        assert snap["serve/retired"] == 5.0
        assert snap["serve/prefill_tokens"] == 15.0
        assert snap["serve/generated_tokens"] == sum(2 + i
                                                     for i in range(5))
        assert snap["serve/active_slots"] == 0.0
        assert snap["serve/queue_depth"] == 0.0
        assert snap["serve/tokens_per_sec"] > 0.0

    def test_no_batch_barrier(self):
        """A short request retires mid-flight and its slot is re-admitted
        while the long request keeps decoding — the continuous-batching
        property itself."""
        sched, _ = _sched(max_seqs=2)
        long_id = sched.submit(Request(prompt=[1], max_new_tokens=12))
        short_id = sched.submit(Request(prompt=[2], max_new_tokens=3))
        late_id = sched.submit(Request(prompt=[3], max_new_tokens=2))
        # 2 slots: long+short admitted; late queued
        sched.step()
        assert sched.pending == 3 and len(sched.queue) == 1
        while not any(c.request_id == short_id for c in sched.completed):
            sched.step()
        done_at_short = {c.request_id for c in sched.completed}
        assert long_id not in done_at_short  # long is still mid-flight
        sched.run([])  # drain
        result = {c.request_id: c for c in sched.completed}
        assert len(result[late_id].tokens) == 2
        assert len(result[long_id].tokens) == 12
        # the late request was admitted into the freed slot and COMPLETED
        # before the long one finished — no barrier (with one, late could
        # only start after both retire)
        order = [c.request_id for c in sched.completed]
        assert order.index(late_id) < order.index(long_id)

    def test_eos_and_capacity_stops(self):
        sched, _ = _sched(max_seqs=1, max_len=6, prefill_len=4)
        # the tiny random model repeats a token; use it as eos
        probe = sched.run([Request(prompt=[1, 2], max_new_tokens=3)])
        eos = probe[0].tokens[-1]
        sched2, _ = _sched(max_seqs=1, max_len=6, prefill_len=4)
        out = sched2.run([
            Request(prompt=[1, 2], max_new_tokens=50, eos_token=eos),
            Request(prompt=[1, 2, 3], max_new_tokens=50),
        ])
        assert out[0].finish_reason == "eos"
        # 6-token cache, 3-token prompt: capacity retires it
        assert out[1].finish_reason == "capacity"
        assert len(out[1].tokens) == 3

    def test_single_token_request_completes_at_prefill(self):
        sched, reg = _sched(max_seqs=2)
        out = sched.run([Request(prompt=[4, 5], max_new_tokens=1)])
        assert len(out[0].tokens) == 1
        assert reg.snapshot().get("serve/decode_steps", 0.0) == 0.0

    def test_int8_engine_serves(self):
        sched, _ = _sched(cache_dtype=jnp.int8)
        out = sched.run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
        assert len(out[0].tokens) == 4

    def test_submit_rejects_bad_prompts_loop_stays_alive(self):
        """Validation happens at submit, not mid-step: a bad request
        bounces off the caller and never kills the serving loop."""
        sched, _ = _sched()
        with pytest.raises(ValueError, match="prefill window"):
            sched.submit(Request(prompt=list(range(9))))
        with pytest.raises(ValueError, match="empty"):
            sched.submit(Request(prompt=[]))
        assert sched.pending == 0
        out = sched.run([Request(prompt=[1], max_new_tokens=2)])
        assert len(out[0].tokens) == 2

    def test_free_slots_never_grow_cursors(self):
        """Freed slots must not keep (or grow) cursors: the decode
        kernel's compute-skip prices a slot's math O(cursor), so a
        retired sequence left in place — or a free slot creeping one
        garbage position per step — would tax every later step. Retire
        resets (release_slot) and the decode active-mask freezes idle
        cursors."""
        sched, _ = _sched(max_seqs=2)
        sched.run([Request(prompt=[1, 2, 3], max_new_tokens=10)])
        # slot 0 ran 10 tokens then released; slot 1 idled 9 steps
        np.testing.assert_array_equal(
            np.asarray(sched.engine.cache.lengths), [0, 0])

    def test_submit_rejects_nonpositive_max_new_tokens(self):
        sched, _ = _sched()
        with pytest.raises(ValueError, match="max_new_tokens"):
            sched.submit(Request(prompt=[1], max_new_tokens=0))
        assert sched.pending == 0

    def test_run_returns_only_this_runs_completions(self):
        sched, _ = _sched()
        first = sched.run([Request(prompt=[1], max_new_tokens=2)])
        second = sched.run([Request(prompt=[2], max_new_tokens=3,
                                    request_id=7)])
        assert sorted(first) == [0] and sorted(second) == [7]
        # the buffer holds both until drained; draining empties it
        assert {c.request_id for c in sched.completed} == {0, 7}
        assert len(sched.drain_completed()) == 2
        assert sched.completed == []

    def test_run_no_recompile_guard(self):
        """run(no_recompile=True) wraps the loop in the analysis
        engine's recompile_guard (PR 11): the steady-state serving loop
        is live-asserted recompile-free, not just test-asserted."""
        sched, _ = _sched()
        reqs = [Request(prompt=[1 + i, 2], max_new_tokens=3)
                for i in range(4)]
        out = sched.run(reqs, no_recompile=True)
        assert sorted(out) == list(range(4))
        # a second guarded run on the warm engine is also clean
        out = sched.run([Request(prompt=[9], max_new_tokens=2,
                                 request_id=9)], no_recompile=True)
        assert sorted(out) == [9]
