"""The unified static-analysis engine (``apex_tpu.analysis``).

One consolidated suite replacing the six per-script test classes that
used to live in ``test_observability.py`` (PR 11):

- **Family B (ast)** — every rule passes on the real tree, and a
  parametrized planted-violation table proves each rule still fires on
  exactly its own violation (same rigor as the old per-script classes,
  one harness).
- **Family A (jaxpr)** — planted-violation fixtures for every program
  rule: one shard_map grad-sync program parameterized by WHICH historical
  bug is planted (flat-gradient barrier, smuggled raw collective,
  missing shared-grad psum) runs the full ``lint_program`` surface and
  must fire exactly its own rule (cross-talk check); donation and
  recompile fixtures cover the other two rules.
- **CLI** — ``python -m apex_tpu.analysis --all`` is green on the clean
  tree (tier-1's consolidated entry point) and red on a planted one.
"""

import contextlib
import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.analysis import iter_rules
from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import AnalysisError
from apex_tpu.analysis.program import (check_donation,
                                       check_shared_grad_reduction,
                                       lint_program, recompile_guard)
from apex_tpu.analysis.rules_ast import (ANNOTATIONS, METRIC_PREFIXES,
                                         rule_annotations,
                                         rule_bench_configs,
                                         rule_bench_history,
                                         rule_collectives,
                                         rule_elastic_exits,
                                         rule_metric_families,
                                         rule_metrics_doc,
                                         rule_remat_names)
from apex_tpu.utils.compat import shard_map_unchecked

REPO = repo_root()


# ---------------------------------------------------------------------------
# Family B: clean tree
# ---------------------------------------------------------------------------

AST_RULES = {r.name: r for r in iter_rules("ast")}


@pytest.mark.parametrize("name", sorted(AST_RULES))
def test_ast_rule_clean_on_this_tree(name):
    findings, notes = AST_RULES[name].run(REPO)
    assert not findings, "\n".join(str(f) for f in findings)
    assert notes  # every rule reports what it checked


def test_annotation_contract_size():
    """The table doubles as the pyprof region vocabulary: 20 contract
    entries as of PR 20 (4 original + bucketed allreduce + optimizer_step
    + 8 model phases + 2 tp layers + 4 serving regions incl.
    serve_verify)."""
    _, notes = rule_annotations(REPO)
    assert len(notes) == len(ANNOTATIONS) == 20


# ---------------------------------------------------------------------------
# Family B: planted violations (one parametrized table)
# ---------------------------------------------------------------------------

def _write(tmp_path, rel, text):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)


def _seed_bench_repo(tmp_path, bench_src):
    _write(tmp_path, "apex_tpu/config.py",
           "import dataclasses\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class ModelConfig:\n"
           "    name: str = 'gpt'\n"
           "    remat_policy: str = None\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class ParallelConfig:\n"
           "    tensor_model_parallel_size: int = 1\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class BatchConfig:\n"
           "    global_batch_size: int = 64\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class OptimizerConfig:\n"
           "    name: str = 'adam'\n"
           "    zero: int = 0\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class TrainConfig:\n"
           "    model: ModelConfig = ModelConfig()\n"
           "    parallel: ParallelConfig = ParallelConfig()\n"
           "    batch: BatchConfig = BatchConfig()\n"
           "    optimizer: OptimizerConfig = OptimizerConfig()\n"
           "    ddp_bucket_bytes: int = None\n")
    _write(tmp_path, "apex_tpu/models/gpt.py",
           "import dataclasses\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class GPTConfig:\n"
           "    hidden_size: int = 768\n"
           "    remat_policy: str = None\n")
    _write(tmp_path, "bench.py", bench_src)


def _plant_annotations(tmp_path):
    (tmp_path / "apex_tpu").mkdir()  # empty tree: every annotation gone


def _expect_annotations(findings):
    assert len(findings) == len(ANNOTATIONS)
    assert all(f.kind == "MISSING" for f in findings)


def _plant_gather(tmp_path):
    _write(tmp_path, "apex_tpu/transformer/bad.py",
           "import jax\n"
           "def f(x):\n"
           "    return jax.lax.all_gather(x, 'tensor', axis=0)\n")


def _expect_gather(findings):
    assert any("bad.py:3" in f.where and "all_gather" in f.message
               for f in findings)


def _plant_scatter(tmp_path):
    _write(tmp_path, "apex_tpu/transformer/bad.py",
           "import jax\n"
           "def sync(g):\n"
           "    return jax.lax.psum_scatter(g, 'data', tiled=True)\n")


def _expect_scatter(findings):
    assert any("bad.py:3" in f.where and "reduce_scatter_grads"
               in f.message for f in findings)


def _plant_grad_psum(tmp_path):
    src = ("import jax\n"
           "def sync(g):\n"
           "    return jax.lax.psum(g, 'data')\n")
    _write(tmp_path, "apex_tpu/optimizers/bad.py", src)
    # the same line OUTSIDE a grad-sync module is legitimate
    _write(tmp_path, "apex_tpu/normalization/fine.py", src)


def _expect_grad_psum(findings):
    assert any("bad.py:3" in f.where and "grad-sync" in f.message
               for f in findings)
    assert not any("fine.py" in f.where for f in findings)


def _plant_metrics_doc(tmp_path):
    _write(tmp_path, "apex_tpu/m.py",
           "from apex_tpu.observability import ingraph\n"
           "def f(x, name, registry, reg, buckets):\n"
           "    ingraph.record('health/rogue_metric', x)\n"
           "    ingraph.record(f'health/{name}/rogue_family', x)\n"
           "    registry.gauge('perf/rogue_attribution').set(x)\n"
           "    reg.counter('ckpt/rogue_bytes').inc(x)\n"
           "    reg.histogram('serve/rogue_ms').observe(x)\n"
           # the PR 12 call shapes: a bucketed latency histogram and an
           # slo/ gauge — the doc contract must see through both
           "    reg.histogram('serve/rogue_wait_ms', buckets).observe(x)\n"
           "    reg.gauge('slo/rogue_goodput').set(x)\n"
           # the PR 13 supervisor family: elastic/* is under the doc
           # contract like every other elastic-runtime family
           "    reg.gauge('elastic/rogue_world').set(x)\n"
           # the PR 14 fleet merge layer: fleet/* (supervisor straggler
           # gauges) and train/* (rank-side step counters) join the
           # contract
           "    reg.gauge('fleet/rogue_skew').set(x)\n"
           "    reg.counter('train/rogue_steps').inc(x)\n"
           # the PR 15 resilience call shapes: reason-keyed retirement
           # counters and the brownout gauge — an undocumented
           # rejection/expiry/poison counter must fire like any other
           "    reg.counter('serve/rogue_rejected').inc()\n"
           "    reg.counter('serve/rogue_poisoned').inc()\n"
           "    reg.gauge('serve/rogue_brownout').set(x)\n"
           # the PR 18 perfwatch call shapes: a scalar drift gauge and a
           # per-metric f-string drift family — the observatory's
           # published names are under the contract like any other perf/
           "    reg.gauge('perf/rogue_drift').set(x)\n"
           "    reg.gauge(f'perf/rogue_drift/{name}').set(x)\n")
    _write(tmp_path, "docs/OBSERVABILITY.md", "| nothing documented |\n")


def _expect_metrics_doc(findings):
    undoc = [f for f in findings if f.kind == "UNDOC"]
    # record x2 + gauge x7 + counter x4 + hist x2
    assert len(undoc) == 15
    for name in ("health/rogue_metric", "health/<>/rogue_family",
                 "perf/rogue_attribution", "ckpt/rogue_bytes",
                 "serve/rogue_ms", "serve/rogue_wait_ms",
                 "slo/rogue_goodput", "elastic/rogue_world",
                 "fleet/rogue_skew", "train/rogue_steps",
                 "serve/rogue_rejected", "serve/rogue_poisoned",
                 "serve/rogue_brownout", "perf/rogue_drift",
                 "perf/rogue_drift/<>"):
        assert any(name in f.message for f in undoc), name


def _plant_metric_family(tmp_path):
    _write(tmp_path, "apex_tpu/m.py",
           "def f(reg, x, i):\n"
           "    reg.counter('newfam/widgets').inc()\n"
           "    reg.counter('jax/compiles').inc()\n"          # exempt
           "    reg.gauge(f'memory/peak/device{i}').set(x)\n"  # exempt
           "    reg.gauge('serve/queue_depth').set(x)\n"       # known
           "    reg.gauge('slo/goodput').set(x)\n"             # known (PR 12)
           "    reg.gauge('elastic/world_size').set(x)\n"      # known (PR 13)
           "    reg.gauge('fleet/step_skew').set(x)\n"         # known (PR 14)
           "    reg.counter('train/steps').inc()\n"            # known (PR 14)
           "    reg.counter('serve/rejected').inc()\n"         # known (PR 15)
           "    reg.counter('serve/poisoned').inc()\n"         # known (PR 15)
           "    reg.gauge('serve/brownout').set(x)\n"          # known (PR 15)
           "    reg.gauge('no_slash_name').set(x)\n")          # unprefixed
    # even a documented row does not excuse an unregistered FAMILY
    _write(tmp_path, "docs/OBSERVABILITY.md", "| `newfam/widgets` |\n")


def _expect_metric_family(findings):
    assert len(findings) == 1
    f = findings[0]
    assert f.kind == "ROGUE" and "m.py:2" in f.where
    assert "newfam/" in f.message and "METRIC_PREFIXES" in f.message


def _plant_remat(tmp_path):
    _write(tmp_path, "apex_tpu/remat.py",
           "CHECKPOINT_NAMES = ('qkv_out', 'ln_out')\n"
           "SELECTIVE_SAVE = ('qkv_out', 'phantom',)\n")
    _write(tmp_path, "apex_tpu/bad.py",
           "from jax.ad_checkpoint import checkpoint_name\n"
           "def f(self, x):\n"
           "    x = checkpoint_name(x, 'rogue_act')\n"
           "    x = self._tag(x, 'another_rogue')\n"
           "    return self._tag(x, 'qkv_out')\n")


def _expect_remat(findings):
    orphans = [f for f in findings if f.kind == "ORPHAN"]
    assert any("rogue_act" in f.message and "bad.py:3" in f.where
               for f in orphans)
    assert any("another_rogue" in f.message and "bad.py:4" in f.where
               for f in orphans)
    assert any("phantom" in f.message and "SELECTIVE_SAVE" in f.where
               for f in orphans)
    assert not any("qkv_out" in f.message for f in orphans)


def _elastic_chokepoint(tmp_path):
    _write(tmp_path, "apex_tpu/utils/autoresume.py",
           "import sys\n"
           "class AutoResume:\n"
           "    def request_resume(self, exit_code=0):\n"
           "        sys.exit(exit_code)\n")
    (tmp_path / "apex_tpu" / "elastic").mkdir(parents=True,
                                              exist_ok=True)


def _plant_elastic_exits(tmp_path):
    _elastic_chokepoint(tmp_path)
    _write(tmp_path, "apex_tpu/elastic/bad.py",
           "import os, sys\n"
           "def f(code):\n"
           "    sys.exit(code)\n"
           "    os._exit(code)\n"
           "    exit(code)\n"
           "    raise SystemExit(code)\n")


def _expect_elastic_exits(findings):
    flagged = [f for f in findings if f.kind == "EXIT"]
    assert len(flagged) == 4
    for spelling, lineno in (("sys.exit", 3), ("os._exit", 4),
                             ("exit", 5), ("raise SystemExit", 6)):
        assert any(spelling in f.message and f"bad.py:{lineno}"
                   in f.where for f in flagged), spelling


def _plant_elastic_choke_rot(tmp_path):
    _elastic_chokepoint(tmp_path)
    _write(tmp_path, "apex_tpu/utils/autoresume.py",
           "class AutoResume:\n"
           "    def request_resume(self, exit_code=0):\n"
           "        pass\n")


def _expect_elastic_choke_rot(findings):
    assert any(f.kind == "CHOKE" for f in findings)


_LAUNCH_CHOKE = ("def _supervisor_exit(code):\n"
                 "    import sys\n"
                 "    sys.exit(int(code))\n")


def _plant_launch_exit(tmp_path):
    """launch.py may exit ONLY inside _supervisor_exit: a sys.exit in
    any other supervisor function is the violation; the blessed one is
    not."""
    _elastic_chokepoint(tmp_path)
    _write(tmp_path, "apex_tpu/elastic/launch.py",
           "import sys\n"
           + _LAUNCH_CHOKE +
           "def run(report):\n"
           "    sys.exit(0 if report else 1)\n")


def _expect_launch_exit(findings):
    flagged = [f for f in findings if f.kind == "EXIT"]
    assert len(flagged) == 1
    assert "launch.py:6" in flagged[0].where
    assert "_supervisor_exit" in flagged[0].message
    # the blessed chokepoint itself never fires, and its shape is fine
    assert not any(f.kind == "CHOKE" for f in findings)


def _plant_launch_choke_rot(tmp_path):
    """Chokepoint rot: _supervisor_exit exists but no longer holds
    exactly one sys.exit (here: two) — the anchor the rule pins must not
    silently decay."""
    _elastic_chokepoint(tmp_path)
    _write(tmp_path, "apex_tpu/elastic/launch.py",
           "import sys\n"
           "def _supervisor_exit(code):\n"
           "    sys.exit(int(code))\n"
           "    sys.exit(1)\n")


def _expect_launch_choke_rot(findings):
    choke = [f for f in findings if f.kind == "CHOKE"
             and "launch.py" in f.where]
    assert len(choke) == 1 and "found 2" in choke[0].message


def _plant_bench(tmp_path):
    _seed_bench_repo(
        tmp_path,
        "BENCH_TRAIN_CONFIGS = {\n"
        "  'leg': {'model': {'remat_policy': 'selective',\n"
        "                    'remat_mode': 'full'},\n"
        "          'bucket_bytes': 4096,\n"
        "          'optimizer': {'zero': 1}},\n"
        "}\n"
        # stated-SLO contract: one bad metric name, one bad quantile,
        # one bad threshold, one fully valid triple
        "DECODE_SLO = (('latency_ms', 95.0, 2000.0),\n"
        "              ('ttft_ms', 101.0, 500.0),\n"
        "              ('tpot_ms', 99.0, 0.0),\n"
        "              ('e2e_ms', 99.0, 4000.0))\n"
        "def _gpt_train_step(batch=8, seq=1024, **cfg_overrides):\n"
        "    pass\n"
        "def bench_ok():\n"
        "    _gpt_train_step(batch=8, hidden_size=768)\n"
        "def bench_bad():\n"
        "    _gpt_train_step(hidden_dims=768)\n")
    _write(tmp_path, "BENCH_CONFIGS.json",
           '[{"metric": "m", "config": {"ddp_bucket_bytes": 1,'
           ' "optimizer": {"zero_stage": 1}}}]')


def _expect_bench(findings):
    unknown = [f for f in findings if f.kind == "UNKNOWN"]
    assert any("model.'remat_mode'" in f.message for f in unknown)
    assert any("'bucket_bytes'" in f.message for f in unknown)
    assert any("optimizer.'zero_stage'" in f.message
               and "BENCH_CONFIGS.json" in f.where for f in unknown)
    assert any("hidden_dims" in f.message for f in unknown)
    # the stated-SLO contract (PR 12): bad metric/quantile/threshold fire
    slo = [f for f in unknown if "DECODE_SLO" in f.where]
    assert any("'latency_ms'" in f.message for f in slo)
    assert any("101.0" in f.message for f in slo)
    assert any("threshold_ms" in f.message for f in slo)
    assert not any("e2e_ms" in f.where for f in slo)  # the valid triple
    # valid keys in the same legs are NOT flagged
    assert not any("remat_policy" in f.message for f in unknown)
    assert not any("'zero'" in f.message for f in unknown)


def _plant_bench_history(tmp_path):
    """A perfwatch-era schema fork: the writer renamed ``value`` to
    ``display_value`` and grew a ``hostname`` promotion the table never
    learned about, while an on-disk history still carries both old- and
    new-world records."""
    _write(tmp_path, "apex_tpu/observability/perfwatch.py",
           "HISTORY_FIELDS = (\n"
           "    ('metric', 'required'),\n"
           "    ('value', 'required'),\n"
           "    ('raw_value', 'required'),\n"
           "    ('unit', 'required'),\n"
           "    ('config', 'optional'),\n"
           ")\n"
           "def make_record(metric, value, unit):\n"
           "    rec = {\n"
           "        'metric': metric,\n"
           "        'display_value': round(value, 2),\n"
           "        'raw_value': value,\n"
           "        'unit': unit,\n"
           "    }\n"
           "    rec['hostname'] = 'n1'\n"
           "    return rec\n")
    _write(tmp_path, "BENCH_HISTORY.jsonl",
           '{"metric": "m", "value": 1.0, "raw_value": 1.0,'
           ' "unit": "ms", "rogue_key": 1}\n'
           '{"metric": "m"}\n')


def _expect_bench_history(findings):
    writer = [f for f in findings if "make_record" in f.where]
    # the renamed required key fires both ways: absent + rogue
    assert any(f.kind == "MISSING" and "'value'" in f.message
               for f in writer)
    assert any(f.kind == "ROGUE" and "'display_value'" in f.message
               for f in writer)
    # the un-tabled promotion
    assert any(f.kind == "ROGUE" and "'hostname'" in f.message
               for f in writer)
    disk = [f for f in findings if "BENCH_HISTORY.jsonl" in f.where]
    assert any(f.kind == "UNKNOWN" and "'rogue_key'" in f.message
               and ":1" in f.where for f in disk)
    missing2 = [f for f in disk if f.kind == "MISSING" and ":2" in f.where]
    assert {m.split("'")[1] for m in (f.message for f in missing2)} == \
        {"value", "raw_value", "unit"}
    # keys the table DOES know are not flagged
    assert not any("'config'" in f.message for f in findings)
    assert not any("'raw_value'" in f.message and f.kind != "MISSING"
                   for f in findings)


def test_slo_metric_mirror_pinned():
    """rules_ast.SLO_METRICS is a jax-free mirror of the slo module's
    latency vocabulary — they must never drift."""
    from apex_tpu.analysis.rules_ast import SLO_METRICS
    from apex_tpu.observability.slo import LATENCY_METRICS
    assert SLO_METRICS == LATENCY_METRICS


PLANTED = [
    ("ast-annotations", rule_annotations, _plant_annotations,
     _expect_annotations),
    ("ast-collectives/gather", rule_collectives, _plant_gather,
     _expect_gather),
    ("ast-collectives/scatter", rule_collectives, _plant_scatter,
     _expect_scatter),
    ("ast-collectives/grad-psum", rule_collectives, _plant_grad_psum,
     _expect_grad_psum),
    ("ast-metrics-doc", rule_metrics_doc, _plant_metrics_doc,
     _expect_metrics_doc),
    ("ast-metric-families", rule_metric_families, _plant_metric_family,
     _expect_metric_family),
    ("ast-remat-names", rule_remat_names, _plant_remat, _expect_remat),
    ("ast-elastic-exits", rule_elastic_exits, _plant_elastic_exits,
     _expect_elastic_exits),
    ("ast-elastic-exits/choke-rot", rule_elastic_exits,
     _plant_elastic_choke_rot, _expect_elastic_choke_rot),
    ("ast-elastic-exits/launch", rule_elastic_exits, _plant_launch_exit,
     _expect_launch_exit),
    ("ast-elastic-exits/launch-choke-rot", rule_elastic_exits,
     _plant_launch_choke_rot, _expect_launch_choke_rot),
    ("ast-bench-configs", rule_bench_configs, _plant_bench,
     _expect_bench),
    ("ast-bench-history", rule_bench_history, _plant_bench_history,
     _expect_bench_history),
]


@pytest.mark.parametrize("case", PLANTED, ids=[c[0] for c in PLANTED])
def test_ast_planted_violation_fires(case, tmp_path):
    _name, rule_fn, plant, expect = case
    plant(tmp_path)
    findings, _notes = rule_fn(str(tmp_path))
    assert findings
    expect(findings)


def test_missing_inputs_fail_loudly(tmp_path):
    """A tree missing the contract anchors is a failure, not a pass."""
    (tmp_path / "apex_tpu").mkdir()
    for rule_fn in (rule_metrics_doc, rule_remat_names,
                    rule_elastic_exits, rule_bench_configs,
                    rule_bench_history):
        findings, _ = rule_fn(str(tmp_path))
        assert any(f.kind == "MISSING" for f in findings), rule_fn


def test_documenting_fixes_metrics_doc(tmp_path):
    """The doc-side fix path: adding rows (any placeholder spelling)
    silences the rule."""
    _plant_metrics_doc(tmp_path)
    _write(tmp_path, "docs/OBSERVABILITY.md",
           "| `health/rogue_metric` | `health/<tree>/rogue_family` |\n"
           "| `perf/rogue_attribution` | `ckpt/rogue_bytes` |\n"
           "| `serve/rogue_ms` | `serve/rogue_wait_ms` |\n"
           "| `slo/rogue_goodput` | `elastic/rogue_world` |\n"
           "| `fleet/rogue_skew` | `train/rogue_steps` |\n"
           "| `serve/rogue_rejected` | `serve/rogue_poisoned` |\n"
           "| `serve/rogue_brownout` | `perf/rogue_drift` |\n"
           "| `perf/rogue_drift/<metric>` |\n")
    findings, _ = rule_metrics_doc(str(tmp_path))
    assert not findings


# ---------------------------------------------------------------------------
# the CLI (tier-1's consolidated entry point)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cli_all_green_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--all"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selfcheck ok" in proc.stdout  # jaxpr rules proved both ways


def test_cli_single_rule_json_and_planted_repo(tmp_path):
    _plant_gather(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--rule",
         "ast-collectives", "--json", "--repo", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    (entry,) = payload["rules"]
    assert entry["rule"] == "ast-collectives"
    assert any("bad.py:3" in f["where"] for f in entry["findings"])


# ---------------------------------------------------------------------------
# Family A: one grad-sync fixture program, one planted bug at a time
# ---------------------------------------------------------------------------

_N1, _N2, _NS = 24, 40, 4
_PADDED = _N1 + _N2


def _grad_sync_program(violation):
    """A miniature hybrid-trainer step on a 2x2 ``data x pipe`` mesh:
    grads of two 'local' params bucket-reduce-scatter over data inside
    the optimizer_step scope, the 'shared' param's grad psums over pipe.
    ``violation`` plants exactly one historical bug:

    - ``"collective"``: the scatters run through a helper OUTSIDE any
      blessed scope (the smuggled-raw-collective class);
    - ``"flat"``: the grads concatenate into the full padded flat vector
      before syncing (the PR 8 barrier class);
    - ``"shared"``: the shared grad is returned as the per-rank partial
      (the PR 7 drift class);
    - ``"none"``: the clean program.
    """
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "pipe"))

    def f(w, b, s, x):
        def loss_fn(w, b, s):
            return (jnp.sum((x[:_N1] * w) ** 2)
                    + jnp.sum((x[:_N2] * b) ** 2)
                    + jnp.sum(x[:_NS] * s))
        gw, gb, gs = jax.grad(loss_fn, argnums=(0, 1, 2))(w, b, s)
        scope = (contextlib.nullcontext() if violation == "collective"
                 else jax.named_scope("optimizer_step"))

        def sync(g):  # the indirection an AST scan cannot see through
            return jax.lax.psum_scatter(g, "data", tiled=True)

        with scope:
            if violation == "flat":
                parts = (sync(jnp.concatenate([gw, gb])),)
            else:
                parts = (sync(gw), sync(gb))
        if violation != "shared":
            gs = jax.lax.psum(gs, "pipe")
        return (gs, *parts)

    out_specs = (P(), *([P("data")] * (1 if violation == "flat" else 2)))
    wrapped = shard_map_unchecked(
        f, mesh=mesh, in_specs=(P(), P(), P(), P()), out_specs=out_specs)
    args = (jnp.arange(_N1, dtype=jnp.float32),
            jnp.arange(_N2, dtype=jnp.float32),
            jnp.arange(_NS, dtype=jnp.float32),
            jnp.ones(64, jnp.float32))
    return jax.make_jaxpr(wrapped)(*args).jaxpr


def _lint_fixture(jaxpr):
    return lint_program(
        jaxpr, collective_axes=("data",), flat_sizes=(_PADDED,),
        shared_outputs=[(0, "shared grad")], shared_axis="pipe",
        label="fixture")


@pytest.mark.parametrize("violation,expected_rule", [
    ("none", None),
    ("collective", "jaxpr-collectives"),
    ("flat", "jaxpr-flat-grad"),
    ("shared", "jaxpr-shared-grad"),
])
def test_jaxpr_fixture_fires_exactly_its_rule(violation, expected_rule):
    """The cross-talk contract: each planted bug fires its own rule and
    ONLY its own rule; the clean program is silent under the full lint."""
    findings = _lint_fixture(_grad_sync_program(violation))
    fired = {f.rule for f in findings}
    assert fired == (set() if expected_rule is None else {expected_rule}
                     ), findings


def test_jaxpr_collective_finding_names_scope_and_axis():
    findings = _lint_fixture(_grad_sync_program("collective"))
    assert len(findings) == 2  # one per smuggled scatter
    for f in findings:
        # lax.psum_scatter traces as psum_scatter or reduce_scatter
        # depending on the jax line
        assert "scatter" in f.message and "data" in f.message
        assert "optimizer_step" in f.message  # tells you where it belongs


def test_jaxpr_flat_finding_names_the_barrier_primitive():
    (finding,) = _lint_fixture(_grad_sync_program("flat"))
    assert "concatenate" in finding.message
    assert str(_PADDED) in finding.message


def test_jaxpr_shared_finding_points_at_the_fix():
    (finding,) = _lint_fixture(_grad_sync_program("shared"))
    assert "pipe" in finding.message
    assert "_finalize_shared" in finding.message  # the PR 7 fix site


# ---------------------------------------------------------------------------
# Family A: donation (the PR 9 double-donated scale-plane class)
# ---------------------------------------------------------------------------

class TestDonation:
    def test_shared_kvcache_scale_plane_detected(self):
        """The literal PR 9 bug, rebuilt: an int8 KVCache whose k/v
        scale planes are the SAME buffer double-donates it."""
        import dataclasses
        from apex_tpu.serving.cache import KVCache
        cache = KVCache.create(1, 2, 2, 8, 4, dtype=jnp.int8)
        assert not check_donation(donated_args=cache)  # create() is safe
        broken = dataclasses.replace(cache, v_scale=cache.k_scale)
        findings = check_donation(donated_args=broken)
        assert [f.kind for f in findings] == ["DOUBLE"]
        assert "donated twice" in findings[0].message

    def test_unaliased_donation_detected(self):
        import warnings
        a, b = jnp.arange(4.0), jnp.arange(8.0)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            lowered = jax.jit(lambda x, dead: x + 1.0,
                              donate_argnums=(0, 1)).trace(a, b).lower()
        findings = check_donation(lowered, expected_donated=2)
        assert any(f.kind == "UNALIASED" for f in findings)

    def test_clean_donation_silent(self):
        a, b = jnp.arange(4.0), jnp.arange(8.0)
        lowered = jax.jit(lambda x, y: (x + 1.0, y * 2.0),
                          donate_argnums=(0, 1)).trace(a, b).lower()
        assert not check_donation(lowered, donated_args=(a, b),
                                  expected_donated=2)

    def test_compiled_hlo_alias_map_parsed(self):
        """The compiled-program path (HLO header map) counts entries —
        the surface the ServingEngine construction self-check and the
        trainer's verify_donation run on."""
        a, b = jnp.arange(4.0), jnp.arange(8.0)
        compiled = jax.jit(lambda x, y: (x + 1.0, y * 2.0),
                           donate_argnums=(0, 1)).trace(
                               a, b).lower().compile()
        assert not check_donation(compiled, expected_donated=2,
                                  min_alias_bytes=a.nbytes + b.nbytes)
        findings = check_donation(compiled, expected_donated=3)
        assert any(f.kind == "UNALIASED" for f in findings)

    def test_cache_deserialized_executable_trusts_the_alias_map(self):
        """An executable deserialized from the PERSISTENT compilation
        cache reports ``alias_size_in_bytes == 0`` while its HLO alias
        map is intact (reproduced live: fresh compile 4096, cache hit 0,
        identical map — this hard-failed the dryrun serving leg on every
        warm-cache retry). With a COMPLETE map the floor must not fire;
        a genuinely partial alias (0 < bytes < floor) still must."""

        class FakeAnalysis:
            def __init__(self, alias):
                self.alias_size_in_bytes = alias

        class FakeCompiled:
            def __init__(self, alias):
                self._alias = alias

            def as_text(self):
                return ("HloModule jit_step, "
                        "input_output_alias={ {0}: (0, {}, "
                        "may-alias), {1}: (1, {}, may-alias) }\n")

            def memory_analysis(self):
                return FakeAnalysis(self._alias)

        # cache case: 0 bytes next to a complete 2-entry map -> silent
        assert not check_donation(FakeCompiled(0), expected_donated=2,
                                  min_alias_bytes=4096)
        # partial alias: nonzero-but-small bytes -> still a finding
        findings = check_donation(FakeCompiled(100), expected_donated=2,
                                  min_alias_bytes=4096)
        assert [f.kind for f in findings] == ["UNALIASED"]
        assert "alias_size_in_bytes 100" in findings[0].message
        # 0 bytes next to an INCOMPLETE map is still two findings
        # (missing leaf + floor), not excused
        findings = check_donation(FakeCompiled(0), expected_donated=3,
                                  min_alias_bytes=4096)
        assert sorted(f.kind for f in findings) == ["UNALIASED",
                                                    "UNALIASED"]


# ---------------------------------------------------------------------------
# Family A: the zero-recompile budget
# ---------------------------------------------------------------------------

class TestRecompileGuard:
    def test_steady_shape_is_silent(self):
        step = jax.jit(lambda x: x * 3.0)
        step(jnp.ones(4))
        with recompile_guard("test") as g:
            for _ in range(3):
                step(jnp.ones(4))
        assert not g.findings

    def test_shape_leak_raises(self):
        step = jax.jit(lambda x: x * 3.0)
        with pytest.raises(AnalysisError, match="compile-storm"):
            with recompile_guard("test") as g:
                g.rebase()
                for n in (5, 6, 7):
                    step(jnp.ones(n))

    def test_rebase_forgives_warmup_only(self):
        step = jax.jit(lambda x: x * 3.0)
        with recompile_guard("test", raise_on_violation=False) as g:
            step(jnp.ones(9))   # warmup compile
            g.rebase()
            step(jnp.ones(9))   # cached: silent
        assert not g.findings
        (finding,) = _storm()
        assert finding.rule == "jaxpr-recompile"

    def test_loop_exception_not_masked(self):
        with pytest.raises(ZeroDivisionError):
            with recompile_guard("test"):
                raise ZeroDivisionError


def _storm():
    step = jax.jit(lambda x: x * 5.0)
    with recompile_guard("test", raise_on_violation=False) as g:
        step(jnp.ones(11))
        step(jnp.ones(12))
    return g.findings


# ---------------------------------------------------------------------------
# shared-grad rule: cone precision across wrappers
# ---------------------------------------------------------------------------

def test_shared_grad_cone_is_per_output():
    """The cone walk is per-output: a psum on ANOTHER output must not
    excuse the unreduced one (no rule-level cross-contamination)."""
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pipe",))

    def f(a, b):
        return jax.lax.psum(a, "pipe"), b * 2.0  # b never reduced

    wrapped = shard_map_unchecked(f, mesh=mesh, in_specs=(P(), P()),
                                  out_specs=(P(), P()))
    jaxpr = jax.make_jaxpr(wrapped)(jnp.ones(4), jnp.ones(4)).jaxpr
    assert not check_shared_grad_reduction(jaxpr, [(0, "a")], "pipe")
    findings = check_shared_grad_reduction(jaxpr, [(1, "b")], "pipe")
    assert len(findings) == 1 and findings[0].kind == "PARTIAL"


# ---------------------------------------------------------------------------
# the port deleted the per-script boilerplate for good
# ---------------------------------------------------------------------------

def test_script_shims_carry_no_walker_boilerplate():
    """Each scripts/check_*.py is a thin shim over the engine: no private
    AST/file-walk copies may creep back in (they went from ~150 lines of
    duplicated walker each to <80-line shims in PR 11)."""
    import glob
    import os
    shims = sorted(glob.glob(os.path.join(REPO, "scripts", "check_*.py")))
    assert len(shims) == 6
    for path in shims:
        src = open(path).read()
        assert len(src.splitlines()) < 80, f"{path} grew boilerplate back"
        for needle in ("ast.walk", "os.walk", "ast.parse"):
            assert needle not in src, f"{path} re-inlined {needle}"
        assert "apex_tpu.analysis" in src  # it really is a shim
