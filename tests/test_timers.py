"""Timers + profiling-annotation tests
(``reference:apex/transformer/pipeline_parallel/_timers.py:6-79``)."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.timers import Timer, Timers, device_fence


def test_timer_accumulates_and_resets():
    t = Timer("t")
    t.start()
    time.sleep(0.01)
    t.stop()
    t.start()
    time.sleep(0.01)
    t.stop()
    assert t.count_ == 2
    elapsed = t.elapsed(reset=True)
    assert elapsed >= 0.02
    assert t.elapsed(reset=False) == 0.0


def test_timer_elapsed_while_running_restarts():
    t = Timer("t")
    t.start()
    time.sleep(0.005)
    first = t.elapsed(reset=False)
    assert first > 0
    assert t.started_  # still running, like the reference
    t.stop()


def test_timer_context_manager_and_fence():
    t = Timer("t")
    x = jnp.ones((256, 256))
    with t(wait_for=None):
        y = jax.jit(lambda a: a @ a)(x)
        device_fence(y)
    assert t.elapsed() > 0


def test_timers_group_log_and_write():
    ts = Timers()
    ts("fwd").start()
    time.sleep(0.002)
    ts("fwd").stop()
    line = ts.log(["fwd"], reset=False)
    assert line.startswith("time (ms) | fwd:")

    class FakeWriter:
        def __init__(self):
            self.calls = []

        def add_scalar(self, tag, value, step):
            self.calls.append((tag, value, step))

    w = FakeWriter()
    ts.write(["fwd"], w, iteration=3)
    assert w.calls and w.calls[0][0] == "fwd-time" and w.calls[0][2] == 3


def test_named_scopes_reach_hlo():
    """The pre-annotated hot paths must show up in lowered HLO metadata —
    that is what makes a captured profile attributable (the pyprof
    annotate-step equivalent)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.parallel.distributed import allreduce_grads
    from apex_tpu.parallel.sync_batchnorm import (BatchNormState,
                                                  sync_batch_norm)

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def scope_text(lowered):
        """Render with op metadata: newer jax carries scopes in the
        lowered text under debug_info=True; 0.4.x only in compiled HLO."""
        try:
            return lowered.as_text(debug_info=True)
        except TypeError:
            return lowered.compile().as_text()

    def step(g):
        return shard_map(
            lambda g: allreduce_grads({"w": g}, "data")["w"],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)

    txt = scope_text(jax.jit(step).lower(jnp.ones((2, 4))))
    assert "apex_ddp_allreduce" in txt

    state = BatchNormState(jnp.zeros(3), jnp.ones(3), jnp.asarray(0))

    def bn(x):
        return sync_batch_norm(x, jnp.ones(3), jnp.zeros(3), state,
                               channel_axis=-1)[0]

    txt = scope_text(jax.jit(bn).lower(jnp.ones((4, 3))))
    assert "sync_bn_stats" in txt


class TestAutoResume:
    def test_sigterm_sets_flag(self):
        import os
        import signal

        from apex_tpu.utils.autoresume import AutoResume
        with AutoResume(interval=10) as ar:
            assert not ar.termination_requested(step=0)
            prev = signal.getsignal(signal.SIGTERM)
            os.kill(os.getpid(), signal.SIGTERM)
            assert ar.termination_requested(step=3)  # flag beats interval
        # context exit restored the previous handler
        assert signal.getsignal(signal.SIGTERM) is not prev

    def test_env_and_hook_polling(self, monkeypatch):
        from apex_tpu.utils.autoresume import AutoResume
        calls = []

        def hook():
            calls.append(1)
            return False

        ar = AutoResume(interval=5, hook=hook,
                        install_sigterm_handler=False)
        for s in range(1, 5):
            assert not ar.termination_requested(step=s)
        assert not calls  # off-interval steps do not poll
        ar.termination_requested(step=5)
        assert len(calls) == 1
        monkeypatch.setenv("APEX_TPU_TERMINATE", "1")
        assert ar.termination_requested(step=10)

    def test_checkpoint_then_resume_flow(self, tmp_path, monkeypatch):
        """The documented recipe: terminate -> checkpoint -> restart ->
        restore latest."""
        import jax.numpy as jnp
        import pytest

        from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint
        from apex_tpu.utils.autoresume import AutoResume

        ar = AutoResume(install_sigterm_handler=False)
        monkeypatch.setenv("APEX_TPU_TERMINATE", "1")
        state = {"w": jnp.ones(4) * 7}
        if ar.termination_requested(step=12):
            save_checkpoint(str(tmp_path), state, step=12,
                            host_state={"step": 12})
            with pytest.raises(SystemExit):
                ar.request_resume()
        restored, host = restore_checkpoint(str(tmp_path), state)
        assert host["step"] == 12
        assert float(restored["w"][0]) == 7.0
