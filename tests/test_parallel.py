"""Data-parallel layer tests on the 8-virtual-device CPU mesh.

Models: ``reference:tests/distributed/synced_batchnorm/`` (single vs multi
device parity, uneven batches via groups, fused relu),
``tests/distributed/DDP/ddp_race_condition_test.py`` (grad-value identities),
``examples/simple/distributed``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from _jaxpr_utils import jaxpr_str
from apex_tpu.utils.compat import shard_map

from apex_tpu.parallel import (
    DistributedDataParallel, Reducer, SyncBatchNorm, allreduce_grads,
    convert_syncbn_model, create_syncbn_process_group, sync_batch_norm)


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def test_allreduce_grads_matches_manual_mean():
    mesh = _mesh()
    grads = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    @jax.jit
    def run(g):
        return shard_map(
            lambda g: allreduce_grads({"w": g}, "data")["w"],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)

    out = run(grads)
    expected = np.tile(np.asarray(grads).mean(0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_predivide_factor_numerics():
    """predivide path must equal plain averaging in exact arithmetic
    (distributed.py:445-454)."""
    mesh = _mesh()
    grads = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)

    def run(pre):
        return shard_map(
            lambda g: allreduce_grads(
                {"w": g}, "data", gradient_predivide_factor=pre)["w"],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(grads)

    np.testing.assert_allclose(np.asarray(run(1.0)), np.asarray(run(8.0)),
                               rtol=1e-5)


def test_ddp_value_and_grad():
    mesh = _mesh()
    ddp = DistributedDataParallel(axis_name="data")
    x = jnp.asarray(np.random.RandomState(1).randn(16, 4), jnp.float32)
    y = jnp.asarray(np.random.RandomState(2).randn(16, 1), jnp.float32)
    w = jnp.zeros((4, 1), jnp.float32)

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    @jax.jit
    def dist_grad(w, x, y):
        return shard_map(
            lambda w, x, y: ddp.value_and_grad(loss_fn)(w, x, y)[1],
            mesh=mesh, in_specs=(P(), P("data"), P("data")),
            out_specs=P())(w, x, y)

    g_dist = dist_grad(w, x, y)
    g_ref = jax.grad(loss_fn)(w, x, y)
    np.testing.assert_allclose(np.asarray(g_dist), np.asarray(g_ref), rtol=1e-5)


def test_reducer_averages_params():
    mesh = _mesh()
    params = jnp.arange(8.0).reshape(8, 1)
    out = jax.jit(shard_map(
        lambda p: Reducer("data").reduce(p),
        mesh=mesh, in_specs=P("data"), out_specs=P("data")))(params)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5), rtol=1e-6)


# ---------------------------------------------------------------------------
# SyncBatchNorm
# ---------------------------------------------------------------------------

def test_syncbn_matches_full_batch_bn():
    """Distributed stats == single-device full-batch stats
    (two_gpu_unit_test.py parity model)."""
    mesh = _mesh()
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(16, 6, 5, 5), jnp.float32)  # NCHW
    bn = SyncBatchNorm(6, axis_name="data")
    params, state = bn.init()

    @jax.jit
    def dist(x):
        return shard_map(
            lambda x: bn(params, state, x, training=True)[0],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)

    out_dist = dist(x)
    bn_local = SyncBatchNorm(6, axis_name=None)
    out_ref, new_state = bn_local(params, state, x, training=True)
    np.testing.assert_allclose(np.asarray(out_dist), np.asarray(out_ref),
                               rtol=1e-4, atol=1e-5)

    # running stats match torch convention
    import torch
    tbn = torch.nn.BatchNorm2d(6, momentum=0.1)
    tbn.train()
    tout = tbn(torch.tensor(np.asarray(x)))
    np.testing.assert_allclose(np.asarray(new_state.running_mean),
                               tbn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state.running_var),
                               tbn.running_var.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_ref), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-4)


def test_syncbn_backward_through_psum():
    """AD through the psum reproduces the reference's allreduced backward:
    grads must equal single-device full-batch BN grads."""
    mesh = _mesh()
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)
    dy = jnp.asarray(rng.randn(16, 4), jnp.float32)
    bn = SyncBatchNorm(4, axis_name="data", channel_axis=-1)
    params, state = bn.init()

    def dist_loss(params, x):
        def inner(params, x, dy):
            out, _ = bn(params, state, x, training=True)
            return jax.lax.psum(jnp.sum(out * dy), "data")
        return shard_map(inner, mesh=mesh, in_specs=(P(), P("data"), P("data")),
                         out_specs=P())(params, x, dy)

    def ref_loss(params, x):
        bn_local = SyncBatchNorm(4, axis_name=None, channel_axis=-1)
        out, _ = bn_local(params, state, x, training=True)
        return jnp.sum(out * dy)

    g_dist = jax.jit(jax.grad(dist_loss))(params, x)
    g_ref = jax.grad(ref_loss)(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_dist),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_syncbn_groups_uneven_semantics():
    """Process-group BN (test_groups.py): groups of 4 normalize separately."""
    mesh = _mesh()
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 3), jnp.float32)
    groups = create_syncbn_process_group(4, 8)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    bn = SyncBatchNorm(3, axis_name="data", axis_index_groups=groups,
                       channel_axis=-1)
    params, state = bn.init()

    @jax.jit
    def dist(x):
        return shard_map(lambda x: bn(params, state, x, training=True)[0],
                         mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)

    out = np.asarray(dist(x))
    # each group of 4 rows is normalized with its own stats
    bn_local = SyncBatchNorm(3, axis_name=None, channel_axis=-1)
    for lo, hi in [(0, 4), (4, 8)]:
        ref, _ = bn_local(params, state, x[lo:hi], training=True)
        np.testing.assert_allclose(out[lo:hi], np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


def test_syncbn_eval_and_fused_relu_and_z():
    bn = SyncBatchNorm(4, channel_axis=-1, fuse_relu=True)
    params, state = bn.init()
    x = jnp.asarray(np.random.RandomState(6).randn(10, 4), jnp.float32)
    z = jnp.ones((10, 4), jnp.float32) * 0.5
    out, _ = bn(params, state, x, training=True, z=z)
    assert (np.asarray(out) >= 0).all()  # relu applied
    out_eval, st = bn(params, state, x, training=False)
    assert int(st.num_batches_tracked) == 0  # eval does not update


def test_convert_syncbn_model():
    class Net:
        def __init__(self):
            self.bn1 = SyncBatchNorm(4)
            self.blocks = [SyncBatchNorm(8), "not-a-bn"]

    net = convert_syncbn_model(Net(), axis_name="data")
    assert net.bn1.axis_name == "data"
    assert net.blocks[0].axis_name == "data"
    assert net.blocks[1] == "not-a-bn"


def test_uneven_group_averaging():
    """Each rank averages by its OWN group size (review fix)."""
    mesh = _mesh()
    grads = jnp.ones((8, 2), jnp.float32)
    groups = [[0, 1], [2, 3, 4, 5, 6, 7]]

    @jax.jit
    def run(g):
        return shard_map(
            lambda g: allreduce_grads({"w": g}, "data",
                                      axis_index_groups=groups)["w"],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"))(g)

    out = np.asarray(run(grads))
    np.testing.assert_allclose(out, np.ones((8, 2)), rtol=1e-6)


def test_syncbn_track_running_stats_false():
    bn = SyncBatchNorm(4, channel_axis=-1, track_running_stats=False)
    params, state = bn.init()
    x = jnp.asarray(np.random.RandomState(8).randn(10, 4) * 3 + 5, jnp.float32)
    out_eval, st = bn(params, state, x, training=False)
    # batch stats used even in eval: output is normalized
    assert abs(float(np.asarray(out_eval).mean())) < 1e-5
    # state untouched
    np.testing.assert_array_equal(np.asarray(st.running_mean),
                                  np.asarray(state.running_mean))
    assert int(st.num_batches_tracked) == 0


def test_syncbn_apply_dtype_matches_fp32_path():
    """apply_dtype folds the normalize to a per-channel x*a+b at input
    precision; statistics stay fp32, so outputs match the fp32 path to
    bf16 rounding and the running stats match exactly (docs/PERF.md)."""
    from apex_tpu.parallel.sync_batchnorm import sync_batch_norm

    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(64, 8) * 2 + 1, jnp.bfloat16)
    w = jnp.asarray(rng.rand(8) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    z = jnp.asarray(rng.randn(64, 8), jnp.bfloat16)
    _, state = SyncBatchNorm(8, channel_axis=-1).init()

    ref, st_ref = sync_batch_norm(x, w, b, state, training=True,
                                  channel_axis=-1, z=z, fuse_relu=True)
    fast, st_fast = sync_batch_norm(x, w, b, state, training=True,
                                    channel_axis=-1, z=z, fuse_relu=True,
                                    apply_dtype=jnp.bfloat16)
    assert fast.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(fast, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)
    # statistics are identical — only the elementwise apply changed
    np.testing.assert_array_equal(np.asarray(st_fast.running_mean),
                                  np.asarray(st_ref.running_mean))
    np.testing.assert_array_equal(np.asarray(st_fast.running_var),
                                  np.asarray(st_ref.running_var))

    # gradients flow and stay finite through the folded path
    def loss(x):
        out, _ = sync_batch_norm(x, w, b, state, training=True,
                                 channel_axis=-1, apply_dtype=jnp.bfloat16)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))


# ---------------------------------------------------------------------------
# delay_allreduce / gradient accumulation (apex no_sync semantics)
# ---------------------------------------------------------------------------

def test_delay_allreduce_returns_unsynced_grads():
    """DDP(delay_allreduce=True) is real: value_and_grad skips the inline
    sync (zero psums in its jaxpr) and returns per-replica grads."""
    mesh = _mesh()
    x = jnp.asarray(np.random.RandomState(3).randn(16, 4), jnp.float32)
    y = jnp.asarray(np.random.RandomState(4).randn(16, 1), jnp.float32)
    w = jnp.asarray(np.random.RandomState(5).randn(4, 1), jnp.float32)

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    def run(ddp, stacked):
        def wrapped(w, x, y):
            def inner(w, x, y):
                g = ddp.value_and_grad(loss_fn)(w, x, y)[1]
                # unsynced grads are per-rank: stack them on a sharded
                # leading axis to bring every replica's copy out
                return g[None] if stacked else g
            return shard_map(
                inner, mesh=mesh, in_specs=(P(), P("data"), P("data")),
                out_specs=P("data") if stacked else P())(w, x, y)
        return wrapped

    delayed = run(DistributedDataParallel(axis_name="data",
                                          delay_allreduce=True), True)
    synced = run(DistributedDataParallel(axis_name="data"), False)
    # the delayed jaxpr has no psum; the synced one has exactly one
    assert jaxpr_str(delayed, w, x, y).count("psum") == 0
    assert jaxpr_str(synced, w, x, y).count("psum") == 1
    # and its value is each replica's own-shard grad, not the mean
    g_delay = jax.jit(delayed)(w, x, y)  # (8, 4, 1): per-rank grads
    g_sync = jax.jit(synced)(w, x, y)
    assert g_delay.shape == (8, 4, 1)
    per_rank = np.stack([
        np.asarray(jax.grad(loss_fn)(w, x[i * 2:(i + 1) * 2],
                                     y[i * 2:(i + 1) * 2]))
        for i in range(8)])
    np.testing.assert_allclose(np.asarray(g_delay).reshape(8, 4, 1),
                               per_rank, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_sync), per_rank.mean(0),
                               rtol=1e-5, atol=1e-6)


def test_accumulate_gradients_single_psum():
    """The gradient-accumulation window fires exactly ONE allreduce: the
    jaxpr over K microbatches holds a single psum (vs K for per-microbatch
    sync), and the result equals the full-batch DDP grads."""
    from apex_tpu.training import accumulate_gradients

    mesh = _mesh()
    rng = np.random.RandomState(6)
    K = 3
    w = jnp.asarray(rng.randn(4, 2), jnp.float32)
    xs = jnp.asarray(rng.randn(K, 16, 4), jnp.float32)
    ys = jnp.asarray(rng.randn(K, 16, 2), jnp.float32)

    def loss_fn(w, mb):
        x, y = mb
        return jnp.mean((x @ w - y) ** 2)

    ddp = DistributedDataParallel(axis_name="data", delay_allreduce=True)

    def run(w, xs, ys):
        def inner(w, xs, ys):
            loss, grads = accumulate_gradients(ddp, loss_fn, w, (xs, ys))
            # the window loss is rank-local: bring the replicas out stacked
            return jnp.reshape(loss, (1,)), grads
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), P(None, "data"), P(None, "data")),
                         out_specs=(P("data"), P()))(w, xs, ys)

    # exactly one psum per accumulation window (single-leaf params)
    assert jaxpr_str(run, w, xs, ys).count("psum") == 1

    _, g = jax.jit(run)(w, xs, ys)

    # reference: grad of the mean loss over all K x full-batch samples
    def ref_loss(w):
        return jnp.mean(jax.vmap(
            lambda x, y: jnp.mean((x @ w - y) ** 2))(xs, ys))

    gr = jax.grad(ref_loss)(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-5,
                               atol=1e-6)


def test_accumulate_gradients_rejects_ragged_microbatches():
    from apex_tpu.training import accumulate_gradients

    ddp = DistributedDataParallel(axis_name="data", delay_allreduce=True)
    w = jnp.zeros((2, 2))
    with pytest.raises(ValueError, match="accumulation axis"):
        accumulate_gradients(ddp, lambda w, mb: jnp.sum(w), w,
                             (jnp.zeros((3, 2)), jnp.zeros((4, 2))))
