"""Speculative decoding (docs/SERVING.md "Speculative decoding"): the
verify_tokens acceptance rule (greedy exact-prefix, rejection sampling
with the corrected residual), the self-drafting NGramDraftSource, the
k-token paged verify window at block boundaries (counts 0/1/k-1/k
across a block edge, pool-exhaustion mid-verify, saturation writing
nothing), dense append_k saturation, the advance-by-accepted rollback
invariant on both engines, greedy spec-stream parity under the
zero-recompile guard, and mid-verify retirement (poison quarantine)
leaving no drafted-but-rejected KV visible to a re-admitted slot."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.elastic.faults import FaultPlan
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.serving import (BlockAllocator, DraftSource, KVCache,
                              NGramDraftSource, PagedKVCache,
                              PagedServingEngine, Rejection, Request,
                              ServingEngine, SlotScheduler, verify_tokens)
from apex_tpu.serving.cache import NULL_BLOCK

K = 2  # the static draft window the spec engines below compile


# ---------------------------------------------------------------------------
# verify_tokens: the acceptance rule
# ---------------------------------------------------------------------------

class TestVerifyTokens:
    V = 7

    def _chain_logits(self, argmaxes):
        """(1, Q, V) logits whose per-row argmax is ``argmaxes``."""
        out = np.zeros((1, len(argmaxes), self.V), np.float32)
        for i, t in enumerate(argmaxes):
            out[0, i, t] = 5.0
        return jnp.asarray(out)

    @pytest.mark.parametrize("drafts,want_accepted,want_emit", [
        ([2, 4], 2, [2, 4, 1]),   # full accept + bonus
        ([2, 3], 1, [2, 4]),      # prefix accept, row 1 corrected
        ([3, 4], 0, [2]),         # first draft wrong: correction only
    ])
    def test_greedy_exact_prefix(self, drafts, want_accepted, want_emit):
        logits = self._chain_logits([2, 4, 1])
        toks, accepted = verify_tokens(
            logits, jnp.asarray([drafts], jnp.int32),
            jax.random.PRNGKey(0), jnp.zeros((1,), jnp.float32))
        assert int(accepted[0]) == want_accepted
        # the emitted window is the accepted prefix + one correction or
        # bonus — and on the greedy path every row IS the argmax, so the
        # stream is bitwise the non-speculative one
        emit = [int(t) for t in toks[0, : want_accepted + 1]]
        assert emit == want_emit

    def test_stochastic_sure_draft_always_accepts(self):
        # the draft carries ~all the model mass: rejection sampling
        # accepts it for every key
        logits = self._chain_logits([2, 4, 1]) * 20.0
        temps = jnp.ones((1,), jnp.float32)
        for seed in range(5):
            toks, accepted = verify_tokens(
                logits, jnp.asarray([[2, 4]], jnp.int32),
                jax.random.PRNGKey(seed), temps)
            assert int(accepted[0]) == 2
            assert [int(t) for t in toks[0, :2]] == [2, 4]

    def test_stochastic_rejection_never_emits_the_draft(self):
        # the draft has ~zero mass: always rejected, and the corrected
        # residual (draft mass zeroed) can never re-emit it
        logits = np.zeros((1, 2, self.V), np.float32)
        logits[0, :, 3] = -1e9
        logits = jnp.asarray(logits)
        for seed in range(8):
            toks, accepted = verify_tokens(
                logits, jnp.asarray([[3]], jnp.int32),
                jax.random.PRNGKey(seed), jnp.ones((1,), jnp.float32))
            assert int(accepted[0]) == 0
            assert int(toks[0, 0]) != 3

    def test_stochastic_marginal_is_exactly_the_model(self):
        """The rejection-sampling correctness property: accept-with-
        p(draft), resample-from-residual makes the emitted token's
        marginal EXACTLY softmax(logits/T) (docs/SERVING.md carries the
        two-line proof)."""
        V = 3
        logits = jnp.asarray([[[0.8, 0.1, -0.4],
                               [0.0, 0.0, 0.0]]], jnp.float32)
        temps = jnp.ones((1,), jnp.float32)
        drafts = jnp.asarray([[1]], jnp.int32)
        keys = jax.random.split(jax.random.PRNGKey(42), 600)
        toks = jax.vmap(
            lambda k: verify_tokens(logits, drafts, k, temps)[0])(keys)
        first = np.asarray(toks)[:, 0, 0]
        want = np.asarray(jax.nn.softmax(logits[0, 0]))
        got = np.bincount(first, minlength=V) / len(first)
        np.testing.assert_allclose(got, want, atol=0.07)

    def test_top_k_one_is_greedy_even_when_stochastic(self):
        logits = self._chain_logits([2, 4, 1])
        toks, accepted = verify_tokens(
            logits, jnp.asarray([[2, 4]], jnp.int32),
            jax.random.PRNGKey(0), jnp.ones((1,), jnp.float32), top_k=1)
        assert int(accepted[0]) == 2
        assert [int(t) for t in toks[0]] == [2, 4, 1]


# ---------------------------------------------------------------------------
# the self-drafting n-gram source
# ---------------------------------------------------------------------------

class TestNGramDraftSource:
    def test_periodic_context_proposes_the_continuation(self):
        src = NGramDraftSource()
        assert src.draft([1, 2, 3, 1, 2, 3, 1, 2], 3) == [3, 1, 2]

    def test_no_repeat_falls_back_to_last_token(self):
        src = NGramDraftSource()
        assert src.draft([5, 6, 7], 3) == [7, 7, 7]

    def test_short_continuation_pads_with_its_tail(self):
        src = NGramDraftSource()
        # suffix [1, 2] matches at the start; the continuation [1, 2]
        # runs out before k and pads with its last token
        assert src.draft([1, 2, 1, 2], 4) == [1, 2, 2, 2]

    def test_longest_suffix_match_wins(self):
        src = NGramDraftSource(max_ngram=3)
        # the 1-gram [9] also matches earlier, but the 2-gram [2, 9]
        # match is longer and pins the prediction to 7
        assert src.draft([2, 9, 7, 4, 9, 5, 2, 9], 1) == [7]

    def test_interface_is_abstract(self):
        with pytest.raises(NotImplementedError):
            DraftSource().draft([1, 2], 2)


# ---------------------------------------------------------------------------
# the k-token paged verify window: allocator + pool
# ---------------------------------------------------------------------------

def _alloc(num_blocks=10, block_size=4, blocks_per_slot=4, max_seqs=2):
    return BlockAllocator(num_blocks, block_size, blocks_per_slot,
                          max_seqs)


class TestPagedVerifyWindow:
    @pytest.mark.parametrize("count", [0, 1, 2, 3])  # 0, 1, k-1, k
    def test_window_across_block_edge_advances_by_count(self, count):
        """The PR 16 regression, extended: a 3-token verify window from
        cursor 3 crosses the block edge at 4 — each token names its own
        (block, offset), every row is physically written, and the
        cursor mirror moves by the ACCEPTED count only."""
        alloc = _alloc()
        alloc.admit(0, [11, 12, 13], prefill_blocks=1)
        plan = alloc.prepare_verify([0], 3)
        assert plan.failed == []
        b0, b1 = int(alloc.tables[0, 0]), int(alloc.tables[0, 1])
        assert b0 != NULL_BLOCK and b1 != NULL_BLOCK  # edge block mapped
        active = np.asarray([True, False])
        bids, offs = alloc.verify_targets(active, 3)
        np.testing.assert_array_equal(bids[0], [b0, b1, b1])
        np.testing.assert_array_equal(offs[0], [3, 0, 1])
        # the inactive slot's whole window aims at the null absorber
        assert np.all(bids[1] == NULL_BLOCK)

        pool = PagedKVCache.create(1, alloc.num_blocks, 1,
                                   alloc.block_size, 2, jnp.float32)
        val = np.zeros((1, 2, 1, 3, 2), np.float32)
        for s in range(2):
            for r in range(3):
                val[0, s, 0, r, :] = 100 * s + r + 1
        pool = pool.append_k(jnp.asarray(val), jnp.asarray(val),
                             jnp.asarray(bids), jnp.asarray(offs))
        k = np.asarray(pool.k)
        # write-all: every row of slot 0's window landed at its target,
        # accepted or not — rejected rows sit ABOVE the cursor, masked
        # from every read and overwritten by the next window
        for r, (b, o) in enumerate(zip(bids[0], offs[0])):
            np.testing.assert_array_equal(k[0, b, 0, o], [r + 1, r + 1])
        # nothing outside the named blocks and the null absorber moved
        untouched = np.ones(alloc.num_blocks, bool)
        untouched[[NULL_BLOCK, b0, b1]] = False
        assert not np.any(k[0, untouched])

        alloc.advance_counts([0], [count])
        assert int(alloc.lengths[0]) == 3 + count
        # the next window starts exactly at the advanced cursor, so the
        # rejected tail (positions 3+count..5) is what it overwrites
        _, offs2 = alloc.verify_targets(active, 3)
        assert int(offs2[0, 0]) == (3 + count) % alloc.block_size

    def test_exhaustion_mid_verify_is_atomic_per_slot(self):
        alloc = _alloc(num_blocks=3, block_size=4, blocks_per_slot=4)
        alloc.admit(0, [1, 2, 3, 4], prefill_blocks=1)
        alloc.admit(1, [5, 6, 7, 8], prefill_blocks=1)
        assert alloc.free_blocks == 0
        # both slots' windows need a fresh edge block; the dry pool
        # fails them WITHOUT mutating tables or the free list
        plan = alloc.prepare_verify([0, 1], 3)
        assert plan.failed == [0, 1]
        assert alloc.free_blocks == 0
        assert int(alloc.tables[0, 1]) == NULL_BLOCK
        # and a failed slot's window aims at the null block end to end
        bids, _ = alloc.verify_targets(np.asarray([False, False]), 3)
        assert np.all(bids == NULL_BLOCK)

    def test_partial_grab_rolls_back(self):
        alloc = _alloc(num_blocks=4, block_size=4, blocks_per_slot=4)
        alloc.admit(0, [1, 2, 3, 4], prefill_blocks=1)
        alloc.admit(1, [5, 6, 7, 8], prefill_blocks=1)
        assert alloc.free_blocks == 1
        # a 6-token window from cursor 4 spans table entries 1 AND 2 —
        # two fresh blocks — but only one is free: the partial grab is
        # handed back (atomic per slot), not kept
        plan = alloc.prepare_verify([0], 6)
        assert plan.failed == [0]
        assert alloc.free_blocks == 1
        assert np.all(alloc.tables[0, 1:] == NULL_BLOCK)

    def test_saturation_masks_past_capacity_then_writes_nothing(self):
        alloc = _alloc(num_blocks=10, block_size=4, blocks_per_slot=2)
        alloc.admit(0, list(range(1, 8)), prefill_blocks=2)  # cursor 7/8
        assert alloc.prepare_verify([0], 3).failed == []
        bids, offs = alloc.verify_targets(np.asarray([True, False]), 3)
        # only position 7 fits; 8 and 9 sit past capacity -> null
        assert int(bids[0, 0]) == int(alloc.tables[0, 1])
        assert int(offs[0, 0]) == 3
        np.testing.assert_array_equal(bids[0, 1:], [NULL_BLOCK] * 2)
        alloc.advance_counts([0], [3])
        assert int(alloc.lengths[0]) == 8      # clamped at capacity
        # AT capacity: the slot fails preparation and the whole window
        # aims at the null block — a saturated slot writes nothing
        assert alloc.prepare_verify([0], 3).failed == [0]
        bids, _ = alloc.verify_targets(np.asarray([True, False]), 3)
        assert np.all(bids[0] == NULL_BLOCK)


class TestDenseAppendKSaturation:
    def _cache(self, length):
        cache = KVCache.create(1, 1, 1, 8, 2, dtype=jnp.float32)
        import dataclasses
        return dataclasses.replace(
            cache, lengths=jnp.asarray([length], jnp.int32))

    def _window(self):
        val = np.zeros((1, 1, 1, 3, 2), np.float32)
        for r in range(3):
            val[0, 0, 0, r, :] = r + 1
        return jnp.asarray(val)

    def test_at_max_len_writes_nothing(self):
        cache = self._cache(8)
        out = cache.append_k(self._window(), self._window(),
                             jnp.asarray([0], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out.k),
                                      np.asarray(cache.k))
        assert int(out.lengths[0]) == 8

    def test_near_saturation_clamps_the_window(self):
        cache = self._cache(7)
        out = cache.append_k(self._window(), self._window(),
                             jnp.asarray([1], jnp.int32))
        k = np.asarray(out.k)[0, 0, 0]
        # row 0 landed at position 7; rows 1-2 (past max_len) dropped,
        # and positions below the cursor came back unchanged
        np.testing.assert_array_equal(k[7], [1.0, 1.0])
        assert not np.any(k[:7])
        assert int(out.lengths[0]) == 8


# ---------------------------------------------------------------------------
# engines: advance-by-accepted + stream parity + retirement
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype=jnp.float32)
    model = GPTModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def dense_ref(model_params):
    model, params = model_params
    return ServingEngine(model, params, max_seqs=2, max_len=24,
                         prefill_len=8, cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def dense_spec(model_params):
    model, params = model_params
    return ServingEngine(model, params, max_seqs=2, max_len=24,
                         prefill_len=8, cache_dtype=jnp.float32,
                         speculate_k=K, quarantine=True)


@pytest.fixture(scope="module")
def paged_ref(model_params):
    model, params = model_params
    return PagedServingEngine(model, params, max_seqs=2, max_len=24,
                              prefill_len=8, num_blocks=16, block_size=4,
                              cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def paged_spec(model_params):
    model, params = model_params
    return PagedServingEngine(model, params, max_seqs=2, max_len=24,
                              prefill_len=8, num_blocks=16, block_size=4,
                              cache_dtype=jnp.float32, speculate_k=K,
                              quarantine=True)


def _ref_stream(eng, prompt, n):
    """n-token greedy stream from the non-speculative engine."""
    out = [eng.prefill(prompt, 0)]
    toks = np.zeros(eng.max_seqs, np.int32)
    temps = np.zeros(eng.max_seqs, np.float32)
    active = np.asarray([True, False])
    for _ in range(n - 1):
        toks[0] = out[-1]
        out.append(int(eng.decode(toks, temps, active)[0]))
    eng.release_slot(0)
    return out


class TestEngineVerify:
    def test_validation(self, model_params):
        model, params = model_params
        with pytest.raises(ValueError, match="speculate_k"):
            ServingEngine(model, params, max_seqs=1, max_len=16,
                          prefill_len=4, speculate_k=-1)
        with pytest.raises(ValueError, match="verify window"):
            ServingEngine(model, params, max_seqs=1, max_len=8,
                          prefill_len=4, speculate_k=8)

    def test_verify_on_plain_engine_raises(self, dense_ref):
        assert dense_ref.verify_compiled is None
        with pytest.raises(ValueError, match="speculative"):
            dense_ref.verify(np.zeros(2, np.int32),
                             np.zeros((2, K), np.int32),
                             np.zeros(2, np.float32))

    def test_scheduler_engine_window_mismatch(self, dense_ref,
                                              dense_spec):
        with pytest.raises(ValueError, match="speculate_k"):
            SlotScheduler(dense_ref, registry=MetricsRegistry(),
                          speculate_k=K)
        with pytest.raises(ValueError, match="speculate_k"):
            SlotScheduler(dense_spec, registry=MetricsRegistry(),
                          speculate_k=K + 1)
        with pytest.raises(ValueError, match="draft_source"):
            SlotScheduler(dense_ref, registry=MetricsRegistry(),
                          draft_source=NGramDraftSource())
        # the default draft source rides in with speculate_k
        sched = SlotScheduler(dense_spec, registry=MetricsRegistry(),
                              speculate_k=K)
        assert isinstance(sched.draft_source, NGramDraftSource)

    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_advance_by_accepted_and_rejected_kv_invisible(
            self, kind, request):
        """The satellite-4 invariant on BOTH engines: the cursor moves
        by exactly the accepted count, and a stream that suffered
        rejections stays bitwise the non-speculative greedy stream —
        rejected rows land above the cursor where no read masks them
        in, so there is nothing to roll back at ANY retirement point."""
        ref_eng = request.getfixturevalue(f"{kind}_ref")
        eng = request.getfixturevalue(f"{kind}_spec")
        prompt = [3, 1, 4, 1, 5]
        ref = _ref_stream(ref_eng, prompt, 12)

        assert eng.prefill(prompt, 0) == ref[0]
        got = [ref[0]]
        temps = np.zeros(eng.max_seqs, np.float32)
        active = np.asarray([True, False])
        for correct in [False, True, False, True]:
            i = len(got)
            draft_row = (ref[i:i + K] if correct
                         else [(ref[i] + 1) % 97] * K)
            toks = np.zeros(eng.max_seqs, np.int32)
            toks[0] = got[-1]
            drafts = np.zeros((eng.max_seqs, K), np.int32)
            drafts[0] = draft_row
            out, counts = eng.verify(toks, drafts, temps, active)
            c = int(counts[0])
            assert c == (K + 1 if correct else 1)
            assert int(counts[1]) == 0          # inactive slot frozen
            got.extend(int(t) for t in out[0, :c])
            cursor = (eng.allocator.lengths if kind == "paged"
                      else np.asarray(eng.cache.lengths))
            # advance-by-accepted: prompt KV + every emitted-and-
            # consumed token, never the rejected tail
            assert int(cursor[0]) == len(prompt) + len(got) - 1
            assert int(cursor[1]) == 0
        assert got == ref[: len(got)]
        eng.release_slot(0)


class TestSchedulerSpeculative:
    PROMPTS = ([1, 2, 1, 2, 1, 2], [3, 4, 3, 4], [5, 5, 5, 5, 5])

    def _run(self, eng, speculate_k, **kw):
        reg = MetricsRegistry()
        sched = SlotScheduler(eng, registry=reg,
                              speculate_k=speculate_k, **kw)
        out = sched.run([Request(prompt=list(p), max_new_tokens=7)
                         for p in self.PROMPTS], no_recompile=True)
        return out, reg

    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_greedy_stream_parity_zero_recompiles(self, kind, request):
        """The tentpole acceptance bar: greedy speculative streams are
        bitwise-identical to non-speculative greedy on both engines,
        with the whole draft/verify/retire loop running under the live
        recompile guard (run(no_recompile=True))."""
        ref, _ = self._run(request.getfixturevalue(f"{kind}_ref"), 0)
        spec, reg = self._run(request.getfixturevalue(f"{kind}_spec"), K)
        assert sorted(spec) == sorted(ref)
        for rid in ref:
            assert spec[rid].tokens == ref[rid].tokens
            assert spec[rid].finish_reason == ref[rid].finish_reason
        snap = dict(reg.snapshot())
        # repetitive prompts: the n-gram source lands accepts, so the
        # verify steps amortize — fewer grid steps than tokens
        assert snap["serve/spec_steps"] >= 1.0
        assert snap["serve/spec_steps"] == snap["serve/decode_steps"]
        assert snap["serve/spec_drafted"] > 0
        assert 0.0 < snap["serve/spec_accept_rate"] <= 1.0
        assert snap["serve/spec_accepted"] > 0
        assert snap["serve/decode_steps"] < sum(
            7 - 1 for _ in self.PROMPTS)

    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_poison_mid_verify_retires_clean(self, kind, request,
                                             tmp_path):
        """Satellite-4 negative test: a slot poisoned MID-VERIFY is
        quarantined before its window is harvested, the neighbor's
        stream is untouched, and a request re-admitted into the freed
        slot produces the clean-run stream — it can never read a
        drafted-but-rejected (or poisoned) KV entry."""
        eng = request.getfixturevalue(f"{kind}_spec")
        reqs = [Request(prompt=[7, 8, 7, 8], max_new_tokens=8),
                Request(prompt=[9, 1, 9, 1], max_new_tokens=8)]

        def run(plan):
            reg = MetricsRegistry()
            sched = SlotScheduler(eng, registry=reg, speculate_k=K,
                                  fault_plan=plan,
                                  dump_dir=str(tmp_path))
            out = sched.run([Request(prompt=list(r.prompt),
                                     max_new_tokens=r.max_new_tokens)
                             for r in reqs])
            return out, reg

        clean, _ = run(None)
        faulted, reg = run(FaultPlan(poison_logits={2: 0}))
        assert faulted[0].finish_reason == "poisoned"
        # everything delivered before the poisoned verify step is the
        # clean prefix; the poisoned window was discarded whole
        n = len(faulted[0].tokens)
        assert faulted[0].tokens == clean[0].tokens[:n]
        assert faulted[1].tokens == clean[1].tokens
        assert faulted[1].finish_reason == clean[1].finish_reason
        assert reg.snapshot()["serve/poisoned"] == 1.0
        # re-admission into the freed slots: the same work on the same
        # engine reproduces the clean streams exactly
        again, _ = run(None)
        for rid in clean:
            assert again[rid].tokens == clean[rid].tokens

    def test_paged_pool_exhaustion_speculative(self, model_params):
        """Submit-side: an impossible prompt gets the typed
        Rejection("pool_exhausted"). Mid-verify: a window the dry pool
        cannot map retires the slot loudly as "capacity" having
        emitted nothing that step."""
        model, params = model_params
        eng = PagedServingEngine(model, params, max_seqs=1, max_len=16,
                                 prefill_len=12, num_blocks=3,
                                 block_size=4, cache_dtype=jnp.float32,
                                 speculate_k=K)
        sched = SlotScheduler(eng, registry=MetricsRegistry(),
                              speculate_k=K)
        r = sched.submit(Request(prompt=list(range(1, 13)),  # 3 blocks
                                 max_new_tokens=12))
        assert isinstance(r, Rejection) and r.reason == "pool_exhausted"
        rid = sched.submit(Request(prompt=[1, 2, 3, 4],
                                   max_new_tokens=12))
        for _ in range(20):
            if not sched.pending:
                break
            sched.step()
        (comp,) = sched.completed
        assert comp.request_id == rid
        assert comp.finish_reason == "capacity"
        # grew from cursor 4 to the 8-token pool limit, then starved
        assert 1 <= len(comp.tokens) < 12
