"""Back-compat shim: the shared jaxpr-inspection helpers were promoted to
:mod:`apex_tpu.analysis.jaxpr` (PR 11) so the Family-A program lints and
the structural test suites share one walk. Import from there in new code;
this module keeps the historical test-local import path resolving."""

from apex_tpu.analysis.jaxpr import (  # noqa: F401
    collective_census, cone_has_reduction, count_eqns, count_primitives,
    eqn_axes, eqn_scopes, flat_materializations, iter_eqns,
    iter_eqns_scoped, jaxpr_of, jaxpr_str, sub_jaxprs, _sub_jaxprs)

__all__ = ["jaxpr_str", "count_primitives", "collective_census",
           "iter_eqns", "count_eqns", "eqn_axes", "flat_materializations"]
