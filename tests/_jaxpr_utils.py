"""Shared jaxpr-inspection helpers for the structural test assertions.

Three suites (parallel/DDP, collective matmul, health) pin *program shape*
— collective counts, zero-cost-off identity — on the traced jaxpr. The
helpers they had each re-implemented live here once:

- :func:`jaxpr_str` — trace + normalize embedded object addresses, so two
  closures tracing identical programs compare equal;
- :func:`count_primitives` — substring census over the jaxpr text (the
  cheap check: primitive names like ``psum`` / ``ppermute`` appear only as
  equation heads in jaxpr pretty-printing);
- :func:`collective_census` — the ring-decomposition census
  (ppermute / all_gather / reduce_scatter) used by the collective-matmul
  and ZeRO bucketing assertions;
- :func:`iter_eqns` / :func:`count_eqns` — structural walk over the jaxpr
  (recursing into sub-jaxprs) for assertions that need equation *params*
  (axis names, operand sizes), where text matching would be ambiguous.
"""

import re

import jax

__all__ = ["jaxpr_str", "count_primitives", "collective_census",
           "iter_eqns", "count_eqns", "eqn_axes", "flat_materializations"]


def eqn_axes(eqn) -> tuple:
    """The mesh axes a collective equation reduces over, normalized to a
    tuple of names. reduce_scatter/all_gather carry ``axis_name``; psum
    (and 0.4.x check_rep's ``psum2`` spelling) carries ``axes``."""
    ax = eqn.params.get("axis_name") or eqn.params.get("axes")
    return (ax,) if isinstance(ax, str) else tuple(ax or ())


def jaxpr_str(fn, *args) -> str:
    """Jaxpr text with embedded object addresses normalized: two trainers
    build distinct model closures, and their reprs (``<function ... at
    0x...>``) would differ even when the traced programs are identical."""
    return re.sub(r"0x[0-9a-f]+", "0x", str(jax.make_jaxpr(fn)(*args)))


def count_primitives(text: str, *names: str) -> dict:
    """``{name: substring count}`` over jaxpr text. Order names from most
    to least specific when one is a prefix of another and subtract at the
    call site (e.g. ``psum`` also matches ``psum2``-style variants)."""
    return {name: text.count(name) for name in names}


def collective_census(text: str) -> dict:
    """The collective census shared by the ring-decomposition and
    DP-bucketing structural tests."""
    return {"ppermute": text.count("ppermute"),
            "all_gather": text.count("all_gather"),
            "reduce_scatter": text.count("reduce_scatter")}


def iter_eqns(jaxpr):
    """Depth-first over every equation, recursing into sub-jaxprs
    (closed call/scan/shard_map bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _sub_jaxprs(value):
    try:  # the classes moved out of jax.core on the current-jax line
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:  # pragma: no cover - early 0.4.x
        from jax.core import ClosedJaxpr, Jaxpr
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _sub_jaxprs(item)


def flat_materializations(jaxpr, size, dtype="float32") -> list:
    """Primitive names of equations that OUTPUT a 1-D ``dtype`` array of
    exactly ``size`` elements — the structural detector for "the full
    padded flat gradient materialized" (the barrier the span-local
    bucketed ravel/unravel removes). Wrapper equations carrying
    sub-jaxprs (shard_map/pjit/scan/...) are excluded: their outvars are
    aggregate *views* (e.g. the global aval of a sharded ZeRO master),
    not buffers the per-device program builds — any real materialization
    inside them is a leaf equation this walk still visits."""
    out = []
    for eqn in iter_eqns(jaxpr):
        if any(True for v in eqn.params.values() for _ in _sub_jaxprs(v)):
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if getattr(aval, "ndim", None) == 1 and aval.size == size \
                    and str(getattr(aval, "dtype", "")) == dtype:
                out.append(eqn.primitive.name)
    return out


def count_eqns(fn_or_jaxpr, name, *args, where=None) -> int:
    """Number of equations whose primitive is ``name``; ``where(eqn)``
    filters (e.g. on ``eqn.params['axis_name']`` or operand aval sizes).
    Pass a traceable callable plus its args, or an already-made
    (Closed)Jaxpr."""
    if callable(fn_or_jaxpr) and not hasattr(fn_or_jaxpr, "eqns"):
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args).jaxpr
    else:
        jaxpr = getattr(fn_or_jaxpr, "jaxpr", fn_or_jaxpr)
    return sum(1 for eqn in iter_eqns(jaxpr)
               if eqn.primitive.name == name
               and (where is None or where(eqn)))
