"""Fleet observability: registry serialization + merge, the rank-side
publisher, the supervisor aggregator's straggler signals, the
``/metrics``+``/fleet`` HTTP endpoint, gang postmortems, and the
cross-rank trace-timebase alignment.

The byte-identical-programs contract (publisher on vs off changes
NOTHING on the device) is asserted here the way PR 12 asserts request
tracing; the launcher-level integration (stall cause, postmortem
wiring, live supervisor scrape) lives in ``tests/test_multiproc.py``
next to the rest of the supervisor policy tests.
"""

import json
import math
import os
import time
import urllib.request

import numpy as np
import pytest

from apex_tpu.observability.fleet import (FleetAggregator, FleetPublisher,
                                          MetricsServer, PostmortemReport,
                                          merge_registry_dicts,
                                          snapshot_path)
from apex_tpu.observability.registry import (MetricsRegistry, log_buckets)
from apex_tpu.observability import trace as trace_mod
from apex_tpu.observability.sinks import ChromeTraceSink


# ---------------------------------------------------------------------------
# registry serialization: snapshot -> JSON -> merge round-trip
# ---------------------------------------------------------------------------

class TestRegistrySerialization:
    def _populated(self):
        reg = MetricsRegistry()
        reg.counter("train/steps").inc(7)
        reg.gauge("perf/step_wall_ms").set(12.5)
        reg.gauge("never_set")                      # must be skipped
        reg.gauge("health/grads/abs_max").set(float("inf"))
        h = reg.histogram("serve/ttft_ms", [1.0, 10.0, 100.0])
        for v in (0.5, 3.0, 40.0, 400.0):
            h.observe(v)
        return reg

    def test_round_trip_is_strict_json_and_value_identical(self):
        reg = self._populated()
        doc = reg.to_dict()
        # strict JSON: the inf gauge serializes as a string spelling
        text = json.dumps(doc, allow_nan=False)
        back = MetricsRegistry.from_dict(json.loads(text))
        assert back.snapshot() == reg.snapshot()
        assert back.gauge("health/grads/abs_max").value == float("inf")

    def test_unset_gauge_skipped_nan_gauge_kept(self):
        reg = MetricsRegistry()
        reg.gauge("unset")
        reg.gauge("bad").set(float("nan"))
        doc = reg.to_dict()
        assert "unset" not in doc["gauges"]
        assert doc["gauges"]["bad"] == "NaN"
        back = MetricsRegistry.from_dict(doc)
        assert math.isnan(back.gauge("bad").value)
        assert not back.gauge("unset").is_set

    def test_histogram_round_trip_preserves_percentiles(self):
        reg = self._populated()
        h = reg.histogram("serve/ttft_ms", [1.0, 10.0, 100.0])
        back = MetricsRegistry.from_dict(reg.to_dict()) \
            .histogram("serve/ttft_ms", [1.0, 10.0, 100.0])
        for q in (0, 25, 50, 90, 100):
            assert back.percentile(q) == h.percentile(q)
        assert back.count == h.count and back.sum == h.sum

    def test_bad_histogram_counts_rejected(self):
        reg = self._populated()
        doc = reg.to_dict()
        doc["histograms"]["serve/ttft_ms"]["counts"] = [1, 2]
        with pytest.raises(ValueError, match="counts"):
            MetricsRegistry.from_dict(doc)


class TestMerge:
    def test_counters_sum_gauges_spread_buckets_add(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("train/steps").inc(3)
        b.counter("train/steps").inc(5)
        a.gauge("perf/step_wall_ms").set(10.0)
        b.gauge("perf/step_wall_ms").set(30.0)
        ha = a.histogram("io/ms", [1.0, 10.0])
        hb = b.histogram("io/ms", [1.0, 10.0])
        ha.observe(0.5), ha.observe(5.0)
        hb.observe(5.0), hb.observe(50.0)
        merged, stats = merge_registry_dicts([a.to_dict(), b.to_dict()])
        snap = merged.snapshot()
        assert snap["train/steps"] == 8.0
        assert snap["perf/step_wall_ms"] == 20.0     # the mean
        g = stats["gauges"]["perf/step_wall_ms"]
        assert (g["min"], g["max"], g["spread"]) == (10.0, 30.0, 20.0)
        assert g["values"] == [10.0, 30.0]
        hm = merged.histogram("io/ms", [1.0, 10.0])
        assert hm.count == 4 and hm.sum == 60.5
        assert hm._min == 0.5 and hm._max == 50.0
        assert stats["counters"]["train/steps"]["total"] == 8.0

    def test_mismatched_bucket_bounds_skipped_loudly(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.histogram("h/ms", [1.0, 10.0]).observe(2.0)
        b.histogram("h/ms", [1.0, 100.0]).observe(2.0)
        merged, stats = merge_registry_dicts([a.to_dict(), b.to_dict()])
        # first source wins; second is listed, never half-merged
        assert merged.histogram("h/ms", [1.0, 10.0]).count == 1
        assert stats["skipped_histograms"] == ["h/ms[source 1]"]

    def test_percentile_after_merge_tracks_numpy_on_pooled_samples(self):
        """The satellite contract: merging per-rank histograms then
        asking for a percentile estimates the percentile of the POOLED
        samples within the documented bucket-resolution bound
        (relative error <= r - 1 on a log_buckets grid; min/max and
        hence p0/p100 are exact)."""
        lo, hi, n = 1e-1, 1e4, 40
        bounds = log_buckets(lo, hi, n)
        r = (hi / lo) ** (1.0 / (n - 1))
        rng = np.random.RandomState(0)
        pools = [rng.lognormal(mean=2.0, sigma=1.0, size=500)
                 for _ in range(3)]
        regs = []
        for pool in pools:
            reg = MetricsRegistry()
            h = reg.histogram("lat/ms", bounds)
            for v in pool:
                h.observe(float(v))
            regs.append(reg.to_dict())
        merged, _ = merge_registry_dicts(regs)
        hm = merged.histogram("lat/ms", bounds)
        pooled = np.concatenate(pools)
        assert hm.percentile(0) == pooled.min()
        assert hm.percentile(100) == pooled.max()
        for q in (10, 50, 90, 99):
            want = float(np.percentile(pooled, q))
            got = hm.percentile(q)
            assert abs(got - want) <= (r - 1.0) * want, (q, got, want)


# ---------------------------------------------------------------------------
# the rank-side publisher
# ---------------------------------------------------------------------------

class TestFleetPublisher:
    def test_atomic_snapshot_with_registry_and_step(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("train/steps").inc(4)
        pub = FleetPublisher(str(tmp_path), rank=2, registry=reg)
        path = pub.publish(4)
        assert path == snapshot_path(str(tmp_path), 2)
        assert not os.path.exists(path + ".tmp")  # replaced, not left
        doc = json.load(open(path))
        assert doc["schema"] == 1 and doc["rank"] == 2
        assert doc["step"] == 4
        assert doc["registry"]["counters"]["train/steps"] == 4.0

    def test_reporter_hook_captures_health_state(self, tmp_path):
        pub = FleetPublisher(str(tmp_path), rank=0,
                             registry=MetricsRegistry())
        pub(3, {"health/grads/nonfinite_count": 2.0,
                "health/grads/abs_max": float("inf"),
                "amp/overflow_count": 1.0,
                "loss": 1.0})
        doc = json.load(open(pub.path))
        assert doc["health"] == {"health/grads/nonfinite_count": 2.0,
                                 "health/grads/abs_max": "Infinity",
                                 "amp/overflow_count": 1.0}
        assert "loss" not in doc["health"]

    def test_amp_overflow_alone_marks_the_rank_nonfinite(self, tmp_path):
        """payload_nonfinite parity: a loss-scale overflow storm with no
        health/* instrumentation must still reach the postmortem as a
        non-finite rank (the culprit class health_nonfinite)."""
        pub = FleetPublisher(str(tmp_path), rank=0,
                             registry=MetricsRegistry())
        pub(3, {"amp/overflow_count": 2.0})
        os.makedirs(os.path.join(str(tmp_path), "logs"), exist_ok=True)
        rep = PostmortemReport.collect(
            str(tmp_path), round_index=0, world_size=1, cause="timeout",
            returncodes={0: None}, heartbeat_ages={0: 0.1},
            heartbeat_timeout_s=300.0)
        assert rep.ranks[0].nonfinite is True
        assert (rep.culprit_rank, rep.culprit_reason) == \
            (0, "health_nonfinite")

    def test_min_interval_throttles_but_force_overrides(self, tmp_path):
        pub = FleetPublisher(str(tmp_path), rank=0,
                             registry=MetricsRegistry(),
                             min_interval_s=3600.0)
        assert pub.publish(1) is not None
        assert pub.publish(2) is None            # throttled
        assert json.load(open(pub.path))["step"] == 1
        assert pub.publish(2, force=True) is not None
        assert json.load(open(pub.path))["step"] == 2

    def test_step_wall_gauge_tracked_across_publishes(self, tmp_path):
        reg = MetricsRegistry()
        pub = FleetPublisher(str(tmp_path), rank=0, registry=reg)
        pub.publish(1)
        time.sleep(0.02)
        pub.publish(3)  # 2 steps later
        wall = reg.gauge("perf/step_wall_ms").value
        assert wall > 0.0
        doc = json.load(open(pub.path))
        assert doc["registry"]["gauges"]["perf/step_wall_ms"] == wall


# ---------------------------------------------------------------------------
# the supervisor-side aggregator
# ---------------------------------------------------------------------------

def _rank_snapshot(run_dir, rank, step, steps_counter, wall_ms=None,
                   health=None, pool_free=None, cow_copies=None,
                   pool_used=None, pool_util=None, spec_drafted=None,
                   spec_accepted=None, spec_rate=None):
    reg = MetricsRegistry()
    reg.counter("train/steps").inc(steps_counter)
    if wall_ms is not None:
        reg.gauge("perf/step_wall_ms").set(wall_ms)
    if pool_free is not None:
        reg.gauge("serve/pool_blocks_free").set(pool_free)
    if cow_copies is not None:
        reg.counter("serve/blocks_cow_copied").inc(cow_copies)
    if pool_used is not None:
        reg.gauge("serve/pool_blocks_used").set(pool_used)
    if pool_util is not None:
        reg.gauge("serve/pool_utilization").set(pool_util)
    if spec_drafted is not None:
        reg.counter("serve/spec_drafted").inc(spec_drafted)
    if spec_accepted is not None:
        reg.counter("serve/spec_accepted").inc(spec_accepted)
    if spec_rate is not None:
        reg.gauge("serve/spec_accept_rate").set(spec_rate)
    pub = FleetPublisher(run_dir, rank=rank, registry=reg)
    if health:
        pub(step, health)
    else:
        pub.publish(step)


class TestFleetAggregator:
    def test_straggler_signals_and_fleet_gauges(self, tmp_path):
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=5, steps_counter=5, wall_ms=10.0)
        _rank_snapshot(run, 1, step=3, steps_counter=3, wall_ms=40.0)
        sup = MetricsRegistry()
        sup.gauge("elastic/world_size").set(2)
        agg = FleetAggregator(run, registry=sup)
        view = agg.refresh()
        assert view["ranks"] == [0, 1]
        assert view["steps"] == {0: 5, 1: 3}
        assert view["step_skew"] == 2 and view["slowest_rank"] == 1
        assert view["step_wall_spread_ms"] == 30.0
        snap = sup.snapshot()
        assert snap["fleet/ranks"] == 2.0
        assert snap["fleet/step_skew"] == 2.0
        assert snap["fleet/slowest_rank"] == 1.0
        assert snap["fleet/step_wall_spread_ms"] == 30.0

    def test_step_tie_breaks_to_largest_wall(self, tmp_path):
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=4, steps_counter=4, wall_ms=10.0)
        _rank_snapshot(run, 1, step=4, steps_counter=4, wall_ms=50.0)
        view = FleetAggregator(run, registry=MetricsRegistry()).view()
        assert view["step_skew"] == 0 and view["slowest_rank"] == 1

    def test_merged_registry_includes_supervisor_and_sums_ranks(
            self, tmp_path):
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=2, steps_counter=2,
                       pool_free=40.0, cow_copies=1,
                       pool_used=23.0, pool_util=23.0 / 63.0,
                       spec_drafted=40, spec_accepted=30,
                       spec_rate=0.75)
        _rank_snapshot(run, 1, step=2, steps_counter=2,
                       pool_free=20.0, cow_copies=2,
                       pool_used=43.0, pool_util=43.0 / 63.0,
                       spec_drafted=40, spec_accepted=10,
                       spec_rate=0.25)
        sup = MetricsRegistry()
        sup.gauge("elastic/world_size").set(2)
        sup.counter("elastic/restarts").inc()
        merged = FleetAggregator(run, registry=sup).merged_registry()
        snap = merged.snapshot()
        assert snap["train/steps"] == 4.0
        # paged-serving pool surface rides the same merge: the free-block
        # gauge lands as the cross-rank mean, the COW counter sums.
        assert snap["serve/pool_blocks_free"] == 30.0
        assert snap["serve/blocks_cow_copied"] == 3.0
        assert snap["serve/pool_blocks_used"] == 33.0
        assert abs(snap["serve/pool_utilization"] - 33.0 / 63.0) < 1e-9
        # speculative-decoding surface: the draft/accept counters sum
        # across ranks, the acceptance-rate gauge lands as the mean
        assert snap["serve/spec_drafted"] == 80.0
        assert snap["serve/spec_accepted"] == 40.0
        assert abs(snap["serve/spec_accept_rate"] - 0.5) < 1e-9
        assert snap["elastic/world_size"] == 2.0
        assert snap["elastic/restarts"] == 1.0
        text = merged.render_prometheus()
        assert "train_steps 4" in text
        assert "elastic_world_size 2" in text

    def test_scrape_is_one_merge_with_fresh_fleet_gauges(self, tmp_path):
        """The /metrics fast path: scrape() returns the view and the
        combined registry from ONE merge — with THIS scrape's fleet/*
        values rendered (not the previous refresh's), the supervisor's
        own metrics folded in, and the rank spread stats rank-only."""
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=5, steps_counter=5, wall_ms=10.0)
        _rank_snapshot(run, 1, step=3, steps_counter=3, wall_ms=40.0)
        sup = MetricsRegistry()
        sup.gauge("elastic/world_size").set(2)
        agg = FleetAggregator(run, registry=sup)
        doc, merged = agg.scrape()
        assert doc["step_skew"] == 2
        snap = merged.snapshot()
        assert snap["train/steps"] == 8.0
        assert snap["elastic/world_size"] == 2.0
        assert snap["fleet/step_skew"] == 2.0       # this scrape's value
        assert sup.snapshot()["fleet/step_skew"] == 2.0  # canonical copy
        # spread stats stayed rank-only despite the supervisor doc
        assert doc["gauges"]["perf/step_wall_ms"]["values"] == \
            [10.0, 40.0]

    def test_refresh_resets_straggler_gauges_when_fleet_empties(
            self, tmp_path):
        """The cleared-between-rounds invariant: after clear(), a
        refresh over zero snapshots must RESET the skew/straggler
        gauges (unset -> skipped), not let a dead gang's numbers read
        as current next to fleet/ranks=0."""
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=5, steps_counter=5, wall_ms=10.0)
        _rank_snapshot(run, 1, step=3, steps_counter=3, wall_ms=40.0)
        sup = MetricsRegistry()
        agg = FleetAggregator(run, registry=sup)
        agg.refresh()
        assert sup.snapshot()["fleet/step_skew"] == 2.0
        agg.clear()
        agg.refresh()
        snap = sup.snapshot()
        assert snap["fleet/ranks"] == 0.0
        for name in ("fleet/step_skew", "fleet/slowest_rank",
                     "fleet/step_wall_spread_ms"):
            assert name not in snap, name

    def test_unreadable_snapshot_skipped_and_clear(self, tmp_path):
        run = str(tmp_path)
        _rank_snapshot(run, 0, step=1, steps_counter=1)
        with open(snapshot_path(run, 1), "w") as f:
            f.write("{torn")
        agg = FleetAggregator(run, registry=MetricsRegistry())
        assert sorted(agg.snapshots()) == [0]
        agg.clear()
        assert agg.snapshots() == {}
        assert agg.view()["ranks"] == []  # empty fleet is not an error


# ---------------------------------------------------------------------------
# the /metrics endpoint
# ---------------------------------------------------------------------------

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def _assert_prometheus(text):
    """Minimal text-exposition parse: every non-comment line is
    ``name{labels}? value`` with a float-parsable value (NaN/+Inf/-Inf
    are the accepted spellings)."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name, line
        float(value)


class TestMetricsServer:
    def test_serves_prometheus_and_fleet_json(self):
        reg = MetricsRegistry()
        reg.counter("train/steps").inc(2)
        reg.gauge("health/grads/abs_max").set(float("nan"))
        srv = MetricsServer(reg.render_prometheus,
                            lambda: {"ranks": [0], "bad": float("inf")})
        port = srv.start()
        try:
            status, text = _get(f"http://127.0.0.1:{port}/metrics")
            assert status == 200
            _assert_prometheus(text)
            assert "train_steps 2" in text
            assert "health_grads_abs_max NaN" in text
            status, body = _get(f"http://127.0.0.1:{port}/fleet")
            assert status == 200
            doc = json.loads(body)  # strict JSON despite the inf
            assert doc["ranks"] == [0] and doc["bad"] == "Infinity"
        finally:
            srv.close()

    def test_unknown_route_404_render_error_500(self):
        def boom():
            raise RuntimeError("render failed")

        srv = MetricsServer(boom)
        port = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"http://127.0.0.1:{port}/nope")
            assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"http://127.0.0.1:{port}/metrics")
            assert e.value.code == 500
            # no /fleet renderer -> 404, not a crash
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"http://127.0.0.1:{port}/fleet")
            assert e.value.code == 404
        finally:
            srv.close()

    def test_close_is_deterministic_and_reusable(self):
        reg = MetricsRegistry()
        srv = MetricsServer(reg.render_prometheus)
        port = srv.start()
        srv.close()
        srv.close()  # idempotent
        with pytest.raises(OSError):
            _get(f"http://127.0.0.1:{port}/metrics", timeout=0.5)


# ---------------------------------------------------------------------------
# postmortems
# ---------------------------------------------------------------------------

def _seed_run_dir(tmp_path, world=2):
    run = str(tmp_path)
    os.makedirs(os.path.join(run, "logs"), exist_ok=True)
    for r in range(world):
        with open(os.path.join(run, "logs",
                               f"round0_rank{r}.log"), "w") as f:
            f.write(f"rank {r} log line\n")
    return run


class TestPostmortem:
    def test_dead_heartbeat_outranks_everything(self, tmp_path):
        run = _seed_run_dir(tmp_path)
        # rank 0 stalled AND nonfinite; rank 1 died -> rank 1 wins
        _rank_snapshot(run, 0, step=3, steps_counter=3,
                       health={"health/grads/nonfinite_count": 2.0})
        rep = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="exit",
            returncodes={0: None, 1: -9},
            heartbeat_ages={0: 0.1, 1: 4.0},
            stalled_ranks=[0], heartbeat_timeout_s=300.0)
        assert rep.culprit_rank == 1
        assert rep.culprit_reason == "heartbeat_dead"

    def test_silent_past_budget_is_dead_even_without_exit(self, tmp_path):
        run = _seed_run_dir(tmp_path)
        rep = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="heartbeat",
            returncodes={0: None, 1: None},
            heartbeat_ages={0: 0.5, 1: 99.0},
            heartbeat_timeout_s=10.0)
        assert rep.culprit_rank == 1
        assert rep.culprit_reason == "heartbeat_dead"

    def test_stalled_step_second_nonfinite_third(self, tmp_path):
        run = _seed_run_dir(tmp_path)
        _rank_snapshot(run, 0, step=3, steps_counter=3,
                       health={"health/grads/nonfinite_count": 1.0})
        rep = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="stall",
            returncodes={0: None, 1: None},
            heartbeat_ages={0: 0.1, 1: 0.1},
            stalled_ranks=[1], heartbeat_timeout_s=300.0)
        assert (rep.culprit_rank, rep.culprit_reason) == \
            (1, "stalled_step")
        rep2 = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="timeout",
            returncodes={0: None, 1: None},
            heartbeat_ages={0: 0.1, 1: 0.1},
            heartbeat_timeout_s=300.0)
        assert (rep2.culprit_rank, rep2.culprit_reason) == \
            (0, "health_nonfinite")

    def test_no_signal_is_unknown_not_a_scapegoat(self, tmp_path):
        run = _seed_run_dir(tmp_path)
        rep = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="timeout",
            returncodes={0: None, 1: None},
            heartbeat_ages={0: 0.1, 1: 0.1},
            heartbeat_timeout_s=300.0)
        assert rep.culprit_rank is None
        assert rep.culprit_reason == "unknown"

    def test_artifacts_strict_json_plus_markdown(self, tmp_path):
        run = _seed_run_dir(tmp_path)
        _rank_snapshot(run, 1, step=2, steps_counter=2,
                       health={"health/grads/abs_max": float("inf"),
                               "health/grads/nonfinite_count": 3.0})
        rep = PostmortemReport.collect(
            run, round_index=0, world_size=2, cause="exit",
            returncodes={0: None, 1: -9},
            heartbeat_ages={0: 0.2, 1: 5.0},
            heartbeat_timeout_s=300.0)
        json_path, md_path = rep.write(os.path.join(run, "postmortem"))
        doc = json.load(open(json_path))  # strict parse (jq contract)
        assert doc["culprit_rank"] == 1
        assert doc["culprit_reason"] == "heartbeat_dead"
        ranks = {r["rank"]: r for r in doc["ranks"]}
        assert ranks[1]["returncode"] == -9
        assert ranks[1]["nonfinite"] is True
        assert ranks[1]["snapshot_step"] == 2
        assert "rank 1 log line" in ranks[1]["log_tail"]
        md = open(md_path).read()
        assert "rank 1" in md and "heartbeat_dead" in md
        assert "| 1 | -9 |" in md


# ---------------------------------------------------------------------------
# trace timebase: epoch offset + two-rank merge
# ---------------------------------------------------------------------------

class TestTraceTimebase:
    def test_epoch_offset_translates_perf_counter_to_wall(self):
        off = trace_mod.epoch_offset()
        assert abs((time.perf_counter() + off) - time.time()) < 0.5

    def test_sink_and_request_exporter_stamp_metadata(self, tmp_path):
        path = tmp_path / "trace.json"
        sink = ChromeTraceSink(str(path), pid=0)
        sink.emit(1, {"loss": 1.0},
                  [trace_mod.Span("step", 0.0, 1.0)])
        sink.close()
        doc = json.loads(path.read_text())
        assert "epoch_offset_s" in doc["metadata"]
        assert doc["metadata"]["clock"] == "perf_counter"
        from apex_tpu.observability.reqtrace import chrome_request_trace
        doc2 = chrome_request_trace([])
        assert "epoch_offset_s" in doc2["metadata"]

    def test_two_rank_merge_aligns_process_local_timebases(self):
        """Rank A's clock started 100s ago, rank B's 5s ago; an event at
        A's perf t=2 happened BEFORE one at B's perf t=1 in wall time.
        Raw ts ordering says otherwise; the merged (epoch) ordering must
        get it right."""
        mk = lambda name, t, off, pid: {
            "traceEvents": trace_mod.chrome_trace_events(
                [trace_mod.Span(name, t, t + 0.5)], pid=pid),
            "metadata": {"clock": "perf_counter", "epoch_offset_s": off}}
        base = 1_700_000_000.0
        doc_a = mk("a", 2.0, base + 100.0, pid=0)   # epoch 102
        doc_b = mk("b", 1.0, base + 200.0, pid=1)   # epoch 201
        merged = trace_mod.merge_chrome_traces([doc_a, doc_b])
        names = [e["name"] for e in merged["traceEvents"]]
        assert names == ["a", "b"]
        ts = {e["name"]: e["ts"] for e in merged["traceEvents"]}
        assert ts["a"] == pytest.approx((base + 102.0) * 1e6)
        assert ts["b"] == pytest.approx((base + 201.0) * 1e6)
        # pids survive: the per-rank lanes stay separable
        assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}
        assert merged["metadata"]["clock"] == "epoch"

    def test_merge_refuses_unstamped_documents(self):
        with pytest.raises(ValueError, match="epoch_offset_s"):
            trace_mod.merge_chrome_traces([{"traceEvents": []}])

    def test_colliding_default_pids_are_separated(self):
        """Both exporters default to pid=0, so two ranks' files collide
        — the merge must re-stamp per-document pids so the ranks stay
        separable lanes instead of interleaving in one."""
        mk = lambda name: {
            "traceEvents": trace_mod.chrome_trace_events(
                [trace_mod.Span(name, 1.0, 2.0)]),   # default pid=0
            "metadata": {"epoch_offset_s": 10.0}}
        merged = trace_mod.merge_chrome_traces([mk("a"), mk("b")])
        by_name = {e["name"]: e["pid"] for e in merged["traceEvents"]}
        assert by_name["a"] != by_name["b"]
        # collision-free inputs keep their pids verbatim (pinned above
        # in test_two_rank_merge_aligns_process_local_timebases)


# ---------------------------------------------------------------------------
# the host-side-only contract: publisher on vs off, byte-identical step
# ---------------------------------------------------------------------------

class TestPublisherZeroCost:
    def test_step_program_byte_identical_with_publisher_on(self,
                                                           tmp_path):
        """The acceptance contract, PR 12 style: running the elastic
        loop with a FleetPublisher attached changes NOTHING on the
        device — the compiled step program is byte-identical, and the
        losses match an unpublished run exactly."""
        import jax
        from test_elastic import ToyTrainer, _toy_data

        from apex_tpu.elastic import ElasticRunner

        def run(ckdir, fleet_dir):
            trainer = ToyTrainer()
            step_fn = trainer.jit_train_step()
            state = trainer.init_state(jax.random.PRNGKey(0))
            batch = next(_toy_data())
            compiled = step_fn.lower(*state, *batch).compile()
            reg = MetricsRegistry()  # shared runner<->publisher, the
            # production wiring (both default to get_registry())
            publisher = (FleetPublisher(str(fleet_dir), rank=0,
                                        registry=reg)
                         if fleet_dir is not None else None)
            runner = ElasticRunner(
                trainer, _toy_data(), str(ckdir), save_interval=10,
                exit_on_preempt=False, registry=reg,
                publisher=publisher)
            res = runner.fit(3, key=jax.random.PRNGKey(0))
            return compiled.as_text(), res, publisher

        text_off, res_off, _ = run(tmp_path / "off", None)
        text_on, res_on, pub = run(tmp_path / "on", tmp_path / "fleet")
        assert text_on == text_off
        assert res_on.loss == res_off.loss and res_on.step == res_off.step
        # and the publisher DID run: final forced snapshot at step 3
        doc = json.load(open(pub.path))
        assert doc["step"] == 3
        assert doc["registry"]["counters"]["train/steps"] == 3.0
