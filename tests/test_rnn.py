"""apex_tpu.RNN parity tests.

The reference pins RNN semantics to torch's cells
(``reference:apex/RNN/RNNBackend.py:25,90`` imports ``torch.nn._functions.rnn``;
``reference:apex/RNN/models.py:19-53`` is the factory surface;
``reference:apex/RNN/cells.py:55`` is mLSTM). We pin ours two ways:
direct torch.nn parity for LSTM/GRU (weights copied across), and
hand-rolled per-step recurrences for every cell kind including mLSTM
and the ``output_size`` projection path the reference's RNNCell carries.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.RNN import GRU, LSTM, ApexRNN, ReLU, Tanh, mLSTM


def _np_params(rnn, seed=0):
    return jax.device_get(rnn.init(jax.random.PRNGKey(seed)))


# ---------------------------------------------------------------------------
# torch parity: LSTM / GRU, incl. stacked + bidirectional
# ---------------------------------------------------------------------------

def _copy_to_torch(tmod, params, num_layers, bidirectional):
    import torch

    dirs = 2 if bidirectional else 1
    for layer in range(num_layers):
        for d in range(dirs):
            p = params[f"l{layer}{'_rev' if d else ''}"]
            suf = f"l{layer}" + ("_reverse" if d else "")
            with torch.no_grad():
                getattr(tmod, f"weight_ih_{suf}").copy_(
                    torch.from_numpy(np.asarray(p["w_ih"])))
                getattr(tmod, f"weight_hh_{suf}").copy_(
                    torch.from_numpy(np.asarray(p["w_hh"])))
                getattr(tmod, f"bias_ih_{suf}").copy_(
                    torch.from_numpy(np.asarray(p["b_ih"])))
                getattr(tmod, f"bias_hh_{suf}").copy_(
                    torch.from_numpy(np.asarray(p["b_hh"])))


@pytest.mark.parametrize("kind,layers,bidi", [
    ("lstm", 1, False),
    ("lstm", 2, True),
    ("gru", 1, False),
    ("gru", 2, True),
])
def test_torch_parity(kind, layers, bidi):
    import torch

    T, B, I, H = 7, 3, 5, 6
    factory = LSTM if kind == "lstm" else GRU
    rnn = factory(I, H, layers, bidirectional=bidi)
    params = _np_params(rnn)
    x = np.random.RandomState(1).randn(T, B, I).astype(np.float32)

    tcls = torch.nn.LSTM if kind == "lstm" else torch.nn.GRU
    tmod = tcls(I, H, layers, bidirectional=bidi)
    _copy_to_torch(tmod, params, layers, bidi)
    with torch.no_grad():
        t_out, t_hid = tmod(torch.from_numpy(x))

    out, hid = rnn(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    if kind == "lstm":
        np.testing.assert_allclose(np.asarray(hid[0]), t_hid[0].numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(hid[1]), t_hid[1].numpy(),
                                   rtol=1e-5, atol=1e-5)
    else:
        np.testing.assert_allclose(np.asarray(hid), t_hid.numpy(),
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# hand-rolled per-step recurrences (no scan, no hoisted matmul)
# ---------------------------------------------------------------------------

def _hand_step(kind, p, x_t, h, c, proj):
    """One timestep of the reference recurrence in plain numpy/fp32."""
    def lin(v, w, b=None):
        y = v @ np.asarray(w).T
        return y + np.asarray(b) if b is not None else y

    if kind in ("lstm", "mlstm"):
        if kind == "mlstm":
            m = lin(x_t, p["w_mih"]) * lin(h, p["w_mhh"])
            gates = lin(x_t, p["w_ih"], p["b_ih"]) + lin(m, p["w_hh"],
                                                         p["b_hh"])
        else:
            gates = (lin(x_t, p["w_ih"], p["b_ih"])
                     + lin(h, p["w_hh"], p["b_hh"]))
        i, f, g, o = np.split(gates, 4, axis=-1)
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        if proj:
            h = lin(h, p["w_ho"])
        return h, c
    if kind == "gru":
        xg = lin(x_t, p["w_ih"], p["b_ih"])
        hg = lin(h, p["w_hh"], p["b_hh"])
        Hd = h.shape[-1]
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        r = sig(xg[..., :Hd] + hg[..., :Hd])
        z = sig(xg[..., Hd:2 * Hd] + hg[..., Hd:2 * Hd])
        n = np.tanh(xg[..., 2 * Hd:] + r * hg[..., 2 * Hd:])
        return (1.0 - z) * n + z * h, None
    act = (lambda v: np.maximum(v, 0.0)) if kind == "relu" else np.tanh
    h = act(lin(x_t, p["w_ih"], p["b_ih"]) + lin(h, p["w_hh"], p["b_hh"]))
    return h, None


@pytest.mark.parametrize("kind,proj", [
    ("lstm", False), ("lstm", True),
    ("gru", False),
    ("relu", False),
    ("tanh", False),
    ("mlstm", False), ("mlstm", True),
])
def test_hand_rolled_parity(kind, proj):
    T, B, I, H, O = 5, 2, 4, 6, 3
    factory = {"lstm": LSTM, "gru": GRU, "relu": ReLU,
               "tanh": Tanh, "mlstm": mLSTM}[kind]
    rnn = factory(I, H, 1, output_size=O if proj else None)
    params = _np_params(rnn, seed=2)
    x = np.random.RandomState(3).randn(T, B, I).astype(np.float32)

    out_w = O if proj else H
    h = np.zeros((B, out_w), np.float32)
    c = np.zeros((B, H), np.float32)
    p = {k: np.asarray(v) for k, v in params["l0"].items()}
    ref = []
    for t in range(T):
        h, c = _hand_step(kind, p, x[t], h, c, proj)
        ref.append(h)
    ref = np.stack(ref)

    out, _ = rnn(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_bidirectional_output_layout():
    """output[t] = concat(fwd_t, rev_t); rev half of output[0] equals the
    reverse-direction final hidden (torch layout)."""
    T, B, I, H = 6, 2, 3, 4
    rnn = Tanh(I, H, 1, bidirectional=True)
    params = _np_params(rnn, seed=4)
    x = np.random.RandomState(5).randn(T, B, I).astype(np.float32)
    out, h = rnn(params, jnp.asarray(x))
    assert out.shape == (T, B, 2 * H)
    assert h.shape == (2, B, H)
    # fwd final hidden is the fwd half of the last output step
    np.testing.assert_allclose(np.asarray(out[-1, :, :H]), np.asarray(h[0]),
                               rtol=1e-6, atol=1e-6)
    # rev final hidden is the rev half of the FIRST output step
    np.testing.assert_allclose(np.asarray(out[0, :, H:]), np.asarray(h[1]),
                               rtol=1e-6, atol=1e-6)


def test_interlayer_dropout_semantics():
    """Dropout applies between stacked layers only — never after the last —
    so a 1-layer net is dropout-invariant and a 2-layer net is not."""
    T, B, I, H = 4, 3, 5, 5
    x = jnp.asarray(np.random.RandomState(6).randn(T, B, I), jnp.float32)
    key = jax.random.PRNGKey(7)

    one = LSTM(I, H, 1, dropout=0.5)
    p1 = one.init(jax.random.PRNGKey(8))
    a, _ = one(p1, x, dropout_rng=key)
    b, _ = one(p1, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    two = LSTM(I, H, 2, dropout=0.5)
    p2 = two.init(jax.random.PRNGKey(9))
    a, _ = two(p2, x, dropout_rng=key)
    b, _ = two(p2, x)
    assert not np.allclose(np.asarray(a), np.asarray(b))
    # no rng supplied -> deterministic eval path
    c, _ = two(p2, x)
    np.testing.assert_array_equal(np.asarray(b), np.asarray(c))


def test_mlstm_projection_shapes():
    """Regression for the round-4 bug: mLSTM(..., output_size=k) crashed with
    a dot_general shape error because w_mih/w_mhh were sized by hidden_size."""
    rnn = mLSTM(4, 8, 2, output_size=3)
    params = _np_params(rnn)
    x = jnp.ones((5, 2, 4), jnp.float32)
    out, (h, c) = rnn(params, x)
    assert out.shape == (5, 2, 3)
    assert h.shape == (2, 2, 3)
    assert c.shape == (2, 2, 8)


def test_batch_first_and_bf16():
    T, B, I, H = 4, 2, 3, 4
    rnn = LSTM(I, H, 1, batch_first=True, params_dtype=jnp.bfloat16)
    params = rnn.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(1).randn(B, T, I), jnp.bfloat16)
    out, (h, c) = jax.jit(lambda p, v: rnn(p, v))(params, x)
    assert out.shape == (B, T, H)
    assert out.dtype == jnp.bfloat16 and h.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


def test_lstm_training_loss_decreases():
    """End-to-end: grads flow through the scan and a few SGD steps reduce a
    sequence-regression loss (reference trains RNNs under amp,
    ``reference:tests/L0/run_amp/test_rnn.py``)."""
    T, B, I, H = 8, 4, 3, 8
    rnn = LSTM(I, H, 1)
    params = rnn.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(T, B, I), jnp.float32)
    # teacher-student: targets from the same architecture, different init,
    # so the loss floor is ~0 and convergence is meaningful
    y, _ = rnn(rnn.init(jax.random.PRNGKey(5)), x)

    def loss_fn(p):
        out, _ = rnn(p, x)
        return jnp.mean((out - y) ** 2)

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda w, gw: w - 0.3 * gw, p, g), loss

    losses = []
    for _ in range(150):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5
