"""Flash attention kernel parity tests (vs XLA reference attention).

Model: ``reference:apex/contrib/test/fmha/test_fmha.py`` (kernel vs Python
attention) and ``apex/contrib/test/multihead_attn/`` (fast vs default impl).
The Pallas kernels run in interpreter mode on the CPU test backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import flash_attention, mha_reference, supports_flash


def _qkv(b=2, h=2, sq=256, sk=256, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype) * 0.3
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype) * 0.3
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype) * 0.3
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fwd_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, use_pallas=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_with_bias_mask():
    q, k, v = _qkv(seed=1)
    rng = np.random.RandomState(2)
    mask = rng.rand(2, 1, 256, 256) > 0.8
    bias = jnp.where(jnp.asarray(mask), -10000.0, 0.0).astype(jnp.float32)
    out = flash_attention(q, k, v, bias=bias, use_pallas=True)
    ref = mha_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_matches_reference(causal):
    q, k, v = _qkv(b=1, h=2, sq=128, sk=128, seed=3)
    dy = jnp.asarray(np.random.RandomState(4).randn(*q.shape), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       use_pallas=True) * dy)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * dy)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bwd_with_bias():
    q, k, v = _qkv(b=1, h=1, sq=128, sk=256, seed=5)
    mask = np.random.RandomState(6).rand(1, 1, 128, 256) > 0.9
    bias = jnp.where(jnp.asarray(mask), -10000.0, 0.0).astype(jnp.float32)
    dy = jnp.asarray(np.random.RandomState(7).randn(*q.shape), jnp.float32)

    def f(q, k, v, use_pallas):
        return jnp.sum(flash_attention(q, k, v, bias=bias,
                                       use_pallas=use_pallas) * dy)

    g_flash = jax.grad(lambda a, b, c: f(a, b, c, True),
                       argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: f(a, b, c, False),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_cross_attention_causal_offset():
    # sq != sk causal: the mask is offset so the last query row sees all keys
    q, k, v = _qkv(b=1, h=1, sq=128, sk=256, seed=8)
    out = flash_attention(q, k, v, causal=True, use_pallas=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bf16_path():
    q, k, v = _qkv(seed=9, dtype=jnp.bfloat16, sq=128, sk=128)
    out = flash_attention(q, k, v, causal=True, use_pallas=True)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2)


def test_bwd_fully_masked_rows_block_misaligned():
    """ADVICE r1 (medium): causal with sk<sq leaves rows 0..(sq-sk-1) fully
    masked; when block_q straddles the masked-row boundary (block_q=24 does
    not divide 128) the backward used to produce exp(-1e30 - -1e30) = 1
    garbage p on those rows, contaminating dk/dv (~7.5 abs divergence)."""
    q, k, v = _qkv(b=1, h=1, sq=240, sk=128, seed=11)
    assert supports_flash(240, 128, 64, 24, 128)
    n_masked = 240 - 128  # rows with no visible keys
    dy = np.random.RandomState(12).randn(1, 1, 240, 64)
    dy[:, :, :n_masked] = 0.0  # fully-masked rows are undefined: exclude
    dy = jnp.asarray(dy, jnp.float32)

    def f(q, k, v, use_pallas):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=24, block_k=128,
                                       use_pallas=use_pallas) * dy)

    g_flash = jax.grad(lambda a, b, c: f(a, b, c, True),
                       argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: f(a, b, c, False),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
    # and the flash fwd output on fully-masked rows is exactly zero
    out = flash_attention(q, k, v, causal=True, block_q=24, block_k=128,
                          use_pallas=True)
    assert np.all(np.asarray(out)[:, :, :n_masked] == 0.0)


@pytest.mark.parametrize("bias_shape", [
    (1, 2, 128, 128),   # shared over batch (rel-pos table)
    (2, 2, 128, 128),   # full (no reduction)
    (1, 1, 128, 128),   # shared over batch and heads
    (2, 1, 128, 128),   # shared over heads
    (1, 2, 1, 128),     # broadcast over sq too (ALiBi-style row)
])
def test_dbias_learned_bias(bias_shape):
    """bias_requires_grad=True returns the real dbias (score cotangent summed
    over broadcast dims), matching the XLA fallback's bias grad."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=13)
    bias = jnp.asarray(np.random.RandomState(14).randn(*bias_shape) * 0.1,
                       jnp.float32)
    dy = jnp.asarray(np.random.RandomState(15).randn(*q.shape), jnp.float32)

    def f(bias, use_pallas):
        return jnp.sum(flash_attention(
            q, k, v, bias=bias, causal=True, use_pallas=use_pallas,
            bias_requires_grad=True) * dy)

    db_flash = jax.grad(lambda b: f(b, True))(bias)
    db_ref = jax.grad(lambda b: f(b, False))(bias)
    np.testing.assert_allclose(np.asarray(db_flash), np.asarray(db_ref),
                               rtol=2e-4, atol=2e-4)


def test_dbias_zero_by_default_both_paths():
    """Without bias_requires_grad the bias grad is zero on the Pallas path
    AND the XLA fallback (semantics must not flip with tile alignment)."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=13)
    bias = jnp.asarray(np.random.RandomState(14).randn(1, 2, 128, 128) * 0.1,
                       jnp.float32)
    dy = jnp.asarray(np.random.RandomState(15).randn(*q.shape), jnp.float32)
    for use_pallas in (True, False):
        db = jax.grad(lambda b: jnp.sum(flash_attention(
            q, k, v, bias=b, use_pallas=use_pallas) * dy))(bias)
        assert np.all(np.asarray(db) == 0.0)


def test_padding_mask_broadcast_shapes():
    """Padding-style biases keep their broadcast shape ((b,1,1,sk) costs
    O(b·sk) HBM, ADVICE r1) and still match the reference."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=256, seed=16)
    rng = np.random.RandomState(17)
    for shape in [(2, 1, 1, 256), (1, 1, 128, 256), (1, 2, 128, 256),
                  (2, 2, 1, 256)]:
        b_ = jnp.where(jnp.asarray(rng.rand(*shape) > 0.2),
                       0.0, -10000.0).astype(jnp.float32)
        out = flash_attention(q, k, v, bias=b_, use_pallas=True)
        ref = mha_reference(q, k, v, bias=b_)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_dropout_matches_reference_mask():
    """In-kernel dropout (philox analog) agrees with the XLA reference using
    the same counter-derived mask — forward AND all gradients."""
    q, k, v = _qkv(b=2, h=2, sq=256, sk=256, seed=20)
    dy = jnp.asarray(np.random.RandomState(21).randn(*q.shape), jnp.float32)
    seed = jnp.asarray(12345, jnp.int32)

    def f(q, k, v, use_pallas):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, dropout_rate=0.3, dropout_seed=seed,
            use_pallas=use_pallas) * dy)

    out_fl = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                             dropout_seed=seed, use_pallas=True)
    out_ref = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                              dropout_seed=seed, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out_fl), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)

    g_fl = jax.grad(lambda a, b, c: f(a, b, c, True), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: f(a, b, c, False), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_dropout_deterministic_and_seed_dependent():
    q, k, v = _qkv(b=1, h=2, sq=128, sk=128, seed=22)
    out1 = flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=7,
                           use_pallas=True)
    out2 = flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=7,
                           use_pallas=True)
    out3 = flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=8,
                           use_pallas=True)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))
    # rate ~ 0.5: dropped entries show up as a large deviation from rate 0
    base = flash_attention(q, k, v, use_pallas=True)
    assert not np.allclose(np.asarray(out1), np.asarray(base))


def test_dropout_mask_statistics():
    from apex_tpu.ops.flash_attention import dropout_keep_mask
    m = np.asarray(dropout_keep_mask(3, 2, 2, 256, 256, 0.3))
    assert abs(m.mean() - 0.7) < 0.01
    # rows/cols not degenerate: no all-dropped row at this size
    assert m.any(axis=-1).all()


def test_dropout_requires_seed():
    q, k, v = _qkv(b=1, h=1, sq=128, sk=128)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, dropout_rate=0.1)


def test_bias_bad_shape_raises():
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=18)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, bias=jnp.zeros((3, 1, 1, 128)),
                        use_pallas=True)


def test_unaligned_falls_back():
    q, k, v = _qkv(sq=100, sk=100, seed=10)
    assert not supports_flash(100, 100, 64, 128, 128)
    out = flash_attention(q, k, v)  # auto-fallback, must not raise
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_dbias_learned_bias_with_dropout(dtype):
    """ADVICE r2: bias_requires_grad=True together with dropout_rate>0 —
    the dropout branch of the dbias kernel (ds rebuilt from the dropped
    probabilities) must match the XLA fallback, in fp32 and with bf16
    q/k/v."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=23)
    q, k, v = (x.astype(dtype) for x in (q, k, v))
    bias = jnp.asarray(np.random.RandomState(24).randn(1, 2, 128, 128) * 0.1,
                       jnp.float32)
    dy = jnp.asarray(np.random.RandomState(25).randn(*q.shape), jnp.float32)

    def f(bias, use_pallas):
        return jnp.sum(flash_attention(
            q, k, v, bias=bias, causal=True, use_pallas=use_pallas,
            bias_requires_grad=True, dropout_rate=0.3,
            dropout_seed=987654321) * dy)

    db_flash = jax.grad(lambda b: f(b, True))(bias)
    db_ref = jax.grad(lambda b: f(b, False))(bias)
    tol = 2e-4 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(db_flash), np.asarray(db_ref),
                               rtol=tol, atol=tol)


def test_seed_uses_full_32_bits():
    """ADVICE r2: seeds differing only above bit 24 must give different
    masks (the old fp32 carrier truncated to 24 bits)."""
    from apex_tpu.ops.flash_attention import dropout_keep_mask
    m1 = np.asarray(dropout_keep_mask(1, 1, 1, 64, 128, 0.5))
    m2 = np.asarray(dropout_keep_mask(1 + (1 << 25), 1, 1, 64, 128, 0.5))
    assert (m1 != m2).any()


# ---------------------------------------------------------------------------
# varlen / packed segments (reference:apex/contrib/csrc/fmha/fmha_api.cpp:420
# cu_seqlens role)
# ---------------------------------------------------------------------------

def _packed_ids(b, s, boundaries):
    ids = np.zeros((b, s), np.int32)
    for bi in range(b):
        seg = 0
        for pos in range(s):
            if pos in boundaries[bi]:
                seg += 1
            ids[bi, pos] = seg
    return jnp.asarray(ids)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_match_reference(causal):
    """Pallas segment masking == XLA fallback, forward and grads."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=31)
    ids = _packed_ids(2, 128, [{40, 90}, {64}])
    dy = jnp.asarray(np.random.RandomState(32).randn(*q.shape), jnp.float32)

    def f(q, k, v, use_pallas):
        return jnp.sum(flash_attention(
            q, k, v, causal=causal, use_pallas=use_pallas,
            segment_ids=ids) * dy)

    out_p = flash_attention(q, k, v, causal=causal, use_pallas=True,
                            segment_ids=ids)
    out_r = flash_attention(q, k, v, causal=causal, use_pallas=False,
                            segment_ids=ids)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_r),
                               rtol=2e-4, atol=2e-4)
    g_p = jax.grad(lambda *a: f(*a, True), argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda *a: f(*a, False), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_p, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_segments_are_isolated():
    """Packing semantics: segment A's outputs must not change when segment
    B's tokens change — the property cu_seqlens packing guarantees."""
    rng = np.random.RandomState(33)
    q, k, v = _qkv(b=1, h=2, sq=128, sk=128, seed=33)
    ids = _packed_ids(1, 128, [{64}])
    base = flash_attention(q, k, v, causal=True, use_pallas=True,
                           segment_ids=ids)
    # perturb the SECOND segment's keys/values
    k2 = k.at[:, :, 64:].set(jnp.asarray(rng.randn(1, 2, 64, 64),
                                         k.dtype))
    v2 = v.at[:, :, 64:].set(jnp.asarray(rng.randn(1, 2, 64, 64),
                                         v.dtype))
    pert = flash_attention(q, k2, v2, causal=True, use_pallas=True,
                           segment_ids=ids)
    np.testing.assert_allclose(np.asarray(base[:, :, :64]),
                               np.asarray(pert[:, :, :64]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(base[:, :, 64:]),
                           np.asarray(pert[:, :, 64:]))


def test_segment_ids_with_dropout_and_bias():
    """Segments compose with in-kernel dropout and learned-bias grads."""
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, seed=34)
    ids = _packed_ids(2, 128, [{50}, {30, 100}])
    bias = jnp.asarray(np.random.RandomState(35).randn(1, 2, 128, 128) * 0.1,
                       jnp.float32)
    dy = jnp.asarray(np.random.RandomState(36).randn(*q.shape), jnp.float32)

    def f(bias, use_pallas):
        return jnp.sum(flash_attention(
            q, k, v, bias=bias, causal=True, use_pallas=use_pallas,
            bias_requires_grad=True, dropout_rate=0.2, dropout_seed=4242,
            segment_ids=ids) * dy)

    db_p = jax.grad(lambda b: f(b, True))(bias)
    db_r = jax.grad(lambda b: f(b, False))(bias)
    np.testing.assert_allclose(np.asarray(db_p), np.asarray(db_r),
                               rtol=2e-4, atol=2e-4)


def test_segment_ids_validation():
    q, k, v = _qkv(b=1, h=1, sq=128, sk=256, seed=37)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, segment_ids=jnp.zeros((1, 128), jnp.int32))
    out = flash_attention(
        q, k, v,
        segment_ids=(jnp.zeros((1, 128), jnp.int32),
                     jnp.zeros((1, 256), jnp.int32)))
    ref = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


# -- decode shapes (sq=1 vs a cached sk) — the serving kernel family's
#    entry points into this module; the cache-streaming kernel itself is
#    covered in tests/test_serving.py


def test_supports_flash_decode_shapes():
    """sq == 1 is a first-class shape: only the key-side tiling gates
    (the historical gate silently assumed sq == sk callers)."""
    assert supports_flash(1, 1024, 64, 1, 128)
    assert supports_flash(1, 256, 64, 1, 256)
    assert not supports_flash(1, 200, 64, 1, 128)   # sk misaligned
    assert not supports_flash(1, 256, 63, 1, 128)   # d misaligned
    assert not supports_flash(1, 256, 64, 8, 128)   # q tile must be 1
    # the training gate is unchanged
    assert supports_flash(256, 256, 64, 128, 128)
    assert not supports_flash(200, 256, 64, 128, 128)


def test_flash_sq1_pallas_matches_reference():
    """The generic flash entry point takes the Pallas path at sq=1
    (block_q=1, one padded sublane tile) and matches the reference —
    causal at sq=1 means 'attend to everything cached'."""
    q, k, v = _qkv(sq=1, sk=256, seed=11)
    out = flash_attention(q, k, v, causal=True, use_pallas=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # auto path selects Pallas for the aligned decode shape
    auto = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_mha_reference_kv_length_oracle():
    """The kv_length oracle path: masks exactly like slicing the cache at
    the cursor, and zeroes empty rows."""
    q, k, v = _qkv(b=3, h=2, sq=1, sk=64, seed=12)
    lengths = jnp.asarray([0, 5, 64], jnp.int32)
    out = mha_reference(q, k, v, kv_length=lengths)
    assert np.all(np.asarray(out[0]) == 0.0)
    for i, L in ((1, 5), (2, 64)):
        ref = mha_reference(q[i:i + 1], k[i:i + 1, :, :L],
                            v[i:i + 1, :, :L])
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(ref[0]),
                                   rtol=2e-6, atol=2e-6)
