"""Flash attention kernel parity tests (vs XLA reference attention).

Model: ``reference:apex/contrib/test/fmha/test_fmha.py`` (kernel vs Python
attention) and ``apex/contrib/test/multihead_attn/`` (fast vs default impl).
The Pallas kernels run in interpreter mode on the CPU test backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import flash_attention, mha_reference, supports_flash


def _qkv(b=2, h=2, sq=256, sk=256, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype) * 0.3
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype) * 0.3
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype) * 0.3
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fwd_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, use_pallas=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_with_bias_mask():
    q, k, v = _qkv(seed=1)
    rng = np.random.RandomState(2)
    mask = rng.rand(2, 1, 256, 256) > 0.8
    bias = jnp.where(jnp.asarray(mask), -10000.0, 0.0).astype(jnp.float32)
    out = flash_attention(q, k, v, bias=bias, use_pallas=True)
    ref = mha_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_matches_reference(causal):
    q, k, v = _qkv(b=1, h=2, sq=128, sk=128, seed=3)
    dy = jnp.asarray(np.random.RandomState(4).randn(*q.shape), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       use_pallas=True) * dy)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * dy)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bwd_with_bias():
    q, k, v = _qkv(b=1, h=1, sq=128, sk=256, seed=5)
    mask = np.random.RandomState(6).rand(1, 1, 128, 256) > 0.9
    bias = jnp.where(jnp.asarray(mask), -10000.0, 0.0).astype(jnp.float32)
    dy = jnp.asarray(np.random.RandomState(7).randn(*q.shape), jnp.float32)

    def f(q, k, v, use_pallas):
        return jnp.sum(flash_attention(q, k, v, bias=bias,
                                       use_pallas=use_pallas) * dy)

    g_flash = jax.grad(lambda a, b, c: f(a, b, c, True),
                       argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: f(a, b, c, False),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_cross_attention_causal_offset():
    # sq != sk causal: the mask is offset so the last query row sees all keys
    q, k, v = _qkv(b=1, h=1, sq=128, sk=256, seed=8)
    out = flash_attention(q, k, v, causal=True, use_pallas=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bf16_path():
    q, k, v = _qkv(seed=9, dtype=jnp.bfloat16, sq=128, sk=128)
    out = flash_attention(q, k, v, causal=True, use_pallas=True)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2)


def test_unaligned_falls_back():
    q, k, v = _qkv(sq=100, sk=100, seed=10)
    assert not supports_flash(100, 100, 64, 128, 128)
    out = flash_attention(q, k, v)  # auto-fallback, must not raise
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
