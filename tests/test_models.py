"""Model zoo tests (``reference:tests/L0/run_transformer/run_gpt_minimal_test.py``,
``run_bert_minimal_test.py``; imagenet example smoke)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models import (
    BertConfig, BertModel, GPTConfig, GPTModel, ResNet50, ResNetConfig)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state


def _small_gpt(tp=1, **kw):
    return GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     tensor_model_parallel_size=tp,
                     compute_dtype=jnp.float32, **kw)


def test_gpt_forward_and_loss_single_chip():
    model = GPTModel(_small_gpt())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    logits = jax.jit(model)(params, tokens)
    assert logits.shape == (2, 16, 128)
    loss = jax.jit(model.loss)(params, tokens, tokens)
    assert np.isfinite(float(loss))
    # untrained loss near ln(vocab)
    assert abs(float(loss) - np.log(128)) < 1.0


def test_gpt_trains():
    model = GPTModel(_small_gpt())
    params = model.init(jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 128, (4, 16)))

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, tokens)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5  # memorizing a fixed batch


def test_gpt_tp_matches_single_chip():
    """TP=2 sharded loss == TP=1 dense loss on the same weights
    (test_layers.py / gpt minimal parity model)."""
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        m1, m2 = GPTModel(_small_gpt(tp=1)), GPTModel(_small_gpt(tp=2))
        p2 = m2.init(jax.random.PRNGKey(2))
        tokens = jnp.asarray(np.random.RandomState(2).randint(0, 128, (2, 16)))

        # explicit spec tree: tp-stacked leaves shard axis 0 (embedding word)
        # or axis 1 (per-layer stacks); everything else replicated
        specs = {
            "embedding": {"word": {"weight": P("tensor")},
                          "position": P()},
            "final_ln": {"weight": P(), "bias": P()},
            "layers": {
                "ln1": {"weight": P(), "bias": P()},
                "ln2": {"weight": P(), "bias": P()},
                "qkv": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
                "fc1": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
                "proj": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
                "fc2": {"weight": P(None, "tensor"), "bias": P(None, "tensor")},
            },
        }

        def tp_loss(p2, tokens):
            def inner(p2, tokens):
                return jax.lax.pmean(jax.lax.pmean(
                    m2.loss(p2, tokens, tokens), "tensor"), "data")
            return shard_map(inner, mesh=mesh, in_specs=(specs, P()),
                             out_specs=P())(p2, tokens)

        loss_tp = jax.jit(tp_loss)(p2, tokens)
        loss_dense = _dense_loss_from_sharded(m1, p2, tokens)
        np.testing.assert_allclose(float(loss_tp), float(loss_dense),
                                   rtol=2e-4)
    finally:
        parallel_state.destroy_model_parallel()


def _dense_loss_from_sharded(m1, p2, tokens):
    """Rebuild the tp=1 param layout from tp=2 stacked shards: column shards
    concatenate along out-features, row shards along in-features."""
    L = p2["layers"]

    def col_w(w):  # (L, 2, o/2, in) -> (L, 1, o, in)
        l, t, o, i = w.shape
        return w.reshape(l, 1, t * o, i)

    def col_b(b):  # (L, 2, o/2) -> (L, 1, o)
        l, t, o = b.shape
        return b.reshape(l, 1, t * o)

    def row_w(w):  # (L, 2, out, in/2) -> (L, 1, out, in)
        return jnp.concatenate([w[:, k] for k in range(w.shape[1])],
                               axis=-1)[:, None]

    p1 = {
        "embedding": {
            "word": {"weight": p2["embedding"]["word"]["weight"].reshape(
                1, 128, -1)},
            "position": p2["embedding"]["position"],
        },
        "final_ln": p2["final_ln"],
        "layers": {
            "ln1": L["ln1"], "ln2": L["ln2"],
            "qkv": {"weight": col_w(L["qkv"]["weight"]),
                    "bias": col_b(L["qkv"]["bias"])},
            "fc1": {"weight": col_w(L["fc1"]["weight"]),
                    "bias": col_b(L["fc1"]["bias"])},
            "proj": {"weight": row_w(L["proj"]["weight"]),
                     "bias": L["proj"]["bias"][:, :1]},
            "fc2": {"weight": row_w(L["fc2"]["weight"]),
                    "bias": L["fc2"]["bias"][:, :1]},
        },
    }
    return m1.loss(p1, tokens, tokens)


def test_bert_forward():
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     compute_dtype=jnp.float32)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(3))
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 16)))
    ttypes = jnp.asarray(rng.randint(0, 2, (2, 16)))
    mask = jnp.asarray(rng.rand(2, 16) > 0.2, jnp.int32)
    logits = jax.jit(lambda p, t, tt, m: model(p, t, tt, m))(
        params, tokens, ttypes, mask)
    assert logits.shape == (2, 16, 128)
    h = model.encode(params, tokens, ttypes, mask)
    pooled = model.pool(params, h)
    assert pooled.shape == (2, 64)
    assert np.isfinite(np.asarray(pooled)).all()


def test_bert_padding_mask_matters():
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=1,
                     num_attention_heads=2, max_position_embeddings=16,
                     compute_dtype=jnp.float32)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(4))
    tokens = jnp.asarray(np.random.RandomState(4).randint(0, 64, (1, 8)))
    full = model.encode(params, tokens, None, jnp.ones((1, 8), jnp.int32))
    half = model.encode(params, tokens, None,
                        jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]]))
    assert not np.allclose(np.asarray(full[:, 0]), np.asarray(half[:, 0]),
                           atol=1e-5)


def test_resnet50_forward_and_train_step():
    cfg = ResNetConfig(num_classes=10, compute_dtype=jnp.float32)
    model = ResNet50(cfg)
    params, state = model.init(jax.random.PRNGKey(5))
    x = jnp.asarray(np.random.RandomState(5).randn(2, 64, 64, 3), jnp.float32)
    logits, new_state = jax.jit(
        lambda p, s, x: model(p, s, x, training=True))(params, state, x)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()
    # running stats updated
    assert int(new_state["stem"]["bn"].num_batches_tracked) == 1
    # eval path uses running stats
    logits_eval, st = jax.jit(
        lambda p, s, x: model(p, s, x, training=False))(params, state, x)
    assert int(st["stem"]["bn"].num_batches_tracked) == 0

    # one grad step decreases loss on a fixed batch
    labels = jnp.asarray([1, 3])
    from apex_tpu.optimizers import FusedSGD
    opt = FusedSGD(lr=0.005)
    ostate = opt.init(params)

    def loss_fn(params, state):
        logits, new_state = model(params, state, x, training=True)
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)), new_state

    @jax.jit
    def step(params, state, ostate):
        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, state)
        params, ostate = opt.step(grads, ostate, params)
        return params, new_state, ostate, loss

    losses = []
    for _ in range(5):
        params, state, ostate, loss = step(params, state, ostate)
        losses.append(float(loss))
    # batch-2 BN makes per-step loss noisy; the optimizer must still make
    # progress below the initial loss at some point
    assert min(losses[1:]) < losses[0]


def test_bert_pretraining_loss_heads():
    """MLM head + binary head (standalone_bert BertLMHead /
    post_language_model_processing): masked-LM CE honors the loss mask,
    the binary head adds its CE, and grads reach both heads and the tied
    embedding."""
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     compute_dtype=jnp.float32)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 128, (2, 32)))
    labels = jnp.asarray(rng.randint(0, 128, (2, 32)))
    mask = jnp.asarray((rng.rand(2, 32) < 0.15).astype(np.float32))
    binary = jnp.asarray([0, 1])
    types = jnp.asarray(rng.randint(0, 2, (2, 32)))
    attn = jnp.ones((2, 32))

    loss = model.loss(params, tokens, labels, loss_mask=mask,
                      token_types=types, attention_mask=attn,
                      binary_labels=binary)
    assert np.isfinite(float(loss))
    lm_only = model.loss(params, tokens, labels, loss_mask=mask,
                         token_types=types, attention_mask=attn)
    assert float(loss) > float(lm_only)  # binary CE adds

    # loss mask: changing labels at masked-OUT positions changes nothing
    labels2 = jnp.where(mask > 0, labels, (labels + 1) % 128)
    np.testing.assert_allclose(
        float(model.loss(params, tokens, labels2, loss_mask=mask,
                         token_types=types, attention_mask=attn)),
        float(lm_only), rtol=1e-6)

    grads = jax.grad(lambda p: model.loss(
        p, tokens, labels, loss_mask=mask, token_types=types,
        attention_mask=attn, binary_labels=binary))(params)
    for path in ("lm_head", "binary_head"):
        assert any(float(np.abs(np.asarray(l)).max()) > 0
                   for l in jax.tree_util.tree_leaves(grads[path]))
    emb = np.asarray(grads["embedding"]["word"]["weight"])
    assert np.abs(emb).max() > 0


def test_bert_mlm_head_under_tp2():
    """Code-review r3: the MLM head must work under TP — vocab-sharded
    output bias and vocab-parallel CE (the all-reduce falls out of
    vocab_parallel_cross_entropy)."""
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        from apex_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=16,
                         compute_dtype=jnp.float32,
                         tensor_model_parallel_size=2, use_flash=False,
                         add_pooler=False, add_binary_head=True)
        model = BertModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        assert "binary_head" not in params  # gated on the pooler
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        labels = jnp.asarray(rng.randint(0, 64, (2, 16)))
        mask = jnp.ones((2, 16), jnp.float32)

        specs = {
            "embedding": {"word": {"weight": P("tensor")},
                          "position": P(), "tokentype": P()},
            "final_ln": {"weight": P(), "bias": P()},
            "layers": jax.tree_util.tree_map(
                lambda p: P(None, "tensor") if p.ndim >= 3 else P(),
                params["layers"]),
            "lm_head": {"dense": {"weight": P(), "bias": P()},
                        "ln": {"weight": P(), "bias": P()},
                        "bias": P("tensor")},
        }

        def run(params, tokens, labels, mask):
            def inner(params, tokens, labels, mask):
                return jax.lax.pmean(jax.lax.pmean(
                    model.loss(params, tokens, labels, loss_mask=mask),
                    "tensor"), "data")
            return shard_map(inner, mesh=mesh,
                             in_specs=(specs, P(), P(), P()),
                             out_specs=P())(params, tokens, labels, mask)

        loss = jax.jit(run)(params, tokens, labels, mask)
        assert np.isfinite(float(loss))
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_sequence_parallel_matches_tp():
    """Megatron-LM SP: sequence-sharded norms/residuals with gather/
    reduce-scatter TP boundaries must reproduce plain TP exactly (same
    params, same mesh)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        kw = dict(vocab_size=128, hidden_size=32, num_layers=2,
                  num_attention_heads=4, max_position_embeddings=16,
                  compute_dtype=jnp.float32, use_flash=False,
                  tensor_model_parallel_size=2)
        m_tp = GPTModel(GPTConfig(**kw))
        m_sp = GPTModel(GPTConfig(**kw, sequence_parallel=True))
        params = m_tp.init(jax.random.PRNGKey(2))
        tokens = jnp.asarray(np.random.RandomState(2).randint(
            0, 128, (2, 16)))

        specs = {
            "embedding": {"word": {"weight": P("tensor")}, "position": P()},
            "final_ln": {"weight": P(), "bias": P()},
            "layers": jax.tree_util.tree_map(
                lambda p: P(None, "tensor") if p.ndim >= 3 else P(),
                params["layers"]),
        }

        def run(model, params, tokens):
            def inner(params, tokens):
                loss, grads = jax.value_and_grad(
                    lambda p: model.loss(p, tokens, tokens))(params)
                # SP: the LN custom_vjp already psums replicated-param
                # cotangents over the tensor axis (Megatron's separate
                # allreduce of sequence_parallel-marked params, moved into
                # the vjp); sp_grad_sync is a retained no-op.
                grads = model.sp_grad_sync(grads)
                pm = lambda v: jax.lax.pmean(
                    jax.lax.pmean(v, "tensor"), "data")
                return pm(loss), jax.tree_util.tree_map(pm, grads)
            return shard_map(inner, mesh=mesh, in_specs=(specs, P()),
                             out_specs=(P(), specs))(params, tokens)

        loss_tp, g_tp = jax.jit(
            lambda p, t: run(m_tp, p, t))(params, tokens)
        loss_sp, g_sp = jax.jit(
            lambda p, t: run(m_sp, p, t))(params, tokens)
        np.testing.assert_allclose(float(loss_sp), float(loss_tp),
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g_sp),
                        jax.tree_util.tree_leaves(g_tp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6)
    finally:
        parallel_state.destroy_model_parallel()


def test_stem_space_to_depth_parity():
    """The conv0 space-to-depth reformulation is bit-equivalent math:
    fwd values, dW, and dX all match the plain 7x7/2 stem (the option is
    default-off by measurement — docs/PERF.md — but must stay correct)."""
    from apex_tpu.models import ResNet50, ResNetConfig

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 64, 64, 3), jnp.float32)
    w = jnp.asarray(rng.randn(7, 7, 3, 16) * 0.1, jnp.float32)
    plain = ResNet50(ResNetConfig(compute_dtype=jnp.float32,
                                  stem_space_to_depth=False))
    s2d = ResNet50(ResNetConfig(compute_dtype=jnp.float32,
                                stem_space_to_depth=True))
    np.testing.assert_allclose(np.asarray(plain._stem_conv(w, x)),
                               np.asarray(s2d._stem_conv(w, x)),
                               rtol=1e-5, atol=1e-5)
    gw_a = jax.grad(lambda w: jnp.sum(plain._stem_conv(w, x) ** 2))(w)
    gw_b = jax.grad(lambda w: jnp.sum(s2d._stem_conv(w, x) ** 2))(w)
    np.testing.assert_allclose(np.asarray(gw_a), np.asarray(gw_b),
                               rtol=1e-4, atol=1e-4)
    gx_a = jax.grad(lambda x: jnp.sum(plain._stem_conv(w, x) ** 2))(x)
    gx_b = jax.grad(lambda x: jnp.sum(s2d._stem_conv(w, x) ** 2))(x)
    np.testing.assert_allclose(np.asarray(gx_a), np.asarray(gx_b),
                               rtol=1e-4, atol=1e-4)
