"""Op parity tests (softmax, xentropy, focal loss, MLP/dense).

Models: ``reference:tests/L0/run_transformer/test_fused_softmax.py``,
``apex/contrib/test/test_label_smoothing.py``,
``apex/contrib/test/focal_loss/test_focal_loss.py``,
``tests/L0/run_mlp/test_mlp.py``, ``apex/contrib/test/fused_dense/``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import ops


# ---------------------------------------------------------------------------
# fused softmax
# ---------------------------------------------------------------------------

def test_scaled_masked_softmax_vs_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 8, 24).astype(np.float32)
    mask = rng.rand(2, 1, 8, 24) > 0.7
    out = ops.scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 0.5)
    tx = torch.tensor(x) * 0.5
    tx = tx.masked_fill(torch.tensor(mask), -10000.0)
    ref = torch.softmax(tx, dim=-1)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-5, atol=1e-6)


def test_causal_softmax_matches_masked():
    rng = np.random.RandomState(1)
    x = rng.randn(3, 16, 16).astype(np.float32)
    out = ops.scaled_upper_triang_masked_softmax(jnp.asarray(x), 1.0)
    tril = np.tril(np.ones((16, 16), bool))
    ref = torch.softmax(
        torch.tensor(x).masked_fill(~torch.tensor(tril), -10000.0), dim=-1)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-5, atol=1e-6)


def test_fused_scale_mask_softmax_dispatcher():
    sm = ops.FusedScaleMaskSoftmax(
        input_in_bf16=True, attn_mask_type=ops.AttnMaskType.causal,
        scaled_masked_softmax_fusion=True, mask_func=None,
        softmax_in_fp32=True, scale=0.25)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 4, 16, 16), jnp.bfloat16)
    out = sm(x, None)
    assert out.shape == x.shape and out.dtype == jnp.bfloat16
    # rows sum to 1
    np.testing.assert_allclose(np.asarray(out.sum(-1), np.float32),
                               np.ones((2, 4, 16)), rtol=0.02)
    # reference kernel-eligibility logic is preserved
    assert sm.is_kernel_available(jnp.ones((2, 1, 16, 16), bool), 2, 4, 16, 64)
    assert not sm.is_kernel_available(None, 2, 4, 16, 64)
    assert not sm.is_kernel_available(jnp.ones((2, 1, 16, 16), bool), 2, 4, 16, 4096)


# ---------------------------------------------------------------------------
# xentropy
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_vs_torch(smoothing):
    rng = np.random.RandomState(3)
    logits = rng.randn(32, 50).astype(np.float32)
    labels = rng.randint(0, 50, size=(32,))
    labels[:4] = 0  # padding_idx rows

    out = ops.softmax_cross_entropy_loss(
        jnp.asarray(logits), jnp.asarray(labels), smoothing=smoothing,
        padding_idx=0)

    tl = torch.tensor(logits, requires_grad=True)
    ref = torch.nn.functional.cross_entropy(
        tl, torch.tensor(labels), reduction="none",
        label_smoothing=smoothing)
    ref = ref.masked_fill(torch.tensor(labels) == 0, 0.0)
    np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                               rtol=1e-5, atol=1e-5)

    # grads
    def loss_fn(lg):
        return jnp.sum(ops.softmax_cross_entropy_loss(
            lg, jnp.asarray(labels), smoothing=smoothing, padding_idx=0))

    g = jax.grad(loss_fn)(jnp.asarray(logits))
    ref.sum().backward()
    np.testing.assert_allclose(np.asarray(g), tl.grad.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_xentropy_memory_structure():
    """Backward recomputes probs from logits+mlse — the saved residuals must
    not include the softmax (the point of the fusion)."""
    logits = jnp.asarray(np.random.RandomState(4).randn(8, 1000), jnp.float32)
    labels = jnp.asarray(np.arange(8) + 1)
    jaxpr = jax.make_jaxpr(
        lambda lg: jax.vjp(lambda l: ops.softmax_cross_entropy_loss(
            l, labels, 0.1, 0).sum(), lg)[0])(logits)
    assert "exp" not in str(jaxpr.jaxpr.outvars)  # structural smoke


# ---------------------------------------------------------------------------
# focal loss
# ---------------------------------------------------------------------------

def _focal_ref_numpy(x, y, npos, num_real, alpha, gamma, s):
    """Direct transcription of focal_loss_cuda_kernel.cu:30-110 math."""
    n, k = x.shape
    if s > 0:
        nn, np_ = 1 - s / k, s / k
        pn, pp = s - s / k, 1 - s + s / k
    else:
        nn, np_, pn, pp = 1.0, 0.0, 0.0, 1.0
    total = 0.0
    for i in range(n):
        if y[i] == -2:
            continue
        for c in range(k):
            if c >= num_real:
                continue
            p = x[i, c]
            sigma = 1 / (1 + np.exp(-p))
            off_a = np.log1p(np.exp(-abs(p))) + max(-p, 0)
            if y[i] >= 0 and c == y[i]:
                coeff_f = alpha * (1 - sigma) ** gamma
                base = pn * p
            else:
                coeff_f = (1 - alpha) * sigma ** gamma
                base = nn * p
            total += coeff_f * (base + off_a)
    return total / npos


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_focal_loss_vs_kernel_math(smoothing):
    rng = np.random.RandomState(5)
    x = rng.randn(12, 8).astype(np.float32)
    y = rng.randint(-2, 8, size=(12,))
    npos = max((y >= 0).sum(), 1)
    out = ops.focal_loss(jnp.asarray(x), jnp.asarray(y),
                         jnp.asarray(float(npos)), num_real_classes=6,
                         alpha=0.25, gamma=2.0, label_smoothing=smoothing)
    ref = _focal_ref_numpy(x, y, npos, 6, 0.25, 2.0, smoothing)
    np.testing.assert_allclose(float(out), ref, rtol=1e-5)
    g = jax.grad(lambda lg: ops.focal_loss(
        lg, jnp.asarray(y), jnp.asarray(float(npos)), 6, 0.25, 2.0,
        smoothing))(jnp.asarray(x))
    assert np.isfinite(np.asarray(g)).all()
    # ignored rows and pad classes have zero grad
    assert np.all(np.asarray(g)[y == -2] == 0)
    assert np.all(np.asarray(g)[:, 6:] == 0)


# ---------------------------------------------------------------------------
# MLP / fused dense
# ---------------------------------------------------------------------------

def test_mlp_vs_torch():
    sizes = (16, 32, 8)
    m = ops.MLP(sizes, bias=True, activation="relu")
    params = m.init(jax.random.PRNGKey(0))
    x = np.random.RandomState(6).randn(4, 16).astype(np.float32)
    out = m(params, jnp.asarray(x))

    tx = torch.tensor(x)
    h = tx
    for w, b in params:
        lin = torch.nn.functional.linear(
            h, torch.tensor(np.asarray(w)), torch.tensor(np.asarray(b)))
        h = torch.relu(lin)
    np.testing.assert_allclose(np.asarray(out), h.numpy(), rtol=1e-5, atol=1e-5)


def test_fused_dense_gelu_dense():
    d = ops.FusedDenseGeluDense(16, 64, 8)
    params = d.init(jax.random.PRNGKey(1))
    x = jnp.asarray(np.random.RandomState(7).randn(4, 16), jnp.float32)
    out = d(params, x)
    tx = torch.tensor(np.asarray(x))
    h = torch.nn.functional.linear(
        tx, torch.tensor(np.asarray(params["dense1"]["weight"])),
        torch.tensor(np.asarray(params["dense1"]["bias"])))
    h = torch.nn.functional.gelu(h, approximate="tanh")
    ref = torch.nn.functional.linear(
        h, torch.tensor(np.asarray(params["dense2"]["weight"])),
        torch.tensor(np.asarray(params["dense2"]["bias"])))
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-4)


def test_mlp_bf16_fp32_accum():
    m = ops.MLP((256, 256), activation="none", param_dtype=jnp.bfloat16)
    params = m.init(jax.random.PRNGKey(2))
    x = jnp.ones((2, 256), jnp.bfloat16)
    out = m(params, x)
    assert out.dtype == jnp.bfloat16
