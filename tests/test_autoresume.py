"""AutoResume termination-detection tests (previously zero coverage).

Covers the latching contract (SIGTERM, env var, and hook requests are
permanent once seen — a hook that fires once at step K then returns False
at K+1 must not lose the request), the ``--adlr-autoresume-interval``
polling semantics, SIGTERM handler chaining + ``close()`` restore, and
context-manager use.
"""

import os
import signal

import pytest

from apex_tpu.utils.autoresume import AutoResume


@pytest.fixture
def clean_env(monkeypatch):
    monkeypatch.delenv("APEX_TPU_TERMINATE", raising=False)


def test_sigterm_latches(clean_env):
    with AutoResume(interval=1) as ar:
        assert not ar.termination_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert ar.termination_requested()
        # latched: every later poll (any step) keeps reporting it
        assert ar.termination_requested(step=3)


def test_env_var_any_nonempty_and_latch(clean_env, monkeypatch):
    with AutoResume(interval=1, install_sigterm_handler=False) as ar:
        monkeypatch.setenv("APEX_TPU_TERMINATE", "")
        assert not ar.termination_requested()  # empty string: no request
        monkeypatch.setenv("APEX_TPU_TERMINATE", " ")  # whitespace-only
        assert ar.termination_requested()      # "any non-empty" contract
        # latched even after the variable is cleared again
        monkeypatch.delenv("APEX_TPU_TERMINATE")
        assert ar.termination_requested()


def test_hook_polled_on_interval_only(clean_env):
    calls = []

    def hook():
        calls.append(1)
        return False

    with AutoResume(interval=5, hook=hook,
                    install_sigterm_handler=False) as ar:
        for step in range(1, 10):
            ar.termination_requested(step)
        # polled at step 5 only; 1-4 and 6-9 are interval-off steps
        assert len(calls) == 1
        ar.termination_requested()  # stepless poll always asks
        assert len(calls) == 2


def test_hook_firing_once_is_latched(clean_env):
    fired = iter([True])

    def hook():
        return next(fired, False)  # True exactly once, then False forever

    with AutoResume(interval=1, hook=hook,
                    install_sigterm_handler=False) as ar:
        assert ar.termination_requested(step=4)
        # the hook now answers False — the latched flag must survive
        assert ar.termination_requested(step=5)
        assert ar.termination_requested()


def test_handler_chaining_and_close_restores(clean_env):
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        ar = AutoResume(interval=1)
        os.kill(os.getpid(), signal.SIGTERM)
        assert ar.termination_requested()
        # the pre-existing handler was chained, not swallowed
        assert seen == [signal.SIGTERM]
        ar.close()
        # close() reinstalled the previous handler
        assert signal.getsignal(signal.SIGTERM) is not ar._on_sigterm
        os.kill(os.getpid(), signal.SIGTERM)
        assert seen == [signal.SIGTERM, signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_context_manager_restores_handler(clean_env):
    before = signal.getsignal(signal.SIGTERM)
    with AutoResume(interval=1) as ar:
        assert signal.getsignal(signal.SIGTERM) == ar._on_sigterm
    assert signal.getsignal(signal.SIGTERM) == before


def test_interval_validation(clean_env):
    with pytest.raises(ValueError):
        AutoResume(interval=0)
