"""Native host flatten/unflatten/gather (apex_C role,
``reference:csrc/flatten_unflatten.cpp:15-18``)."""

import numpy as np
import pytest

from apex_tpu._native import (flatten, gather_rows, native_available,
                              unflatten)


def test_native_builds():
    """The toolchain exists in CI images; the .so must actually build."""
    assert native_available()


def test_flatten_unflatten_roundtrip():
    rng = np.random.RandomState(0)
    arrays = [rng.randn(17, 9).astype(np.float32),
              rng.randn(4).astype(np.float16),
              rng.randint(0, 100, (3, 3)).astype(np.int32)]
    flat = flatten(arrays)
    assert flat.dtype == np.uint8
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = unflatten(flat, arrays)
    for a, b in zip(arrays, back):
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(a, b)


def test_unflatten_validates_size():
    with pytest.raises(ValueError):
        unflatten(np.zeros(3, np.uint8), [np.zeros((4,), np.float32)])


def test_gather_rows_matches_take_and_validates():
    rng = np.random.RandomState(1)
    src = rng.randn(64, 7, 3).astype(np.float32)
    idx = rng.randint(0, 64, 33)
    np.testing.assert_array_equal(gather_rows(src, idx),
                                  np.take(src, idx, axis=0))
    with pytest.raises(IndexError):
        gather_rows(src, [64])


def test_python_fallback_matches_native():
    import apex_tpu._native as nat
    rng = np.random.RandomState(2)
    arrays = [rng.randn(5, 5).astype(np.float32), rng.randn(2).astype(np.float64)]
    native = flatten(arrays)
    lib, tried = nat._LIB, nat._TRIED
    nat._LIB, nat._TRIED = None, True  # force fallback
    try:
        fallback = flatten(arrays)
        np.testing.assert_array_equal(native, fallback)
    finally:
        nat._LIB, nat._TRIED = lib, tried
