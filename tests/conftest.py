"""Test configuration: run all tests on a virtual 8-device CPU mesh.

The reference (krunt/apex) requires real GPUs for every test (SURVEY.md §4). We
improve on that: XLA's CPU backend with ``--xla_force_host_platform_device_count=8``
lets every distributed code path (DP/TP/PP/SP shardings, collectives, pipeline
schedules) compile and execute on any host. Real-TPU benchmarking happens in
``bench.py``, not in the test suite.

Note: the environment may pre-set ``JAX_PLATFORMS`` (e.g. to a TPU plugin) and
the plugin's sitecustomize may import jax before this conftest runs, so we
switch platforms via ``jax.config`` — which works any time before the backend
is first used — rather than via environment variables.
"""

import os

# Bench smoke tests drive bench.py's real _emit path; their shrunken-shape
# numbers must never land in the repo's longitudinal BENCH_HISTORY.jsonl.
# Tests that exercise the history round-trip re-point this at a tmp path.
os.environ.setdefault("APEX_BENCH_HISTORY", "off")

from apex_tpu.utils.hostmesh import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)


def pytest_report_header(config):
    return f"jax {jax.__version__} devices: {jax.device_count()} ({jax.default_backend()})"
