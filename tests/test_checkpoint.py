"""Checkpoint/resume tests.

Model: the reference's bitwise-resume recipe (``reference:README.md:57-97``),
amp scaler persistence (``reference:apex/amp/frontend.py:361-400``), the
fp32-on-disk rule of ``O2StateDictHook``
(``reference:apex/amp/_initialize.py:133-142``), and sharded optimizer
state_dicts (``reference:apex/contrib/optimizers/distributed_fused_adam_v2.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp.scaler import DynamicLossScale, all_finite
from apex_tpu.checkpoint import (all_steps, latest_step, restore_checkpoint,
                                 save_checkpoint)
from apex_tpu.optimizers import (DistributedFusedAdam, FusedAdam, FusedSGD,
                                 ZeroAdamState)
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    RampupBatchsizeNumMicroBatches)
from apex_tpu.transformer.tensor_parallel.random import RNGStatesTracker


def _bits(tree):
    out = []
    for x in jax.tree_util.tree_leaves(tree):
        if not hasattr(x, "dtype"):
            continue
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x = jax.random.key_data(x)
        out.append((str(np.asarray(x).dtype), np.asarray(x).tobytes()))
    return out


def test_roundtrip_bitwise_identity(tmp_path):
    """save → restore is the identity for every leaf, across dtypes and
    PRNG-key flavors."""
    state = {
        "w32": jnp.asarray(np.random.RandomState(0).randn(5, 3), jnp.float32),
        "wb16": jnp.asarray(
            np.random.RandomState(1).randn(7), jnp.bfloat16),
        "w16": jnp.asarray(np.random.RandomState(2).randn(4), jnp.float16),
        "step": jnp.asarray(11, jnp.int32),
        "legacy_key": jax.random.PRNGKey(42),
        "typed_key": jax.random.key(43),
    }
    save_checkpoint(str(tmp_path), state, step=11)
    restored, host = restore_checkpoint(str(tmp_path), state)
    assert _bits(restored) == _bits(state)
    # typed key stays typed
    assert jnp.issubdtype(restored["typed_key"].dtype, jax.dtypes.prng_key)


def test_fp32_on_disk_loadable_into_fp32_model(tmp_path):
    """The O2StateDictHook rule: a bf16-trained model's checkpoint restores
    directly into an fp32 (O0) model with full-precision values."""
    w = jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16)
    save_checkpoint(str(tmp_path), {"w": w}, step=0)
    target32 = {"w": jax.ShapeDtypeStruct((3,), jnp.float32)}
    restored, _ = restore_checkpoint(str(tmp_path), target32)
    assert restored["w"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(w, np.float32))


def test_latest_step_keep_and_host_state(tmp_path):
    calc = RampupBatchsizeNumMicroBatches(4, 4, 64, 16, 2, 1)
    calc.update(40, False)
    for s in (1, 3, 7):
        save_checkpoint(str(tmp_path), {"x": jnp.zeros(2)}, step=s,
                        host_state={"microbatch_calculator":
                                    calc.state_dict(),
                                    "consumed_samples": 40},
                        keep=2)
    assert latest_step(str(tmp_path)) == 7
    assert all_steps(str(tmp_path)) == [3, 7]
    _, host = restore_checkpoint(str(tmp_path), {"x": jnp.zeros(2)})
    calc2 = RampupBatchsizeNumMicroBatches(4, 4, 64, 16, 2, 1)
    calc2.load_state_dict(host["microbatch_calculator"])
    assert calc2.num_micro_batches == calc.num_micro_batches
    assert calc2.current_global_batch_size == calc.current_global_batch_size


def _train_setup(dtype):
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 8), dtype),
              "b": jnp.asarray(rng.randn(8), dtype)}
    x = jnp.asarray(rng.randn(16, 8), dtype)
    y = jnp.asarray(rng.randn(16, 8), jnp.float32)
    opt = FusedAdam(lr=1e-2)
    scaler = DynamicLossScale(init_scale=2.0 ** 8, growth_interval=3)

    @jax.jit
    def step(params, opt_state, ls):
        def loss_fn(p):
            h = x @ p["w"] + p["b"]
            return jnp.mean((h.astype(jnp.float32) - y) ** 2) * ls.loss_scale
        grads = jax.grad(loss_fn)(params)
        grads = scaler.unscale(ls, grads)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite)
        return params, opt_state, new_ls

    return params, opt, scaler, step


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bitwise_resume(tmp_path, dtype):
    """5 steps + save + restore + 5 more == 10 straight steps, bitwise —
    params, optimizer moments, and loss-scaler scalars all resume exactly,
    including through the fp32-on-disk widening for bf16 params."""
    params, opt, scaler, step = _train_setup(dtype)
    state = {"params": params, "opt": opt.init(params), "ls": scaler.init()}

    ref = dict(state)
    for _ in range(10):
        ref["params"], ref["opt"], ref["ls"] = step(
            ref["params"], ref["opt"], ref["ls"])

    run = dict(state)
    for _ in range(5):
        run["params"], run["opt"], run["ls"] = step(
            run["params"], run["opt"], run["ls"])
    save_checkpoint(str(tmp_path), run, step=5)
    restored, _ = restore_checkpoint(str(tmp_path), run)
    for _ in range(5):
        restored["params"], restored["opt"], restored["ls"] = step(
            restored["params"], restored["opt"], restored["ls"])

    assert _bits(restored) == _bits(ref)


def test_resume_under_tp2(tmp_path):
    """TP-sharded params keep values and shardings through save/restore."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("tensor",))
    sh = NamedSharding(mesh, P(None, "tensor"))
    w = jax.device_put(
        jnp.asarray(np.random.RandomState(3).randn(8, 4), jnp.float32), sh)
    save_checkpoint(str(tmp_path), {"w": w}, step=0)
    target = {"w": jax.ShapeDtypeStruct(w.shape, w.dtype, sharding=sh)}
    restored, _ = restore_checkpoint(str(tmp_path), target)
    assert restored["w"].sharding == sh
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(w))


def test_bitwise_resume_distributed_fused_adam(tmp_path):
    """ZeRO resume: the sharded master/moment flat shards round-trip with
    their P('data') sharding and continue bitwise."""
    DP = 4
    mesh = Mesh(np.array(jax.devices()[:DP]), ("data",))
    opt = DistributedFusedAdam(lr=1e-2)
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 11), jnp.float32),
              "b": jnp.asarray(rng.randn(11), jnp.float32)}
    grads_stacked = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(DP, *np.shape(p)), jnp.float32),
        params)
    state_spec = ZeroAdamState(step=P(), master=P("data"),
                               exp_avg=P("data"), exp_avg_sq=P("data"),
                               bucket_stamp=P())
    gspec = jax.tree_util.tree_map(lambda _: P("data"), grads_stacked)

    @jax.jit
    def init_fn(params):
        return shard_map(lambda p: opt.init(p), mesh=mesh,
                         in_specs=(P(),), out_specs=state_spec)(params)

    @jax.jit
    def step_fn(params, state, grads_stacked):
        def inner(params, state, g):
            g0 = jax.tree_util.tree_map(lambda s: s[0], g)
            return opt.step(g0, state, params)
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), state_spec, gspec),
                         out_specs=(P(), state_spec))(
                             params, state, grads_stacked)

    ref_p, ref_s = params, init_fn(params)
    for _ in range(6):
        ref_p, ref_s = step_fn(ref_p, ref_s, grads_stacked)

    p, s = params, init_fn(params)
    for _ in range(3):
        p, s = step_fn(p, s, grads_stacked)
    save_checkpoint(str(tmp_path), {"params": p, "opt": s}, step=3)
    restored, _ = restore_checkpoint(str(tmp_path), {"params": p, "opt": s})
    # shardings preserved on the flat shards
    assert restored["opt"].master.sharding.spec == P("data")
    p, s = restored["params"], restored["opt"]
    for _ in range(3):
        p, s = step_fn(p, s, grads_stacked)

    assert _bits((p, s)) == _bits((ref_p, ref_s))


def test_rng_tracker_states_roundtrip(tmp_path):
    tracker = RNGStatesTracker()
    tracker.add("model-parallel-rng", 123)
    tracker.add("data-parallel-rng", 7)
    tracker.make_key("model-parallel-rng")  # advance
    save_checkpoint(str(tmp_path), {"rng": tracker.get_states()}, step=0)
    restored, _ = restore_checkpoint(str(tmp_path),
                                     {"rng": tracker.get_states()})
    t2 = RNGStatesTracker()
    t2.set_states(restored["rng"])
    k1 = tracker.make_key("model-parallel-rng")
    k2 = t2.make_key("model-parallel-rng")
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_restore_missing_and_uncommitted(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path), {"x": jnp.zeros(1)})
    # a checkpoint without its COMMITTED marker is invisible
    path = save_checkpoint(str(tmp_path), {"x": jnp.zeros(1)}, step=4)
    import os
    os.remove(os.path.join(path, "COMMITTED"))
    assert latest_step(str(tmp_path)) is None


def test_restore_skips_torn_dir_with_warning(tmp_path):
    """A torn dir NEWER than the latest COMMITTED step (a writer died
    mid-save) is skipped loudly: latest-step restore warns naming the
    skipped step and falls back to the committed one."""
    import os

    from apex_tpu.checkpoint import torn_steps

    save_checkpoint(str(tmp_path), {"x": jnp.full(2, 1.0)}, step=1)
    path2 = save_checkpoint(str(tmp_path), {"x": jnp.full(2, 2.0)}, step=2)
    os.remove(os.path.join(path2, "COMMITTED"))
    assert torn_steps(str(tmp_path)) == [2]
    with pytest.warns(UserWarning, match=r"torn.*\[2\]"):
        restored, host = restore_checkpoint(str(tmp_path),
                                            {"x": jnp.zeros(2)})
    np.testing.assert_array_equal(np.asarray(restored["x"]), [1.0, 1.0])


def test_restore_with_only_torn_dirs_names_them(tmp_path):
    import os

    path = save_checkpoint(str(tmp_path), {"x": jnp.zeros(1)}, step=3)
    os.remove(os.path.join(path, "COMMITTED"))
    with pytest.warns(UserWarning, match="torn"):
        with pytest.raises(FileNotFoundError, match=r"torn.*\[3\]"):
            restore_checkpoint(str(tmp_path), {"x": jnp.zeros(1)})


def test_keep_last_is_canonical_keep_spelling(tmp_path):
    for s in (1, 2, 3):
        save_checkpoint(str(tmp_path), {"x": jnp.zeros(2)}, step=s,
                        keep_last=2)
    assert all_steps(str(tmp_path)) == [2, 3]
    # conflicting double spelling is rejected
    with pytest.raises(ValueError, match="keep_last"):
        save_checkpoint(str(tmp_path), {"x": jnp.zeros(2)}, step=4,
                        keep=1, keep_last=2)
    with pytest.raises(ValueError):
        save_checkpoint(str(tmp_path), {"x": jnp.zeros(2)}, step=4,
                        keep_last=0)
